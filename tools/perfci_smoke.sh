#!/usr/bin/env bash
# Perf-console smoke: one command proves the unattended perf-CI chain on CPU.
#
#   1. the COMMITTED matrix (benchmarks/perfci.json) must validate and plan
#      under `tpudist-perfci --dry-run` — what tpu_watch.sh checks at arm
#      time;
#   2. a tiny CPU matrix runs end to end: a row-producing stage appends to
#      a scratch history through regress.append_history, a platform-guarded
#      stage is skipped, the report/exit contract is 0;
#   3. a second run with a 30% slower row must trip the trailing-median
#      gate: exit 1 (findings), and a crashing stage must outrank it: 2;
#   4. `--dashboard` must render the self-contained trend artifact with the
#      regressed series flagged.
#
# Runs standalone (`bash tools/perfci_smoke.sh [workdir]`) and as the
# perfci-marked test tests/test_perfci.py::test_perfci_smoke_script.
# Prints PERFCI_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_PERFCI_SMOKE_DIR:-$(mktemp -d)}}"
mkdir -p "$WORK"
HIST="$WORK/hist.jsonl"
REPORT="$WORK/perfci_report.json"
MANIFEST="$WORK/manifest.json"

echo "[perfci-smoke] 1/4 committed manifest validates" >&2
python -m tpudist.perfci --dry-run --platform cpu >/dev/null

cat > "$MANIFEST" <<'JSON'
{
  "stages": [
    {"name": "rows",
     "cmd": ["python", "-c",
             "import json, os; print(json.dumps({'metric': 'smoke_ips', 'value': float(os.environ['SMOKE_VAL']), 'unit': 'images/sec'}))"],
     "append_stdout_rows": true, "series": ["smoke_ips"], "timeout_s": 120},
    {"name": "chip_only",
     "cmd": ["python", "-c", "raise SystemExit('must never run on cpu')"],
     "platforms": ["tpu"], "timeout_s": 60}
  ]
}
JSON

echo "[perfci-smoke] 2/4 clean matrix run (scratch history)" >&2
SMOKE_VAL=1000 python -m tpudist.perfci --manifest "$MANIFEST" \
    --history "$HIST" --report "$REPORT" --platform cpu
python - "$REPORT" <<'PY'
import json, sys
rep = json.load(open(sys.argv[1]))
s = rep["summary"]
assert rep["exit"] == 0 and s["stages_ok"] == 1 and s["stages_skipped"] == 1
assert s["rows_appended"] == 1, s
by = {st["name"]: st["status"] for st in rep["stages"]}
assert by == {"rows": "ok", "chip_only": "skipped_platform"}, by
print("[perfci-smoke] report ok", file=sys.stderr)
PY

echo "[perfci-smoke] 3/4 gate + exit contract" >&2
# arm the baseline, then a 30% slower row must exit 1
SMOKE_VAL=1010 python -m tpudist.perfci --manifest "$MANIFEST" \
    --history "$HIST" --report "$REPORT" --platform cpu
set +e
SMOKE_VAL=700 python -m tpudist.perfci --manifest "$MANIFEST" \
    --history "$HIST" --report "$REPORT" --platform cpu \
    --dashboard "$WORK/dashboard.html"
rc=$?
set -e
if [[ "$rc" != 1 ]]; then
    echo "[perfci-smoke] expected exit 1 on a 30% regression, got $rc" >&2
    exit 1
fi
# an operationally failed stage outranks the finding: exit 2
cat > "$WORK/crash.json" <<'JSON'
{"stages": [{"name": "dies",
             "cmd": ["python", "-c", "import sys; sys.exit(3)"],
             "timeout_s": 60}]}
JSON
set +e
python -m tpudist.perfci --manifest "$WORK/crash.json" --history "$HIST" \
    --report "$WORK/crash_report.json" --platform cpu
rc=$?
set -e
if [[ "$rc" != 2 ]]; then
    echo "[perfci-smoke] expected exit 2 on a crashed stage, got $rc" >&2
    exit 1
fi

echo "[perfci-smoke] 4/4 dashboard artifact" >&2
python - "$WORK/dashboard.html" <<'PY'
import os, sys
doc = open(sys.argv[1], encoding="utf-8").read()
assert os.path.getsize(sys.argv[1]) > 0
assert 'data-metric="smoke_ips"' in doc and 'data-status="regression"' in doc
assert "<script" not in doc.lower(), "dashboard must stay zero-dependency"
print(f"[perfci-smoke] dashboard ok ({len(doc)} bytes)", file=sys.stderr)
PY

echo "PERFCI_SMOKE_OK"
