#!/usr/bin/env bash
# Fused-norm smoke: one command proves the measurement-honest --fused-bn
# plane works on CPU.
#
#   1. dispatch cache round-trip (synthetic timings injected through the
#      generic measure_pair hook): a measured win is cached per device_kind
#      in the fused_norm.<kind>.json file, the second resolve is a cache
#      HIT (measuring again is an error), a cleared cache re-measures, and
#      `auto` never picks the losing kernel;
#   2. forced-fused train step: TPUDIST_FUSED_BN=on trains one resnet18 DP
#      step through the Pallas BN+ReLU / BN+add+ReLU forward + single-pass
#      backward (interpreter mode — the same kernel bodies that compile on
#      TPU) and the loss matches the XLA-epilogue twin;
#   3. a `--telemetry --fused-bn auto` resnet Trainer run on this CPU host
#      must resolve to the XLA epilogue on platform grounds (no Pallas, no
#      fake measurement), emit a schema-valid `fused_norm_dispatch` event,
#      and `python -m tpudist.summarize` must print the fused-norm dispatch
#      line and the prefetch (overlap) budget row.
#
# Runs standalone (`bash tools/fused_smoke.sh [workdir]`) and as
# tests/test_fused_norm.py::test_fused_smoke_script. Prints FUSED_SMOKE_OK
# as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_FUSED_SMOKE_DIR:-$(mktemp -d)}}"
RUN="$WORK/run"
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi
export TPUDIST_DISPATCH_CACHE="$WORK/dispatch_cache"

echo "[fused-smoke] 1/3 dispatch cache round-trip" >&2
python - <<'PY'
import os
import jax.numpy as jnp
from tpudist.ops import norm_dispatch as nd

kind = "smoke-tpu-v0"
args = dict(platform="tpu", device_kind=kind)
shape = dict(rows=100352, channels=64, dtype=jnp.bfloat16, residual=True)

def measured(pallas_ms, xla_ms):
    return lambda: (pallas_ms, xla_ms)

def must_not_measure():
    raise AssertionError("cache hit must not re-measure")

def decide(**kw):
    s = dict(shape)
    return nd.decide(s.pop("rows"), s.pop("channels"), s.pop("dtype"),
                     residual=s.pop("residual"), mode="auto", **kw)

# Losing kernel is never selected; winner is cached.
d = decide(measure_pair=measured(2.0, 1.0), **args)
assert d["kernel"] == "xla" and d["source"] == "measured", d
d = decide(measure_pair=must_not_measure, **args)
assert d["kernel"] == "xla" and d["source"] == "cache" and d["cache_hit"], d
assert os.path.exists(nd.cache_path(kind)), "cache file missing"
assert "fused_norm." in os.path.basename(nd.cache_path(kind))
# Cleared cache re-measures; a now-winning kernel is selected — and the
# trace-safe use_fused() sees it.
assert nd.clear_cache(kind) == 1
d = decide(measure_pair=measured(1.0, 2.0), **args)
assert d["kernel"] == "pallas" and d["source"] == "measured", d
d = decide(measure_pair=must_not_measure, **args)
assert d["kernel"] == "pallas" and d["source"] == "cache", d
assert nd.use_fused(100352, 64, jnp.bfloat16, residual=True, **args)
assert not nd.use_fused(100352, 64, jnp.bfloat16, residual=False, **args)
print("[fused-smoke] cache round-trip ok")
PY

echo "[fused-smoke] 2/3 forced-fused resnet18 train step (interpret)" >&2
TPUDIST_FUSED_BN=on python - <<'PY'
import sys
import jax, jax.numpy as jnp, numpy as np
from tpudist.config import Config
from tpudist.dist import make_mesh, shard_host_batch
from tpudist.models import create_model
from tpudist.train import create_train_state, make_train_step

n = jax.device_count()
mesh = make_mesh((n,), ("data",), jax.devices())
cfg = Config(arch="resnet18", num_classes=8, image_size=32,
             batch_size=2 * n, use_amp=False, seed=0).finalize(n)
model = create_model(cfg.arch, num_classes=cfg.num_classes)
state = create_train_state(jax.random.PRNGKey(0), model, cfg,
                           input_shape=(1, 32, 32, 3))
rng = np.random.default_rng(0)
images = rng.standard_normal((cfg.batch_size, 32, 32, 3)).astype(np.float32)
labels = rng.integers(0, 8, size=(cfg.batch_size,)).astype(np.int32)
images, labels = shard_host_batch(mesh, (images, labels))
state, metrics = make_train_step(mesh, model, cfg)(
    state, images, labels, jnp.float32(0.1))
loss = float(metrics["loss"])
assert np.isfinite(loss), loss
assert "tpudist.ops.pallas.fused_norm" in sys.modules, \
    "TPUDIST_FUSED_BN=on never reached the Pallas kernels"
print(f"[fused-smoke] forced-fused step ok: loss={loss:.4f}")
PY

echo "[fused-smoke] 3/3 --telemetry --fused-bn auto run + summarize" >&2
python - "$RUN" <<'PY'
import glob, json, sys
from tpudist.config import Config
from tpudist.telemetry import validate_event
from tpudist.trainer import Trainer

out = sys.argv[1]
cfg = Config(arch="resnet18", num_classes=4, image_size=32, batch_size=8,
             epochs=1, lr=0.01, workers=0, print_freq=1, synthetic=True,
             synthetic_size=16, use_amp=False, outpath=out,
             overwrite="delete", seed=0, telemetry=True)
t = Trainer(cfg, writer=None)
assert t.fused_norm_decision is not None
assert t.fused_norm_decision["kernel"] == "xla", t.fused_norm_decision
# CPU host: resolved on platform grounds, no Pallas import, no measurement.
assert t.fused_norm_decision["source"] == "platform", t.fused_norm_decision
assert "tpudist.ops.pallas.fused_norm" not in sys.modules, \
    "--fused-bn auto touched Pallas on a CPU backend"
t.fit()
events = []
for p in glob.glob(out + "/events.*.jsonl"):
    with open(p) as f:
        events += [json.loads(line) for line in f if line.strip()]
for e in events:
    validate_event(e)                  # schema-valid, dispatch included
disp = [e for e in events if e["type"] == "fused_norm_dispatch"]
assert disp and disp[0]["kernel"] == "xla" and disp[0]["mode"] == "auto", disp
steps = [e for e in events if e["type"] == "step"]
assert steps and all("prefetch_s" in e for e in steps), \
    "device prefetch (default on) left no overlap accounting on steps"
print(f"[fused-smoke] trainer run ok ({len(events)} schema-valid events)")
PY
python -m tpudist.summarize "$RUN" | tee "$WORK/summary.txt" >&2
grep -q "fused-norm dispatch: xla epilogue (mode auto, platform" \
    "$WORK/summary.txt"
grep -q "prefetch (ovl.)" "$WORK/summary.txt"

echo "FUSED_SMOKE_OK"
