#!/usr/bin/env bash
# Observability smoke: one command proves the whole live plane works on CPU.
#
#   1. an in-process `--telemetry --metrics-port 0` run is scraped WHILE it
#      trains — the Prometheus endpoint must serve step/MFU/goodput gauges;
#   2. `python -m tpudist.summarize <run> --trace` must emit a Chrome/
#      Perfetto trace JSON with real step + compile spans;
#   3. `python -m tpudist.regress` must pass an unchanged synthetic history
#      and fail (exit 2) on an injected 20% slowdown.
#
# Runs standalone (`bash tools/obs_smoke.sh [workdir]`) and as the
# obs-marked test tests/test_obs.py::test_obs_smoke_script. Prints
# OBS_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_OBS_SMOKE_DIR:-$(mktemp -d)}}"
RUN="$WORK/run"
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi
export TPUDIST_PEAK_FLOPS="${TPUDIST_PEAK_FLOPS:-1e12}"

echo "[obs-smoke] 1/3 live endpoint (telemetry run in $RUN)" >&2
python - "$RUN" <<'PY'
import os, sys, threading, time, urllib.request
from tpudist.config import Config
from tpudist.trainer import Trainer

out = sys.argv[1]
cfg = Config(arch="resnet18", num_classes=4, image_size=16, batch_size=16,
             epochs=1, lr=0.02, workers=2, print_freq=1, synthetic=True,
             synthetic_size=48, use_amp=False, outpath=out,
             overwrite="delete", seed=0, telemetry=True, metrics_port=0)
t = Trainer(cfg, writer=None)
url = f"http://127.0.0.1:{t.metrics_server.port}/metrics"
scrapes, stop = [], threading.Event()

def scrape():
    while not stop.is_set():
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                scrapes.append(r.read().decode())
        except OSError:
            pass
        time.sleep(0.1)

th = threading.Thread(target=scrape, daemon=True)
th.start()
t.fit()
stop.set(); th.join(timeout=10)
live = [s for s in scrapes if "tpudist_last_step" in s]
assert live, "endpoint never served a completed step"
final = live[-1]
for gauge in ("tpudist_steps_total", "tpudist_goodput",
              "tpudist_step_time_seconds", "tpudist_heartbeat_age_seconds"):
    assert gauge in final, f"missing {gauge}"
print(f"[obs-smoke] endpoint ok ({len(scrapes)} scrapes)", file=sys.stderr)
PY

echo "[obs-smoke] 2/3 trace export" >&2
python -m tpudist.summarize "$RUN" --trace "$WORK/trace.json" \
    --peak-flops "$TPUDIST_PEAK_FLOPS" >/dev/null
python - "$WORK/trace.json" <<'PY'
import json, sys
obj = json.load(open(sys.argv[1]))
spans = [e for e in obj["traceEvents"] if e["ph"] == "X"]
assert any(e["name"].startswith("step ") for e in spans), "no step spans"
assert any(e["name"].startswith("compile:") for e in spans), "no compile span"
assert all(e["dur"] > 0 and e["ts"] >= 0 for e in spans)
print(f"[obs-smoke] trace ok ({len(spans)} spans)", file=sys.stderr)
PY

echo "[obs-smoke] 3/3 regression gate" >&2
HIST="$WORK/hist.jsonl"
python - "$HIST" <<'PY'
import json, sys
with open(sys.argv[1], "w") as f:
    for v in (1000, 1005, 995, 1002, 998, 1001):   # unchanged tail
        f.write(json.dumps({"metric": "smoke_1chip", "value": float(v),
                            "mfu": 0.4, "unit": "images/sec"}) + "\n")
PY
python -m tpudist.regress --history "$HIST"          # unchanged: exit 0
echo '{"metric": "smoke_1chip", "value": 800.0, "mfu": 0.4}' >> "$HIST"
if python -m tpudist.regress --history "$HIST"; then  # 20% slower: exit 2
    echo "[obs-smoke] gate FAILED to flag a 20% slowdown" >&2
    exit 1
fi

echo "OBS_SMOKE_OK"
