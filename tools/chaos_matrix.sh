#!/usr/bin/env bash
# Chaos matrix driver (ISSUE 13): fault × topology recovery cells through
# real `tpudist.launch` CPU gangs — see tests/test_chaos.py for the cell
# definitions and the per-cell recovery contract.
#
#   bash tools/chaos_matrix.sh                # smoke: one representative cell
#   CHAOS_CELLS='rank_exit and compress' ...  # any pytest -k selection
#   CHAOS_FULL=1 bash tools/chaos_matrix.sh   # the full 12-cell matrix
#
# The smoke cell (straggle × dp) is tier-1-safe: CPU-only, ~1 min, and it
# is the full proactive-eviction chain — persistent straggler flagged N
# consecutive windows → eviction event → SIGTERM drain (emergency
# checkpoint with cursor) → reform → completion. The other chains get
# their tier-1 runs from tests/test_elastic.py's reform e2es; the full
# matrix covers every pairing. Prints CHAOS_MATRIX_OK as the last line on
# success.
set -euo pipefail
cd "$(dirname "$0")/.."

SELECT="${CHAOS_CELLS:-straggle and dp and not dp_tp}"
if [[ "${CHAOS_FULL:-0}" == "1" ]]; then
    # Full matrix: the 12 fault×topology cells PLUS the ISSUE 15 doctor
    # rows (nanbomb → skip-step, lossbomb → rollback+replay, bitflip →
    # SDC self-quarantine + reform, each with loss parity vs a clean twin).
    SELECT="test_chaos_cell or test_doctor_cell"
fi

echo "[chaos-matrix] cells: -k '$SELECT'" >&2
# TPUDIST_CHAOS_TMP: put the cells' gang outpaths under the caller's own
# tmp dir (the wired test passes its pytest tmp_path so cleanup rides it).
BASETEMP=()
if [[ -n "${TPUDIST_CHAOS_TMP:-}" ]]; then
    BASETEMP=(--basetemp "$TPUDIST_CHAOS_TMP")
fi
python -m pytest tests/test_chaos.py -q -p no:cacheprovider \
    -m "slow or not slow" -k "$SELECT" "${BASETEMP[@]}" "$@"

echo "CHAOS_MATRIX_OK"
