#!/usr/bin/env bash
# Blackbox smoke: one command proves the incident chain works on CPU.
#
#   1. an in-process `--telemetry --blackbox` run with an injected nanbomb
#      (via the doctor's guarded step) must dump its ring and arm the
#      one-shot deep capture;
#   2. the incident bundler must correlate the dump into ONE
#      incidents/<id>/ bundle with a manifest + causal event chain;
#   3. `tpudist-incident report` must name the trigger + suspect rank, and
#      `--trace` must export a non-empty Perfetto trace of the window;
#   4. `python -m tpudist.summarize` must print the incidents: section.
#
# Runs standalone (`bash tools/blackbox_smoke.sh [workdir]`) and as the
# blackbox-marked test tests/test_blackbox.py::test_blackbox_smoke_script.
# Prints BLACKBOX_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_BLACKBOX_SMOKE_DIR:-$(mktemp -d)}}"
RUN="$WORK/run"
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

echo "[blackbox-smoke] 1/4 nanbomb run with --blackbox (in $RUN)" >&2
TPUDIST_NO_DONATE=1 \
python -m tpudist --synthetic --synthetic-size 64 -b 16 --epochs 2 \
    -a resnet18 --image-size 16 --num-classes 4 --no-use_amp --workers 2 \
    -p 1 --overwrite delete --seed 0 --lr 0.01 \
    --inject "nanbomb@step=3@attempt=0" \
    --telemetry --no-telemetry_mfu \
    --doctor --doctor-spike-min-steps 2 \
    --blackbox --blackbox-capture-steps 2 \
    --outpath "$RUN" >/dev/null
ls "$RUN"/blackbox/dump.*.json >/dev/null \
    || { echo "[blackbox-smoke] no ring dump written" >&2; exit 1; }

echo "[blackbox-smoke] 2/4 incident bundling" >&2
python - "$RUN" <<'PY'
import sys
from tpudist.blackbox import IncidentBundler, list_incidents
run = sys.argv[1]
b = IncidentBundler(run)
b.close()
incs = list_incidents(run)
assert len(incs) == 1, f"expected exactly one bundle, got {incs}"
m = incs[0]
assert m["trigger"], m
assert m["suspect_rank"] is not None, m
assert m["dumps"], m
print(f"[blackbox-smoke] bundle ok: {m['id']}", file=sys.stderr)
PY

echo "[blackbox-smoke] 3/4 tpudist-incident report + trace" >&2
REPORT=$(python -m tpudist.blackbox report "$RUN" \
             --trace "$WORK/incident.trace.json")
echo "$REPORT" | grep -q "trigger" \
    || { echo "[blackbox-smoke] report names no trigger" >&2; exit 1; }
echo "$REPORT" | grep -q "suspect rank" \
    || { echo "[blackbox-smoke] report names no suspect rank" >&2; exit 1; }
python - "$WORK/incident.trace.json" <<'PY'
import json, sys
obj = json.load(open(sys.argv[1]))
assert obj["traceEvents"], "empty incident trace"
print(f"[blackbox-smoke] trace ok ({len(obj['traceEvents'])} events)",
      file=sys.stderr)
PY

echo "[blackbox-smoke] 4/4 summarize incidents section" >&2
python -m tpudist.summarize "$RUN" > "$WORK/summary.txt"
grep -q "incidents:" "$WORK/summary.txt" \
    || { echo "[blackbox-smoke] summarize has no incidents section" >&2
         exit 1; }

echo "BLACKBOX_SMOKE_OK"
