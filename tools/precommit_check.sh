#!/usr/bin/env bash
# Pre-commit wrapper for tpudist-check: analyze the whole tree (findings
# are whole-program facts — a changed file can re-point the call graph at
# hazards elsewhere) but GATE only findings whose lines changed vs HEAD,
# plus untracked files. The per-file result cache makes the warm path
# sub-second, so this is cheap enough for every commit.
#
# Wired by .pre-commit-config.yaml; runs standalone too:
#     bash tools/precommit_check.sh [git-ref]     # default ref: HEAD
#
# Exit codes follow tpudist-check's contract: 0 clean / 1 new gating
# findings on changed lines / 2 usage or internal error.
set -euo pipefail
cd "$(dirname "$0")/.."

REF="${1:-HEAD}"
exec python -m tpudist.check --diff "$REF"
