#!/usr/bin/env bash
# Serving smoke: one command proves the serving plane end to end on CPU.
#
#   1. EXPORT — a tiny `--telemetry --compile-cache` training run writes a
#      real checkpoint (and stamps warm/cold cache provenance on its
#      compile events);
#   2. SERVE — `python -m tpudist.serve` loads that checkpoint, AOT-
#      compiles the bucket set against the SAME persistent cache, serves
#      synthetic open-loop load with `--metrics-port 0`, and is SCRAPED
#      while serving (latency/queue/occupancy gauges must be live);
#   3. SUMMARIZE — `python -m tpudist.summarize` on the serve run dir must
#      print the serving section, report ZERO steady-state recompiles
#      (every compile event phase serve_aot), and validate every event
#      line against the schema (--strict).
#
# Runs standalone (`bash tools/serve_smoke.sh [workdir]`) and as the
# serve-marked test tests/test_serve.py::test_serve_smoke_script. Prints
# SERVE_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_SERVE_SMOKE_DIR:-$(mktemp -d)}}"
TRAIN="$WORK/train"
SERVE="$WORK/serve"
CACHE="$WORK/compile_cache"
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

echo "[serve-smoke] 1/3 export: training a checkpoint into $TRAIN" >&2
python -m tpudist --synthetic --synthetic-size 32 -a resnet18 \
    --num-classes 4 --image-size 16 -b 16 --epochs 1 --lr 0.02 -j 2 -p 1 \
    --no-use_amp --telemetry --compile-cache "$CACHE" \
    --outpath "$TRAIN" --overwrite delete --seed 0 >/dev/null
test -f "$TRAIN/checkpoint.msgpack"
grep -q '"type": "compile"' "$TRAIN"/events.0.jsonl
grep -q '"cache": "cold"' "$TRAIN"/events.0.jsonl \
    || { echo "[serve-smoke] trainer compile events lack cache provenance" >&2; exit 1; }

echo "[serve-smoke] 2/3 serve: checkpoint -> AOT buckets -> load -> scrape" >&2
python -m tpudist.serve --arch resnet18 --checkpoint "$TRAIN" \
    --num-classes 4 --image-size 16 --buckets 1,2,4 \
    --compile-cache "$CACHE" --telemetry --metrics-port 0 \
    --outpath "$SERVE" --load-rate 40 --load-duration 3 --seed 0 \
    > "$WORK/serve.log" 2>&1 &
SERVE_PID=$!
PORTFILE="$SERVE/metrics.0.port"
SCRAPED=""
for _ in $(seq 1 120); do
    if [[ -f "$PORTFILE" ]]; then
        PORT=$(cat "$PORTFILE")
        TXT=$(curl -sf "http://127.0.0.1:$PORT/metrics" || true)
        if [[ "$TXT" == *tpudist_serve_request_latency_seconds* ]]; then
            SCRAPED="$TXT"
            break
        fi
    fi
    sleep 0.25
done
wait "$SERVE_PID"
[[ -n "$SCRAPED" ]] \
    || { echo "[serve-smoke] never scraped live serve gauges" >&2; cat "$WORK/serve.log" >&2; exit 1; }
for gauge in tpudist_serve_requests_total tpudist_serve_queue_depth \
             tpudist_serve_batch_occupancy tpudist_serve_aot_seconds; do
    [[ "$SCRAPED" == *$gauge* ]] \
        || { echo "[serve-smoke] missing $gauge in live scrape" >&2; exit 1; }
done
grep -q SERVE_SUMMARY "$WORK/serve.log"

echo "[serve-smoke] 3/3 summarize: serving section + zero recompiles" >&2
SUMMARY=$(python -m tpudist.summarize "$SERVE" --strict)
echo "$SUMMARY" | grep -q "serving:" \
    || { echo "[serve-smoke] summarize lacks the serving section" >&2; echo "$SUMMARY" >&2; exit 1; }
echo "$SUMMARY" | grep -q "ZERO steady-state recompiles" \
    || { echo "[serve-smoke] recompile-free claim missing" >&2; echo "$SUMMARY" >&2; exit 1; }
echo "$SUMMARY" | grep -q "persistent cache" \
    || { echo "[serve-smoke] cache provenance missing" >&2; exit 1; }

echo "SERVE_SMOKE_OK"
