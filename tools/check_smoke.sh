#!/usr/bin/env bash
# Static-analysis smoke: one command proves the tpudist-check gate works
# end to end, with NO jax import anywhere in the chain.
#
#   1. the committed tree must be CLEAN: `python -m tpudist.check` exits 0
#      against the committed baseline (the tier-1 invariant);
#   2. a seeded hazard (rank-guarded psum) must flip the gate to exit 1,
#      and `--json` must carry the finding with rule id + fingerprint;
#   3. baseline round trip: `--write-baseline` over the seeded hazard must
#      make the same tree pass, while a SECOND, different hazard still
#      fails (the gate fails only on NEW findings);
#   4. pragma semantics: the seeded hazard with an inline
#      `# tpudist: ignore[COLL01] — reason` must pass again;
#   5. exit-code contract: unknown rule id exits 2;
#   6. baseline PRUNE round trip: fixing the hazards and re-writing the
#      baseline must drop the stale fingerprints and say how many;
#   7. diff mode: in a scratch git tree, a hazard on a changed line gates,
#      the same hazard committed with only unrelated edits does not;
#   8. cache economics: a second full-tree run against a warm cache
#      reports the warm path AND is measurably faster than the cold run.
#
# Runs standalone (`bash tools/check_smoke.sh [workdir]`) and as the
# analysis-marked test tests/test_check.py::test_check_smoke_script.
# Prints CHECK_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_CHECK_SMOKE_DIR:-$(mktemp -d)}}"
mkdir -p "$WORK"
export TPUDIST_CHECK_CACHE="$WORK/cache"

echo "[check-smoke] 1/8 committed tree is clean" >&2
python -m tpudist.check --root . --no-cache >/dev/null

echo "[check-smoke] 2/8 seeded hazard fails the gate (+ --json carries it)" >&2
HAZ="$WORK/hazard.py"
cat > "$HAZ" <<'PY'
import jax

DATA_AXIS = "data"   # declares the axis so only COLL01 fires


def step(x, rank):
    if rank == 0:
        x = jax.lax.psum(x, "data")
    return x
PY
if python -m tpudist.check --root . "$HAZ" >/dev/null; then
    echo "[check-smoke] gate FAILED to flag a rank-guarded collective" >&2
    exit 1
fi
python -m tpudist.check --root . --json "$HAZ" > "$WORK/out.json" || true
python - "$WORK/out.json" <<'PY'
import json, sys
obj = json.load(open(sys.argv[1]))
assert obj["exit"] == 1, obj["exit"]
rules = [f["rule"] for f in obj["findings"]]
assert "COLL01" in rules, rules
assert all(f["fingerprint"] for f in obj["findings"])
PY

echo "[check-smoke] 3/8 baseline round trip (old passes, new still fails)" >&2
BASE="$WORK/baseline.json"
python -m tpudist.check --root . --baseline "$BASE" --write-baseline \
    "$HAZ" >/dev/null
# Same file, same findings: baselined debt passes…
python -m tpudist.check --root . --baseline "$BASE" "$HAZ" >/dev/null
# …but a NEW hazard appended to the same file still gates (fingerprints
# are content-addressed, so the old finding stays baselined even though
# the file changed).
cat >> "$HAZ" <<'PY'


def step2(y, rank):
    if rank == 0:
        y = jax.lax.pmean(y, "data")
    return y
PY
if python -m tpudist.check --root . --baseline "$BASE" "$HAZ" >/dev/null; then
    echo "[check-smoke] baseline FAILED to gate a NEW finding" >&2
    exit 1
fi

echo "[check-smoke] 4/8 pragma with reason suppresses" >&2
cat > "$WORK/hazard3.py" <<'PY'
import jax

DATA_AXIS = "data"   # declares the axis so only COLL01 fires


def step(x, rank):
    if rank == 0:
        # tpudist: ignore[COLL01] — smoke fixture: deliberate, single-rank path
        x = jax.lax.psum(x, "data")
    return x
PY
python -m tpudist.check --root . "$WORK/hazard3.py" >/dev/null

echo "[check-smoke] 5/8 usage-error exit code is 2" >&2
set +e
python -m tpudist.check --root . --rules NOSUCH >/dev/null 2>&1
rc=$?
set -e
if [[ "$rc" -ne 2 ]]; then
    echo "[check-smoke] unknown rule id exited $rc, want 2" >&2
    exit 1
fi

echo "[check-smoke] 6/8 --write-baseline prunes stale fingerprints" >&2
# Stage 3 left ONE baselined fingerprint in $BASE (the second hazard was
# appended after the write and still gates). Fix the file and re-write:
# that fingerprint is stale now — the rewrite must drop it and say so.
cat > "$HAZ" <<'PY'
DATA_AXIS = "data"
x = 1
PY
PRUNE_OUT=$(python -m tpudist.check --root . --baseline "$BASE" \
    --write-baseline "$HAZ")
echo "$PRUNE_OUT" | grep -q "wrote 0 baseline" || {
    echo "[check-smoke] pruned baseline not empty: $PRUNE_OUT" >&2; exit 1; }
echo "$PRUNE_OUT" | grep -q "1 stale entry pruned" || {
    echo "[check-smoke] prune count not reported: $PRUNE_OUT" >&2; exit 1; }
python -m tpudist.check --root . --baseline "$BASE" "$HAZ" >/dev/null

echo "[check-smoke] 7/8 --diff gates changed lines only" >&2
GITTREE="$WORK/gittree"
rm -rf "$GITTREE" && mkdir -p "$GITTREE"
printf 'DATA_AXIS = "data"\nx = 1\n' > "$GITTREE/m.py"
git -C "$GITTREE" init -q
git -C "$GITTREE" -c user.email=smoke@tpudist -c user.name=smoke \
    add -A
git -C "$GITTREE" -c user.email=smoke@tpudist -c user.name=smoke \
    commit -qm clean
cat >> "$GITTREE/m.py" <<'PY'
import jax


def f(x, rank):
    if rank == 0:
        x = jax.lax.psum(x, "data")
    return x
PY
if python -m tpudist.check --root "$GITTREE" --no-baseline --no-cache \
        --diff HEAD >/dev/null; then
    echo "[check-smoke] --diff FAILED to gate a changed-line hazard" >&2
    exit 1
fi
git -C "$GITTREE" -c user.email=smoke@tpudist -c user.name=smoke \
    commit -qam "hazard accepted"
printf '\nz = 3\n' >> "$GITTREE/m.py"
# The committed hazard still exists but sits off-diff: the gate passes.
python -m tpudist.check --root "$GITTREE" --no-baseline --no-cache \
    --diff HEAD >/dev/null

echo "[check-smoke] 8/8 warm cache beats cold (asserted)" >&2
rm -rf "$TPUDIST_CHECK_CACHE"
COLD_T0=$(python -c 'import time; print(time.monotonic())')
python -m tpudist.check --root . >/dev/null
COLD_T1=$(python -c 'import time; print(time.monotonic())')
WARM_OUT=$(python -m tpudist.check --root .)
WARM_T1=$(python -c 'import time; print(time.monotonic())')
echo "$WARM_OUT" | grep -q "cache: warm" || {
    echo "[check-smoke] second run did not hit the warm path: $WARM_OUT" >&2
    exit 1; }
python - "$COLD_T0" "$COLD_T1" "$WARM_T1" <<'PY'
import sys
t0, t1, t2 = map(float, sys.argv[1:])
cold, warm = t1 - t0, t2 - t1
assert warm < cold, f"warm {warm:.2f}s not below cold {cold:.2f}s"
print(f"[check-smoke] cold {cold:.2f}s -> warm {warm:.2f}s", file=sys.stderr)
PY

echo "CHECK_SMOKE_OK"
