#!/usr/bin/env bash
# Static-analysis smoke: one command proves the tpudist-check gate works
# end to end, with NO jax import anywhere in the chain.
#
#   1. the committed tree must be CLEAN: `python -m tpudist.check` exits 0
#      against the committed baseline (the tier-1 invariant);
#   2. a seeded hazard (rank-guarded psum) must flip the gate to exit 1,
#      and `--json` must carry the finding with rule id + fingerprint;
#   3. baseline round trip: `--write-baseline` over the seeded hazard must
#      make the same tree pass, while a SECOND, different hazard still
#      fails (the gate fails only on NEW findings);
#   4. pragma semantics: the seeded hazard with an inline
#      `# tpudist: ignore[COLL01] — reason` must pass again;
#   5. exit-code contract: unknown rule id exits 2.
#
# Runs standalone (`bash tools/check_smoke.sh [workdir]`) and as the
# analysis-marked test tests/test_check.py::test_check_smoke_script.
# Prints CHECK_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_CHECK_SMOKE_DIR:-$(mktemp -d)}}"
mkdir -p "$WORK"

echo "[check-smoke] 1/5 committed tree is clean" >&2
python -m tpudist.check --root . >/dev/null

echo "[check-smoke] 2/5 seeded hazard fails the gate (+ --json carries it)" >&2
HAZ="$WORK/hazard.py"
cat > "$HAZ" <<'PY'
import jax

DATA_AXIS = "data"   # declares the axis so only COLL01 fires


def step(x, rank):
    if rank == 0:
        x = jax.lax.psum(x, "data")
    return x
PY
if python -m tpudist.check --root . "$HAZ" >/dev/null; then
    echo "[check-smoke] gate FAILED to flag a rank-guarded collective" >&2
    exit 1
fi
python -m tpudist.check --root . --json "$HAZ" > "$WORK/out.json" || true
python - "$WORK/out.json" <<'PY'
import json, sys
obj = json.load(open(sys.argv[1]))
assert obj["exit"] == 1, obj["exit"]
rules = [f["rule"] for f in obj["findings"]]
assert "COLL01" in rules, rules
assert all(f["fingerprint"] for f in obj["findings"])
PY

echo "[check-smoke] 3/5 baseline round trip (old passes, new still fails)" >&2
BASE="$WORK/baseline.json"
python -m tpudist.check --root . --baseline "$BASE" --write-baseline \
    "$HAZ" >/dev/null
# Same file, same findings: baselined debt passes…
python -m tpudist.check --root . --baseline "$BASE" "$HAZ" >/dev/null
# …but a NEW hazard appended to the same file still gates (fingerprints
# are content-addressed, so the old finding stays baselined even though
# the file changed).
cat >> "$HAZ" <<'PY'


def step2(y, rank):
    if rank == 0:
        y = jax.lax.pmean(y, "data")
    return y
PY
if python -m tpudist.check --root . --baseline "$BASE" "$HAZ" >/dev/null; then
    echo "[check-smoke] baseline FAILED to gate a NEW finding" >&2
    exit 1
fi

echo "[check-smoke] 4/5 pragma with reason suppresses" >&2
cat > "$WORK/hazard3.py" <<'PY'
import jax

DATA_AXIS = "data"   # declares the axis so only COLL01 fires


def step(x, rank):
    if rank == 0:
        # tpudist: ignore[COLL01] — smoke fixture: deliberate, single-rank path
        x = jax.lax.psum(x, "data")
    return x
PY
python -m tpudist.check --root . "$WORK/hazard3.py" >/dev/null

echo "[check-smoke] 5/5 usage-error exit code is 2" >&2
set +e
python -m tpudist.check --root . --rules NOSUCH >/dev/null 2>&1
rc=$?
set -e
if [[ "$rc" -ne 2 ]]; then
    echo "[check-smoke] unknown rule id exited $rc, want 2" >&2
    exit 1
fi

echo "CHECK_SMOKE_OK"
