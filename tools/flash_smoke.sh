#!/usr/bin/env bash
# Flash-dispatch smoke: one command proves the measurement-honest --flash
# plane works on CPU.
#
#   1. dispatch cache round-trip (synthetic timings injected through the
#      measure_pair hook): a measured win is cached per device_kind, the
#      second resolve is a cache HIT (measuring again is an error), a
#      cleared cache re-measures, and `auto` never picks the losing kernel;
#   2. forced-flash train step: a tiny ViT with flash=True trains one DP
#      step through the Pallas forward + rebuilt two-pass backward
#      (interpreter mode — the same kernel bodies that compile on TPU);
#   3. a `--telemetry --flash auto` ViT Trainer run on this CPU host must
#      resolve to XLA attention on platform grounds (no Pallas, no fake
#      measurement), emit a schema-valid `attention_dispatch` event, and
#      `python -m tpudist.summarize` must print the dispatch line.
#
# Runs standalone (`bash tools/flash_smoke.sh [workdir]`) and as
# tests/test_attention_dispatch.py::test_flash_smoke_script. Prints
# FLASH_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_FLASH_SMOKE_DIR:-$(mktemp -d)}}"
RUN="$WORK/run"
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi
export TPUDIST_DISPATCH_CACHE="$WORK/dispatch_cache"

echo "[flash-smoke] 1/3 dispatch cache round-trip" >&2
python - <<'PY'
import os
from tpudist.ops import attention_dispatch as ad

kind = "smoke-tpu-v0"
args = dict(platform="tpu", device_kind=kind)
shape = (8, 197, 12, 64, "bfloat16")

def measured(flash_ms, xla_ms):
    return lambda: (flash_ms, xla_ms)

def must_not_measure():
    raise AssertionError("cache hit must not re-measure")

# Losing kernel is never selected; winner is cached.
d = ad.decide(*shape, mode="auto", measure_pair=measured(2.0, 1.0), **args)
assert d["kernel"] == "xla" and d["source"] == "measured", d
d = ad.decide(*shape, mode="auto", measure_pair=must_not_measure, **args)
assert d["kernel"] == "xla" and d["source"] == "cache" and d["cache_hit"], d
assert os.path.exists(ad.cache_path(kind)), "cache file missing"
# Cleared cache re-measures; a now-winning kernel is selected.
assert ad.clear_cache(kind) == 1
d = ad.decide(*shape, mode="auto", measure_pair=measured(1.0, 2.0), **args)
assert d["kernel"] == "flash" and d["source"] == "measured", d
d = ad.decide(*shape, mode="auto", measure_pair=must_not_measure, **args)
assert d["kernel"] == "flash" and d["source"] == "cache", d
print("[flash-smoke] cache round-trip ok")
PY

echo "[flash-smoke] 2/3 forced-flash train step (interpret kernels)" >&2
python - <<'PY'
import jax, jax.numpy as jnp, numpy as np
from tpudist.config import Config
from tpudist.dist import make_mesh, shard_host_batch
from tpudist.models.vit import VisionTransformer
from tpudist.train import create_train_state, make_train_step

n = jax.device_count()
mesh = make_mesh((n,), ("data",), jax.devices())
cfg = Config(arch="vit_b_16", num_classes=8, image_size=16,
             batch_size=2 * n, use_amp=False, seed=0).finalize(n)
model = VisionTransformer(patch_size=4, hidden_dim=32, num_layers=2,
                          num_heads=4, mlp_dim=64, num_classes=8, flash=True)
state = create_train_state(jax.random.PRNGKey(0), model, cfg,
                           input_shape=(1, 16, 16, 3))
rng = np.random.default_rng(0)
images = rng.standard_normal((cfg.batch_size, 16, 16, 3)).astype(np.float32)
labels = rng.integers(0, 8, size=(cfg.batch_size,)).astype(np.int32)
images, labels = shard_host_batch(mesh, (images, labels))
state, metrics = make_train_step(mesh, model, cfg)(
    state, images, labels, jnp.float32(0.1))
loss = float(metrics["loss"])
assert np.isfinite(loss), loss
print(f"[flash-smoke] forced-flash step ok: loss={loss:.4f}")
PY

echo "[flash-smoke] 3/3 --telemetry --flash auto run + summarize" >&2
python - "$RUN" <<'PY'
import glob, json, sys
from tpudist.config import Config
from tpudist.telemetry import validate_event
from tpudist.trainer import Trainer

out = sys.argv[1]
cfg = Config(arch="vit_b_32", num_classes=4, image_size=32, batch_size=8,
             epochs=1, lr=0.01, workers=0, print_freq=1, synthetic=True,
             synthetic_size=8, use_amp=False, outpath=out,
             overwrite="delete", seed=0, telemetry=True)
t = Trainer(cfg, writer=None)
assert t.flash_decision is not None
assert t.flash_decision["kernel"] == "xla", t.flash_decision
# The 2-token workload is statically ineligible for the kernel (below one
# (8,128) tile) — resolved before the platform is even consulted.
assert t.flash_decision["source"] == "ineligible", t.flash_decision
assert t.model.flash is False          # auto resolved OUTSIDE the trace
t.fit()
events = []
for p in glob.glob(out + "/events.*.jsonl"):
    with open(p) as f:
        events += [json.loads(line) for line in f if line.strip()]
for e in events:
    validate_event(e)                  # schema-valid, dispatch included
disp = [e for e in events if e["type"] == "attention_dispatch"]
assert disp and disp[0]["kernel"] == "xla" and disp[0]["mode"] == "auto", disp
print(f"[flash-smoke] trainer run ok ({len(events)} schema-valid events)")
PY
python -m tpudist.summarize "$RUN" | tee "$WORK/summary.txt" >&2
grep -q "attention dispatch: xla attention (mode auto, ineligible" \
    "$WORK/summary.txt"

echo "FLASH_SMOKE_OK"
