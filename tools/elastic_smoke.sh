#!/usr/bin/env bash
# Elastic smoke: one command proves the whole elastic plane works on CPU.
#
#   1. a 2-rank `tpudist.launch --elastic` gang loses rank 1 to an injected
#      rank_exit; the launcher drains rank 0 (SIGTERM -> emergency
#      checkpoint carrying the epoch's sample cursor -> exit 75) and
#      REFORMS the gang at world 1, which resumes mid-epoch and finishes —
#      no full-size restart, `events.launcher.jsonl` records the
#      `topology_change`;
#   2. the surviving checkpoint's topology tag + reshard math round-trip:
#      zero1 cut/merge is exact and `plan_reshard` onto a different world
#      reports the re-cut;
#   3. `python -m tpudist.summarize <run>` renders the topology timeline.
#
# Runs standalone (`bash tools/elastic_smoke.sh [workdir]`) and as the
# elastic-marked test tests/test_elastic.py::test_elastic_smoke_script.
# Prints ELASTIC_SMOKE_OK as the last line on success.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-${TPUDIST_ELASTIC_SMOKE_DIR:-$(mktemp -d)}}"
RUN="$WORK/run"
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=1"
fi
# This container's CPU runtime corrupts the heap when checkpoint-restored
# buffers are donated (pre-existing seed bug, see tests/test_faults.py).
export TPUDIST_NO_DONATE=1

echo "[elastic-smoke] 1/3 inject rank loss -> reform at world 1 ($RUN)" >&2
python -m tpudist.launch --nprocs 2 --devices-per-proc 1 \
    --elastic --min-ranks 1 --max-restarts 0 --drain-grace 180 \
    --inject 'rank_exit@step=3@rank=1@attempt=0' \
    -- python -m tpudist --outpath "$RUN" \
    --synthetic --synthetic-size 48 -b 24 --epochs 2 -a resnet18 \
    --image-size 16 --num-classes 4 --no-use_amp --workers 2 -p 1 \
    --overwrite keep --resume auto --keep-checkpoints 2 --seed 0 \
    --telemetry --no-telemetry_mfu

grep -q '"type": "topology_change"' "$RUN/events.launcher.jsonl" \
    || { echo "[elastic-smoke] no topology_change event" >&2; exit 1; }
echo "[elastic-smoke] reform ok (topology_change recorded)" >&2

echo "[elastic-smoke] 2/3 reshard-restore round trip" >&2
python - "$RUN" <<'PY'
import sys
import numpy as np
from tpudist.checkpoint import load_checkpoint
from tpudist.elastic.reshard import (cut_zero1, merge_zero1, plan_reshard,
                                     topology_tag, zero1_layout)

ckpt = load_checkpoint(sys.argv[1])
tag = ckpt.get("topology")
assert tag and tag.get("world"), f"checkpoint carries no topology tag: {tag}"

# zero1 cut/merge is exact on the REAL optimizer tree, at several worlds.
tree = ckpt["state"]
for w in (1, 2, 4):
    shards, cut = cut_zero1(tree, w)
    merged = merge_zero1(shards, cut)
    flat = {}
    def walk(t, p=()):
        if isinstance(t, dict):
            for k, v in t.items():
                walk(v, p + (k,))
        else:
            flat[p] = t
    walk(tree)
    for p, leaf in flat.items():
        node = merged
        for k in p:
            node = node[k]
        if hasattr(leaf, "shape"):
            assert np.array_equal(np.asarray(node), np.asarray(leaf)), p

target = topology_tag(world=4, mesh_shape=(4,), mesh_axes=("data",),
                      n_devices=4, per_device_batch=6, global_batch=24,
                      zero1=True, zero1_axis="data")
plan = plan_reshard(tag, target, state_dict=tree)
assert plan.changed and plan.world_to == 4, plan
layout = zero1_layout(tree, 4)
print(f"[elastic-smoke] reshard ok (saved world {tag['world']}; "
      f"{len(layout)} zero1-cuttable leaves at world 4; "
      f"plan: {plan.describe()})", file=sys.stderr)
PY

echo "[elastic-smoke] 3/3 summarize topology timeline" >&2
python -m tpudist.summarize "$RUN" | tee "$WORK/summary.txt" >&2
grep -q "topology timeline" "$WORK/summary.txt" \
    || { echo "[elastic-smoke] summarize rendered no topology timeline" >&2; exit 1; }
grep -qE "\[reform\].*world 2 -> 1" "$WORK/summary.txt" \
    || { echo "[elastic-smoke] timeline missing the 2 -> 1 reform" >&2; exit 1; }

echo "ELASTIC_SMOKE_OK"
