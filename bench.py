"""Benchmark: resnet18 ImageNet-shape training throughput on the local chip(s).

Prints ONE JSON line to stdout:
  {"metric", "value", "unit", "vs_baseline", ...extras}
with extras: step_time_ms, mfu, peak_hbm_gb, platform, n_devices,
per_device_batch, steps.

Baseline (BASELINE.md): the reference's DDP row — 5 ImageNet epochs in 4612 s
on 3× TITAN Xp = 1,281,167*5/4612 ≈ 1389 images/sec aggregate. ``vs_baseline``
is our measured training throughput divided by that number (>1 = faster than
the whole 3-GPU reference using however many chips are attached — typically
one v5e chip here).

Hardening (VERDICT r1 #1): per-phase progress goes to stderr so a hang is
attributable; backend init is probed in a subprocess with a timeout and
retried so a flaky remote-TPU tunnel (the round-1 `UNAVAILABLE` crash /
240 s silent hang) yields diagnostics instead of rc=1; if the accelerator
never comes up the bench falls back to CPU with the platform stamped in the
metric name so the number cannot be mistaken for a TPU result.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_IMAGES_PER_SEC = 1_281_167 * 5 / 4612.0   # ≈ 1389 (BASELINE.md DDP row)

# Peak dense bf16 FLOP/s per chip, by device_kind substring (public specs).
_PEAK_FLOPS = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),       # v5e / "v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _phase(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:8.2f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _probe_backend(timeout: float) -> tuple[bool, str]:
    """Check (in a killable subprocess) that jax can initialize a backend.

    A hung tunnel can block ``jax.devices()`` forever with no exception —
    in-process retry loops cannot recover from that, a subprocess timeout can.
    """
    code = ("import jax; ds = jax.devices(); "
            "print(jax.default_backend(), len(ds), ds[0].device_kind)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout:.0f}s (hung tunnel?)"
    if proc.returncode != 0:
        return False, (proc.stderr or proc.stdout).strip()[-800:]
    return True, proc.stdout.strip()


def _reexec_cpu() -> None:
    """Replace this process with a clean-env CPU copy of the bench.

    Setting ``JAX_PLATFORMS=cpu`` in-process is NOT enough: a sitecustomize
    hook (e.g. the axon TPU-tunnel plugin on PYTHONPATH) can make ``import
    jax`` block on a dead tunnel regardless of the platform filter, so the
    interpreter itself must restart without it."""
    from tpudist.cleanenv import cpu_env
    env = cpu_env()
    env["TPUDIST_BENCH_CHILD"] = "cpu"
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)] + sys.argv[1:], env)


def _init_backend(attempts: int, probe_timeout: float) -> bool:
    """Probe-with-retry; on persistent failure force the CPU backend.

    Returns True if running on the ambient (accelerator) platform, False if
    we fell back to CPU (in a re-exec'd clean child)."""
    if os.environ.get("TPUDIST_BENCH_CHILD") == "cpu":
        _phase("clean CPU child — running fallback bench")
        return False
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        _phase("JAX_PLATFORMS=cpu requested — re-exec'ing with a clean env")
        _reexec_cpu()
    for i in range(1, attempts + 1):
        _phase(f"probing jax backend (attempt {i}/{attempts}, "
               f"timeout {probe_timeout:.0f}s)...")
        ok, detail = _probe_backend(probe_timeout)
        if ok:
            _phase(f"backend ok: {detail}")
            return True
        _phase(f"backend probe FAILED: {detail}")
        if i < attempts:
            time.sleep(5.0 * i)
    _phase("accelerator backend unavailable after retries — "
           "FALLING BACK TO CPU (metric will be stamped 'cpu')")
    _reexec_cpu()
    raise AssertionError("unreachable")


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, flops in _PEAK_FLOPS:
        if sub in kind:
            return flops
    return None


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default="resnet18")
    ap.add_argument("--per-device-batch", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--probe-attempts", type=int, default=2)
    args = ap.parse_args()

    on_accel = _init_backend(args.probe_attempts, args.probe_timeout)
    if not on_accel:
        # Keep the CPU fallback fast: a full 128x224x224 resnet18 train step
        # takes ~10s/step on host CPU — shrink unless explicitly overridden.
        argv_s = " ".join(sys.argv[1:])
        if "--per-device-batch" not in argv_s:
            args.per_device_batch = 8
        if "--steps" not in argv_s:
            args.steps = 3
        if "--warmup" not in argv_s:
            args.warmup = 1
        _phase(f"cpu fallback workload: batch={args.per_device_batch} "
               f"steps={args.steps}")

    _phase("importing jax + tpudist...")
    import jax
    import jax.numpy as jnp
    from tpudist.config import Config
    from tpudist.dist import make_mesh, shard_host_batch
    from tpudist.models import create_model
    from tpudist.train import compute_dtype, create_train_state, make_train_step

    n = jax.device_count()
    platform = jax.default_backend()
    device_kind = jax.devices()[0].device_kind
    _phase(f"platform={platform} n_devices={n} kind={device_kind}")

    mesh = make_mesh((n,), ("data",))
    cfg = Config(arch=args.arch, num_classes=1000, image_size=args.image_size,
                 batch_size=args.per_device_batch * n, use_amp=True,
                 seed=0).finalize(n)

    _phase(f"initializing {cfg.arch} (global batch {cfg.batch_size})...")
    model = create_model(cfg.arch, num_classes=cfg.num_classes,
                         dtype=compute_dtype(cfg))
    state = create_train_state(jax.random.PRNGKey(0), model, cfg)
    train_step = make_train_step(mesh, model, cfg)

    rng = np.random.default_rng(0)
    images = rng.standard_normal(
        (cfg.batch_size, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    labels = rng.integers(0, cfg.num_classes, size=(cfg.batch_size,)).astype(np.int32)
    images, labels = shard_host_batch(mesh, (images, labels))
    lr = jnp.asarray(cfg.lr, jnp.float32)

    _phase("lowering + compiling train step (first compile can take 20-40s)...")
    t_c0 = time.perf_counter()
    compiled = train_step.lower(state, images, labels, lr).compile()
    compile_s = time.perf_counter() - t_c0
    _phase(f"compiled in {compile_s:.1f}s")

    flops_per_step = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        flops_per_step = float(cost.get("flops", 0.0)) or None
    except Exception as e:  # cost analysis is best-effort
        _phase(f"cost_analysis unavailable: {e!r}")

    # Timing notes:
    # - run the `compiled` executable directly: calling the jitted fn would
    #   recompile (~20s) since lower().compile() does not seed the jit cache;
    # - on remote-tunnel platforms block_until_ready() can return at
    #   enqueue-ack rather than execution-complete (observed: 20 resnet18
    #   steps "finishing" in 0.03s, MFU 4.1 — physically impossible). A host
    #   readback of the final metrics cannot lie: it transitively depends on
    #   every step in the chain, so time through jax.device_get instead.
    _phase(f"warmup x{args.warmup}...")
    for _ in range(args.warmup):
        state, metrics = compiled(state, images, labels, lr)
    jax.device_get(metrics["loss"])

    _phase(f"measuring {args.steps} steps...")
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = compiled(state, images, labels, lr)
    jax.device_get(metrics["loss"])
    dt = time.perf_counter() - t0

    step_time_ms = dt / args.steps * 1e3
    images_per_sec = cfg.batch_size * args.steps / dt

    mfu = None
    peak = _peak_flops(device_kind)
    if flops_per_step and peak:
        # cost_analysis() reports the per-device (SPMD-partitioned) module's
        # FLOPs, so normalize by ONE device's peak — not peak * n.
        mfu = round(flops_per_step / (dt / args.steps) / peak, 4)
        if mfu > 1.0:
            _phase(f"WARNING: mfu={mfu} > 1 — timing did not capture real "
                   "execution (async platform?); treat throughput as invalid")

    peak_hbm_gb = None
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            peak_hbm_gb = round(stats["peak_bytes_in_use"] / 2**30, 3)
    except Exception:
        pass

    suffix = f"{n}chip" if on_accel else f"{n}dev_cpu_fallback"
    _phase(f"done: {images_per_sec:.1f} img/s, {step_time_ms:.1f} ms/step, "
           f"mfu={mfu}, peak_hbm={peak_hbm_gb}GB")
    print(json.dumps({
        "metric": f"{cfg.arch}_{cfg.image_size}_bf16_train_images_per_sec_{suffix}",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / REFERENCE_IMAGES_PER_SEC, 4),
        "step_time_ms": round(step_time_ms, 2),
        "mfu": mfu,
        "peak_hbm_gb": peak_hbm_gb,
        "platform": platform,
        "device_kind": device_kind,
        "n_devices": n,
        "per_device_batch": args.per_device_batch,
        "steps": args.steps,
        "compile_s": round(compile_s, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
