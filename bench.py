"""Benchmark: resnet18 ImageNet-shape training throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference's DDP row — 5 ImageNet epochs in 4612 s
on 3× TITAN Xp = 1,281,167*5/4612 ≈ 1389 images/sec aggregate. ``vs_baseline``
is our measured training throughput divided by that number (>1 = faster than
the whole 3-GPU reference using however many chips are attached — typically
one v5e chip here).
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_IMAGES_PER_SEC = 1_281_167 * 5 / 4612.0   # ≈ 1389 (BASELINE.md DDP row)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from tpudist.config import Config
    from tpudist.dist import make_mesh, shard_host_batch
    from tpudist.models import create_model
    from tpudist.train import compute_dtype, create_train_state, make_train_step

    n = jax.device_count()
    mesh = make_mesh((n,), ("data",))
    per_device_batch = 128
    cfg = Config(arch="resnet18", num_classes=1000, image_size=224,
                 batch_size=per_device_batch * n, use_amp=True, seed=0).finalize(n)

    model = create_model(cfg.arch, num_classes=cfg.num_classes,
                         dtype=compute_dtype(cfg))
    state = create_train_state(jax.random.PRNGKey(0), model, cfg)
    train_step = make_train_step(mesh, model, cfg)

    rng = np.random.default_rng(0)
    images = rng.standard_normal(
        (cfg.batch_size, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    labels = rng.integers(0, cfg.num_classes, size=(cfg.batch_size,)).astype(np.int32)
    images, labels = shard_host_batch(mesh, (images, labels))
    lr = jnp.asarray(cfg.lr, jnp.float32)

    # Warmup (compile + stabilize).
    for _ in range(3):
        state, metrics = train_step(state, images, labels, lr)
    jax.block_until_ready(metrics)

    steps = 20
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, images, labels, lr)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    images_per_sec = cfg.batch_size * steps / dt
    print(json.dumps({
        "metric": f"resnet18_224_bf16_train_images_per_sec_{n}chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / REFERENCE_IMAGES_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
