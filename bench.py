"""Benchmark: resnet18 ImageNet-shape training throughput on the local chip(s).

Prints one or more JSON lines to stdout — the LAST line is authoritative:
  {"metric", "value", "unit", ...extras}
with extras: step_time_ms, mfu, goodput (productive step time over
compile+warmup+measure wall — tpudist/telemetry.py's run-level accounting
scoped to the bench), peak_hbm_gb, platform, n_devices,
per_device_batch, steps — plus "vs_baseline" on resnet18 rows ONLY (the
reference baseline is a resnet18 number; a cross-arch ratio would mislead).
(An earlier line, when present, is the startup provisional stale emission
described below; consumers keying on a single line must take the last one.)

Baseline (BASELINE.md): the reference's DDP row — 5 ImageNet epochs in 4612 s
on 3× TITAN Xp = 1,281,167*5/4612 ≈ 1389 images/sec aggregate. ``vs_baseline``
is our measured training throughput divided by that number (>1 = faster than
the whole 3-GPU reference using however many chips are attached — typically
one v5e chip here).

Hardening (VERDICT r1 #1, r2 weak #1, r3 weak #1): per-phase progress goes to
stderr so a hang is attributable; backend init is probed in a killable
subprocess under a wall-clock *budget* (default 900 s, ``--probe-budget``)
with escalating per-probe timeouts, because the remote-TPU tunnel flakes on
hour scales. Every successful accelerator measurement is persisted to
``benchmarks/results/last_tpu.json``.

The persisted measurement is emitted to stdout *immediately at startup*,
stamped ``"stale": true, "provisional": true`` — BEFORE any probing — so an
external kill at any later point (the round-3 failure: the driver's timeout
fired mid-probe, before the budget-exhaustion fallback could run) still
leaves a parseable TPU line on stdout. A fresh measurement, or the final
budget-exhaustion re-emission, supersedes it as a later line. Only with no
persisted measurement at all does the bench fall back to a CPU run with the
platform stamped in the metric name.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_IMAGES_PER_SEC = 1_281_167 * 5 / 4612.0   # ≈ 1389 (BASELINE.md DDP row)

_REPO = os.path.dirname(os.path.abspath(__file__))
LAST_TPU_PATH = os.environ.get(
    "TPUDIST_LAST_TPU_PATH",
    os.path.join(_REPO, "benchmarks", "results", "last_tpu.json"))

# Peak FLOP/s table lives in tpudist.telemetry (single source shared with
# the trainer's per-step MFU accounting); resolve_peak_flops also honors the
# TPUDIST_PEAK_FLOPS env override. tpudist's package __init__ is jax-free,
# so this import cannot hang on a dead accelerator tunnel.
from tpudist.telemetry import resolve_peak_flops as _peak_flops  # noqa: E402


def _phase(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:8.2f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _probe_backend(timeout: float) -> tuple[bool, str]:
    """Check (in a killable subprocess) that jax can initialize a backend.

    A hung tunnel can block ``jax.devices()`` forever with no exception —
    in-process retry loops cannot recover from that, a subprocess timeout can.
    """
    code = ("import jax; ds = jax.devices(); "
            "print(jax.default_backend(), len(ds), ds[0].device_kind)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout:.0f}s (hung tunnel?)"
    if proc.returncode != 0:
        return False, (proc.stderr or proc.stdout).strip()[-800:]
    return True, proc.stdout.strip()


def _reexec_cpu() -> None:
    """Replace this process with a clean-env CPU copy of the bench.

    Setting ``JAX_PLATFORMS=cpu`` in-process is NOT enough: a sitecustomize
    hook (e.g. the axon TPU-tunnel plugin on PYTHONPATH) can make ``import
    jax`` block on a dead tunnel regardless of the platform filter, so the
    interpreter itself must restart without it."""
    from tpudist.cleanenv import cpu_env
    env = cpu_env()
    env["TPUDIST_BENCH_CHILD"] = "cpu"
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)] + sys.argv[1:], env)


def _age_hours(measured_at: str) -> float | None:
    """Hours since ``measured_at`` (ISO), or None if unparseable."""
    try:
        t = datetime.datetime.fromisoformat(measured_at)
        if t.tzinfo is None:
            t = t.replace(tzinfo=datetime.timezone.utc)
        return round((datetime.datetime.now(datetime.timezone.utc) - t)
                     .total_seconds() / 3600.0, 2)
    except (ValueError, TypeError):
        return None


def _try_emit_stale(want: dict, *, provisional: bool = False) -> dict | None:
    """Emit the persisted last-good accelerator measurement, stamped stale.

    ``provisional=True`` is the startup emission (before any probing): the
    line additionally carries ``"provisional": true`` and
    ``"fresh_probe": "pending"`` so a reader can tell it from the
    budget-exhaustion re-emission that confirms the probe actually failed.

    Returns the emitted record on success, else None (without printing
    anything) if the file is missing, unreadable, or records a different
    workload than the caller asked for — emitting resnet18@224 numbers for
    a resnet50@96 invocation would poison any harness that keys results by
    its own command line."""
    try:
        with open(LAST_TPU_PATH) as f:
            rec = json.load(f)
        rec.setdefault("remat", False)   # records persisted before the flag
        # Records persisted before the s2d field existed ran the DIRECT
        # conv1 — exactly the s2d=False program, so stamp them truthfully
        # (they match today's canonical want, which defaults to the
        # direct stem precisely so the persisted claim and HEAD's default
        # program coincide) and keep the provenance note.
        if "s2d" not in rec:
            rec["s2d"] = False
            rec["stem_note"] = "measured pre-s2d-stem (direct conv1 program)"
        mismatched = {k: (rec.get(k), v) for k, v in want.items()
                      if rec.get(k) != v}
        if mismatched:
            _phase(f"persisted measurement is for a different workload "
                   f"({mismatched}) — not emitting it")
            return None
        measured_at = rec.get("measured_at", "")
        # If unparseable, only the age annotation degrades; record stays usable
        age_h = _age_hours(measured_at)
        rec.update({"stale": True, "stale_age_hours": age_h,
                    "fresh_probe": "pending" if provisional else "failed"})
        if provisional:
            rec["provisional"] = True
        out = json.dumps(rec)
    except Exception as e:
        _phase(f"persisted measurement unusable ({e!r}) — ignoring it")
        return None
    _phase(f"emitting persisted TPU measurement from {measured_at} "
           f"({age_h} h old){' [provisional]' if provisional else ''}")
    print(out, flush=True)
    return rec


def _init_backend(probe_budget: float, probe_timeout: float,
                  want: dict, provisional_rec: dict | None = None) -> bool:
    """Probe under a wall-clock budget; on exhaustion prefer the persisted
    last-good accelerator measurement over a fresh CPU number.

    Returns True if running on the ambient (accelerator) platform, False if
    we fell back to CPU (in a re-exec'd clean child)."""
    if os.environ.get("TPUDIST_BENCH_CHILD") == "cpu":
        _phase("clean CPU child — running fallback bench")
        return False
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        _phase("JAX_PLATFORMS=cpu requested — re-exec'ing with a clean env")
        _reexec_cpu()
    deadline = time.perf_counter() + probe_budget
    timeout, i, same_err = probe_timeout, 0, 0
    last_err = None
    while True:
        i += 1
        left = deadline - time.perf_counter()
        if left <= 5.0:
            break
        t = min(timeout, left)
        _phase(f"probing jax backend (attempt {i}, timeout {t:.0f}s, "
               f"budget left {left:.0f}s)...")
        ok, detail = _probe_backend(t)
        if ok:
            if detail.split()[0] == "cpu":
                # The ambient backend IS the cpu platform (tunnel plugin
                # absent/dead without hanging). That is not an accelerator:
                # prefer the persisted measurement / shrunk-CPU fallback.
                _phase(f"probe reached only the cpu backend ({detail})")
                break
            _phase(f"backend ok: {detail}")
            return True
        _phase(f"backend probe FAILED: {detail}")
        # Escalating timeouts are for hangs (a tunnel mid-recovery can need
        # minutes to answer); a deterministic error repeating verbatim will
        # not heal over a 30-min budget — short-circuit after 3.
        if "exceeded" not in detail:
            same_err = same_err + 1 if detail == last_err else 1
            last_err = detail
            if same_err >= 3:
                _phase("same non-timeout error 3x — giving up on the probe")
                break
        timeout = min(timeout * 1.5, 300.0)
        time.sleep(min(60.0, 10.0 * i, max(0.0, deadline - time.perf_counter())))
    _phase("probe budget exhausted — checking for a persisted measurement")
    if _emit_exhaustion_record(want, provisional_rec):
        sys.exit(0)
    _phase("no usable persisted measurement — "
           "FALLING BACK TO CPU (metric will be stamped 'cpu')")
    _reexec_cpu()
    raise AssertionError("unreachable")


def _emit_exhaustion_record(want: dict,
                            provisional_rec: dict | None) -> bool:
    """The probe budget is spent: re-emit the persisted record stamped
    ``fresh_probe: "failed"``, or — when the file vanished mid-run after the
    startup provisional emission — print a corrected copy of the provisional
    record. Consumers take the LAST stdout line, so exiting with only the
    pending-stamped provisional line would misreport the probe outcome.
    Returns True if a line was printed (caller exits 0), False if the CPU
    fallback should run instead."""
    if _try_emit_stale(want) is not None:
        return True
    if provisional_rec is not None:
        rec = dict(provisional_rec)
        rec.pop("provisional", None)
        rec["fresh_probe"] = "failed"
        # The provisional copy's age was computed at startup; a long probe
        # budget can make that understate the record's true age by hours —
        # restamp it as of NOW, when this (authoritative) line prints.
        age_h = _age_hours(rec.get("measured_at", ""))
        if age_h is not None:
            rec["stale_age_hours"] = age_h
        _phase("persisted file no longer readable — correcting the "
               "provisional line's probe outcome")
        print(json.dumps(rec), flush=True)
        return True
    return False


def build_compiled_step(arch: str, per_device_batch: int, image_size: int,
                        *, use_amp: bool = True, amp_dtype: str = "bfloat16",
                        sync_batchnorm: bool = False, remat: bool = False,
                        s2d: bool = False, seed: int = 0):
    """Build + compile the canonical SPMD train step on the already-
    initialized backend. Returns ``(cfg, compiled, state, images, labels,
    lr, compile_s)`` — shared by ``measure_row`` (which then times it) and
    by the compiled-cost fingerprint test (``tests/test_compiled_cost.py``),
    which pins cost/memory analysis of THIS exact program so stem/remat/
    fusion changes can't silently shift the canonical program between rare
    hardware windows (VERDICT r4 next #6)."""
    import jax
    import jax.numpy as jnp
    from tpudist.config import Config
    from tpudist.dist import make_mesh, shard_host_batch
    from tpudist.models import create_model
    from tpudist.train import compute_dtype, create_train_state, make_train_step

    n = jax.device_count()
    mesh = make_mesh((n,), ("data",))
    cfg = Config(arch=arch, num_classes=1000, image_size=image_size,
                 batch_size=per_device_batch * n, use_amp=use_amp,
                 amp_dtype=amp_dtype, sync_batchnorm=sync_batchnorm,
                 remat=remat, seed=seed).finalize(n)

    _phase(f"initializing {cfg.arch} (global batch {cfg.batch_size}, "
           f"amp={use_amp}/{amp_dtype if use_amp else '-'}, "
           f"syncbn={sync_batchnorm}, remat={remat})...")
    model = create_model(cfg.arch, num_classes=cfg.num_classes,
                         dtype=compute_dtype(cfg),
                         **({"remat": True} if remat else {}),
                         **({"s2d_stem": True} if s2d else {}))
    state = create_train_state(jax.random.PRNGKey(0), model, cfg)
    train_step = make_train_step(mesh, model, cfg)

    rng = np.random.default_rng(0)
    images = rng.standard_normal(
        (cfg.batch_size, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    labels = rng.integers(0, cfg.num_classes, size=(cfg.batch_size,)).astype(np.int32)
    images, labels = shard_host_batch(mesh, (images, labels))
    lr = jnp.asarray(cfg.lr, jnp.float32)

    _phase("lowering + compiling train step (first compile can take 20-40s)...")
    t_c0 = time.perf_counter()
    compiled = train_step.lower(state, images, labels, lr).compile()
    compile_s = time.perf_counter() - t_c0
    _phase(f"compiled in {compile_s:.1f}s")
    return cfg, compiled, state, images, labels, lr, compile_s


def compiled_flops(compiled) -> float | None:
    """Per-device FLOPs of a compiled executable (best-effort; the unwrap
    lives in tpudist.telemetry so the trainer's MFU shares it)."""
    from tpudist.telemetry import cost_analysis_flops
    return cost_analysis_flops(compiled, log=_phase)


def measure_row(arch: str, per_device_batch: int, image_size: int,
                steps: int, warmup: int, *, use_amp: bool = True,
                amp_dtype: str = "bfloat16", sync_batchnorm: bool = False,
                remat: bool = False, s2d: bool = False, seed: int = 0) -> dict:
    """Compile + time one training-recipe row on the already-initialized
    backend; returns the measurement dict (metric name excluded).

    Shared by the single-row driver bench below and by
    ``benchmarks/recipe_table.py`` (the reference's four-row README table,
    ``/root/reference/README.md:9-14``, re-created on TPU)."""
    import jax

    platform = jax.default_backend()
    device_kind = jax.devices()[0].device_kind
    n = jax.device_count()

    cfg, compiled, state, images, labels, lr, compile_s = build_compiled_step(
        arch, per_device_batch, image_size, use_amp=use_amp,
        amp_dtype=amp_dtype, sync_batchnorm=sync_batchnorm, remat=remat,
        s2d=s2d, seed=seed)

    # XLA introspection (tpudist/obs/xla_introspect.py): ONE pass over the
    # compiler surfaces yields the MFU numerator, the compiled-HBM view,
    # and the collective census + temp-buffer attribution — so a row that
    # got slower also says whether comms or scratch HBM grew.
    try:
        from tpudist.obs.xla_introspect import event_fields, introspect
        intro = event_fields(introspect(compiled, log=_phase))
    except Exception as e:
        _phase(f"xla introspection unavailable: {e!r}")
        intro = {}
    flops_per_step = intro.get("flops") or None
    hbm_compiled_gb = (round(intro["hbm_compiled_bytes"] / 2**30, 3)
                       if intro.get("hbm_compiled_bytes") is not None
                       else None)

    # Timing notes:
    # - run the `compiled` executable directly: calling the jitted fn would
    #   recompile (~20s) since lower().compile() does not seed the jit cache;
    # - on remote-tunnel platforms block_until_ready() can return at
    #   enqueue-ack rather than execution-complete (observed: 20 resnet18
    #   steps "finishing" in 0.03s, MFU 4.1 — physically impossible). A host
    #   readback of the final metrics cannot lie: it transitively depends on
    #   every step in the chain, so time through jax.device_get instead.
    _phase(f"warmup x{warmup}...")
    t_w0 = time.perf_counter()
    for _ in range(warmup):
        state, metrics = compiled(state, images, labels, lr)
    jax.device_get(metrics["loss"])
    dt_warmup = time.perf_counter() - t_w0

    _phase(f"measuring {steps} steps...")
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, images, labels, lr)
    jax.device_get(metrics["loss"])
    dt = time.perf_counter() - t0

    step_time_ms = dt / steps * 1e3
    images_per_sec = cfg.batch_size * steps / dt
    # Bench-scope goodput (telemetry.py's run-level definition, scoped to
    # this process's work): productive step time over compile+warmup+measure
    # wall. Dominated by compile amortization at bench step counts — the
    # number a short real run would see, which is why BENCH rows carry it.
    goodput = round((dt_warmup + dt) / (compile_s + dt_warmup + dt), 4)

    mfu = None
    peak = _peak_flops(device_kind)
    if flops_per_step and peak:
        # cost_analysis() reports the per-device (SPMD-partitioned) module's
        # FLOPs, so normalize by ONE device's peak — not peak * n.
        mfu = round(flops_per_step / (dt / steps) / peak, 4)
        if mfu > 1.0:
            _phase(f"WARNING: mfu={mfu} > 1 — timing did not capture real "
                   "execution (async platform?); treat throughput as invalid")

    # Runtime allocator view: true high-water mark including transient
    # activations the compiler view can miss (and vice versa). TPU backends
    # expose it; CPU returns nothing.
    from tpudist.utils import peak_hbm_gb as _runtime_peak_hbm
    peak_hbm_gb = _runtime_peak_hbm()
    if peak_hbm_gb is None:
        peak_hbm_gb = hbm_compiled_gb

    _phase(f"row done: {images_per_sec:.1f} img/s, {step_time_ms:.1f} ms/step, "
           f"mfu={mfu}, peak_hbm={peak_hbm_gb}GB")
    row = {
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "step_time_ms": round(step_time_ms, 2),
        "mfu": mfu,
        "goodput": goodput,
        "peak_hbm_gb": peak_hbm_gb,
        "hbm_compiled_gb": hbm_compiled_gb,
        "platform": platform,
        "device_kind": device_kind,
        "n_devices": n,
        "per_device_batch": per_device_batch,
        "steps": steps,
        "compile_s": round(compile_s, 1),
        "arch": arch,
        "image_size": image_size,
        "remat": remat,
        "s2d": s2d,
    }
    if intro.get("temp_bytes") is not None:
        row["hbm_temp_gb"] = round(intro["temp_bytes"] / 2**30, 3)
    for k in ("collective_ops", "collective_bytes_per_step",
              "collective_link_bytes",
              "all_reduce_count", "all_reduce_bytes", "bytes_accessed"):
        if intro.get(k) is not None:
            row[k] = intro[k]
    if arch == "resnet18":
        # The 3×TITAN-Xp reference baseline IS a resnet18 number (BASELINE.md
        # DDP row): stamping the ratio onto resnet50/vit rows would compare
        # different architectures and mislead anyone quoting it (ADVICE r5).
        row["vs_baseline"] = round(images_per_sec / REFERENCE_IMAGES_PER_SEC,
                                   4)
    return row


# The canonical driver workload (also the argparse defaults in main()); only
# its measurements feed the stale fallback — a batch-sweep row would
# otherwise overwrite last_tpu.json with a workload that _try_emit_stale
# then refuses to substitute for the default run.
_CANONICAL = {"arch": "resnet18", "image_size": 224, "per_device_batch": 128,
              "remat": False, "s2d": False}


def persist_if_accelerator(record: dict) -> None:
    """Save the freshest accelerator measurement for the stale-fallback path."""
    if record.get("platform") == "cpu":
        return
    if any(record.get(k) != v for k, v in _CANONICAL.items()):
        _phase("non-canonical workload — not persisting to last_tpu.json")
        return
    rec = dict(record)
    rec["measured_at"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    os.makedirs(os.path.dirname(LAST_TPU_PATH), exist_ok=True)
    tmp = LAST_TPU_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
    os.replace(tmp, LAST_TPU_PATH)
    _phase(f"persisted accelerator measurement to {LAST_TPU_PATH}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default=_CANONICAL["arch"])
    ap.add_argument("--per-device-batch", type=int,
                    default=_CANONICAL["per_device_batch"])
    ap.add_argument("--image-size", type=int,
                    default=_CANONICAL["image_size"])
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--remat", action="store_true",
                    help="bench with --remat (activation recompute): "
                         "non-canonical; quantifies the HBM/throughput trade")
    ap.add_argument("--s2d", action="store_true",
                    help="bench with the space-to-depth stem rewrite instead "
                         "of the direct 7x7/s2 conv: non-canonical; the A/B "
                         "side for the s2d MFU claim (resnets only). The "
                         "DIRECT stem is the default/canonical program — "
                         "it is the one every persisted TPU record measured")
    ap.add_argument("--no-s2d", action="store_true",
                    help="explicitly request the direct stem (the default; "
                         "kept for older watcher scripts)")
    ap.add_argument("--regress-strict", action="store_true",
                    dest="regress_strict",
                    help="exit 3 when the post-bench regression gate trips "
                         "(default: the REGRESSION banner on stderr only — "
                         "the row already printed to stdout stays usable)")
    ap.add_argument("--probe-timeout", type=float, default=90.0,
                    help="first probe's subprocess timeout; later probes "
                         "escalate 1.5x up to 300s")
    ap.add_argument("--probe-budget", type=float,
                    default=float(os.environ.get("TPUDIST_PROBE_BUDGET", 900)),
                    help="total wall-clock seconds to keep probing before "
                         "falling back (env TPUDIST_PROBE_BUDGET); keep well "
                         "under any outer harness timeout — the final "
                         "measurement still needs compile+run headroom")
    args = ap.parse_args()
    if args.s2d and args.no_s2d:
        ap.error("--s2d and --no-s2d are mutually exclusive")
    if (args.s2d or args.no_s2d) and not args.arch.startswith(
            ("resnet", "resnext", "wide_resnet")):
        # Fail BEFORE the probe/compile preamble: only the resnet family has
        # the s2d stem lever; anything else would TypeError in
        # create_model after minutes of tunnel probing.
        ap.error(f"stem flags apply to the resnet family; got '{args.arch}'")

    want = {"arch": args.arch, "image_size": args.image_size,
            "per_device_batch": args.per_device_batch,
            "remat": args.remat, "s2d": args.s2d}
    # Emit the last-good TPU line FIRST (stamped provisional+stale): if an
    # outer timeout kills this process at any later point — mid-probe,
    # mid-compile, mid-measure — stdout already carries a parseable TPU
    # number. A later fresh (or final-stale) line supersedes it. Suppressed
    # when the operator explicitly forced CPU: a TPU-stamped line for a
    # deliberate CPU run would misattribute the platform.
    provisional_rec = None
    if (os.environ.get("TPUDIST_BENCH_CHILD") != "cpu"
            and os.environ.get("JAX_PLATFORMS") != "cpu"):
        provisional_rec = _try_emit_stale(want, provisional=True)

    on_accel = _init_backend(args.probe_budget, args.probe_timeout,
                             want, provisional_rec)
    if not on_accel:
        # Keep the CPU fallback fast: a full 128x224x224 resnet18 train step
        # takes ~10s/step on host CPU — shrink unless explicitly overridden.
        argv_s = " ".join(sys.argv[1:])
        if "--per-device-batch" not in argv_s:
            args.per_device_batch = 8
        if "--steps" not in argv_s:
            args.steps = 3
        if "--warmup" not in argv_s:
            args.warmup = 1
        _phase(f"cpu fallback workload: batch={args.per_device_batch} "
               f"steps={args.steps}")

    _phase("importing jax + tpudist...")
    rec = measure_row(args.arch, args.per_device_batch, args.image_size,
                      args.steps, args.warmup, remat=args.remat,
                      s2d=args.s2d)
    # Suffix from the platform actually measured, not the probe: the tunnel
    # can die between probe success and measure_row's in-process jax init,
    # silently landing the run on CPU.
    suffix = (f"{rec['n_devices']}chip" if rec["platform"] != "cpu"
              else f"{rec['n_devices']}dev_cpu_fallback")
    remat_tag = "remat_" if args.remat else ""
    stem_tag = "s2d_" if args.s2d else ""
    rec = {"metric": f"{args.arch}_{args.image_size}_bf16_{remat_tag}"
                     f"{stem_tag}train_images_per_sec_{suffix}", **rec}
    persist_if_accelerator(rec)
    print(json.dumps(rec), flush=True)

    # Every FRESH measurement lands in the history; then the regression gate
    # (tpudist/regress.py, also runnable standalone as tpudist-regress)
    # compares it to the trailing median of its own workload. The verdict
    # goes to stderr (stdout's last line stays the authoritative row);
    # --regress-strict makes a tripped gate fail the bench process itself.
    from tpudist.regress import (analyze_history, append_history,
                                 format_verdict, history_path, load_history)
    hist_row = dict(rec)
    hist_row["measured_at"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    append_history(hist_row)
    verdict = analyze_history(load_history(history_path()),
                              metric=rec["metric"])
    print(format_verdict(verdict), file=sys.stderr, flush=True)
    if verdict["status"] == "regression" and args.regress_strict:
        sys.exit(3)


if __name__ == "__main__":
    main()
