#!/bin/bash
# Bonus zoo rows (attended): throughput/MFU breadth beyond the canonical
# resnet18 row — resnet50 (the reference zoo's other headline conv net,
# /root/reference/distributed.py:129-133 `models.__dict__[args.arch]`) and
# vit_b_16 (this repo's beyond-reference attention path) at the canonical
# 224px / per-device batch 128 / bf16 recipe.
#
# The tunnel serves one client, so capture-time exclusion is mechanical:
# this script takes the shared CAPTURE lock (/tmp/tpudist_watch_r5.lock)
# per arch. The r5 watcher holds that lock only AROUND its run_stage()
# captures (its single-instance guard moved to a separate .instance file,
# ADVICE r5 #3), so zoo rows are reachable between watcher stages while
# the watcher is alive — the flock below waits out an in-flight stage
# instead of giving up for the whole round.
# Rows append to bench_tpu_fresh.jsonl only when genuinely fresh. The
# admission rule below MIRRORS tpu_watch_r5.sh's bench_capture() and must
# change in lockstep with it — not factored into a shared helper yet
# because the watcher script is long-running and bash re-reads a running
# script incrementally (editing it mid-run corrupts execution); fold both
# onto one sourced helper at the next watcher relaunch.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
FRESH=benchmarks/results/bench_tpu_fresh.jsonl
exec 9>/tmp/tpudist_watch_r5.lock
for ARCH in resnet50 vit_b_16; do
  # Per-arch timeout (ADVICE r5): ViT compile over the tunnel can exceed
  # 15 min (the watcher's flash stage budgets 2400s for the same reason),
  # which left <15 min of an 1800s budget for the 50 measured steps.
  case "$ARCH" in
    vit_*) BUDGET=2400 ;;
    *)     BUDGET=1800 ;;
  esac
  # Capture lock held per arch, waiting up to 10 min for an in-flight
  # watcher stage to finish; a watcher mid-capture for longer than that
  # means the window is busy — skip this arch rather than queue forever.
  if ! flock -w 600 9; then
    echo "[zoo $(date -u +%FT%TZ)] $ARCH: capture lock busy >600s — skipping" >> "$LOG"
    continue
  fi
  # Dedup (ADVICE r5) — checked AFTER the lock is held: two zoo runs that
  # both pass a pre-lock check would serialize on the flock and append
  # duplicate rows; under the lock the second sees the first's row.
  if [ -f "$FRESH" ] && grep -q "\"metric\": \"${ARCH}_224_bf16_" "$FRESH"; then
    echo "[zoo $(date -u +%FT%TZ)] $ARCH already in $(basename "$FRESH") — skipping" >> "$LOG"
    flock -u 9
    continue
  fi
  # 9>&- : bench children must not inherit the capture lock (an orphaned
  # child outliving a killed zoo run would block the watcher's flock).
  OUT=$(timeout "$BUDGET" python bench.py --probe-budget 120 --steps 50 \
        --arch "$ARCH" 2>> "$LOG" 9>&-)
  RC=$?
  LAST=$(echo "$OUT" | tail -n 1)
  # Admit the row BEFORE releasing the lock: the dedup check above runs
  # under the lock, so the append must too or a second run could pass
  # dedup while this row is still only in memory.
  if [ $RC -eq 0 ] && [ -n "$LAST" ] \
      && ! echo "$LAST" | grep -qE '"stale": true|cpu_fallback'; then
    echo "$LAST" >> "$FRESH"
    echo "[zoo $(date -u +%FT%TZ)] $ARCH ok: $LAST" >> "$LOG"
  else
    echo "[zoo $(date -u +%FT%TZ)] $ARCH stale/failed (rc=$RC): $LAST" >> "$LOG"
  fi
  flock -u 9
done
