#!/bin/bash
# Bonus zoo rows (attended): throughput/MFU breadth beyond the canonical
# resnet18 row — resnet50 (the reference zoo's other headline conv net,
# /root/reference/distributed.py:129-133 `models.__dict__[args.arch]`) and
# vit_b_16 (this repo's beyond-reference attention path) at the canonical
# 224px / per-device batch 128 / bf16 recipe.
#
# The tunnel serves one client and these rows rank below every watcher
# stage in evidence value, so exclusion is mechanical: this script takes
# the SAME instance lock as tpu_watch_r5.sh and exits if the watcher (or
# another zoo run) holds it.
# Rows append to bench_tpu_fresh.jsonl only when genuinely fresh. The
# admission rule below MIRRORS tpu_watch_r5.sh's bench_capture() and must
# change in lockstep with it — not factored into a shared helper yet
# because the watcher script is long-running and bash re-reads a running
# script incrementally (editing it mid-run corrupts execution); fold both
# onto one sourced helper at the next watcher relaunch.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
FRESH=benchmarks/results/bench_tpu_fresh.jsonl
exec 9>/tmp/tpudist_watch_r5.lock
if ! flock -n 9; then
  echo "[zoo $(date -u +%FT%TZ)] watcher (or another zoo run) holds the tunnel lock — exiting" >> "$LOG"
  exit 1
fi
for ARCH in resnet50 vit_b_16; do
  # Dedup (ADVICE r5): a rerun must not append duplicate rows — skip any
  # arch whose canonical-workload metric already has a fresh line.
  if [ -f "$FRESH" ] && grep -q "\"metric\": \"${ARCH}_224_bf16_" "$FRESH"; then
    echo "[zoo $(date -u +%FT%TZ)] $ARCH already in $(basename "$FRESH") — skipping" >> "$LOG"
    continue
  fi
  # 9>&- : bench children must not inherit the instance lock (an orphaned
  # child outliving a killed zoo run would block the watcher's flock).
  OUT=$(timeout 1800 python bench.py --probe-budget 120 --steps 50 \
        --arch "$ARCH" 2>> "$LOG" 9>&-)
  RC=$?
  LAST=$(echo "$OUT" | tail -n 1)
  if [ $RC -eq 0 ] && [ -n "$LAST" ] \
      && ! echo "$LAST" | grep -qE '"stale": true|cpu_fallback'; then
    echo "$LAST" >> "$FRESH"
    echo "[zoo $(date -u +%FT%TZ)] $ARCH ok: $LAST" >> "$LOG"
  else
    echo "[zoo $(date -u +%FT%TZ)] $ARCH stale/failed (rc=$RC): $LAST" >> "$LOG"
  fi
done
