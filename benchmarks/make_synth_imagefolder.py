"""Synthesize a small real-image ImageFolder corpus for the accuracy proxy run
(VERDICT r1 #8: the nearest executable stand-in for the reference's ImageNet
top-1 target, `/root/reference/README.md:12`, with zero network egress).

Classes are procedural textures — oriented stripes, checkerboards, dots,
radial gradients, rings, blobs, diagonal waves, noise-free flats — rendered
with random color, phase, scale and additive noise, then JPEG-encoded. A
linear probe cannot trivially separate them at pixel level (random colors
decorrelate class from mean color), but a convnet learns them in a few
epochs, so "top-1 well above chance" is a meaningful end-to-end assertion
through the REAL pipeline: JPEG decode → transforms → sharded loader → SPMD
train step.

Usage:
  python benchmarks/make_synth_imagefolder.py --root /tmp/synthfolder \
      --classes 8 --train-per-class 200 --val-per-class 50 --size 128
"""

from __future__ import annotations

import argparse
import os

import numpy as np
from PIL import Image


def _grid(size):
    y, x = np.mgrid[0:size, 0:size].astype(np.float32) / size
    return x, y


def _stripes(rng, size, angle):
    x, y = _grid(size)
    freq = rng.uniform(4, 9)
    phase = rng.uniform(0, 2 * np.pi)
    t = x * np.cos(angle) + y * np.sin(angle)
    return 0.5 + 0.5 * np.sin(2 * np.pi * freq * t + phase)


def _checker(rng, size):
    x, y = _grid(size)
    n = rng.integers(3, 7)
    return (((x * n).astype(int) + (y * n).astype(int)) % 2).astype(np.float32)


def _dots(rng, size):
    x, y = _grid(size)
    n = rng.integers(4, 8)
    fx, fy = (x * n) % 1.0 - 0.5, (y * n) % 1.0 - 0.5
    r = np.sqrt(fx ** 2 + fy ** 2)
    return (r < rng.uniform(0.2, 0.35)).astype(np.float32)


def _radial(rng, size):
    x, y = _grid(size)
    cx, cy = rng.uniform(0.3, 0.7, size=2)
    r = np.sqrt((x - cx) ** 2 + (y - cy) ** 2)
    return np.clip(1.0 - r / rng.uniform(0.5, 0.9), 0, 1)


def _rings(rng, size):
    x, y = _grid(size)
    cx, cy = rng.uniform(0.35, 0.65, size=2)
    r = np.sqrt((x - cx) ** 2 + (y - cy) ** 2)
    return 0.5 + 0.5 * np.sin(2 * np.pi * rng.uniform(5, 10) * r)


def _blobs(rng, size):
    img = np.zeros((size, size), np.float32)
    x, y = _grid(size)
    for _ in range(rng.integers(3, 6)):
        cx, cy = rng.uniform(0, 1, size=2)
        s = rng.uniform(0.05, 0.15)
        img += np.exp(-((x - cx) ** 2 + (y - cy) ** 2) / (2 * s ** 2))
    return np.clip(img, 0, 1)


def _waves(rng, size):
    x, y = _grid(size)
    return 0.5 + 0.25 * (np.sin(2 * np.pi * rng.uniform(3, 6) * x)
                         + np.sin(2 * np.pi * rng.uniform(3, 6) * y))


def _flat(rng, size):
    x, y = _grid(size)
    gx, gy = rng.uniform(-1, 1, size=2)
    return np.clip(0.5 + gx * (x - 0.5) + gy * (y - 0.5), 0, 1)


_FAMILIES = [
    lambda r, s: _stripes(r, s, 0.0),
    lambda r, s: _stripes(r, s, np.pi / 2),
    _checker, _dots, _radial, _rings, _blobs, _waves,
    lambda r, s: _stripes(r, s, np.pi / 4),
    _flat,
]


def render(rng, size, cls):
    field = _FAMILIES[cls % len(_FAMILIES)](rng, size)
    # Two random colors; class information lives in TEXTURE, not color.
    c0 = rng.uniform(0.05, 0.95, size=3)
    c1 = rng.uniform(0.05, 0.95, size=3)
    img = field[..., None] * c1 + (1 - field[..., None]) * c0
    img = img + rng.normal(0, 0.04, img.shape)
    return Image.fromarray(
        (np.clip(img, 0, 1) * 255).astype(np.uint8), "RGB")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--train-per-class", type=int, default=200)
    ap.add_argument("--val-per-class", type=int, default=50)
    ap.add_argument("--size", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    assert args.classes <= len(_FAMILIES), f"max {len(_FAMILIES)} classes"

    rng = np.random.default_rng(args.seed)
    for split, per_class in (("train", args.train_per_class),
                             ("val", args.val_per_class)):
        for c in range(args.classes):
            d = os.path.join(args.root, split, f"class_{c:02d}")
            os.makedirs(d, exist_ok=True)
            for i in range(per_class):
                render(rng, args.size, c).save(
                    os.path.join(d, f"{i:05d}.jpg"), quality=88)
    n_train = args.classes * args.train_per_class
    n_val = args.classes * args.val_per_class
    print(f"wrote {n_train} train + {n_val} val JPEGs "
          f"({args.classes} classes, {args.size}px) under {args.root}")


if __name__ == "__main__":
    main()
