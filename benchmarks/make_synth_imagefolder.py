"""Synthesize a small real-image ImageFolder corpus for the accuracy proxy run
(VERDICT r1 #8: the nearest executable stand-in for the reference's ImageNet
top-1 target, `/root/reference/README.md:12`, with zero network egress).

Classes are procedural STATIONARY textures — h/v/diagonal stripes,
checkerboards, dots, waves, smooth gradients (radial/ring patterns sit at
the tail, >7-class use only: centered objects don't survive random crops) —
rendered multi-octave (tiled higher frequencies, so tight RandomResizedCrop
zooms still see several cycles) with random color, phase and additive
noise, then JPEG-encoded. For the ≤9-class base corpus random colors
decorrelate class from mean color, so a convnet must learn texture; the
>9-class composite corpus instead makes hue one of three GRADED class
attributes (see the composite note below) — either way "top-1 well above
chance" is a meaningful end-to-end assertion through the REAL pipeline:
JPEG decode → transforms → sharded loader → SPMD train step.

Usage:
  python benchmarks/make_synth_imagefolder.py --root /tmp/synthfolder \
      --classes 6 --train-per-class 300 --val-per-class 60 --size 64
"""

from __future__ import annotations

import argparse
import os

import numpy as np
from PIL import Image


def _grid(size):
    y, x = np.mgrid[0:size, 0:size].astype(np.float32) / size
    return x, y


def _stripes(rng, size, angle):
    x, y = _grid(size)
    freq = rng.uniform(4, 9)
    phase = rng.uniform(0, 2 * np.pi)
    t = x * np.cos(angle) + y * np.sin(angle)
    return 0.5 + 0.5 * np.sin(2 * np.pi * freq * t + phase)


def _checker(rng, size):
    x, y = _grid(size)
    n = rng.integers(3, 7)
    return (((x * n).astype(int) + (y * n).astype(int)) % 2).astype(np.float32)


def _dots(rng, size):
    x, y = _grid(size)
    n = rng.integers(4, 8)
    fx, fy = (x * n) % 1.0 - 0.5, (y * n) % 1.0 - 0.5
    r = np.sqrt(fx ** 2 + fy ** 2)
    return (r < rng.uniform(0.2, 0.35)).astype(np.float32)


def _radial(rng, size):
    x, y = _grid(size)
    cx, cy = rng.uniform(0.3, 0.7, size=2)
    r = np.sqrt((x - cx) ** 2 + (y - cy) ** 2)
    return np.clip(1.0 - r / rng.uniform(0.5, 0.9), 0, 1)


def _rings(rng, size):
    x, y = _grid(size)
    cx, cy = rng.uniform(0.35, 0.65, size=2)
    r = np.sqrt((x - cx) ** 2 + (y - cy) ** 2)
    return 0.5 + 0.5 * np.sin(2 * np.pi * rng.uniform(5, 10) * r)


def _waves(rng, size):
    x, y = _grid(size)
    return 0.5 + 0.25 * (np.sin(2 * np.pi * rng.uniform(3, 6) * x)
                         + np.sin(2 * np.pi * rng.uniform(3, 6) * y))


def _flat(rng, size):
    x, y = _grid(size)
    gx, gy = rng.uniform(-1, 1, size=2)
    return np.clip(0.5 + gx * (x - 0.5) + gy * (y - 0.5), 0, 1)


def _diag_pair(rng, size):
    """45° or 135° stripes, drawn at random: a flip-CLOSED class (horizontal
    flip maps 45°↔135°, so either orientation stays in-class under the train
    pipeline's RandomHorizontalFlip)."""
    angle = np.pi / 4 if rng.random() < 0.5 else 3 * np.pi / 4
    return _stripes(rng, size, angle)


# The first seven families are STATIONARY (translation-invariant, fill the
# whole image) and pairwise distributionally distinct under the train
# pipeline's crop/flip augmentations: class identity survives
# RandomResizedCrop in train AND center-crop in val. Centered-object
# patterns (radial, rings) lose signal under random crops — observed:
# train 42% / val 19% with them in an 8-class set — so they sit at the
# tail, reachable only by asking for >7 classes (with that caveat). The
# committed r2 accuracy run used --classes 6; _flat (index 6) is believed
# crop-safe but was not exercised in that run.
_FAMILIES = [
    lambda r, s: _stripes(r, s, 0.0),
    lambda r, s: _stripes(r, s, np.pi / 2),
    _diag_pair, _checker, _dots, _waves, _flat,
    _radial, _rings,
]


def _tiled(fam, rng, size, k):
    """Render ``fam`` on a 2^?-smaller grid and tile it to ``size`` (k tiles
    per side): k× the cycles per image, so tight crops still see several
    cycles. Shared by the base and composite renderers."""
    sub = fam(rng, max(8, size // k))
    up = np.tile(sub, (k, k))[:size, :size]
    pad_y, pad_x = size - up.shape[0], size - up.shape[1]
    if pad_y or pad_x:
        up = np.pad(up, ((0, pad_y), (0, pad_x)), mode="wrap")
    return up


def render(rng, size, cls, octaves=3):
    """Multi-octave rendering: the class pattern is superimposed at several
    spatial frequencies (weights 0.5/0.3/0.2), so a RandomResizedCrop zoom
    (train) and a mild center crop (val) both see class-discriminative
    structure — single-frequency textures generalize poorly across the
    train/val scale gap (first-run observation: train 42% / val 19%)."""
    fam = _FAMILIES[cls % len(_FAMILIES)]
    weights = [0.5, 0.3, 0.2][:octaves]
    field = np.zeros((size, size), np.float32)
    for i, w in enumerate(weights):
        # Families draw frequency in NORMALIZED coordinates (cycles per
        # image), so octave i renders on a 2^i-smaller grid and TILES it:
        # 2^i× the cycles per image. The point is the train/val scale gap —
        # a RandomResizedCrop zoom to area s shows only f·√s cycles of the
        # base band (≈1-2 at s=0.08, too few to classify); the tiled high
        # octaves keep several cycles visible in even the tightest crop,
        # while the base octave dominates the val center crop.
        field = field + w * _tiled(fam, rng, size, 2 ** i)
    field = (field - field.min()) / max(field.max() - field.min(), 1e-6)
    # Two random colors; class information lives in TEXTURE, not color.
    c0 = rng.uniform(0.05, 0.95, size=3)
    c1 = rng.uniform(0.05, 0.95, size=3)
    img = field[..., None] * c1 + (1 - field[..., None]) * c0
    img = img + rng.normal(0, 0.04, img.shape)
    return Image.fromarray(
        (np.clip(img, 0, 1) * 255).astype(np.uint8), "RGB")


# --- composite classes (r3: the ~100-class rehearsal, VERDICT #8) ---------
#
# The 9 base families cap the single-pattern class count, so larger label
# spaces compose three GRADED attributes, all invariant to the train
# pipeline's crop/zoom/flip:
#   class = dominant family [7] × dominant hue bucket [5] × secondary [3]
# (105 classes). The dominant pattern renders at octaves 0-1 (weight 0.65)
# in a color whose HUE is the class's bucket (saturation/value jittered);
# the secondary pattern tiles the fine octave (weight 0.35) in a random
# color. Hue is the easy attribute (real-world classes correlate with color
# too), the two texture attributes carry the discriminative depth — a first
# design using amplitude-ranked triples of colorless patterns trained at
# exactly chance (12 classes, 50+ steps, loss pinned at ln(C)), so the
# label space needs at least one low-level-salient factor to bootstrap.

_STATIONARY = 7
_HUE_BUCKETS = 5
_SECONDARY = 3
MAX_COMPOSITE = _STATIONARY * _HUE_BUCKETS * _SECONDARY      # 105

# --- extended composite classes (r4: the 1000-class parity run, VERDICT
# r3 #7 — reference hyperparameters include a 1000-way head,
# /root/reference/README.md:12) ------------------------------------------
#
# A fourth graded attribute and finer dominant-hue buckets lift the label
# space past 1000:
#   class = dominant family [7] × dominant hue [10] × secondary family [3]
#           × secondary hue [5]                                   (1050)
# The secondary pattern's color — random in the 105-class scheme — becomes
# the fourth class attribute. Hue jitter shrinks with the bucket width so
# adjacent buckets stay separable (dominant ±0.028 on 0.1-wide buckets,
# secondary ±0.055 on 0.2-wide). All four attributes remain crop/zoom/flip
# invariant, so the train pipeline cannot destroy the label signal.
_HUE_BUCKETS_EXT = 10
_SEC_HUE = 5
MAX_COMPOSITE_EXT = (_STATIONARY * _HUE_BUCKETS_EXT
                     * _SECONDARY * _SEC_HUE)                 # 1050


def _hsv_to_rgb(h, s, v):
    import colorsys
    return np.array(colorsys.hsv_to_rgb(h % 1.0, s, v), np.float32)


def render_composite(rng, size, cls):
    """Graded three-attribute composite rendering (see note above)."""
    d, rem = divmod(cls % MAX_COMPOSITE, _HUE_BUCKETS * _SECONDARY)
    h, g = divmod(rem, _SECONDARY)
    sec = (d + 1 + g) % _STATIONARY         # secondary family != dominant
    field = np.zeros((size, size), np.float32)
    for k, w in ((1, 0.40), (2, 0.25)):     # dominant at octaves 0-1
        field = field + w * _tiled(_FAMILIES[d], rng, size, k)
    sfield = _tiled(_FAMILIES[sec], rng, size, 4)   # secondary: fine octave
    field = (field - field.min()) / max(field.max() - field.min(), 1e-6)
    sfield = (sfield - sfield.min()) / max(sfield.max() - sfield.min(), 1e-6)
    # Dominant pattern colored in the class hue (jittered sat/val); the
    # secondary modulates brightness in a random color; background random.
    hue = h / _HUE_BUCKETS + rng.uniform(-0.05, 0.05)
    c_dom = _hsv_to_rgb(hue, rng.uniform(0.6, 1.0), rng.uniform(0.6, 1.0))
    c_bg = rng.uniform(0.05, 0.95, size=3).astype(np.float32)
    c_sec = rng.uniform(0.05, 0.95, size=3).astype(np.float32)
    img = (field[..., None] * c_dom
           + (1 - field[..., None]) * (0.65 * c_bg[None, None]
                                       + 0.35 * sfield[..., None] * c_sec))
    img = img + rng.normal(0, 0.04, img.shape)
    return Image.fromarray(
        (np.clip(img, 0, 1) * 255).astype(np.uint8), "RGB")


def render_composite_ext(rng, size, cls):
    """Four-attribute graded composite (see MAX_COMPOSITE_EXT note)."""
    d, rem = divmod(cls % MAX_COMPOSITE_EXT,
                    _HUE_BUCKETS_EXT * _SECONDARY * _SEC_HUE)
    h, rem = divmod(rem, _SECONDARY * _SEC_HUE)
    g, sh = divmod(rem, _SEC_HUE)
    sec = (d + 1 + g) % _STATIONARY         # secondary family != dominant
    field = np.zeros((size, size), np.float32)
    for k, w in ((1, 0.40), (2, 0.25)):     # dominant at octaves 0-1
        field = field + w * _tiled(_FAMILIES[d], rng, size, k)
    sfield = _tiled(_FAMILIES[sec], rng, size, 4)   # secondary: fine octave
    field = (field - field.min()) / max(field.max() - field.min(), 1e-6)
    sfield = (sfield - sfield.min()) / max(sfield.max() - sfield.min(), 1e-6)
    hue = h / _HUE_BUCKETS_EXT + rng.uniform(-0.028, 0.028)
    sec_hue = sh / _SEC_HUE + rng.uniform(-0.055, 0.055)
    c_dom = _hsv_to_rgb(hue, rng.uniform(0.6, 1.0), rng.uniform(0.6, 1.0))
    c_sec = _hsv_to_rgb(sec_hue, rng.uniform(0.6, 1.0), rng.uniform(0.6, 1.0))
    c_bg = rng.uniform(0.05, 0.95, size=3).astype(np.float32)
    img = (field[..., None] * c_dom
           + (1 - field[..., None]) * (0.65 * c_bg[None, None]
                                       + 0.35 * sfield[..., None] * c_sec))
    img = img + rng.normal(0, 0.04, img.shape)
    return Image.fromarray(
        (np.clip(img, 0, 1) * 255).astype(np.uint8), "RGB")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    # Default stays inside the stationary, crop-safe family set (indices
    # 0-6); radial/rings are opt-in via --classes 8/9; >9 switches to the
    # graded composite classes (up to 105).
    ap.add_argument("--classes", type=int, default=7)
    ap.add_argument("--train-per-class", type=int, default=200)
    ap.add_argument("--val-per-class", type=int, default=50)
    ap.add_argument("--size", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    composite = args.classes > len(_FAMILIES)
    if args.classes > MAX_COMPOSITE:
        assert args.classes <= MAX_COMPOSITE_EXT, \
            f"max {MAX_COMPOSITE_EXT} extended-composite classes"
        draw = render_composite_ext
    elif composite:
        draw = render_composite
    else:
        draw = render
    for split in ("train", "val"):
        d = os.path.join(args.root, split)
        if os.path.isdir(d) and os.listdir(d):
            # Refuse to mix generations: class-dir naming/count changes
            # would silently interleave old and new classes under the same
            # ImageFolder root, shifting every label.
            raise SystemExit(
                f"refusing to write into non-empty {d} — delete it first")

    rng = np.random.default_rng(args.seed)
    width = max(3, len(str(args.classes - 1)))   # lexical order == label order
    for split, per_class in (("train", args.train_per_class),
                             ("val", args.val_per_class)):
        for c in range(args.classes):
            d = os.path.join(args.root, split, f"class_{c:0{width}d}")
            os.makedirs(d, exist_ok=True)
            for i in range(per_class):
                draw(rng, args.size, c).save(
                    os.path.join(d, f"{i:05d}.jpg"), quality=88)
    n_train = args.classes * args.train_per_class
    n_val = args.classes * args.val_per_class
    print(f"wrote {n_train} train + {n_val} val JPEGs "
          f"({args.classes} classes, {'composite' if composite else 'base'}, "
          f"{args.size}px) under {args.root}")


if __name__ == "__main__":
    main()
