"""Guard-overhead A/B: the REAL trainer with and without ``--doctor``
(ISSUE 15 satellite): the sentinels' "free" claim — finiteness flags +
global grad norm fused into the compiled step, flags riding the async
metric drain — is measured, not asserted.

Runs ``python -m tpudist`` twice with identical configs — doctor ON
(in-step guard + EWMA monitor; probes left OFF so the A/B isolates the
per-step cost, the probe being an every-N-steps maintenance fetch) and
OFF — parses the steady-state step meter from each ``experiment.log``
(same parser as ``bench_prefetch``), and emits one JSON line per side
plus an overhead verdict. On TPU both sides append to
``benchmarks/results/bench_history.jsonl`` as their own ``images/sec``
series (``guard_on_...`` / ``guard_off_...``), so ``tpudist-regress``
gates the guarded step's cost round over round; off-TPU nothing is
appended (CPU step time is compute-bound noise for this question).

Usage: python benchmarks/bench_guard.py [--arch resnet18] [--batch 128]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import re
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# last per-step progress line of the train loop:
#   Epoch[0]:  [150/157]  Time 0.129 ( 0.141)  Data  0.010 ( 0.022)  ...
_LINE = re.compile(r"Epoch\[\d+\]:\s*\[\d+/(\d+)\]\s*"
                   r"Time\s*[\d.]+\s*\(\s*([\d.]+)\)\s*"
                   r"Data\s*[\d.]+\s*\(\s*([\d.]+)\)")


def _run_trainer(outpath: str, extra: list[str], timeout: float) -> dict:
    cmd = [sys.executable, "-m", "tpudist", "-p", "10",
           "--outpath", outpath, "--overwrite", "delete", "--telemetry"] \
        + extra
    print(f"[guard] {' '.join(cmd)}", file=sys.stderr, flush=True)
    subprocess.run(cmd, check=True, timeout=timeout, cwd=_REPO,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    log = open(os.path.join(outpath, "experiment.log")).read()
    m = None
    for m in _LINE.finditer(log):
        pass
    if m is None:
        raise SystemExit(f"no train progress line in {outpath}/experiment.log")
    out = {"steps_per_epoch": int(m.group(1)),
           "avg_step_s": float(m.group(2)),
           "avg_data_wait_s": float(m.group(3))}
    try:
        from tpudist.summarize import analyze, load_events
        a = analyze(load_events(outpath))
        b = a.get("budget") or {}
        for k in ("compute_s", "step_s"):
            if b.get(k):
                out[f"{k}_p50"] = round(b[k]["p50"], 6)
        # Any intervention in the A/B run means the comparison measured
        # response work, not steady-state guard cost — flag it.
        dc = a.get("doctor")
        out["interventions"] = dc["interventions"] if dc else 0
    except Exception as e:
        print(f"[guard] telemetry parse failed: {e!r}", file=sys.stderr)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="resnet18")
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--synthetic-size", type=int, default=0,
                    help="synthetic train-set size (0 = 20 batches)")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--outdir", default="")
    args = ap.parse_args()

    outdir = args.outdir or tempfile.mkdtemp(prefix="guard_")
    n = args.synthetic_size or args.batch * 20
    common = ["-a", args.arch, "--num-classes", str(args.num_classes),
              "--image-size", str(args.image_size), "-b", str(args.batch),
              "--epochs", str(args.epochs), "--lr", "0.01",
              "-j", str(args.workers), "--seed", "0",
              "--synthetic", "--synthetic-size", str(n)]

    sides = {}
    for side, flags in (("on", ["--doctor"]), ("off", ["--no-doctor"])):
        sides[side] = _run_trainer(os.path.join(outdir, side),
                                   common + flags, args.timeout)

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend()); "
             "print(jax.device_count())"],
            capture_output=True, text=True, timeout=120).stdout.split()
        platform = out[0] if out else "unknown"
        n_devices = int(out[1]) if len(out) > 1 else 1
    except Exception:
        platform, n_devices = "unknown", 1

    rows = []
    for side, r in sides.items():
        rows.append({
            "metric": (f"guard_{side}_{args.arch}_{args.image_size}"
                       f"_images_per_sec_{platform}"),
            "value": round(args.batch / r["avg_step_s"], 1),
            "unit": "images/sec",
            "per_device_batch": max(1, args.batch // n_devices),
            "avg_step_s": r["avg_step_s"],
            **{k: v for k, v in r.items()
               if k.endswith("_p50") or k == "interventions"},
        })
    verdict = {
        "metric": f"guard_ab_{args.arch}_{args.image_size}_b{args.batch}",
        "platform": platform,
        "on_images_per_sec": rows[0]["value"],
        "off_images_per_sec": rows[1]["value"],
        # Guarded-step overhead as a fraction of the unguarded step: the
        # acceptance bar is "within noise" — the regress gate holds the
        # guard_on series to the same ±threshold every series gets.
        "overhead": round(sides["on"]["avg_step_s"]
                          / max(sides["off"]["avg_step_s"], 1e-9) - 1.0, 4),
        "interventions_on": sides["on"].get("interventions", 0),
    }
    for row in rows + [verdict]:
        print(json.dumps(row), flush=True)

    if platform != "tpu":
        print("[guard] platform != tpu — rows NOT appended to bench "
              "history", file=sys.stderr)
        return 0
    from tpudist.regress import append_history
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    for row in rows:
        append_history({**row, "measured_at": now})
    print(f"[guard] {len(rows)} row(s) appended to bench history",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
