#!/bin/bash
# THE tunnel watcher (replaces the per-round tpu_watch_r3b..r10 copies):
# poll for a TPU; whenever it answers, run the next pending stage of the
# perfci manifest through `tpudist-perfci` — stage commands, timeouts,
# platform guards, corpus gates, history appends and the regression gate
# all live in benchmarks/perfci.json now, so a new capture round is a
# manifest edit (or TPUDIST_WATCH_STAGES), never a 13th copy of this file.
#
# Preserved semantics from the r* lineage:
#   - single-instance lock on fd 8 (r10's path, so an orphaned older
#     watcher and this one still exclude each other);
#   - capture lock on fd 9 (r5's path, shared with bench_zoo.sh), taken
#     with flock -w 600 ONLY around an actual stage run;
#   - stage children must not inherit either lock (8>&- 9>&-);
#   - TPU probe before every stage (jax.devices() happily returns CPU
#     without the tunnel plugin — exit-0 alone is NOT chip evidence);
#   - CPU-stamp rejection: a stage whose fresh series landed with a CPU
#     suffix is a failure, not a capture (the tunnel died mid-stage);
#   - TPUDIST_WATCH_SKIP="stage ..." pre-marks carried-done stages;
#   - MAX_TRIES per stage with 300 s backoff; corpus-gated stages wait
#     without burning a try (perfci reports them skipped_corpus).
#
# NOTE: tpu_watch_r11.sh is the currently ARMED watcher (tunnel down
# since 2026-08-02, its process holds every pending capture). It stays
# byte-frozen — bash reads a running script incrementally, so editing it
# into a wrapper could corrupt the armed instance mid-loop. Its stage
# list is exactly this manifest's; delete it once its window completes.
#
# Usage: benchmarks/tpu_watch.sh [manifest]
#   TPUDIST_WATCH_STAGES  space-separated stage order/subset override
#   TPUDIST_WATCH_SKIP    stages already captured this session
cd "$(dirname "$0")/.." || exit 1
MANIFEST=${1:-benchmarks/perfci.json}
LOG=benchmarks/results/tpu_watch.log
REPORT=benchmarks/results/perfci_report.json
MAX_TRIES=${TPUDIST_WATCH_MAX_TRIES:-3}

exec 8>/tmp/tpudist_watch_r10.instance.lock
if ! flock -n 8; then
  echo "[watch $(date -u +%FT%TZ)] another instance holds the lock — exiting" >> "$LOG"
  exit 1
fi
exec 9>/tmp/tpudist_watch_r5.lock

# Fail at arm time on a manifest typo, not at capture time.
if ! python -m tpudist.perfci --manifest "$MANIFEST" --dry-run >> "$LOG" 2>&1 8>&- 9>&-; then
  echo "[watch $(date -u +%FT%TZ)] manifest $MANIFEST invalid — see log" >> "$LOG"
  exit 2
fi
STAGES=${TPUDIST_WATCH_STAGES:-$(python -c "import json,sys; \
print(' '.join(st['name'] for st in json.load(open(sys.argv[1]))['stages']))" "$MANIFEST")}
echo "[watch $(date -u +%FT%TZ)] started (pid $$, manifest $MANIFEST, stages: $STAGES)" >> "$LOG"

declare -A TRIES DONE
for s in $STAGES; do TRIES[$s]=0; DONE[$s]=0; done
for s in ${TPUDIST_WATCH_SKIP:-}; do
  if [ -n "${DONE[$s]+x}" ]; then
    DONE[$s]=1
    echo "[watch $(date -u +%FT%TZ)] stage $s pre-marked done (TPUDIST_WATCH_SKIP)" >> "$LOG"
  else
    echo "[watch $(date -u +%FT%TZ)] unknown stage '$s' in TPUDIST_WATCH_SKIP — ignored" >> "$LOG"
  fi
done

stage_status() {  # status of the single stage in the last perfci report
  python -c "import json,sys; r=json.load(open('$REPORT')); \
print(r['stages'][0]['status'] if r['stages'] else 'failed')" 2>/dev/null || echo failed
}

cpu_stamped() {  # fresh series carrying a CPU suffix = tunnel died mid-stage
  python -c "import json,sys; r=json.load(open('$REPORT')); \
names=[m for st in r['stages'] for m in st.get('series',[])]; \
sys.exit(0 if any('cpu' in m for m in names) else 1)" 2>/dev/null
}

PROBES=0
while :; do
  PENDING=0
  for s in $STAGES; do [ "${DONE[$s]}" -eq 0 ] && PENDING=1; done
  [ $PENDING -eq 0 ] && break
  PROBES=$((PROBES + 1))
  if ! timeout 180 python -c "import jax; assert any(d.platform == 'tpu' for d in jax.devices())" >/dev/null 2>&1 8>&- 9>&-; then
    [ $((PROBES % 30)) -eq 0 ] && \
      echo "[watch $(date -u +%FT%TZ)] alive, tunnel still down (probe $PROBES)" >> "$LOG"
    sleep 120 8>&- 9>&-
    continue
  fi
  RAN_ONE=0
  for s in $STAGES; do
    [ "${DONE[$s]}" -ne 0 ] && continue
    RAN_ONE=1
    if ! flock -w 600 9; then
      echo "[watch $(date -u +%FT%TZ)] capture lock busy >600s (zoo run in flight?) — re-probing" >> "$LOG"
      break
    fi
    TRIES[$s]=$((TRIES[$s] + 1))
    echo "[watch $(date -u +%FT%TZ)] tunnel UP — stage $s (try ${TRIES[$s]})" >> "$LOG"
    python -m tpudist.perfci --manifest "$MANIFEST" --stages "$s" \
      --report "$REPORT" --platform tpu >> "$LOG" 2>&1 8>&- 9>&-
    RC=$?
    flock -u 9
    STATUS=$(stage_status)
    if [ "$STATUS" = "skipped_corpus" ] || [ "$STATUS" = "skipped_platform" ]; then
      # Not runnable yet: wait without burning a try (carried pending).
      TRIES[$s]=$((TRIES[$s] - 1))
      echo "[watch $(date -u +%FT%TZ)] stage $s $STATUS — carried pending" >> "$LOG"
    elif [ "$STATUS" = "ok" ] && [ $RC -le 1 ] && ! cpu_stamped; then
      # rc 1 = the regress gate tripped on an honestly-captured row: the
      # capture itself succeeded (the verdict is the news, not a retry).
      DONE[$s]=1
      echo "[watch $(date -u +%FT%TZ)] stage $s DONE (perfci rc=$RC)" >> "$LOG"
    else
      echo "[watch $(date -u +%FT%TZ)] stage $s failed (rc=$RC status=$STATUS)" >> "$LOG"
      [ "${TRIES[$s]}" -ge "$MAX_TRIES" ] && { DONE[$s]=2; echo "[watch] stage $s gave up" >> "$LOG"; }
      sleep 300 8>&- 9>&-
    fi
    break   # re-probe the tunnel between stages
  done
  [ $RAN_ONE -eq 0 ] && sleep 120 8>&- 9>&-
done
echo "[watch $(date -u +%FT%TZ)] all stages terminal: $(for s in $STAGES; do printf '%s=%s ' "$s" "${DONE[$s]}"; done)" >> "$LOG"
