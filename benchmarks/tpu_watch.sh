#!/bin/bash
# Poll the remote-TPU tunnel; when it answers, capture the round's fresh
# numbers (single-row bench -> persists last_tpu.json, then the four-row
# recipe table), then exit. The tunnel is known to flake for hours at a
# stretch (see benchmarks/results/README.md), so captures are opportunistic:
# run this in the background for the whole session.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
echo "[watch $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch $(date -u +%FT%TZ)] tunnel UP — capturing" >> "$LOG"
    OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 50 2>> "$LOG")
    RC=$?
    echo "$OUT" >> benchmarks/results/bench_tpu_fresh.jsonl
    echo "[watch $(date -u +%FT%TZ)] bench rc=$RC" >> "$LOG"
    # bench exits 0 for a stale re-emission too (the driver artifact must
    # never be empty-handed) — only a genuinely fresh capture ends the watch.
    if [ $RC -ne 0 ] || echo "$OUT" | grep -q '"stale": true'; then
      echo "[watch $(date -u +%FT%TZ)] capture was stale/failed — resuming poll" >> "$LOG"
      sleep 120
      continue
    fi
    timeout 2400 python benchmarks/recipe_table.py --steps 30 \
      >> benchmarks/results/recipe_tpu_fresh.jsonl 2>> "$LOG"
    echo "[watch $(date -u +%FT%TZ)] recipe_table rc=$?" >> "$LOG"
    exit 0
  fi
  echo "[watch $(date -u +%FT%TZ)] tunnel down" >> "$LOG"
  sleep 120
done
