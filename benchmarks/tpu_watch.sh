#!/bin/bash
# Poll the remote-TPU tunnel; when it answers, capture the round's fresh
# numbers (single-row bench -> persists last_tpu.json, then the four-row
# recipe table), then exit. The tunnel is known to flake for hours at a
# stretch (see benchmarks/results/README.md), so captures are opportunistic:
# run this in the background for the whole session.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
echo "[watch $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch $(date -u +%FT%TZ)] tunnel UP — capturing" >> "$LOG"
    OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 50 2>> "$LOG")
    RC=$?
    echo "$OUT" | tail -n 1 >> benchmarks/results/bench_tpu_fresh.jsonl
    echo "[watch $(date -u +%FT%TZ)] bench rc=$RC" >> "$LOG"
    # bench exits 0 for a stale re-emission too (the driver artifact must
    # never be empty-handed) — only a genuinely fresh capture ends the watch.
    if [ $RC -ne 0 ] || echo "$OUT" | tail -n 1 | grep -q '"stale": true'; then
      echo "[watch $(date -u +%FT%TZ)] capture was stale/failed — resuming poll" >> "$LOG"
      sleep 120
      continue
    fi
    timeout 2400 python benchmarks/recipe_table.py --steps 30 \
      >> benchmarks/results/recipe_tpu_fresh.jsonl 2>> "$LOG"
    echo "[watch $(date -u +%FT%TZ)] recipe_table rc=$?" >> "$LOG"
    # Per-device batch sweep (VERDICT r2 weak #2: 128 was never swept).
    # Same stale/CPU guard as the main capture: a mid-sweep tunnel drop must
    # not pollute the fresh-TPU log or grind out CPU rows until timeout.
    for b in 64 256 512; do
      OUT=$(timeout 900 python bench.py --probe-budget 120 --steps 30 \
        --per-device-batch "$b" 2>> "$LOG")
      RC=$?
      if [ $RC -ne 0 ] || echo "$OUT" | tail -n 1 | grep -qE '"stale": true|cpu_fallback'; then
        echo "[watch $(date -u +%FT%TZ)] sweep b=$b stale/failed (rc=$RC) — aborting sweep" >> "$LOG"
        break
      fi
      echo "$OUT" | tail -n 1 >> benchmarks/results/bench_tpu_fresh.jsonl
      echo "[watch $(date -u +%FT%TZ)] bench b=$b ok" >> "$LOG"
    done
    # Accuracy rehearsal (VERDICT r3 #8): reference recipe (b=1200 effective
    # via accumulation, lr 0.1, MultiStep [3,4], 5 epochs) on a 100-class
    # 224px procedural corpus, on the real chip.
    # Generate into a temp root and rename on success: a timeout mid-write
    # must not leave a partial corpus that later invocations silently reuse.
    if [ ! -d /tmp/rehearsal224/train ]; then
      echo "[watch $(date -u +%FT%TZ)] generating 224px rehearsal corpus" >> "$LOG"
      rm -rf /tmp/rehearsal224.partial
      if timeout 3000 python benchmarks/make_synth_imagefolder.py \
          --root /tmp/rehearsal224.partial --classes 100 --train-per-class 200 \
          --val-per-class 40 --size 224 --seed 3 >> "$LOG" 2>&1; then
        mv /tmp/rehearsal224.partial /tmp/rehearsal224
      else
        echo "[watch $(date -u +%FT%TZ)] corpus generation FAILED — skipping rehearsal" >> "$LOG"
        exit 0
      fi
    fi
    timeout 5400 python -m tpudist --data /tmp/rehearsal224 -a resnet18 \
      --num-classes 100 --image-size 224 -b 1200 --accum-steps 8 \
      --epochs 5 --step 3,4 --lr 0.1 -j 8 -p 5 --replica-check-freq 2 \
      --outpath runs/accuracy_rehearsal_r3_tpu --overwrite delete --seed 0 \
      >> "$LOG" 2>&1
    echo "[watch $(date -u +%FT%TZ)] rehearsal rc=$?" >> "$LOG"
    exit 0
  fi
  echo "[watch $(date -u +%FT%TZ)] tunnel down" >> "$LOG"
  sleep 120
done
