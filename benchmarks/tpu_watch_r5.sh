#!/bin/bash
# Round-5 capture chain (VERDICT r4 next #1/#8): poll the tunnel; whenever it
# answers, run the next pending stage in priority order. Changes vs r4:
#   - stem sides swapped: HEAD's default is now the DIRECT conv1 (the
#     measured configuration, VERDICT r4 weak #2), so `bench_fresh` measures
#     the canonical/default program and `s2d` is the opt-in A/B side.
#   - cheap stages front-loaded: the only observed window (r3) lasted
#     ~35 min, so the four ~3-15 min captures go before the hour-scale runs.
#   - corpus-gated stages (rehearsal, overlap, parity1000) skip in the
#     scheduler WITHOUT burning a retry while their corpus is absent
#     (ADVICE r4 #1 — r4 burned rehearsal tries on a missing directory).
# Stage order:
#   1 bench_fresh  canonical bench (direct stem == HEAD default; persists the
#                  record the provisional fallback re-emits; ~3 min)
#   2 s2d         space-to-depth stem A/B side (decides the default, ~3 min)
#   3 remat        remat A/B (~3 min)
#   4 recipe       4-row recipe table refresh (~15 min)
#   5 overlap      real-data vs synthetic step time + input_stall_pct
#                  (VERDICT r4 missing #4; needs /tmp/rehearsal224)
#   6 rehearsal    5-epoch 224px/100-class Trainer.fit through the real
#                  loader (VERDICT r4 missing #3; needs /tmp/rehearsal224)
#   7 flash        long-context proof + block sweep (ViT compile over the
#                  tunnel can take >15 min — late for window-risk reasons)
#   8 parity1000   5-epoch 1000-class run at reference hyperparameters
#                  (VERDICT r4 missing #1; needs /tmp/parity1000; ~2 h)
# Each stage gets MAX_TRIES attempts with 300 s backoff: a deterministic
# failure must not hot-loop scarce chip time; a mid-run tunnel drop gets
# retried. Stages append to benchmarks/results/*; the session (or, after it
# ends, the driver's end-of-round commit) picks the artifacts up.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
FRESH=benchmarks/results/bench_tpu_fresh.jsonl
MAX_TRIES=3
# Single-instance guard (code-review r5): the tunnel serves ONE client —
# two watchers would contend for it mid-capture and duplicate stage rows.
# Split locks (ADVICE r5 #3): the instance guard lives on its own file and
# is held for the watcher's lifetime; the shared CAPTURE lock
# (/tmp/tpudist_watch_r5.lock, fd 9 — the file bench_zoo.sh flocks) is
# taken only AROUND run_stage() below, so zoo rows are reachable during
# the watcher's tunnel-down sleeps and between stages.
exec 8>/tmp/tpudist_watch_r5.instance.lock
if ! flock -n 8; then
  echo "[watch-r5 $(date -u +%FT%TZ)] another instance holds the lock — exiting" >> "$LOG"
  exit 1
fi
exec 9>/tmp/tpudist_watch_r5.lock
echo "[watch-r5 $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"

declare -A TRIES DONE
STAGES="bench_fresh s2d remat recipe overlap rehearsal flash parity1000"
for s in $STAGES; do TRIES[$s]=0; DONE[$s]=0; done
# TPUDIST_WATCH_SKIP: space-separated stages already captured this session
# (e.g. by an attended run) — marked done at start so a relaunch mid-round
# doesn't spend scarce window time re-measuring landed rows.
for s in ${TPUDIST_WATCH_SKIP:-}; do
  if [ -n "${DONE[$s]+x}" ]; then
    DONE[$s]=1
    echo "[watch-r5 $(date -u +%FT%TZ)] stage $s pre-marked done (TPUDIST_WATCH_SKIP)" >> "$LOG"
  else
    echo "[watch-r5 $(date -u +%FT%TZ)] unknown stage '$s' in TPUDIST_WATCH_SKIP — ignored" >> "$LOG"
  fi
done

corpus_for() {  # stage -> required corpus dir ("" = none)
  case $1 in
    rehearsal|overlap) echo /tmp/rehearsal224/train ;;
    parity1000)        echo /tmp/parity1000/train ;;
    *)                 echo "" ;;
  esac
}

bench_capture() {  # $1 = extra bench args, $2 = stage name
  local OUT RC LAST
  OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 50 $1 2>> "$LOG")
  RC=$?
  LAST=$(echo "$OUT" | tail -n 1)
  if [ $RC -eq 0 ] && [ -n "$LAST" ] \
      && ! echo "$LAST" | grep -qE '"stale": true|cpu_fallback'; then
    # Only genuinely-fresh lines enter the fresh artifact: a stale-fallback
    # or empty line appended here (r4 behavior) would pollute it with
    # duplicate stale records across the MAX_TRIES retries.
    echo "$LAST" >> "$FRESH"
    echo "[watch-r5 $(date -u +%FT%TZ)] $2 ok: $LAST" >> "$LOG"
    return 0
  fi
  echo "[watch-r5 $(date -u +%FT%TZ)] $2 stale/failed (rc=$RC): $LAST" >> "$LOG"
  return 1
}

jsonl_capture() {  # $1 = stage, $2 = output file, rest = one or more
                   # ;-separated commands (run in order into ONE temp file)
  # Non-bench JSONL stages (code-review r5): exit 0 alone is NOT success —
  # the tunnel can die between the watcher's probe and the tool's in-process
  # jax init, silently landing the run on CPU. Capture to a temp file, admit
  # the rows only if none are CPU-stamped; multi-command stages admit all
  # rows or none (a half-captured stage would duplicate rows on retry).
  # CPU signatures: a "platform" JSON field, bench_flash's metric-name
  # "_cpu" suffix, and its interpreter-mode fallback note.
  local STAGE=$1 OUTFILE=$2 TMP; shift 2
  TMP=$(mktemp)
  local -a CMD=()
  local TOK RC=0
  for TOK in "$@" ";"; do
    if [ "$TOK" = ";" ]; then
      [ ${#CMD[@]} -eq 0 ] && continue
      if ! "${CMD[@]}" >> "$TMP" 2>> "$LOG"; then RC=1; break; fi
      CMD=()
    else
      CMD+=("$TOK")
    fi
  done
  if [ $RC -ne 0 ]; then rm -f "$TMP"; return 1; fi
  if grep -qE '"platform": *"cpu"|_cpu"|interpreter mode' "$TMP"; then
    echo "[watch-r5 $(date -u +%FT%TZ)] $STAGE landed on CPU — rejecting" >> "$LOG"
    rm -f "$TMP"
    return 1
  fi
  cat "$TMP" >> "$OUTFILE"
  rm -f "$TMP"
}

run_stage() {  # $1 = stage name; returns 0 on success
  case $1 in
    bench_fresh) bench_capture "" bench_fresh ;;
    s2d)   bench_capture --s2d s2d ;;
    remat) bench_capture --remat remat ;;
    recipe)
      jsonl_capture recipe benchmarks/results/recipe_tpu_fresh.jsonl \
        timeout 3600 python benchmarks/recipe_table.py --steps 30 ;;
    overlap)
      jsonl_capture overlap benchmarks/results/input_overlap_r5.jsonl \
        timeout 3600 python benchmarks/bench_input_overlap.py \
        --data /tmp/rehearsal224 --num-classes 100 --batch 128 --workers 4 \
        --outdir runs/input_overlap_r5_tpu ;;
    rehearsal)
      # --require-platform tpu: a CPU-fallback init exits nonzero instead of
      # permanently marking this scarce on-chip capture done.
      timeout 3600 python -m tpudist --data /tmp/rehearsal224 -a resnet18 \
        --num-classes 100 --image-size 224 -b 1200 --accum-steps 8 \
        --epochs 5 --step 3,4 --lr 0.1 -j 4 -p 5 --replica-check-freq 2 \
        --require-platform tpu \
        --outpath runs/accuracy_rehearsal_r5_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
    flash)
      jsonl_capture flash benchmarks/results/flash_r5_tpu.jsonl \
        timeout 2400 python benchmarks/bench_flash.py --steps 10 \
        --long-context 16384 \
        ";" \
        timeout 2400 python benchmarks/bench_flash.py --steps 10 \
        --sweep-blocks ;;
    parity1000)
      timeout 7200 python -m tpudist --data /tmp/parity1000 -a resnet18 \
        --num-classes 1000 --image-size 224 -b 1200 --accum-steps 8 \
        --epochs 5 --step 3,4 --lr 0.1 -j 4 -p 10 \
        --require-platform tpu \
        --outpath runs/accuracy_parity_r5_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
  esac
}

PROBES=0
while :; do
  PENDING=0
  for s in $STAGES; do [ "${DONE[$s]}" -eq 0 ] && PENDING=1; done
  [ $PENDING -eq 0 ] && break
  # 180 s probe: under co-runner CPU load (the parity CPU run), jax import +
  # tunnel handshake can exceed 90 s even with the tunnel UP — missing a
  # scarce window to contention would be worse than a slow poll.
  PROBES=$((PROBES + 1))
  # 8>&- 9>&- : probe children must NOT inherit either lock — an orphaned
  # probe outliving a killed watcher would block the replacement's flock.
  if ! timeout 180 python -c "import jax; jax.devices()" >/dev/null 2>&1 8>&- 9>&-; then
    [ $((PROBES % 30)) -eq 0 ] && \
      echo "[watch-r5 $(date -u +%FT%TZ)] alive, tunnel still down (probe $PROBES)" >> "$LOG"
    sleep 120 8>&- 9>&-
    continue
  fi
  RAN_ONE=0
  for s in $STAGES; do
    [ "${DONE[$s]}" -ne 0 ] && continue
    # corpus-gated stages: skip (without burning a try) until corpus exists
    C=$(corpus_for "$s")
    if [ -n "$C" ] && [ ! -d "$C" ]; then continue; fi
    RAN_ONE=1
    # Capture lock held only around the stage (ADVICE r5 #3): wait out a
    # zoo capture in flight; a longer wait means the window is contended —
    # re-probe WITHOUT burning one of the stage's tries.
    if ! flock -w 600 9; then
      echo "[watch-r5 $(date -u +%FT%TZ)] capture lock busy >600s (zoo run in flight?) — re-probing" >> "$LOG"
      break
    fi
    TRIES[$s]=$((TRIES[$s] + 1))
    echo "[watch-r5 $(date -u +%FT%TZ)] tunnel UP — stage $s (try ${TRIES[$s]})" >> "$LOG"
    if run_stage "$s" 8>&- 9>&-; then  # stages must not inherit the locks
      flock -u 9
      DONE[$s]=1
      echo "[watch-r5 $(date -u +%FT%TZ)] stage $s DONE" >> "$LOG"
    else
      RC=$?
      flock -u 9
      echo "[watch-r5 $(date -u +%FT%TZ)] stage $s failed (rc=$RC)" >> "$LOG"
      [ "${TRIES[$s]}" -ge "$MAX_TRIES" ] && { DONE[$s]=2; echo "[watch-r5] stage $s gave up" >> "$LOG"; }
      sleep 300 8>&- 9>&-
    fi
    break   # re-probe the tunnel between stages
  done
  # nothing runnable (every pending stage corpus-gated on a missing corpus)
  [ $RAN_ONE -eq 0 ] && sleep 120 8>&- 9>&-
done
echo "[watch-r5 $(date -u +%FT%TZ)] all stages terminal: $(for s in $STAGES; do printf '%s=%s ' "$s" "${DONE[$s]}"; done)" >> "$LOG"
