#!/bin/bash
# Fifth capture stage: quantify --remat's HBM/throughput trade on the chip.
# A/B at per-device batch 512 against the morning's non-remat sweep row
# (8288 img/s, 4.59 GB peak HBM). Chains after r3d; capped retries.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
MAX_TRIES=3
TRIES=0
echo "[watch-r3e $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"
while pgrep -f "tpu_watch_r3[bcd].sh" > /dev/null; do
  sleep 120
done
echo "[watch-r3e $(date -u +%FT%TZ)] r3b-d done — waiting for tunnel" >> "$LOG"
while [ "$TRIES" -lt "$MAX_TRIES" ]; do
  if ! timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    sleep 120
    continue
  fi
  TRIES=$((TRIES + 1))
  echo "[watch-r3e $(date -u +%FT%TZ)] tunnel UP — remat HBM A/B (try $TRIES)" >> "$LOG"
  OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 30 \
    --per-device-batch 512 --remat 2>> "$LOG")
  RC=$?
  echo "$OUT" | tail -n 1 >> benchmarks/results/bench_tpu_fresh.jsonl
  if [ $RC -eq 0 ] && ! echo "$OUT" | tail -n 1 | grep -qE '"stale": true|cpu_fallback'; then
    echo "[watch-r3e $(date -u +%FT%TZ)] remat bench ok: $OUT" >> "$LOG"
    exit 0
  fi
  echo "[watch-r3e $(date -u +%FT%TZ)] remat bench stale/failed (rc=$RC) — backoff" >> "$LOG"
  sleep 300
done
echo "[watch-r3e $(date -u +%FT%TZ)] gave up after $MAX_TRIES tries" >> "$LOG"
