#!/bin/bash
# Fourth capture stage: A/B the space-to-depth ResNet stem (commit ed5539b)
# against the morning's pre-s2d capture (8145.6 img/s, 15.71 ms/step,
# MFU 0.412) on the same canonical workload. Chains after r3c; capped
# retries like the other stages.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
MAX_TRIES=3
TRIES=0
echo "[watch-r3d $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"
while pgrep -f "tpu_watch_r3[bc].sh" > /dev/null; do
  sleep 120
done
echo "[watch-r3d $(date -u +%FT%TZ)] r3b/r3c done — waiting for tunnel" >> "$LOG"
while [ "$TRIES" -lt "$MAX_TRIES" ]; do
  if ! timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    sleep 120
    continue
  fi
  TRIES=$((TRIES + 1))
  echo "[watch-r3d $(date -u +%FT%TZ)] tunnel UP — s2d-stem bench A/B (try $TRIES)" >> "$LOG"
  OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 50 2>> "$LOG")
  RC=$?
  echo "$OUT" | tail -n 1 >> benchmarks/results/bench_tpu_fresh.jsonl
  if [ $RC -eq 0 ] && ! echo "$OUT" | tail -n 1 | grep -qE '"stale": true|cpu_fallback'; then
    echo "[watch-r3d $(date -u +%FT%TZ)] s2d bench ok: $OUT" >> "$LOG"
    exit 0
  fi
  echo "[watch-r3d $(date -u +%FT%TZ)] s2d bench stale/failed (rc=$RC) — backoff" >> "$LOG"
  sleep 300
done
echo "[watch-r3d $(date -u +%FT%TZ)] gave up after $MAX_TRIES tries" >> "$LOG"
