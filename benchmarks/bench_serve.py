"""Serving load-test harness: open-loop rate sweep → latency/throughput
curve artifact + gateable bench-history series (ISSUE 14 tentpole (d)).

Drives a ``ServeEngine`` + ``ContinuousBatcher`` with synthetic Poisson
arrivals at each swept rate (OPEN loop: submission is independent of
completion, so saturation shows up as latency growth, not silently
throttled offered load) and writes:

- a curve artifact (``benchmarks/results/serve_curve_<arch>_<plat>.json``:
  one row per rate — offered vs achieved req/s, p50/p99 latency, batch
  occupancy) — the latency/throughput curve;
- ``bench_history.jsonl`` series ``tpudist-regress`` gates in the correct
  directions: per-rate p99 rows (``unit: ms`` — regress UPWARD) and ONE
  saturation row (``unit: req/s``, the max achieved completion rate across
  the sweep — regress DOWNWARD);
- the AOT cold-start numbers (``aot_s`` / ``aot_compile_s`` / cache
  provenance) embedded in the artifact, so the warm-vs-cold startup claim
  rides the same file.

Metric names embed arch, image size, rate, and PLATFORM (a CPU sweep can
never gate TPU history — same convention as every other bench). Weights
are fresh-init: serving performance does not depend on their values, and
a checkpoint requirement would couple the perf harness to a training run.

Usage::

    python benchmarks/bench_serve.py --arch resnet18 --rates 5,10,20,40
    python benchmarks/bench_serve.py --regress-strict   # CI: exit 2 on gate
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--arch", default="resnet18")
    p.add_argument("--image-size", type=int, default=224, dest="image_size")
    p.add_argument("--num-classes", type=int, default=1000,
                   dest="num_classes")
    p.add_argument("--buckets", default="1,2,4,8")
    p.add_argument("--rates", default="5,10,20,40",
                   help="comma-separated offered request rates (req/s) to "
                        "sweep, low to high")
    p.add_argument("--duration", type=float, default=5.0,
                   help="seconds of open-loop load per rate point")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   dest="max_wait_ms")
    p.add_argument("--compile-cache", default="", dest="compile_cache")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="",
                   help="curve artifact path (default: benchmarks/results/"
                        "serve_curve_<arch>_<platform>.json)")
    p.add_argument("--no-history", action="store_true", dest="no_history",
                   help="skip bench_history.jsonl appends (exploratory "
                        "runs)")
    p.add_argument("--regress-strict", action="store_true",
                   dest="regress_strict",
                   help="exit 2 when any appended series trips the "
                        "regression gate")
    args = p.parse_args(argv)

    from tpudist.serve.batching import (ContinuousBatcher, open_loop_load,
                                        parse_buckets)
    from tpudist.serve.cache import configure_compile_cache, resolve_cache_dir
    buckets = parse_buckets(args.buckets)
    rates = [float(r) for r in args.rates.split(",") if r]
    if not rates:
        p.error("--rates needs at least one rate")
    cache_dir = resolve_cache_dir(args.compile_cache)
    cache = configure_compile_cache(cache_dir) if cache_dir else "off"

    import jax
    import numpy as np
    from tpudist.serve.engine import ServeEngine
    from tpudist.serve.export import load_serve_state
    from tpudist.telemetry import percentile

    plat = jax.default_backend()
    model, variables = load_serve_state(
        args.arch, num_classes=args.num_classes,
        image_size=args.image_size, max_batch=buckets[-1], seed=args.seed,
        log=lambda m: print(m, flush=True))
    engine = ServeEngine(model, variables, image_size=args.image_size,
                         buckets=buckets, cache=cache,
                         log=lambda m: print(m, flush=True))

    shape = (1, args.image_size, args.image_size, 3)

    def make_images(rng):
        return rng.standard_normal(shape).astype(np.float32)

    import time
    curve = []
    for rate in rates:
        batcher = ContinuousBatcher(engine,
                                    max_wait_s=args.max_wait_ms / 1e3)
        t0 = time.perf_counter()
        results = open_loop_load(batcher, rate, args.duration, make_images,
                                 seed=args.seed)
        span = time.perf_counter() - t0
        batcher.close()
        errs = [r for r in results if r.error is not None]
        if errs:
            # open_loop_load completes errored futures instead of raising
            # (so the serving CLI can shut down cleanly); for the BENCH a
            # failed request invalidates the measurement — refuse to
            # write a curve over failures.
            print(f"[bench_serve] {len(errs)}/{len(results)} requests "
                  f"errored at rate {rate:g} (first: {errs[0].error!r}) — "
                  f"a latency curve over failing requests is not a "
                  f"measurement; aborting", flush=True)
            return 1
        lats = sorted(r.latency_s for r in results)
        occ = (sum(i["n_valid"] / i["bucket"] for i in engine.last_info)
               / max(len(engine.last_info), 1))
        row = {
            "rate": rate,
            "n_requests": len(results),
            "achieved_req_s": round(len(results) / max(span, 1e-9), 2),
            "p50_ms": round(percentile(lats, 50) * 1e3, 3),
            "p99_ms": round(percentile(lats, 99) * 1e3, 3),
            "occupancy_last": round(occ, 4),
        }
        curve.append(row)
        print(f"[bench_serve] rate {rate:g} req/s: achieved "
              f"{row['achieved_req_s']:g}, p50 {row['p50_ms']:.1f} ms, "
              f"p99 {row['p99_ms']:.1f} ms", flush=True)

    saturation = max(r["achieved_req_s"] for r in curve)
    measured_at = datetime.datetime.now(
        datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
    artifact = {
        "arch": args.arch, "image_size": args.image_size,
        "buckets": list(buckets), "platform": plat,
        "device_kind": jax.devices()[0].device_kind,
        "duration_per_rate_s": args.duration,
        "aot_s": round(engine.aot_s, 3),
        "aot_compile_s": round(engine.aot_compile_s, 3),
        "compile_cache": cache,
        "curve": curve,
        "saturation_req_s": saturation,
        "measured_at": measured_at,
    }
    out_path = args.out or os.path.join(
        _REPO, "benchmarks", "results",
        f"serve_curve_{args.arch}_{plat}.json")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"[bench_serve] wrote curve artifact {out_path}", flush=True)

    rc = 0
    if not args.no_history:
        from tpudist.regress import (analyze_history, append_history,
                                     format_verdict, history_path,
                                     load_history)
        base = f"serve_{args.arch}_{args.image_size}px"
        rows = []
        for r in curve:
            # Per-rate latency series: unit ms → the gate regresses UPWARD.
            rows.append({
                "metric": f"{base}_r{r['rate']:g}_p99_ms_{plat}",
                "unit": "ms", "value": r["p99_ms"],
                "per_device_batch": buckets[-1],
                "achieved_req_s": r["achieved_req_s"],
                "p50_ms": r["p50_ms"], "measured_at": measured_at,
            })
        # THE saturation row: highest achieved completion rate across the
        # sweep; unit req/s → the gate regresses DOWNWARD (value drop).
        rows.append({
            "metric": f"{base}_sat_req_s_{plat}", "unit": "req/s",
            "value": saturation, "per_device_batch": buckets[-1],
            "aot_s": round(engine.aot_s, 3), "compile_cache": cache,
            "measured_at": measured_at,
        })
        hist = history_path()
        for row in rows:
            append_history(row, hist)
            # Echo the row as a JSONL line: the tunnel watcher captures
            # stdout and its CPU-fallback check greps the platform-stamped
            # metric names.
            print(json.dumps(row), flush=True)
        for row in rows:
            v = analyze_history(load_history(hist), metric=row["metric"])
            print("[bench_serve] " + format_verdict(v), flush=True)
            if v["status"] == "regression":
                rc = 2
    else:
        # --no-history runs (the watcher's warm-cache pass) still need a
        # platform-stamped JSONL line for the capture file.
        print(json.dumps({"serve_curve": out_path, "platform": plat,
                          "saturation_req_s": saturation,
                          "aot_s": round(engine.aot_s, 3),
                          "aot_compile_s": round(engine.aot_compile_s, 3),
                          "compile_cache": cache,
                          "measured_at": measured_at}), flush=True)
    print("SERVE_BENCH_OK" if rc == 0 else "SERVE_BENCH_REGRESSION",
          flush=True)
    return rc if args.regress_strict else 0


if __name__ == "__main__":
    sys.exit(main())
