"""Gradient-communication microbenchmark: int8-compressed vs dense gradient
exchange, and ZeRO-full vs ZeRO-1 state placement (ISSUE 11 tentpole: the
A/B evidence behind ``--compress-grads auto`` and ``--zero full``).

Two stages:

- ``--compress-ab`` (default): times ONE gradient reduction — dense
  ``lax.pmean`` vs the quantized two-phase exchange — at the canonical
  model gradient sizes (resnet18 / resnet50 / vit_b_16 parameter counts)
  over the full attached mesh. The workload pair comes from
  ``ops/comm_dispatch.build_measure_fns`` and the timing from the shared
  dispatch harness (``ops/dispatch.measure_ms``), so bench rows and
  ``--compress-grads auto`` verdicts measure the same exchange by
  construction. Each int8/dense pair carries the dispatch verdict derived
  from the row's own timings; on TPU the verdict also lands in the
  dispatch cache (a bench run doubles as an ``auto`` cache warm) and every
  numeric row is appended to ``bench_history.jsonl`` as its own gateable
  ``unit: ms`` series — plus the census collective bytes of both compiled
  exchanges, so ``tpudist-regress`` gates the byte claim, not just the
  time.

- ``--zerofull-ab``: compiles one resnet18 train step per ZeRO mode
  (off / 1 / full) on the attached mesh and reports per-device STATE
  bytes (sharding-aware: what each device actually holds) and the step's
  collective census — the memory-vs-comms trade ``--zero full`` makes,
  as data. Step-time rows append on TPU only.

Off-TPU nothing is appended or cached: CPU collective timings say nothing
about ICI (the exchange itself still runs — it is plain jnp — which is
what the CPU parity tests use).

Usage: python benchmarks/bench_comm.py [--compress-ab|--zerofull-ab]
       [--steps N] [--sizes n1,n2,...]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Canonical gradient sizes: total trainable element counts of the zoo's
# headline archs (what --compress-grads actually reduces every step).
GRAD_SIZES = {
    "resnet18": 11_689_512,
    "resnet50": 25_557_032,
    "vit_b_16": 86_567_656,
}


def _census(lowered_compiled) -> dict:
    from tpudist.obs.xla_introspect import hlo_op_census
    c = hlo_op_census(lowered_compiled.as_text())
    return {
        "collective_bytes_per_step": sum(v["bytes"]
                                         for v in c["collectives"].values()),
        "collective_link_bytes": sum(c["link_bytes"].values()),
        "all_reduce_bytes": c["collectives"].get(
            "all-reduce", {}).get("bytes", 0),
    }


def compress_ab(steps: int, sizes: list[tuple[str, int]]) -> bool:
    import jax
    from tpudist.ops import comm_dispatch
    from tpudist.ops.dispatch import measure_ms
    from tpudist.parallel.comm import DEFAULT_CHUNK
    from tpudist.dist import make_mesh
    from tpudist.regress import append_history

    platform = jax.default_backend()
    mesh = make_mesh((jax.device_count(),), ("data",))
    world = mesh.shape["data"]
    failed = False
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    for name, n in sizes:
        int8_fn, dense_fn, fargs = comm_dispatch.build_measure_fns(
            n, mesh, "data", DEFAULT_CHUNK)
        rows_out = {}
        for label, fn in (("int8", int8_fn), ("dense", dense_fn)):
            row = {"metric": f"commreduce_{name}_{label}_w{world}_ms_"
                             f"{platform}",
                   "unit": "ms", "n_grads": n, "world": world,
                   "dense_bytes": 4 * n}
            try:
                row["value"] = round(measure_ms(fn, fargs, steps,
                                                warmup=3), 3)
            except Exception as e:
                row["value"] = None
                row["error"] = f"{type(e).__name__}: {e}"[:200]
                failed = True
            rows_out[label] = row
        # Census of both compiled exchanges: the byte claim as data on the
        # row, gateable by tpudist-regress (bytes regress UPWARD).
        try:
            import jax.numpy as jnp  # noqa: F401
            # Per-workload A/B sweep: each gradient size IS a distinct
            # program; the jit exists to census exactly one of them.
            i_c = jax.jit(lambda: int8_fn()).lower().compile()  # tpudist: ignore[RECOMP01] — one program per benched workload, censused then discarded
            d_c = jax.jit(lambda: dense_fn()).lower().compile()  # tpudist: ignore[RECOMP01] — one program per benched workload, censused then discarded
            rows_out["int8"].update(_census(i_c))
            rows_out["dense"].update(_census(d_c))
        except Exception as e:
            print(f"[bench_comm] census failed: {e!r}", file=sys.stderr)
        ir, dr = rows_out["int8"], rows_out["dense"]
        if ir.get("value") is not None and dr.get("value") is not None:
            try:
                dec = comm_dispatch.decide(
                    n, world, mode="auto", chunk=DEFAULT_CHUNK,
                    platform=platform, refresh=True,
                    measure_pair=lambda: (ir["value"], dr["value"]))
                disp = {"kernel": dec["kernel"], "source": dec["source"],
                        "int8_ms": ir["value"], "dense_ms": dr["value"]}
                ir["dispatch"] = disp
                dr["dispatch"] = disp
            except Exception as e:
                print(f"[bench_comm] dispatch verdict failed: {e!r}",
                      file=sys.stderr)
        for row in rows_out.values():
            print(json.dumps(row), flush=True)
        if platform != "tpu":
            continue
        for row in rows_out.values():
            if isinstance(row.get("value"), (int, float)):
                append_history({**row, "measured_at": now})
    if platform != "tpu":
        print("[bench_comm] platform != tpu — rows NOT appended to bench "
              "history (CPU collective timings are not measurements)",
              file=sys.stderr)
    return failed


def zerofull_ab(steps: int, batch: int) -> bool:
    import jax
    import jax.numpy as jnp
    from tpudist.config import Config
    from tpudist.dist import make_mesh, shard_host_batch
    from tpudist.models import create_model
    from tpudist.ops.dispatch import measure_ms
    from tpudist.parallel import (make_gspmd_train_step, make_wus_train_step,
                                  shard_tree)
    from tpudist.regress import append_history
    from tpudist.train import (compute_dtype, create_train_state,
                               make_train_step)

    platform = jax.default_backend()
    n_dev = jax.device_count()
    mesh = make_mesh((n_dev,), ("data",))
    cfg = Config(arch="resnet18", num_classes=1000, image_size=224,
                 batch_size=batch * n_dev, use_amp=True, seed=0)
    cfg.finalize(n_dev)
    model = create_model(cfg.arch, num_classes=cfg.num_classes,
                         dtype=compute_dtype(cfg))
    state0 = create_train_state(jax.random.PRNGKey(0), model, cfg)
    rng = np.random.default_rng(0)
    images = rng.standard_normal(
        (cfg.batch_size, 224, 224, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, size=(cfg.batch_size,)).astype(np.int32)
    im, lb = shard_host_batch(mesh, (images, labels))
    lr = jnp.float32(0.1)

    def device_state_bytes(tree) -> int:
        tot = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "addressable_shards"):
                sh = leaf.addressable_shards[0]
                tot += int(np.prod(sh.data.shape)) * leaf.dtype.itemsize
            elif hasattr(leaf, "nbytes"):
                tot += int(leaf.nbytes)
        return tot

    modes = {
        "off": (state0, make_train_step(mesh, model, cfg)),
        "zero1": (shard_tree(mesh, state0, (), opt_shard_axis="data"),
                  make_gspmd_train_step(mesh, model, cfg, (),
                                        opt_shard_axis="data")),
        "zerofull": (shard_tree(mesh, state0, (), opt_shard_axis="data",
                                zero_mode="full"),
                     make_wus_train_step(mesh, model, cfg)),
    }
    failed = False
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    for name, (st, step) in modes.items():
        row = {"metric": f"zero_{name}_step_b{batch}_{n_dev}dev_ms_"
                         f"{platform}",
               "unit": "ms", "per_device_batch": batch,
               "state_bytes_per_device": device_state_bytes(
                   {"params": st.params, "opt": st.opt_state})}
        try:
            lowered = step.lower(st, im, lb, lr) if hasattr(step, "lower") \
                else None
            if lowered is not None:
                row.update(_census(lowered.compile()))
            # The steps donate their state buffers: thread the returned
            # state through the timing loop instead of re-feeding a
            # donated-away array.
            holder = {"st": st}

            def one_step():
                holder["st"], m = step(holder["st"], im, lb, lr)
                return m

            row["value"] = round(measure_ms(one_step, (), steps, warmup=2),
                                 3)
        except Exception as e:
            row["value"] = None
            row["error"] = f"{type(e).__name__}: {e}"[:200]
            failed = True
        print(json.dumps(row), flush=True)
        if platform == "tpu" and isinstance(row.get("value"), (int, float)):
            append_history({**row, "measured_at": now})
    if platform != "tpu":
        print("[bench_comm] platform != tpu — rows NOT appended",
              file=sys.stderr)
    return failed


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--compress-ab", action="store_true", dest="compress_ab")
    ap.add_argument("--zerofull-ab", action="store_true", dest="zerofull_ab")
    ap.add_argument("--sizes", default="",
                    help="comma-separated gradient element counts "
                         "(default: the resnet18/resnet50/vit_b_16 zoo "
                         "sizes)")
    args = ap.parse_args()

    if args.sizes:
        sizes = [(f"n{s}", int(s)) for s in args.sizes.split(",") if s]
    else:
        sizes = sorted(GRAD_SIZES.items(), key=lambda kv: kv[1])
    failed = False
    if args.compress_ab or not args.zerofull_ab:
        failed |= compress_ab(args.steps, sizes)
    if args.zerofull_ab:
        failed |= zerofull_ab(args.steps, args.batch)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
