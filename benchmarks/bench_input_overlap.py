"""Input-pipeline overlap measurement on the live backend (VERDICT r3 #4).

The open question it answers: can the loader feed the chip? The native fused
JPEG path decodes ~731 img/s/core while the chip consumes ~8,146 img/s
(canonical bench), so a 1-core host cannot saturate it — but the *overlap*
accounting (how much of a step is spent blocked on input vs computing) is
measurable on any host and validates the per-core extrapolation to a real
v5e host (>100 vCPUs, cf. the reference's 8 pinned DataLoader workers,
/root/reference/distributed.py:168-169).

Method: run the REAL trainer twice through ``python -m tpudist`` — once on a
real JPEG ImageFolder corpus, once on synthetic in-memory data with identical
shapes — and parse the train-loop meters from each run's ``experiment.log``
(``Time c (avg)  Data c (avg)`` — data_time is the blocked-on-input wait,
trainer.py:500). Emits ONE JSON line:

  real_images_per_sec, synth_images_per_sec, input_stall_pct
  (= avg data wait / avg step time on the real run), avg step times, and
  the real/synth step-time ratio (1.0 = full overlap, loader invisible).

Usage: python benchmarks/bench_input_overlap.py [--data /tmp/rehearsal224]
       [--num-classes 100] [--batch 128] [--epochs 1]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# last per-step progress line of the train loop:
#   Epoch[0]:  [150/157]  Time 0.129 ( 0.141)  Data  0.010 ( 0.022)  ...
_LINE = re.compile(r"Epoch\[\d+\]:\s*\[\d+/(\d+)\]\s*"
                   r"Time\s*[\d.]+\s*\(\s*([\d.]+)\)\s*"
                   r"Data\s*[\d.]+\s*\(\s*([\d.]+)\)")


def _run_trainer(outpath: str, extra: list[str], timeout: float) -> dict:
    cmd = [sys.executable, "-m", "tpudist", "-p", "10",
           "--outpath", outpath, "--overwrite", "delete"] + extra
    print(f"[overlap] {' '.join(cmd)}", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    subprocess.run(cmd, check=True, timeout=timeout, cwd=_REPO,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    wall = time.perf_counter() - t0
    log = open(os.path.join(outpath, "experiment.log")).read()
    m = None
    for m in _LINE.finditer(log):
        pass
    if m is None:
        raise SystemExit(f"no train progress line in {outpath}/experiment.log")
    n_steps, avg_step, avg_data = int(m.group(1)), float(m.group(2)), float(m.group(3))
    return {"steps_per_epoch": n_steps, "avg_step_s": avg_step,
            "avg_data_wait_s": avg_data, "wall_s": round(wall, 1)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default="/tmp/rehearsal224")
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--arch", default="resnet18")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--outdir", default="")
    args = ap.parse_args()

    outdir = args.outdir or tempfile.mkdtemp(prefix="overlap_")
    common = ["-a", args.arch, "--num-classes", str(args.num_classes),
              "--image-size", str(args.image_size), "-b", str(args.batch),
              "--epochs", str(args.epochs), "--lr", "0.1",
              "-j", str(args.workers), "--seed", "0"]
    real = _run_trainer(os.path.join(outdir, "real"),
                        common + ["--data", args.data], args.timeout)
    # Synthetic twin: same shapes/steps; the loader hands out prebuilt
    # in-memory arrays, so its step time is the pure-compute floor.
    n_imgs = real["steps_per_epoch"] * args.batch
    synth = _run_trainer(os.path.join(outdir, "synth"),
                         common + ["--synthetic",
                                   "--synthetic-size", str(n_imgs)],
                         args.timeout)

    stall = (real["avg_data_wait_s"] / real["avg_step_s"]
             if real["avg_step_s"] else 0.0)
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=120).stdout.strip()
        platform = out or "unknown"
    except Exception:
        platform = "unknown"
    rec = {
        "metric": f"input_overlap_{args.arch}_{args.image_size}_b{args.batch}",
        "platform": platform,
        "real_images_per_sec": round(args.batch / real["avg_step_s"], 1),
        "synth_images_per_sec": round(args.batch / synth["avg_step_s"], 1),
        "real_avg_step_s": real["avg_step_s"],
        "synth_avg_step_s": synth["avg_step_s"],
        "real_avg_data_wait_s": real["avg_data_wait_s"],
        "input_stall_pct": round(100.0 * stall, 1),
        "real_over_synth_step_ratio": round(
            real["avg_step_s"] / synth["avg_step_s"], 3),
        "steps_per_epoch": real["steps_per_epoch"],
        "workers": args.workers,
        "corpus": args.data,
    }
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
