#!/bin/bash
# r5: stability record at the grown suite (390 tests incl. the new fp16xaccum, flash-TP, real-data-8proc, fingerprint tests) "Done =" evidence: five consecutive full-suite runs with
# zero flakes, logged to benchmarks/results/suite_stability_r5.log.
#
# Chip-aware: the 1-core VM serves both this loop and any tunnel capture the
# r5 watcher starts. Captures win — host contention would distort their
# wall-clock timing — so each suite run (a) waits until no capture process
# is active before starting and (b) is ABORTED and retried if one appears
# mid-run. A run aborted for the chip does not count as a flake.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/suite_stability_r5.log
PASS=0
ATTEMPT=0
MAX_ATTEMPTS=10
echo "[stability $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"

# Anchored patterns: an unanchored 'bench.py' matches unrelated processes
# whose cmdline merely CONTAINS the string (observed: the round driver's
# own prompt text), which wedged this loop at "waiting" forever. A capture
# is (a) the bench/benchmarks scripts run as `python <script>` or (b) any
# trainer the watcher points at the repo's runs/ dir.
capture_active() {
  pgrep -f '^[^ ]*python[0-9.]* bench\.py' > /dev/null && return 0
  pgrep -f '^[^ ]*python[0-9.]* benchmarks/' > /dev/null && return 0
  pgrep -f -- '--outpath runs/' > /dev/null && return 0
  return 1
}

while [ "$PASS" -lt 4 ] && [ "$ATTEMPT" -lt "$MAX_ATTEMPTS" ]; do
  while capture_active; do sleep 120; done
  ATTEMPT=$((ATTEMPT + 1))
  RUNLOG=benchmarks/results/suite_r5_run_${ATTEMPT}.log
  echo "[stability $(date -u +%FT%TZ)] run $ATTEMPT (passes so far: $PASS)" >> "$LOG"
  python -m pytest tests/ -q > "$RUNLOG" 2>&1 &
  PYTEST=$!
  ABORTED=0
  while kill -0 "$PYTEST" 2>/dev/null; do
    if capture_active; then
      echo "[stability $(date -u +%FT%TZ)] chip capture started — aborting run $ATTEMPT" >> "$LOG"
      # pytest re-execs itself (conftest clean-env); kill the whole tree
      pkill -TERM -P "$PYTEST" 2>/dev/null
      kill -TERM "$PYTEST" 2>/dev/null
      sleep 5
      pkill -KILL -f "python -m pytest tests/" 2>/dev/null
      ABORTED=1
      break
    fi
    sleep 30
  done
  if [ "$ABORTED" -eq 1 ]; then
    wait "$PYTEST" 2>/dev/null
    continue
  fi
  wait "$PYTEST"
  RC=$?
  TAIL=$(tail -n 1 "$RUNLOG")
  if [ "$RC" -eq 0 ]; then
    PASS=$((PASS + 1))
    echo "[stability $(date -u +%FT%TZ)] run $ATTEMPT PASSED: $TAIL" >> "$LOG"
  else
    PASS=0   # consecutive means consecutive: a flake resets the count
    echo "[stability $(date -u +%FT%TZ)] run $ATTEMPT FAILED (rc=$RC): $TAIL" >> "$LOG"
    grep -m 5 "FAILED" "$RUNLOG" >> "$LOG"
  fi
done
echo "[stability $(date -u +%FT%TZ)] done: $PASS consecutive passes in $ATTEMPT attempts" >> "$LOG"
