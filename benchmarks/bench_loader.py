"""Input-pipeline throughput bench (VERDICT r1 weak #5 / next #6).

Measures end-to-end loader images/sec — JPEG decode + train-transform
(RandomResizedCrop→flip→normalize) + batch assembly — over a synthetic JPEG
corpus, for the pure-PIL path and the fused native C++ kernel path
(``native/transforms.cc``), at several worker counts.

The target: the reference's 3-GPU DDP row consumed ImageNet at ≈1,389
images/sec aggregate (BASELINE.md); a single-host loader must sustain that to
keep one TPU host fed at parity.

Usage: python benchmarks/bench_loader.py [--images 800] [--batch 128]
Prints one JSON line per (path, workers) combination.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REFERENCE_IMAGES_PER_SEC = 1_281_167 * 5 / 4612.0   # ≈ 1389


def make_corpus(root: str, n_images: int, seed: int = 0,
                noise: bool = False) -> None:
    """ImageFolder layout: 2 classes of JPEGs at ImageNet-ish sizes.

    Default content is photo-like (low-frequency: small noise upsampled),
    landing near ImageNet's ~1 bit/pixel entropy — decode cost tracks the
    compressed bitstream, so content statistics ARE the workload. ``noise``
    switches to uniform noise (~8 bits/pixel, entropy-decode worst case,
    3-6x the bitstream of a real photo)."""
    from PIL import Image
    rng = np.random.default_rng(seed)
    for cls in ("class_a", "class_b"):
        os.makedirs(os.path.join(root, cls), exist_ok=True)
    for i in range(n_images):
        cls = "class_a" if i % 2 == 0 else "class_b"
        h = int(rng.integers(256, 513))
        w = int(rng.integers(256, 513))
        if noise:
            arr = rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)
            img = Image.fromarray(arr)
        else:
            small = rng.integers(0, 256, size=(24, 24, 3), dtype=np.uint8)
            img = Image.fromarray(small).resize((w, h), Image.BILINEAR)
        img.save(os.path.join(root, cls, f"img_{i:05d}.jpg"), quality=85)


def run_one(root: str, transform, batch: int, workers: int,
            label: str, raw_loader: bool = False) -> dict:
    from tpudist.data import DataLoader, ImageFolder
    ds = ImageFolder(root, loader=ImageFolder.raw_loader if raw_loader
                     else None)
    loader = DataLoader(ds, batch_size=batch, transform=transform,
                        num_workers=workers, prefetch=2, drop_last=True)
    # Warm one batch (file cache, thread spin-up), then time a full epoch.
    it = iter(loader)
    next(it)
    for _ in it:
        pass
    n = len(loader) * batch
    t0 = time.perf_counter()
    count = 0
    for images, labels in loader:
        count += images.shape[0]
    dt = time.perf_counter() - t0
    ips = count / dt
    return {
        "metric": f"loader_images_per_sec_{label}_{workers}w",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMAGES_PER_SEC, 4),
        "images": count,
        "seconds": round(dt, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=800)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--workers", type=int, nargs="*", default=[8, 16])
    ap.add_argument("--noise", action="store_true",
                    help="uniform-noise corpus (entropy-decode worst case) "
                         "instead of photo-like content")
    args = ap.parse_args()

    from functools import partial
    from tpudist.data import native
    from tpudist.data.pipeline import (_native_jpeg_train_tf,
                                       _native_train_tf, _train_tf)

    with tempfile.TemporaryDirectory() as root:
        print(f"building {args.images}-image JPEG corpus "
              f"({'noise' if args.noise else 'photo-like'})...",
              file=sys.stderr)
        make_corpus(root, args.images, noise=args.noise)

        results = []
        for w in args.workers:
            results.append(run_one(
                root, partial(_train_tf, size=args.size),
                args.batch, w, "pil"))
            print(json.dumps(results[-1]), flush=True)
        if native.available() or native.build():
            for w in args.workers:
                results.append(run_one(
                    root, partial(_native_train_tf, size=args.size),
                    args.batch, w, "native"))
                print(json.dumps(results[-1]), flush=True)
        else:
            print(json.dumps({"metric": "loader_native", "error":
                              "native library unavailable"}), flush=True)
        if native.jpeg_available():
            # Fully-fused path: raw bytes in, partial libjpeg decode + fused
            # transform in one native call (no PIL anywhere).
            for w in args.workers:
                results.append(run_one(
                    root, partial(_native_jpeg_train_tf, size=args.size),
                    args.batch, w, "native_jpeg", raw_loader=True))
                print(json.dumps(results[-1]), flush=True)
        else:
            print(json.dumps({"metric": "loader_native_jpeg", "error":
                              "jpeg kernels unavailable"}), flush=True)


if __name__ == "__main__":
    main()
