"""Device-prefetch A/B: the REAL trainer with and without
``--device_prefetch`` (ISSUE 6 tentpole (3): does overlapping the next
batch's H2D with compute buy wall-clock?).

Runs ``python -m tpudist`` twice with identical configs — prefetch ON
(default) and OFF — parses the steady-state step/data meters from each
``experiment.log`` (same parser as ``bench_input_overlap``), and emits one
JSON line per side plus a combined verdict. On TPU both sides append to
``benchmarks/results/bench_history.jsonl`` as their own ``images/sec``
series (``prefetch_on_...`` / ``prefetch_off_...``), so ``tpudist-regress``
gates the prefetch win round over round; off-TPU nothing is appended
(CPU step time is compute-bound noise for this question).

By default the data path is synthetic with a worker-paced loader (the
prefetcher's job is hiding H2D + loader wait — a corpus via ``--data``
exercises the full decode path like the overlap bench).

Usage: python benchmarks/bench_prefetch.py [--data DIR] [--batch 128]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import re
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# last per-step progress line of the train loop:
#   Epoch[0]:  [150/157]  Time 0.129 ( 0.141)  Data  0.010 ( 0.022)  ...
_LINE = re.compile(r"Epoch\[\d+\]:\s*\[\d+/(\d+)\]\s*"
                   r"Time\s*[\d.]+\s*\(\s*([\d.]+)\)\s*"
                   r"Data\s*[\d.]+\s*\(\s*([\d.]+)\)")


def _run_trainer(outpath: str, extra: list[str], timeout: float) -> dict:
    cmd = [sys.executable, "-m", "tpudist", "-p", "10",
           "--outpath", outpath, "--overwrite", "delete", "--telemetry"] \
        + extra
    print(f"[prefetch] {' '.join(cmd)}", file=sys.stderr, flush=True)
    subprocess.run(cmd, check=True, timeout=timeout, cwd=_REPO,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    log = open(os.path.join(outpath, "experiment.log")).read()
    m = None
    for m in _LINE.finditer(log):
        pass
    if m is None:
        raise SystemExit(f"no train progress line in {outpath}/experiment.log")
    out = {"steps_per_epoch": int(m.group(1)),
           "avg_step_s": float(m.group(2)),
           "avg_data_wait_s": float(m.group(3))}
    # overlap evidence straight from the telemetry stream: prefetch_s is
    # the hidden (overlapped-with-compute) staging time per step.
    try:
        from tpudist.summarize import analyze, load_events
        a = analyze(load_events(outpath))
        b = a.get("budget") or {}
        for k in ("data_s", "h2d_s", "prefetch_s"):
            if b.get(k):
                out[f"{k}_p50"] = round(b[k]["p50"], 6)
    except Exception as e:
        print(f"[prefetch] telemetry parse failed: {e!r}", file=sys.stderr)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default="",
                    help="ImageFolder corpus ('' = synthetic)")
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--arch", default="resnet18")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--synthetic-size", type=int, default=0,
                    help="synthetic train-set size (0 = 20 batches)")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--outdir", default="")
    args = ap.parse_args()

    outdir = args.outdir or tempfile.mkdtemp(prefix="prefetch_")
    common = ["-a", args.arch, "--num-classes", str(args.num_classes),
              "--image-size", str(args.image_size), "-b", str(args.batch),
              "--epochs", str(args.epochs), "--lr", "0.1",
              "-j", str(args.workers), "--seed", "0"]
    if args.data:
        common += ["--data", args.data]
    else:
        n = args.synthetic_size or args.batch * 20
        common += ["--synthetic", "--synthetic-size", str(n)]

    sides = {}
    for side, flag in (("on", "--device_prefetch"),
                       ("off", "--no-device_prefetch")):
        sides[side] = _run_trainer(os.path.join(outdir, side),
                                   common + [flag], args.timeout)

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend()); "
             "print(jax.device_count())"],
            capture_output=True, text=True, timeout=120).stdout.split()
        platform = out[0] if out else "unknown"
        n_devices = int(out[1]) if len(out) > 1 else 1
    except Exception:
        platform, n_devices = "unknown", 1

    rows = []
    for side, r in sides.items():
        rows.append({
            "metric": (f"prefetch_{side}_{args.arch}_{args.image_size}"
                       f"_images_per_sec_{platform}"),
            "value": round(args.batch / r["avg_step_s"], 1),
            "unit": "images/sec",
            # -b is the GLOBAL batch (Config splits it across devices);
            # per_device_batch is part of the regress series identity and
            # must carry the value the chips actually ran, like bench.py.
            "per_device_batch": max(1, args.batch // n_devices),
            "avg_step_s": r["avg_step_s"],
            "avg_data_wait_s": r["avg_data_wait_s"],
            **{k: v for k, v in r.items() if k.endswith("_p50")},
        })
    verdict = {
        "metric": f"prefetch_ab_{args.arch}_{args.image_size}_b{args.batch}",
        "platform": platform,
        "on_images_per_sec": rows[0]["value"],
        "off_images_per_sec": rows[1]["value"],
        "speedup": round(sides["off"]["avg_step_s"]
                         / max(sides["on"]["avg_step_s"], 1e-9), 4),
        "corpus": args.data or "synthetic",
        "workers": args.workers,
    }
    for row in rows + [verdict]:
        print(json.dumps(row), flush=True)

    if platform != "tpu":
        print("[prefetch] platform != tpu — rows NOT appended to bench "
              "history", file=sys.stderr)
        return 0
    from tpudist.regress import append_history
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    for row in rows:
        append_history({**row, "measured_at": now})
    print(f"[prefetch] {len(rows)} row(s) appended to bench history",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
