#!/bin/bash
# Third capture stage: flash-attention long-context capability proof
# (XLA O(T^2) logits OOM vs flash O(T)) and the (block_q, block_k) sweep.
# Waits for the r3b watcher (rehearsal + ViT drive) to finish so it never
# competes for the chip, then runs each capture once per tunnel-up window,
# with the same capped-retry discipline (3 tries, 300 s backoff).
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
OUT=benchmarks/results/flash_r3_long.jsonl
MAX_TRIES=3
TRIES=0
echo "[watch-r3c $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"
while pgrep -f tpu_watch_r3b.sh > /dev/null; do
  sleep 120
done
echo "[watch-r3c $(date -u +%FT%TZ)] r3b done — waiting for tunnel" >> "$LOG"
while [ "$TRIES" -lt "$MAX_TRIES" ]; do
  if ! timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    sleep 120
    continue
  fi
  TRIES=$((TRIES + 1))
  echo "[watch-r3c $(date -u +%FT%TZ)] tunnel UP — flash long-context (try $TRIES)" >> "$LOG"
  if timeout 2400 python benchmarks/bench_flash.py --steps 10 \
      --long-context 16384 >> "$OUT" 2>> "$LOG" \
     && timeout 2400 python benchmarks/bench_flash.py --steps 10 \
      --sweep-blocks >> "$OUT" 2>> "$LOG"; then
    echo "[watch-r3c $(date -u +%FT%TZ)] flash captures ok" >> "$LOG"
    exit 0
  fi
  echo "[watch-r3c $(date -u +%FT%TZ)] flash captures failed — backoff" >> "$LOG"
  sleep 300
done
echo "[watch-r3c $(date -u +%FT%TZ)] gave up after $MAX_TRIES tries" >> "$LOG"
