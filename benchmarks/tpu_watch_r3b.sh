#!/bin/bash
# Round-3 second-half watcher: the headline captures (bench, recipe table,
# batch sweep, flash microbench) landed 2026-07-31 03:46-04:15Z; this picks
# up the two remaining on-chip items whenever the tunnel next answers:
#   1. the 224px/100-class accuracy rehearsal (VERDICT r2 #8, chip version)
#   2. a ViT train-step drive (exercises the Pallas flash kernel inside the
#      real trainer on hardware; its first attempt died to a tunnel drop
#      mid-compile at 04:21Z)
# Rehearsal first when its corpus is ready — it is the review item; the ViT
# drive fills chip time while the corpus generator finishes otherwise.
# Each item gets at most MAX_TRIES attempts (a deterministic failure — OOM,
# bad flag, corpus rot — must not hot-loop a 2 h job on scarce chip time);
# failures back off 300 s so a mid-run tunnel drop isn't retried instantly.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
CORPUS=/tmp/rehearsal224
MAX_TRIES=3
TRIES_REHEARSAL=0
TRIES_VIT=0
DONE_REHEARSAL=0
DONE_VIT=0
echo "[watch-r3b $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"

ensure_corpus() {
  [ -d "$CORPUS/train" ] && return 0
  echo "[watch-r3b $(date -u +%FT%TZ)] corpus missing — regenerating" >> "$LOG"
  rm -rf "$CORPUS.partial"
  if timeout 3000 python benchmarks/make_synth_imagefolder.py \
      --root "$CORPUS.partial" --classes 100 --train-per-class 200 \
      --val-per-class 40 --size 224 --seed 3 >> "$LOG" 2>&1; then
    mv "$CORPUS.partial" "$CORPUS"
    return 0
  fi
  echo "[watch-r3b $(date -u +%FT%TZ)] corpus regeneration FAILED" >> "$LOG"
  return 1
}

while true; do
  [ "$TRIES_REHEARSAL" -ge "$MAX_TRIES" ] && [ "$DONE_REHEARSAL" -eq 0 ] && \
    { echo "[watch-r3b $(date -u +%FT%TZ)] rehearsal gave up after $MAX_TRIES tries" >> "$LOG"; DONE_REHEARSAL=2; }
  [ "$TRIES_VIT" -ge "$MAX_TRIES" ] && [ "$DONE_VIT" -eq 0 ] && \
    { echo "[watch-r3b $(date -u +%FT%TZ)] vit drive gave up after $MAX_TRIES tries" >> "$LOG"; DONE_VIT=2; }
  [ "$DONE_REHEARSAL" -ne 0 ] && [ "$DONE_VIT" -ne 0 ] && break

  if ! timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch-r3b $(date -u +%FT%TZ)] tunnel down" >> "$LOG"
    sleep 120
    continue
  fi
  if [ "$DONE_REHEARSAL" -eq 0 ] && ensure_corpus; then
    TRIES_REHEARSAL=$((TRIES_REHEARSAL + 1))
    echo "[watch-r3b $(date -u +%FT%TZ)] tunnel UP — rehearsal (try $TRIES_REHEARSAL)" >> "$LOG"
    timeout 7200 python -m tpudist --data "$CORPUS" -a resnet18 \
      --num-classes 100 --image-size 224 -b 1200 --accum-steps 8 \
      --epochs 5 --step 3,4 --lr 0.1 -j 8 -p 5 --replica-check-freq 2 \
      --outpath runs/accuracy_rehearsal_r3_tpu --overwrite delete --seed 0 \
      >> "$LOG" 2>&1
    RC=$?
    echo "[watch-r3b $(date -u +%FT%TZ)] rehearsal rc=$RC" >> "$LOG"
    if [ $RC -eq 0 ]; then DONE_REHEARSAL=1; else sleep 300; fi
    continue
  fi
  if [ "$DONE_VIT" -eq 0 ]; then
    TRIES_VIT=$((TRIES_VIT + 1))
    echo "[watch-r3b $(date -u +%FT%TZ)] tunnel UP — vit flash drive (try $TRIES_VIT)" >> "$LOG"
    timeout 2400 python -m tpudist --synthetic -a vit_b_16 --num-classes 8 \
      --image-size 224 -b 32 --epochs 1 --step 1 --lr 0.01 -j 2 -p 1 \
      --outpath runs/vit_flash_drive_r3_tpu --overwrite delete --seed 0 \
      >> "$LOG" 2>&1
    RC=$?
    echo "[watch-r3b $(date -u +%FT%TZ)] vit drive rc=$RC" >> "$LOG"
    if [ $RC -eq 0 ]; then DONE_VIT=1; else sleep 300; fi
    continue
  fi
  # only reachable while the rehearsal waits on a corpus the vit drive
  # already ceded the chip to
  echo "[watch-r3b $(date -u +%FT%TZ)] tunnel up, waiting on corpus" >> "$LOG"
  sleep 120
done
echo "[watch-r3b $(date -u +%FT%TZ)] watcher exiting (rehearsal=$DONE_REHEARSAL vit=$DONE_VIT; 1=ok 2=gave up)" >> "$LOG"
