"""dp×tp A/B benchmark: the same arch at the same device count, pure DP
vs a 2-axis (data×model) mesh through the single parallelism plane
(ISSUE 12 tentpole evidence).

For each (arch, tp) in {resnet18, vit_b_16} × {1, 2}:

- ``tp=1``: the canonical shard_map DP step (the baseline every bench row
  to date ran);
- ``tp>1``: the GSPMD step on a ``(n/tp, tp)`` ('data','model') mesh with
  the family's plane rule table (channel-sharded convs for resnet,
  Megatron splits for vit), state placed by ``plane.shard_state``.

Each row reports step ms (via the shared dispatch harness
``ops/dispatch.measure_ms`` — bench rows and dispatch verdicts cannot
drift in methodology), derived img/s over the GLOBAL batch, per-device
state bytes, and the census collective bytes of the compiled step (the
``xla_introspect`` census — the TP tax/win is a comms number, so the
byte claim is gateable data on the row, not prose).

Every numeric row appends to ``benchmarks/results/bench_history.jsonl``
as its own gateable ``unit: ms`` series (``tpudist-regress`` trips on
time increase AND collective-byte increase). Off-TPU nothing is appended:
CPU step timings are not measurements.

Usage: python benchmarks/bench_tp.py [--steps N] [--batch B]
       [--archs resnet18,vit_b_16] [--tp 1,2] [--image-size 224]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _census(lowered_compiled) -> dict:
    from tpudist.obs.xla_introspect import hlo_op_census
    c = hlo_op_census(lowered_compiled.as_text())
    return {
        "collective_bytes_per_step": sum(v["bytes"]
                                         for v in c["collectives"].values()),
        "collective_link_bytes": sum(c["link_bytes"].values()),
        "all_gather_bytes": c["collectives"].get(
            "all-gather", {}).get("bytes", 0),
        "all_reduce_bytes": c["collectives"].get(
            "all-reduce", {}).get("bytes", 0),
    }


def _device_state_bytes(tree) -> int:
    import jax
    tot = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            sh = leaf.addressable_shards[0]
            tot += int(np.prod(sh.data.shape)) * leaf.dtype.itemsize
        elif hasattr(leaf, "nbytes"):
            tot += int(leaf.nbytes)
    return tot


def tp_ab(steps: int, batch: int, archs: list[str], tps: list[int],
          image_size: int, num_classes: int) -> bool:
    import jax
    import jax.numpy as jnp
    from tpudist.config import Config
    from tpudist.dist import make_mesh, shard_host_batch
    from tpudist.models import create_model
    from tpudist.ops.dispatch import measure_ms
    from tpudist.parallel import plane
    from tpudist.parallel.tensor_parallel import make_gspmd_train_step
    from tpudist.regress import append_history
    from tpudist.train import (compute_dtype, create_train_state,
                               make_train_step)

    platform = jax.default_backend()
    n_dev = jax.device_count()
    failed = False
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    for arch in archs:
        for tp in tps:
            if n_dev % tp:
                print(f"[bench_tp] skip {arch} tp={tp}: {n_dev} devices "
                      f"not divisible", file=sys.stderr)
                continue
            global_batch = batch * n_dev
            cfg = Config(arch=arch, num_classes=num_classes,
                         image_size=image_size, batch_size=global_batch,
                         use_amp=True, seed=0)
            cfg.finalize(n_dev)
            row = {"metric": f"tp_{arch}_tp{tp}_b{batch}_{n_dev}dev_ms_"
                             f"{platform}",
                   "unit": "ms", "arch": arch, "tp": tp,
                   "per_device_batch": batch,
                   "global_batch": cfg.batch_size,
                   "path": "gspmd" if tp > 1 else "dp_shard_map"}
            try:
                model = create_model(arch, num_classes=num_classes,
                                     dtype=compute_dtype(cfg))
                if tp > 1:
                    mesh = make_mesh((n_dev // tp, tp), ("data", "model"))
                    rules = plane.rules_for_mesh(arch, mesh)
                    st = plane.shard_state(
                        mesh,
                        create_train_state(jax.random.PRNGKey(0), model,
                                           cfg),
                        rules)
                    step = make_gspmd_train_step(mesh, model, cfg, rules)
                else:
                    mesh = make_mesh((n_dev,), ("data",))
                    st = create_train_state(jax.random.PRNGKey(0), model,
                                            cfg)
                    step = make_train_step(mesh, model, cfg)
                rng = np.random.default_rng(0)
                images = rng.standard_normal(
                    (cfg.batch_size, image_size, image_size, 3)
                ).astype(np.float32)
                labels = rng.integers(
                    0, num_classes,
                    size=(cfg.batch_size,)).astype(np.int32)
                im, lb = shard_host_batch(mesh, (images, labels))
                lr = jnp.float32(0.1)
                row["state_bytes_per_device"] = _device_state_bytes(
                    {"params": st.params, "opt": st.opt_state})
                if hasattr(step, "lower"):
                    try:
                        row.update(_census(
                            step.lower(st, im, lb, lr).compile()))
                    except Exception as e:
                        print(f"[bench_tp] census failed: {e!r}",
                              file=sys.stderr)
                # The steps donate their state: thread it through the
                # timing loop instead of re-feeding a donated-away array.
                holder = {"st": st}

                def one_step():
                    holder["st"], m = step(holder["st"], im, lb, lr)
                    return m

                ms = measure_ms(one_step, (), steps, warmup=2)
                row["value"] = round(ms, 3)
                row["img_per_s"] = round(cfg.batch_size / (ms / 1e3), 1)
            except Exception as e:
                row["value"] = None
                row["error"] = f"{type(e).__name__}: {e}"[:200]
                failed = True
            print(json.dumps(row), flush=True)
            if platform == "tpu" and isinstance(row.get("value"),
                                               (int, float)):
                append_history({**row, "measured_at": now})
    if platform != "tpu":
        print("[bench_tp] platform != tpu — rows NOT appended to bench "
              "history (CPU step timings are not measurements)",
              file=sys.stderr)
    return failed


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=128,
                    help="PER-DEVICE batch (global = batch × devices)")
    ap.add_argument("--archs", default="resnet18,vit_b_16")
    ap.add_argument("--tp", default="1,2",
                    help="comma-separated model-axis sizes to A/B")
    ap.add_argument("--image-size", type=int, default=224,
                    dest="image_size")
    ap.add_argument("--num-classes", type=int, default=1000,
                    dest="num_classes")
    args = ap.parse_args()
    archs = [a for a in args.archs.split(",") if a]
    tps = [int(t) for t in args.tp.split(",") if t]
    return 1 if tp_ab(args.steps, args.batch, archs, tps, args.image_size,
                      args.num_classes) else 0


if __name__ == "__main__":
    sys.exit(main())
