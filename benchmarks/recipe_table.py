"""The reference's headline artifact, re-created on TPU: a four-row table
comparing training recipes on one fixed workload.

Reference table (``/root/reference/README.md:9-14``): resnet18 / ImageNet /
5 epochs on 3× TITAN Xp, rows = DataParallel, DDP, DDP+AMP, DDP+AMP+SyncBN,
columns = time + per-GPU peak memory. The reference's rows differ by process
topology; under SPMD there is one topology, so the rows that still exist as
distinct recipes are the precision/BN states:

  fp32          (use_amp off — reference rows 1-2)
  bf16          (TPU-native AMP — reference row 3's autocast)
  bf16+SyncBN   (reference row 4)
  fp16+scaler   (literal torch.cuda.amp semantics: fp16 + DynamicScale)

Each row reports images/sec, step ms, MFU and peak HBM (runtime allocator
high-water mark, falling back to the compiler's memory analysis on backends
without allocator stats). Results go to stdout (one JSON line per row) and
``benchmarks/results/recipe_table.json``; run with the repo root on PYTHONPATH
or from the repo root.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402  (the root bench module: probe + measure_row)

ROWS = (
    ("fp32", dict(use_amp=False)),
    ("bf16", dict(use_amp=True, amp_dtype="bfloat16")),
    ("bf16_syncbn", dict(use_amp=True, amp_dtype="bfloat16",
                         sync_batchnorm=True)),
    ("fp16_scaler", dict(use_amp=True, amp_dtype="float16")),
)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default="resnet18")
    ap.add_argument("--per-device-batch", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--probe-budget", type=float, default=600.0)
    ap.add_argument("--out", default=os.path.join(
        _REPO, "benchmarks", "results", "recipe_table.json"))
    ap.add_argument("--rows", default=",".join(name for name, _ in ROWS),
                    help="comma-separated subset of rows to run")
    args = ap.parse_args()

    if os.environ.get("TPUDIST_BENCH_CHILD") != "cpu" \
            and os.environ.get("JAX_PLATFORMS") != "cpu":
        # Reuse the bench's killable-subprocess probe, but without its stale/
        # CPU fallback: a recipe table is only worth producing on a live
        # backend the caller chose.
        ok, detail = bench._probe_backend(args.probe_timeout)
        if not ok:
            print(f"recipe_table: backend probe failed: {detail}",
                  file=sys.stderr)
            sys.exit(3)

    want = set(args.rows.split(","))
    records = []
    for name, overrides in ROWS:
        if name not in want:
            continue
        rec = bench.measure_row(args.arch, args.per_device_batch,
                                args.image_size, args.steps, args.warmup,
                                **overrides)
        rec = {"row": name, **rec}
        records.append(rec)
        print(json.dumps(rec), flush=True)

    out = {
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "command": " ".join(sys.argv),
        "rows": records,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"recipe_table: wrote {len(records)} rows to {args.out}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
