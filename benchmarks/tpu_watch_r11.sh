#!/bin/bash
# Round-11 capture chain: poll the tunnel; whenever it answers, run the next
# pending stage in priority order. Changes vs r10:
#   - NEW serve_ab stage, FIRST among the chip stages (ISSUE 14 tentpole):
#     the serving plane's first on-chip numbers — bench_serve sweeps
#     open-loop rates through the AOT-bucketed ServeEngine, writing the
#     latency/throughput curve artifact + per-rate p99 ms series + the
#     saturation req/s row into bench_history, AND runs TWICE against one
#     TPUDIST_COMPILE_CACHE dir so the artifact pair measures cold-vs-warm
#     AOT startup on real chips (the 25-45 s compile_s the cold-start kill
#     targets). The chaos gate still runs before any chip time.
#   - everything below carried over from r10 (all still pending):
#   - chaos stage (ISSUE 13 satellite): the full fault x topology chaos
#     matrix (tools/chaos_matrix.sh CHAOS_FULL=1, CPU gang sims — no chip
#     time) runs once on the capture host before any chip stage. Not a
#     capture: it gates, it does not append rows.
#   - NEW tp_ab stage, first in line (ISSUE 12 tentpole): dp-vs-dp×tp A/B
#     at fixed device count (resnet18 + vit_b_16, tp ∈ {1,2}) through the
#     single parallelism plane — the conv families' channel-sharded rule
#     tables and the shard_map-wrapped kernels get their first on-chip
#     step-time / img-per-s / collective-bytes / state-bytes rows.
#     bench_tp appends ms-series rows (census bytes embedded) to
#     bench_history.jsonl, arming tpudist-regress on TP step time AND the
#     TP comms-byte claim (docs/PARALLELISM.md).
#   - fused_ab now ALSO matters under sharding (the GSPMD stand-down is
#     gone): its dispatch-cache warm feeds --fused-bn auto on dp×tp runs
#     too, since the shard-local workloads are keyed identically.
#   - carried over from r8, still pending: compress_ab, zerofull_ab,
#     fused_ab, prefetch_ab, flash_ab, remat, recipe, overlap, rehearsal,
#     parity1000.
#   - locks renamed to r9 (an orphaned r8 watcher must not serialize us,
#     but bench_zoo's shared capture lock path is kept so zoo runs and this
#     watcher still exclude each other around actual chip use).
# Stage order:
#   0 chaos       full chaos matrix on CPU sims (~10 min; gate, no chip)
#   1 tp_ab       dp vs dp×tp step A/B, resnet18 + vit_b_16 (~10-20 min;
#                 THE r9 headline evidence — it goes first)
#   2 compress_ab int8-vs-dense gradient exchange at zoo gradient sizes
#   3 zerofull_ab ZeRO off/1/full step + state-bytes A/B (~10-20 min)
#   4 fused_ab    fused-norm vs XLA epilogue at resnet stage shapes
#   5 prefetch_ab trainer A/B with/without device prefetch (~10-20 min)
#   6 flash_ab    flash-vs-XLA fwd+bwd at ViT-B/2k shapes + block sweep
#   7 remat       remat A/B (~3 min)
#   8 recipe      4-row recipe table refresh (~15 min)
#   9 overlap     real-data vs synthetic step time (needs /tmp/rehearsal224)
#  10 rehearsal   5-epoch 224px/100-class Trainer.fit (needs /tmp/rehearsal224)
#  11 parity1000  5-epoch 1000-class reference-protocol run (needs
#                 /tmp/parity1000; ~2 h)
# Each stage gets MAX_TRIES attempts with 300 s backoff; corpus-gated
# stages skip without burning a try while their corpus is absent.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
FRESH=benchmarks/results/bench_tpu_fresh.jsonl
MAX_TRIES=3
# Single-instance guard on r8's own file; capture lock shared with
# bench_zoo.sh (held only around run_stage so zoo rows stay reachable).
exec 8>/tmp/tpudist_watch_r10.instance.lock
if ! flock -n 8; then
  echo "[watch-r11 $(date -u +%FT%TZ)] another instance holds the lock — exiting" >> "$LOG"
  exit 1
fi
exec 9>/tmp/tpudist_watch_r5.lock
echo "[watch-r11 $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"

declare -A TRIES DONE
STAGES="chaos serve_ab tp_ab compress_ab zerofull_ab fused_ab prefetch_ab flash_ab remat recipe overlap rehearsal parity1000"
for s in $STAGES; do TRIES[$s]=0; DONE[$s]=0; done
# TPUDIST_WATCH_SKIP: space-separated stages already captured this session.
for s in ${TPUDIST_WATCH_SKIP:-}; do
  if [ -n "${DONE[$s]+x}" ]; then
    DONE[$s]=1
    echo "[watch-r11 $(date -u +%FT%TZ)] stage $s pre-marked done (TPUDIST_WATCH_SKIP)" >> "$LOG"
  else
    echo "[watch-r11 $(date -u +%FT%TZ)] unknown stage '$s' in TPUDIST_WATCH_SKIP — ignored" >> "$LOG"
  fi
done

corpus_for() {  # stage -> required corpus dir ("" = none)
  case $1 in
    rehearsal|overlap) echo /tmp/rehearsal224/train ;;
    parity1000)        echo /tmp/parity1000/train ;;
    *)                 echo "" ;;
  esac
}

bench_capture() {  # $1 = extra bench args, $2 = stage name
  local OUT RC LAST
  OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 50 $1 2>> "$LOG")
  RC=$?
  LAST=$(echo "$OUT" | tail -n 1)
  if [ $RC -eq 0 ] && [ -n "$LAST" ] \
      && ! echo "$LAST" | grep -qE '"stale": true|cpu_fallback'; then
    echo "$LAST" >> "$FRESH"
    echo "[watch-r11 $(date -u +%FT%TZ)] $2 ok: $LAST" >> "$LOG"
    return 0
  fi
  echo "[watch-r11 $(date -u +%FT%TZ)] $2 stale/failed (rc=$RC): $LAST" >> "$LOG"
  return 1
}

jsonl_capture() {  # $1 = stage, $2 = output file, rest = ;-separated commands
  # Exit 0 alone is NOT success — the tunnel can die between the watcher's
  # probe and the tool's in-process jax init, silently landing on CPU.
  # Capture to a temp file; admit rows only if none are CPU-stamped.
  local STAGE=$1 OUTFILE=$2 TMP; shift 2
  TMP=$(mktemp)
  local -a CMD=()
  local TOK RC=0
  for TOK in "$@" ";"; do
    if [ "$TOK" = ";" ]; then
      [ ${#CMD[@]} -eq 0 ] && continue
      if ! "${CMD[@]}" >> "$TMP" 2>> "$LOG"; then RC=1; break; fi
      CMD=()
    else
      CMD+=("$TOK")
    fi
  done
  if [ $RC -ne 0 ]; then rm -f "$TMP"; return 1; fi
  if grep -qE '"platform": *"cpu"|_cpu"|interpreter mode' "$TMP"; then
    echo "[watch-r11 $(date -u +%FT%TZ)] $STAGE landed on CPU — rejecting" >> "$LOG"
    rm -f "$TMP"
    return 1
  fi
  cat "$TMP" >> "$OUTFILE"
  rm -f "$TMP"
}

run_stage() {  # $1 = stage name; returns 0 on success
  case $1 in
    chaos)
      # Correctness gate, not a capture: every fault x topology cell of
      # the elasticity chaos matrix, end to end through real CPU gangs.
      # Forced onto the CPU backend — it must not touch the chips the
      # window is for, and the cells are CPU-sim by design.
      timeout 3600 env JAX_PLATFORMS=cpu CHAOS_FULL=1 \
        bash tools/chaos_matrix.sh >> "$LOG" 2>&1 ;;
    serve_ab)
      # Serving-plane curve + cold/warm AOT pair (ISSUE 14): TWO runs
      # against one fresh compile-cache dir — the first pays the real
      # compile (cold), the second proves the cache-hit startup (warm);
      # both artifacts and the history rows carry the provenance. The
      # curve/saturation series arm tpudist-regress on serving latency
      # and throughput from this round on.
      rm -rf /tmp/tpudist_serve_cache_r11
      jsonl_capture serve_ab benchmarks/results/serve_r11_tpu.jsonl \
        timeout 2400 python benchmarks/bench_serve.py \
        --rates 20,50,100,200 --duration 10 \
        --compile-cache /tmp/tpudist_serve_cache_r11 \
        --out benchmarks/results/serve_curve_resnet18_tpu_cold.json \
        ";" \
        timeout 1200 python benchmarks/bench_serve.py \
        --rates 100 --duration 10 --no-history \
        --compile-cache /tmp/tpudist_serve_cache_r11 \
        --out benchmarks/results/serve_curve_resnet18_tpu_warm.json ;;
    tp_ab)
      # dp vs dp×tp A/B through the parallelism plane. History rows
      # (step ms + img/s + census collective/state bytes) happen inside
      # the bench.
      jsonl_capture tp_ab benchmarks/results/tp_r9_tpu.jsonl \
        timeout 3600 python benchmarks/bench_tp.py --steps 10 \
        --batch 128 ;;
    compress_ab)
      # int8-vs-dense gradient exchange A/B. History rows + comm
      # dispatch-cache warm happen inside the bench.
      jsonl_capture compress_ab benchmarks/results/comm_r8_tpu.jsonl \
        timeout 2400 python benchmarks/bench_comm.py --compress-ab \
        --steps 20 ;;
    zerofull_ab)
      jsonl_capture zerofull_ab benchmarks/results/zerofull_r8_tpu.jsonl \
        timeout 3600 python benchmarks/bench_comm.py --zerofull-ab \
        --steps 10 --batch 128 ;;
    fused_ab)
      # Fused BN-epilogue A/B at the canonical stage workloads. History
      # rows + fused_norm dispatch-cache warm happen inside the bench.
      jsonl_capture fused_ab benchmarks/results/fused_norm_r7_tpu.jsonl \
        timeout 2400 python benchmarks/bench_fused_norm.py --steps 20 ;;
    prefetch_ab)
      jsonl_capture prefetch_ab benchmarks/results/prefetch_r7_tpu.jsonl \
        timeout 3600 python benchmarks/bench_prefetch.py --batch 128 \
        --workers 4 --outdir runs/prefetch_ab_r7_tpu ;;
    flash_ab)
      # The rebuilt-backward A/B: ViT-B + 2k shapes (fwd AND fwd+bwd, both
      # sides), the long-context capability proof, then the block sweep.
      # History rows + dispatch-cache warm happen inside bench_flash.
      jsonl_capture flash_ab benchmarks/results/flash_r6_tpu.jsonl \
        timeout 2400 python benchmarks/bench_flash.py --steps 10 \
        --long-context 16384 \
        ";" \
        timeout 2400 python benchmarks/bench_flash.py --steps 10 \
        --sweep-blocks ;;
    remat) bench_capture --remat remat ;;
    recipe)
      jsonl_capture recipe benchmarks/results/recipe_tpu_fresh.jsonl \
        timeout 3600 python benchmarks/recipe_table.py --steps 30 ;;
    overlap)
      jsonl_capture overlap benchmarks/results/input_overlap_r6.jsonl \
        timeout 3600 python benchmarks/bench_input_overlap.py \
        --data /tmp/rehearsal224 --num-classes 100 --batch 128 --workers 4 \
        --outdir runs/input_overlap_r6_tpu ;;
    rehearsal)
      timeout 3600 python -m tpudist --data /tmp/rehearsal224 -a resnet18 \
        --num-classes 100 --image-size 224 -b 1200 --accum-steps 8 \
        --epochs 5 --step 3,4 --lr 0.1 -j 4 -p 5 --replica-check-freq 2 \
        --require-platform tpu \
        --outpath runs/accuracy_rehearsal_r6_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
    parity1000)
      timeout 7200 python -m tpudist --data /tmp/parity1000 -a resnet18 \
        --num-classes 1000 --image-size 224 -b 1200 --accum-steps 8 \
        --epochs 5 --step 3,4 --lr 0.1 -j 4 -p 10 \
        --require-platform tpu \
        --outpath runs/accuracy_parity_r6_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
  esac
}

PROBES=0
while :; do
  PENDING=0
  for s in $STAGES; do [ "${DONE[$s]}" -eq 0 ] && PENDING=1; done
  [ $PENDING -eq 0 ] && break
  PROBES=$((PROBES + 1))
  # 8>&- 9>&- : probe children must NOT inherit either lock. The probe
  # requires an actual TPU device: in an env without the tunnel plugin,
  # jax.devices() HAPPILY returns CPU — r6's first arming burned flash_ab
  # tries on CPU before the per-stage CPU check could reject the artifact.
  if ! timeout 180 python -c "import jax; assert any(d.platform == 'tpu' for d in jax.devices())" >/dev/null 2>&1 8>&- 9>&-; then
    [ $((PROBES % 30)) -eq 0 ] && \
      echo "[watch-r11 $(date -u +%FT%TZ)] alive, tunnel still down (probe $PROBES)" >> "$LOG"
    sleep 120 8>&- 9>&-
    continue
  fi
  RAN_ONE=0
  for s in $STAGES; do
    [ "${DONE[$s]}" -ne 0 ] && continue
    C=$(corpus_for "$s")
    if [ -n "$C" ] && [ ! -d "$C" ]; then continue; fi
    RAN_ONE=1
    if ! flock -w 600 9; then
      echo "[watch-r11 $(date -u +%FT%TZ)] capture lock busy >600s (zoo run in flight?) — re-probing" >> "$LOG"
      break
    fi
    TRIES[$s]=$((TRIES[$s] + 1))
    echo "[watch-r11 $(date -u +%FT%TZ)] tunnel UP — stage $s (try ${TRIES[$s]})" >> "$LOG"
    if run_stage "$s" 8>&- 9>&-; then  # stages must not inherit the locks
      flock -u 9
      DONE[$s]=1
      echo "[watch-r11 $(date -u +%FT%TZ)] stage $s DONE" >> "$LOG"
    else
      RC=$?
      flock -u 9
      echo "[watch-r11 $(date -u +%FT%TZ)] stage $s failed (rc=$RC)" >> "$LOG"
      [ "${TRIES[$s]}" -ge "$MAX_TRIES" ] && { DONE[$s]=2; echo "[watch-r11] stage $s gave up" >> "$LOG"; }
      sleep 300 8>&- 9>&-
    fi
    break   # re-probe the tunnel between stages
  done
  # nothing runnable (every pending stage corpus-gated on a missing corpus)
  [ $RAN_ONE -eq 0 ] && sleep 120 8>&- 9>&-
done
echo "[watch-r11 $(date -u +%FT%TZ)] all stages terminal: $(for s in $STAGES; do printf '%s=%s ' "$s" "${DONE[$s]}"; done)" >> "$LOG"
