#!/bin/bash
# Round-4 capture chain (VERDICT r3 next #2 #3 #4 #7): one consolidated
# watcher that polls the tunnel and, whenever it answers, runs the next
# pending stage in priority order. Stage order trades judged value against
# window risk (the round-3 window lasted ~35 min):
#   1 bench_fresh   fresh canonical bench on post-s2d HEAD (persists the
#                   record the provisional fallback re-emits; ~3 min)
#   2 rehearsal     5-epoch 224px/100-class Trainer.fit through the real
#                   loader -> runs/accuracy_rehearsal_r4_tpu (VERDICT #2)
#   3 nos2d         s2d stem A/B baseline (VERDICT #3)
#   4 remat         remat A/B (VERDICT #3)
#   5 flash         long-context proof + block sweep (VERDICT #3)
#   6 recipe        4-row recipe table refresh on post-s2d HEAD
#   7 overlap       real-data vs synthetic step time + input_stall_pct
#                   (VERDICT #4)
#   8 parity1000    5-epoch 1000-class run at reference hyperparameters
#                   (bs=1200 via accum, MultiStep [3,4]) -> VERDICT #7;
#                   waits for /tmp/parity1000 (generator runs on CPU)
#   9 vitdrive      ViT-B flash-in-trainer drive (carried over from r3b)
# Each stage gets MAX_TRIES attempts with 300 s backoff: a deterministic
# failure must not hot-loop scarce chip time; a mid-run tunnel drop gets
# retried. Stages append to benchmarks/results/*; the session commits them.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/results/tpu_watch.log
FRESH=benchmarks/results/bench_tpu_fresh.jsonl
MAX_TRIES=3
echo "[watch-r4 $(date -u +%FT%TZ)] started (pid $$)" >> "$LOG"

declare -A TRIES DONE
STAGES="bench_fresh rehearsal nos2d remat flash recipe overlap parity1000 vitdrive"
for s in $STAGES; do TRIES[$s]=0; DONE[$s]=0; done

bench_capture() {  # $1 = extra bench args, $2 = stage name
  local OUT RC
  OUT=$(timeout 1200 python bench.py --probe-budget 120 --steps 50 $1 2>> "$LOG")
  RC=$?
  echo "$OUT" | tail -n 1 >> "$FRESH"
  if [ $RC -eq 0 ] && ! echo "$OUT" | tail -n 1 | grep -qE '"stale": true|cpu_fallback'; then
    echo "[watch-r4 $(date -u +%FT%TZ)] $2 ok: $(echo "$OUT" | tail -n 1)" >> "$LOG"
    return 0
  fi
  echo "[watch-r4 $(date -u +%FT%TZ)] $2 stale/failed (rc=$RC)" >> "$LOG"
  return 1
}

run_stage() {  # $1 = stage name; returns 0 on success
  case $1 in
    bench_fresh) bench_capture "" bench_fresh ;;
    rehearsal)
      [ -d /tmp/rehearsal224/train ] || { echo "[watch-r4] rehearsal corpus missing" >> "$LOG"; return 1; }
      timeout 3600 python -m tpudist --data /tmp/rehearsal224 -a resnet18 \
        --num-classes 100 --image-size 224 -b 1200 --accum-steps 8 \
        --epochs 5 --step 3,4 --lr 0.1 -j 4 -p 5 --replica-check-freq 2 \
        --outpath runs/accuracy_rehearsal_r4_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
    nos2d) bench_capture --no-s2d nos2d ;;
    remat) bench_capture --remat remat ;;
    flash)
      timeout 2400 python benchmarks/bench_flash.py --steps 10 \
        --long-context 16384 >> benchmarks/results/flash_r4_tpu.json 2>> "$LOG" \
      && timeout 2400 python benchmarks/bench_flash.py --steps 10 \
        --sweep-blocks >> benchmarks/results/flash_r4_tpu.json 2>> "$LOG" ;;
    recipe)
      timeout 3600 python benchmarks/recipe_table.py --steps 30 \
        >> benchmarks/results/recipe_tpu_fresh.jsonl 2>> "$LOG" ;;
    overlap)
      timeout 3600 python benchmarks/bench_input_overlap.py \
        --data /tmp/rehearsal224 --num-classes 100 --batch 128 --workers 4 \
        --outdir runs/input_overlap_r4_tpu \
        >> benchmarks/results/input_overlap_r4.jsonl 2>> "$LOG" ;;
    parity1000)
      [ -d /tmp/parity1000/train ] || { echo "[watch-r4] parity corpus not ready" >> "$LOG"; return 1; }
      timeout 7200 python -m tpudist --data /tmp/parity1000 -a resnet18 \
        --num-classes 1000 --image-size 224 -b 1200 --accum-steps 8 \
        --epochs 5 --step 3,4 --lr 0.1 -j 4 -p 10 \
        --outpath runs/accuracy_parity_r4_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
    vitdrive)
      timeout 2400 python -m tpudist --synthetic -a vit_b_16 --num-classes 8 \
        --image-size 224 -b 32 --epochs 1 --step 1 --lr 0.01 -j 2 -p 1 \
        --outpath runs/vit_flash_drive_r4_tpu --overwrite delete --seed 0 \
        >> "$LOG" 2>&1 ;;
  esac
}

PROBES=0
while :; do
  PENDING=0
  for s in $STAGES; do [ "${DONE[$s]}" -eq 0 ] && PENDING=1; done
  [ $PENDING -eq 0 ] && break
  # 180 s probe: under co-runner CPU load (the suite-stability loop), jax
  # import + tunnel handshake can exceed 90 s even with the tunnel UP —
  # missing a scarce window to contention would be worse than a slow poll.
  PROBES=$((PROBES + 1))
  if ! timeout 180 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    [ $((PROBES % 30)) -eq 0 ] && \
      echo "[watch-r4 $(date -u +%FT%TZ)] alive, tunnel still down (probe $PROBES)" >> "$LOG"
    sleep 120
    continue
  fi
  RAN_ONE=0
  for s in $STAGES; do
    [ "${DONE[$s]}" -ne 0 ] && continue
    # corpus-gated stages: skip (without burning a try) until corpus exists
    if [ "$s" = parity1000 ] && [ ! -d /tmp/parity1000/train ]; then continue; fi
    RAN_ONE=1
    TRIES[$s]=$((TRIES[$s] + 1))
    echo "[watch-r4 $(date -u +%FT%TZ)] tunnel UP — stage $s (try ${TRIES[$s]})" >> "$LOG"
    if run_stage "$s"; then
      DONE[$s]=1
      echo "[watch-r4 $(date -u +%FT%TZ)] stage $s DONE" >> "$LOG"
    else
      echo "[watch-r4 $(date -u +%FT%TZ)] stage $s failed (rc=$?)" >> "$LOG"
      [ "${TRIES[$s]}" -ge "$MAX_TRIES" ] && { DONE[$s]=2; echo "[watch-r4] stage $s gave up" >> "$LOG"; }
      sleep 300
    fi
    break   # re-probe the tunnel between stages
  done
  # nothing runnable (e.g. only parity1000 left, corpus still generating)
  [ $RAN_ONE -eq 0 ] && sleep 120
done
echo "[watch-r4 $(date -u +%FT%TZ)] all stages terminal: $(for s in $STAGES; do printf '%s=%s ' "$s" "${DONE[$s]}"; done)" >> "$LOG"
