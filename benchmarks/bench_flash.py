"""Flash-attention microbenchmark: Pallas kernel vs plain XLA attention on
the attached chip (VERDICT r1 #7: 'fwd+bwd kernel benched vs attention() on
the real chip, numbers in repo').

Times forward and forward+backward for both implementations at ViT-B shape
(T=197, the actual zoo workload) and a long-context shape (T=2048, where
flash's O(T) memory matters). Timing goes through jax.device_get of a value
depending on the full computation (remote-tunnel block_until_ready returns
at enqueue-ack — see bench.py).

Usage: python benchmarks/bench_flash.py   (on the TPU env; falls back to
interpreter-mode Pallas on CPU, where numbers are meaningless — the platform
is stamped into the metric name so they can't be misread).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench(fn, args, steps: int, warmup: int = 3) -> float:
    """Median-of-steps wall time per call, forced via device_get."""
    import jax
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / steps


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from tpudist.ops.pallas import flash_attention
    from tpudist.parallel.ring_attention import attention

    platform = jax.default_backend()
    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    shapes = [
        ("vitb_224", (8, 197, 12, 64)),     # ViT-B/16 @224: B=8, T=196+cls
        ("long_2k", (2, 2048, 12, 64)),     # long-context: flash O(T) memory
    ]
    if platform != "tpu":
        print(f"[bench_flash] WARNING: platform={platform} — Pallas runs in "
              f"interpreter mode, numbers are meaningless off-TPU",
              file=sys.stderr)
        shapes = [("tiny_64", (1, 64, 4, 16))]

    rng = np.random.default_rng(0)
    for name, (b, t, h, d) in shapes:
        q, k, v = (jnp.asarray(rng.standard_normal((b, t, h, d)), dt)
                   for _ in range(3))

        flash_f = jax.jit(lambda q, k, v: flash_attention(q, k, v))
        plain_f = jax.jit(lambda q, k, v: attention(q, k, v))

        def loss_flash(q, k, v):
            return flash_attention(q, k, v).astype(jnp.float32).sum()

        def loss_plain(q, k, v):
            return attention(q, k, v).astype(jnp.float32).sum()

        flash_g = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
        plain_g = jax.jit(jax.grad(loss_plain, argnums=(0, 1, 2)))

        for label, fn in (("flash_fwd", flash_f), ("xla_fwd", plain_f),
                          ("flash_fwdbwd", flash_g), ("xla_fwdbwd", plain_g)):
            ms = _bench(fn, (q, k, v), args.steps) * 1e3
            # attention flops: 2 matmuls of [T,d]x[d,T] and [T,T]x[T,d]
            # per head (x3 for fwd+bwd rule of thumb).
            flops = 4.0 * b * h * t * t * d * (3.0 if "bwd" in label else 1.0)
            print(json.dumps({
                "metric": f"attn_{name}_{label}_ms_{platform}",
                "value": round(ms, 3),
                "unit": "ms",
                "tflops_per_s": round(flops / (ms / 1e3) / 1e12, 2),
                "shape": [b, t, h, d],
                "dtype": args.dtype,
            }), flush=True)


if __name__ == "__main__":
    main()
