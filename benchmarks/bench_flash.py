"""Flash-attention microbenchmark: Pallas kernel vs plain XLA attention on
the attached chip (VERDICT r1 #7: 'fwd+bwd kernel benched vs attention() on
the real chip, numbers in repo').

Times forward and forward+backward for both implementations at ViT-B shape
(T=197, the actual zoo workload) and a long-context shape (T=2048, where
flash's O(T) memory matters). Timing goes through jax.device_get of a value
depending on the full computation (remote-tunnel block_until_ready returns
at enqueue-ack — see bench.py).

Every numeric row is also appended to ``benchmarks/results/
bench_history.jsonl`` as its own gateable series — ``fwd`` and ``fwd+bwd``
separately, flash and XLA separately — so ``tpudist-regress`` (which gates
``unit: ms`` rows on time INCREASE) covers kernel perf round over round.
Each flash/XLA pair additionally carries the measurement-honest dispatch
verdict (``tpudist/ops/attention_dispatch``) derived from the very numbers
in the row; on TPU that verdict is written into the dispatch cache — a
cache warm for ``--flash auto`` **at the benched shapes** (the cache keys
on batch too, so a training run at a different per-device batch still
measures its own shape once).

Usage: python benchmarks/bench_flash.py   (on the TPU env; falls back to
interpreter-mode Pallas on CPU, where numbers are meaningless — the platform
is stamped into the metric name so they can't be misread, and no dispatch
verdict is cached).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_row(fn, qkv, steps: int, metric: str, shape, dtype: str,
              flops: float) -> dict:
    """One JSON row timed by THE timing harness (attention_dispatch.
    measure_ms, with the remote-tunnel device_get forcing), so bench rows
    and dispatch verdicts cannot drift in methodology; failures become an
    'error' field ('oom' normalized) so the capability probe can report
    XLA's expected long-context OOM."""
    from tpudist.ops.attention_dispatch import measure_ms
    row = {"metric": metric, "unit": "ms", "shape": list(shape),
           "dtype": dtype}
    try:
        ms = measure_ms(fn, qkv, steps, warmup=3)
        row["value"] = round(ms, 3)
        row["tflops_per_s"] = round(flops / (ms / 1e3) / 1e12, 2)
    except Exception as e:
        row["value"] = None
        row["error"] = _norm_error(e)
    print(json.dumps(row), flush=True)
    return row


def _norm_error(e: Exception) -> str:
    """Normalize any out-of-memory-shaped failure to 'oom' (ADVICE r3:
    allocator/Mosaic phrasings vary — substring-matching only XLA's
    RESOURCE_EXHAUSTED flipped the capability-proof exit code on wording).
    'allocat' alone is NOT enough: device-lost/semaphore errors say
    'failed to allocate <resource>' without being memory exhaustion, and the
    long-context capability proof treats an XLA 'oom' as the one tolerated
    failure — so the allocation phrasing must also mention memory."""
    s = str(e).lower()
    if ("resource_exhausted" in s or "out of memory" in s
            or ("allocat" in s and "memory" in s)):
        return "oom"
    return f"{type(e).__name__}: {e}"[:200]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--sweep-blocks", action="store_true",
                    help="sweep (block_q, block_k) for the flash kernel at "
                         "the long-context shape instead of the default "
                         "flash-vs-XLA comparison")
    ap.add_argument("--long-context", type=int, default=0, metavar="T",
                    help="add a (1, T, 12, 64) shape; XLA attention is "
                         "attempted and reported as 'oom' when its O(T^2) "
                         "logits exceed HBM — the flash capability proof")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from tpudist.ops.pallas import flash_attention
    from tpudist.parallel.ring_attention import attention

    platform = jax.default_backend()
    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    long_t = args.long_context
    shapes = [
        ("vitb_224", (8, 197, 12, 64)),     # ViT-B/16 @224: B=8, T=196+cls
        ("long_2k", (2, 2048, 12, 64)),     # long-context: flash O(T) memory
    ]
    if platform != "tpu":
        # Interpreter-mode Pallas is both meaningless to time and hours-slow
        # at real shapes, and XLA's O(T^2) logits can OOM the host — cap
        # everything, including the long-context/sweep shapes, off-TPU.
        print(f"[bench_flash] WARNING: platform={platform} — Pallas runs in "
              f"interpreter mode, numbers are meaningless off-TPU",
              file=sys.stderr)
        shapes = [("tiny_64", (1, 64, 4, 16))]
        if long_t:
            long_t = min(long_t, 256)
    if long_t:
        shapes.append((f"long_{long_t}", (1, long_t, 12, 64)))

    rng = np.random.default_rng(0)

    def qkv(shape):
        return tuple(jnp.asarray(rng.standard_normal(shape), dt)
                     for _ in range(3))

    flash_failed = False

    if args.sweep_blocks:
        b, t, h, d = shapes[-1][1] if long_t else (2, 2048, 12, 64)
        if platform != "tpu":
            b, t, h, d = (1, min(t, 256), 4, 16)
        try:
            args_qkv = qkv((b, t, h, d))
        except Exception as e:
            # Input allocation for the long-context shape can itself OOM;
            # classify it like a kernel OOM instead of crashing (ADVICE r3).
            print(json.dumps({"metric": f"attn_sweep_inputs_{platform}",
                              "value": None, "shape": [b, t, h, d],
                              "dtype": args.dtype,
                              "error": _norm_error(e)}), flush=True)
            return 1
        # flash_attention clamps blocks to ceil8(T); dedupe by the clamped
        # values so the JSON never labels the same compiled kernel as two
        # different configs (a reader picking the fastest row must get a
        # block size that actually ran).
        ceil8 = (t + 7) // 8 * 8
        seen = set()
        for bq in (128, 256, 512):
            for bk in (128, 256, 512):
                eff = (min(bq, ceil8), min(bk, ceil8))
                if eff in seen:
                    continue
                seen.add(eff)
                def loss(q, k, v, bq=bq, bk=bk):
                    return flash_attention(
                        q, k, v, block_q=bq,
                        block_k=bk).astype(jnp.float32).sum()
                # tpudist: ignore[RECOMP01] — block-size sweep: each iteration IS a distinct program; _time_row excludes compile
                fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                row = _time_row(
                    fn, args_qkv, args.steps,
                    f"attn_sweep_bq{eff[0]}_bk{eff[1]}_fwdbwd_ms_{platform}",
                    (b, t, h, d), args.dtype, 12.0 * b * h * t * t * d)
                flash_failed |= "error" in row
        return 1 if flash_failed else 0

    for name, (b, t, h, d) in shapes:
        try:
            q, k, v = qkv((b, t, h, d))
        except Exception as e:
            row = {"metric": f"attn_{name}_inputs_{platform}", "value": None,
                   "shape": [b, t, h, d], "dtype": args.dtype,
                   "error": _norm_error(e)}
            print(json.dumps(row), flush=True)
            flash_failed = True
            continue

        # tpudist: ignore[RECOMP01] — per-shape A/B bench: one jit per benched workload, compile excluded by _time_row
        flash_f = jax.jit(lambda q, k, v: flash_attention(q, k, v))
        # tpudist: ignore[RECOMP01] — per-shape A/B bench: one jit per benched workload, compile excluded by _time_row
        plain_f = jax.jit(lambda q, k, v: attention(q, k, v))

        def loss_flash(q, k, v):
            return flash_attention(q, k, v).astype(jnp.float32).sum()

        def loss_plain(q, k, v):
            return attention(q, k, v).astype(jnp.float32).sum()

        # tpudist: ignore[RECOMP01] — per-shape A/B bench: one jit per benched workload, compile excluded by _time_row
        flash_g = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
        # tpudist: ignore[RECOMP01] — per-shape A/B bench: one jit per benched workload, compile excluded by _time_row
        plain_g = jax.jit(jax.grad(loss_plain, argnums=(0, 1, 2)))

        rows: dict[str, dict] = {}
        for label, fn in (("flash_fwd", flash_f), ("xla_fwd", plain_f),
                          ("flash_fwdbwd", flash_g), ("xla_fwdbwd", plain_g)):
            # attention flops: 2 matmuls of [T,d]x[d,T] and [T,T]x[T,d]
            # per head (x3 for fwd+bwd rule of thumb).
            flops = 4.0 * b * h * t * t * d * (3.0 if "bwd" in label else 1.0)
            row = _time_row(fn, (q, k, v), args.steps,
                            f"attn_{name}_{label}_ms_{platform}",
                            (b, t, h, d), args.dtype, flops)
            rows[label] = row
            # Any erroring row fails the bench EXCEPT the one expected
            # capability-proof outcome: XLA reporting 'oom' at a
            # long-context shape. A flash error is a kernel regression; an
            # XLA non-oom error (or an oom at the ViT shape) is a broken
            # baseline — neither may exit 0.
            if "error" in row and not (
                    label.startswith("xla") and row["error"] == "oom"
                    and name.startswith("long_")):
                flash_failed = True
        _embed_dispatch_and_append(rows, b, t, h, d, args.dtype, platform)
    return 1 if flash_failed else 0


def _embed_dispatch_and_append(rows: dict, b: int, t: int, h: int, d: int,
                               dtype: str, platform: str) -> None:
    """Stamp the measurement-honest dispatch verdict onto each flash/XLA
    pair (separately for fwd = eval and fwd+bwd = train) and append every
    numeric row to the bench history as its own regress-gateable series.
    On TPU the verdict (derived from the rows' own timings via the
    ``measure_pair`` hook) is also written into the dispatch cache — a
    bench run doubles as a ``--flash auto`` cache warm; off-TPU ``decide``
    resolves to XLA on platform grounds and caches nothing."""
    from tpudist.ops import attention_dispatch
    from tpudist.regress import append_history

    for pass_name, train in (("fwd", False), ("fwdbwd", True)):
        fr = rows.get(f"flash_{pass_name}")
        xr = rows.get(f"xla_{pass_name}")
        if not fr or not xr or fr.get("value") is None \
                or xr.get("value") is None:
            continue
        try:
            dec = attention_dispatch.decide(
                b, t, h, d, dtype, train=train, mode="auto",
                platform=platform, refresh=True,
                measure_pair=lambda fr=fr, xr=xr: (fr["value"], xr["value"]))
        except Exception as e:
            print(f"[bench_flash] dispatch verdict failed: {e!r}",
                  file=sys.stderr)
            continue
        disp = {"kernel": dec["kernel"], "source": dec["source"],
                "flash_ms": fr["value"], "xla_ms": xr["value"]}
        fr["dispatch"] = disp
        xr["dispatch"] = disp
    if platform != "tpu":
        # Interpreter-mode timings are "meaningless off-TPU" by this file's
        # own banner — they must not become gateable history either
        # (tpudist-regress now trips ms series UPWARD, and interpreter
        # noise routinely exceeds any threshold). Stdout still carries the
        # rows for capability probing; history stays measurement-only.
        print("[bench_flash] platform != tpu — rows NOT appended to bench "
              "history (interpreter timings are not measurements)",
              file=sys.stderr)
        return
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    appended = 0
    for row in rows.values():
        if isinstance(row.get("value"), (int, float)):
            append_history({**row, "measured_at": now})
            appended += 1
    if appended:
        print(f"[bench_flash] {appended} row(s) appended to bench history",
              file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
