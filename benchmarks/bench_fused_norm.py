"""Fused BN-epilogue microbenchmark: Pallas BN+ReLU / BN+add+ReLU kernels vs
the XLA epilogue on the attached chip (ISSUE 6 tentpole: the A/B evidence
behind ``--fused-bn auto``).

Times forward+backward (the training configuration — BN epilogues only
matter there) for both implementations at the resnet18@224/bs128 stage
workloads — the canonical bench's ACTUAL epilogue shapes, where PR 5's
attribution table says the VPU time goes — plus a wide-channel bottleneck
shape. Timing goes through the shared dispatch harness
(``ops/dispatch.measure_ms``, the remote-tunnel device_get forcing), so
bench rows and dispatch verdicts cannot drift in methodology.

Every numeric row is appended to ``benchmarks/results/bench_history.jsonl``
as its own gateable ``unit: ms`` series (``tpudist-regress`` trips on time
INCREASE), and each pallas/XLA pair carries the measurement-honest dispatch
verdict derived from the very numbers in the row; on TPU that verdict is
written into the dispatch cache — a ``--fused-bn auto`` cache warm **at the
benched workloads** (a training run at a different per-device batch still
measures its own shapes once). Off-TPU nothing is appended or cached:
interpreter timings are not measurements.

Usage: python benchmarks/bench_fused_norm.py [--steps N] [--batch B]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_row(fn, args, steps: int, metric: str, rows: int, channels: int,
              dtype: str, residual: bool) -> dict:
    from tpudist.ops.dispatch import measure_ms
    row = {"metric": metric, "unit": "ms", "shape": [rows, channels],
           "dtype": dtype}
    try:
        ms = measure_ms(fn, args, steps, warmup=3)
        row["value"] = round(ms, 3)
        # epilogue traffic across fwd+bwd, in activation-tensor passes:
        # plain = fwd read x, write y + bwd read x, dy, write dx (5);
        # residual = fwd read x, res, write y + bwd read x, res, dy
        # (the relu mask recompute needs both), write dx, dres (8). A
        # bandwidth number, the roofline the kernel plays against.
        passes = 8 if residual else 5
        nbytes = np.dtype(dtype).itemsize * rows * channels
        row["gb_per_s"] = round(passes * nbytes / (ms / 1e3) / 1e9, 1)
    except Exception as e:
        row["value"] = None
        row["error"] = f"{type(e).__name__}: {e}"[:200]
    print(json.dumps(row), flush=True)
    return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--batch", type=int, default=128,
                    help="per-device batch the resnet stage shapes derive "
                         "from (canonical bench: 128)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from tpudist.ops import norm_dispatch

    platform = jax.default_backend()
    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    b = args.batch
    # resnet18@224 stage activations (NHWC rows = B·H·W), plain BN+ReLU at
    # every stage plus the residual epilogue at the two ends; one
    # wide-channel bottleneck shape rides along for resnet50 coverage.
    shapes = [
        ("stage1", b * 56 * 56, 64, False),
        ("stage1_res", b * 56 * 56, 64, True),
        ("stage2", b * 28 * 28, 128, False),
        ("stage3", b * 14 * 14, 256, False),
        ("stage4", b * 7 * 7, 512, False),
        ("stage4_res", b * 7 * 7, 512, True),
        ("wide", b * 7 * 7, 2048, True),
    ]
    if platform != "tpu":
        print(f"[bench_fused_norm] WARNING: platform={platform} — Pallas "
              f"runs in interpreter mode, numbers are meaningless off-TPU",
              file=sys.stderr)
        shapes = [("tiny", 256, 64, False), ("tiny_res", 256, 64, True)]

    failed = False
    for name, rows, channels, residual in shapes:
        # The workload pair comes from norm_dispatch's OWN builder: bench
        # rows and dispatch verdicts measure the same computation by
        # construction, not by parallel maintenance.
        pallas_c, xla_c, fargs = norm_dispatch.build_measure_fns(
            rows, channels, dt, residual, interpret=platform != "tpu")

        rows_out = {}
        for label, fn in (("pallas", pallas_c), ("xla", xla_c)):
            row = _time_row(
                fn, fargs, args.steps,
                f"fusednorm_{name}_b{b}_{label}_fwdbwd_ms_{platform}",
                rows, channels, args.dtype, residual)
            rows_out[label] = row
            failed |= "error" in row
        _embed_dispatch_and_append(rows_out, rows, channels, args.dtype,
                                   residual, platform)
    return 1 if failed else 0


def _embed_dispatch_and_append(rows_out: dict, rows: int, channels: int,
                               dtype: str, residual: bool,
                               platform: str) -> None:
    """Stamp the measurement-honest dispatch verdict onto the pallas/XLA
    pair and append both to the bench history as regress-gateable ms
    series. On TPU the verdict (derived from the rows' own timings via the
    ``measure_pair`` hook) also lands in the dispatch cache — a bench run
    doubles as a ``--fused-bn auto`` cache warm; off-TPU ``decide``
    resolves to XLA on platform grounds and caches nothing, and nothing is
    appended (interpreter timings are not measurements)."""
    from tpudist.ops import norm_dispatch
    from tpudist.regress import append_history

    pr, xr = rows_out.get("pallas"), rows_out.get("xla")
    if pr and xr and pr.get("value") is not None \
            and xr.get("value") is not None:
        try:
            dec = norm_dispatch.decide(
                rows, channels, dtype, residual=residual, mode="auto",
                platform=platform, refresh=True,
                measure_pair=lambda: (pr["value"], xr["value"]))
            disp = {"kernel": dec["kernel"], "source": dec["source"],
                    "pallas_ms": pr["value"], "xla_ms": xr["value"]}
            pr["dispatch"] = disp
            xr["dispatch"] = disp
        except Exception as e:
            print(f"[bench_fused_norm] dispatch verdict failed: {e!r}",
                  file=sys.stderr)
    if platform != "tpu":
        print("[bench_fused_norm] platform != tpu — rows NOT appended to "
              "bench history (interpreter timings are not measurements)",
              file=sys.stderr)
        return
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    appended = 0
    for row in rows_out.values():
        if isinstance(row.get("value"), (int, float)):
            append_history({**row, "measured_at": now})
            appended += 1
    if appended:
        print(f"[bench_fused_norm] {appended} row(s) appended to bench "
              f"history", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
