// tpudist native JPEG decode (VERDICT r2 next #5).
//
// The r2 loader kept JPEG *decode* in PIL and only fused the transforms, so
// decode dominated (+22% total). This file moves decode into the same .so
// using libjpeg(-turbo), fused with the transform so the decode itself
// shrinks to what the crop actually needs:
//
// - DCT scaling: decode at 1/2, 1/4 or 1/8 resolution when the sampled crop
//   is much larger than the output size — an 8x8 DCT block can be
//   reconstructed at 4/2/1 pixels directly from its low-frequency
//   coefficients, so a 512px image headed for a 224px crop-resize never
//   materializes at full resolution (PIL decodes all of it, full size).
// - jpeg_crop_scanline / jpeg_skip_scanlines (libjpeg-turbo partial decode):
//   only the iMCU-aligned horizontal band and vertical rows covering the
//   crop are entropy-decoded at all.
// - The decoded band feeds the existing fused crop→bilinear→flip→normalize
//   kernel (transforms.cc) — one intermediate, one output pass.
//
// Anything the fast path cannot handle (CMYK, corrupt files, non-JPEG)
// returns nonzero and the Python caller falls back to PIL.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <algorithm>

#include <jpeglib.h>

extern "C" {
// transforms.cc
void crop_resize_normalize(const uint8_t* src, int src_h, int src_w,
                           int x0, int y0, int cw, int ch,
                           int out_size, int flip,
                           const float* mean, const float* std_,
                           float* dst);
void val_resize_crop_normalize(const uint8_t* src, int src_h, int src_w,
                               int resize, int out_size,
                               const float* mean, const float* std_,
                               float* dst);
}

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void err_exit(j_common_ptr cinfo) {
  ErrMgr* e = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(e->jb, 1);
}

void err_silent(j_common_ptr, int) {}
void err_silent_msg(j_common_ptr) {}

// Decode `data` with scale 1/denom, cropped to the iMCU-aligned band around
// [*xs, *xs+*ws) and rows [*ys, *ys+*hs) (all in SCALED coordinates; the
// box is clamped in place to the scaled frame). On success *out holds a
// malloc'd (*hs, band_w, 3) u8 buffer and *x_in_band is the scaled crop's
// x offset within it. Caller frees *out.
//
// denom <= 0 selects the scale HERE, from this call's own header parse: the
// largest 1/2^k keeping the scaled shorter edge >= auto_min_edge (the val
// stack's Resize target) — so val needs no separate dimension query.
int decode_band(const uint8_t* data, size_t len, int denom, int auto_min_edge,
                int* ys_io, int* hs_io, int* xs_io, int* ws_io,
                uint8_t** out, int* band_w, int* x_in_band) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  // volatile: assigned between setjmp and the longjmp that reads it in the
  // error handler (libjpeg example.c pattern) — without it the -O3 register
  // copy seen after longjmp is indeterminate.
  uint8_t* volatile buf = nullptr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  jerr.pub.emit_message = err_silent;
  jerr.pub.output_message = err_silent_msg;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(buf);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;       // grayscale/YCbCr → RGB; CMYK errors
  if (denom <= 0) {
    int short_edge = (int)std::min(cinfo.image_width, cinfo.image_height);
    denom = 1;
    while (denom < 8 && short_edge / (denom * 2) >= auto_min_edge)
      denom *= 2;
  }
  cinfo.scale_num = 1;
  cinfo.scale_denom = (unsigned)denom;
  // The decode feeds a bilinear down-resize, which low-passes anyway — the
  // fast integer IDCT and plain (non-fancy) chroma upsampling are visually
  // equivalent here and measurably cheaper than PIL's islow+fancy defaults.
  cinfo.dct_method = JDCT_IFAST;
  cinfo.do_fancy_upsampling = FALSE;
  // Output dims are fixed by the scale — compute them BEFORE start so the
  // partial-decode decision below can feed the upsampling choice (which
  // must be made before jpeg_start_decompress).
  jpeg_calc_output_dimensions(&cinfo);
  int ow = (int)cinfo.output_width, oh = (int)cinfo.output_height;
  int xs = std::clamp(*xs_io, 0, ow - 1);
  int ys = std::clamp(*ys_io, 0, oh - 1);
  int ws = std::clamp(*ws_io, 1, ow - xs);
  int hs = std::clamp(*hs_io, 1, oh - ys);
  *xs_io = xs; *ys_io = ys; *ws_io = ws; *hs_io = hs;
  if (ws < ow || ys > 0) {
    // Partial decode (jpeg_crop_scanline / jpeg_skip_scanlines) combined
    // with MERGED chroma upsampling — the non-fancy 4:2:0 fast path —
    // corrupts the heap in several libjpeg-turbo versions (writes past the
    // crop band; found by the fault-injection suite's data-path stress:
    // free(): invalid next size). Fancy (separable) upsampling uses the
    // well-tested skip/crop implementation, so force it whenever the
    // decode is partial; full-frame decodes keep the fast merged path.
    cinfo.do_fancy_upsampling = TRUE;
  }
  jpeg_start_decompress(&cinfo);
  JDIMENSION xoff = (JDIMENSION)xs, w_adj = (JDIMENSION)ws;
  if (ws < ow)                          // full-width crop needs no realign
    jpeg_crop_scanline(&cinfo, &xoff, &w_adj);
  if (ys > 0)
    jpeg_skip_scanlines(&cinfo, (JDIMENSION)ys);
  buf = (uint8_t*)std::malloc((size_t)hs * w_adj * 3);
  if (!buf)
    longjmp(jerr.jb, 1);
  while ((int)cinfo.output_scanline < ys + hs) {
    JSAMPROW row = buf + (size_t)((int)cinfo.output_scanline - ys) * w_adj * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_abort_decompress(&cinfo);         // legally skip the remaining rows
  jpeg_destroy_decompress(&cinfo);
  *out = buf;
  *band_w = (int)w_adj;
  *x_in_band = xs - (int)xoff;
  return 0;
}

}  // namespace

extern "C" {

// Parse only the header; writes full-resolution dims. Returns 0 on success.
int jpeg_header_dims(const uint8_t* data, size_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  jerr.pub.emit_message = err_silent;
  jerr.pub.output_message = err_silent_msg;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, len);
  jpeg_read_header(&cinfo, TRUE);
  *h = (int)cinfo.image_height;
  *w = (int)cinfo.image_width;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Fused decode → RandomResizedCrop box (FULL-RES coords) → bilinear resize
// to out_size → flip → normalize. Returns 0 on success.
int jpeg_decode_crop_resize_normalize(const uint8_t* data, size_t len,
                                      int x0, int y0, int cw, int ch,
                                      int out_size, int flip,
                                      const float* mean, const float* std_,
                                      float* dst) {
  // Largest 1/2^k scale whose scaled crop still covers the output — never
  // upsample out of a reduced decode.
  int denom = 1;
  while (denom < 8 && cw / (denom * 2) >= out_size
         && ch / (denom * 2) >= out_size)
    denom *= 2;
  // Scaled crop box (floor offset, round extent; decode_band clamps).
  int xs = x0 / denom, ys = y0 / denom;
  int ws = std::max(1, (cw + denom / 2) / denom);
  int hs = std::max(1, (ch + denom / 2) / denom);
  uint8_t* band = nullptr;
  int band_w = 0, x_in_band = 0;
  if (decode_band(data, len, denom, 0, &ys, &hs, &xs, &ws, &band, &band_w,
                  &x_in_band))
    return 1;
  crop_resize_normalize(band, hs, band_w, x_in_band, 0, ws, hs,
                        out_size, flip, mean, std_, dst);
  std::free(band);
  return 0;
}

// Fused decode → Resize(shorter=resize) → CenterCrop(out_size) → normalize
// (the reference's val stack). Returns 0 on success.
int jpeg_decode_val(const uint8_t* data, size_t len, int resize, int out_size,
                    const float* mean, const float* std_, float* dst) {
  // Full-frame box (decode_band clamps to the scaled frame); the scale is
  // chosen inside decode_band from its own header parse — one parse total.
  int ys = 0, xs = 0, oh = 1 << 28, ow = 1 << 28;
  uint8_t* full = nullptr;
  int band_w = 0, x_in_band = 0;
  if (decode_band(data, len, /*denom=*/0, /*auto_min_edge=*/resize,
                  &ys, &oh, &xs, &ow, &full, &band_w, &x_in_band))
    return 1;
  val_resize_crop_normalize(full, oh, band_w, resize, out_size,
                            mean, std_, dst);
  std::free(full);
  return 0;
}

}  // extern "C"
