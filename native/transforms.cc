// tpudist native data-path kernels.
//
// The reference's input pipeline leans on native code it never shows: torch
// DataLoader's C worker pool and PIL/torchvision's C transform kernels
// (SURVEY.md §2.3 "DataLoader multiprocess workers"). This is our equivalent:
// a fused crop→bilinear-resize→flip→normalize kernel that turns a decoded
// uint8 HWC image into a normalized float32 HWC tensor in ONE pass over the
// output (PIL does crop, resize, to-float, normalize as separate passes over
// full intermediates).
//
// Called from Python via ctypes (loader threads call it with the GIL
// released, so batch assembly parallelizes across cores).

#include <cstdint>
#include <algorithm>
#include <cmath>

extern "C" {

// Fused: crop box (x0,y0,w,h) from src (H,W,3 uint8, row stride = W*3),
// bilinear-resize to (out_size, out_size), optional horizontal flip,
// normalize ((v/255 - mean)/std), write float32 HWC.
void crop_resize_normalize(const uint8_t* src, int src_h, int src_w,
                           int x0, int y0, int cw, int ch,
                           int out_size, int flip,
                           const float* mean, const float* std_,
                           float* dst) {
  const float sx = (float)cw / out_size;
  const float sy = (float)ch / out_size;
  const float inv255 = 1.0f / 255.0f;
  float inv_std[3], mean_[3];
  for (int c = 0; c < 3; ++c) {
    inv_std[c] = 1.0f / std_[c];
    mean_[c] = mean[c];
  }
  // Per-column sample positions are row-invariant: precompute byte offsets
  // and weights once instead of floor/clamp per pixel per row.
  int* xoff1 = new int[out_size];
  int* xoff2 = new int[out_size];
  float* wxs = new float[out_size];
  for (int ox = 0; ox < out_size; ++ox) {
    float fx = (ox + 0.5f) * sx - 0.5f + x0;
    int x1 = (int)std::floor(fx);
    wxs[ox] = fx - x1;
    xoff1[ox] = std::clamp(x1, 0, src_w - 1) * 3;
    xoff2[ox] = std::clamp(x1 + 1, 0, src_w - 1) * 3;
  }
  for (int oy = 0; oy < out_size; ++oy) {
    // PIL-convention bilinear: sample at pixel centers.
    float fy = (oy + 0.5f) * sy - 0.5f + y0;
    int y1 = (int)std::floor(fy);
    float wy = fy - y1;
    int y1c = std::clamp(y1, 0, src_h - 1);
    int y2c = std::clamp(y1 + 1, 0, src_h - 1);
    const uint8_t* row1 = src + (size_t)y1c * src_w * 3;
    const uint8_t* row2 = src + (size_t)y2c * src_w * 3;
    float* out_row = dst + (size_t)oy * out_size * 3;
    for (int ox = 0; ox < out_size; ++ox) {
      float wx = wxs[ox];
      int o1 = xoff1[ox], o2 = xoff2[ox];
      int out_x = flip ? (out_size - 1 - ox) : ox;
      float* px = out_row + (size_t)out_x * 3;
      for (int c = 0; c < 3; ++c) {
        float v11 = row1[o1 + c], v12 = row1[o2 + c];
        float v21 = row2[o1 + c], v22 = row2[o2 + c];
        float top = v11 + (v12 - v11) * wx;
        float bot = v21 + (v22 - v21) * wx;
        float v = top + (bot - top) * wy;
        px[c] = (v * inv255 - mean_[c]) * inv_std[c];
      }
    }
  }
  delete[] xoff1;
  delete[] xoff2;
  delete[] wxs;
}

// Center-crop + shorter-side-resize + normalize (the val stack,
// distributed.py:171-176) as one call: resize so shorter edge == resize_to,
// then center-crop out_size — expressed as a single crop box in SOURCE
// coordinates so no intermediate image is materialized.
void val_resize_crop_normalize(const uint8_t* src, int src_h, int src_w,
                               int resize_to, int out_size,
                               const float* mean, const float* std_,
                               float* dst) {
  // Scale factor of the virtual Resize(shorter=resize_to).
  float scale = (src_w <= src_h) ? (float)src_w / resize_to
                                 : (float)src_h / resize_to;
  // The out_size×out_size center crop in resized coords maps to a
  // crop_px×crop_px box centered in the source.
  float crop_src = out_size * scale;
  float x0f = (src_w - crop_src) * 0.5f;
  float y0f = (src_h - crop_src) * 0.5f;
  // Reuse the fused kernel with a float-precise box via rounded ints; the
  // sub-pixel residual is within bilinear tolerance.
  crop_resize_normalize(src, src_h, src_w,
                        (int)std::lround(x0f), (int)std::lround(y0f),
                        (int)std::lround(crop_src), (int)std::lround(crop_src),
                        out_size, /*flip=*/0, mean, std_, dst);
}

}  // extern "C"
