"""Perf-regression gate over the bench history
(``python -m tpudist.regress`` / ``tpudist-regress``).

``bench.py`` appends every fresh measurement to
``benchmarks/results/bench_history.jsonl`` (one JSON row per line, the same
shape it prints to stdout plus ``measured_at``). This gate compares the
NEWEST fresh row of a workload against the trailing median of its
predecessors and **fails loudly** (exit 2, ``REGRESSION`` banner) when
images/sec or MFU dropped — or, for latency series (``unit: ms``, e.g. the
``bench_flash`` kernel rows), the time ROSE — or, on any row carrying
``collective_bytes_per_step`` (the XLA census), the per-step collective
bytes GREW (a step-builder change silently re-densifying a compressed
exchange, or a sharding change widening a gather) — more than
``--threshold`` (default 10%) — the
automated tripwire the ROADMAP's "as fast as the hardware allows" needs,
instead of a human eyeballing BENCH_r* files across rounds.

Row identity is the row's ``metric`` name — it encodes arch, image size,
precision, remat/s2d levers, AND the platform suffix (``..._1chip`` vs
``..._8dev_cpu_fallback``), so a CPU-fallback bench can never gate against
TPU history — PLUS ``per_device_batch``, which the metric name does NOT
encode: a batch sweep (b=16 after b=128 history) must open its own series,
not trip a false REGRESSION against the other batch's median. Rows stamped
``stale``/``provisional`` (bench's re-emission path) are measurement
*echoes*, not measurements — they are never appended by bench and are
ignored here if present.

Median (not mean) over the trailing window: one noisy historical row must
not move the baseline; an improvement simply raises future medians.
``analyze_history`` is a pure function of the row list so the gate is
unit-testable against synthetic histories.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def history_path() -> str:
    """The bench history file, resolved at CALL time so a test/tool setting
    ``TPUDIST_BENCH_HISTORY`` after import still redirects appends."""
    return os.environ.get(
        "TPUDIST_BENCH_HISTORY",
        os.path.join(_REPO, "benchmarks", "results", "bench_history.jsonl"))


def load_history(path: str) -> list[dict]:
    """All parseable, non-stale rows, file order (= append order)."""
    rows: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(row, dict) or row.get("stale") \
                        or row.get("provisional"):
                    continue
                if row.get("metric") and isinstance(row.get("value"),
                                                    (int, float)):
                    rows.append(row)
    except OSError:
        pass
    return rows


def append_history(row: dict, path: Optional[str] = None) -> None:
    """One fresh bench row → one history line (callers stamp measured_at)."""
    path = path or history_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")


def _median(xs: list[float]) -> float:
    # telemetry.percentile is the repo's one interpolated-percentile
    # implementation (import-light, jax-free) — q=50 IS the median for
    # both parities.
    from tpudist.telemetry import percentile
    return percentile(xs, 50)


def _series_key(row: dict) -> tuple:
    return (row.get("metric"), row.get("per_device_batch"))


def analyze_history(rows: list[dict], metric: Optional[str] = None,
                    window: int = 5, threshold: float = 0.10,
                    min_history: int = 1) -> dict:
    """Gate verdict for one workload's newest row vs its trailing median.

    ``metric`` selects the workload; default = the workload of the LAST row
    in the history (what bench just appended). The series the newest row
    gates against additionally matches on ``per_device_batch``
    (``_series_key``). Returns a dict with ``status`` in {"pass",
    "regression", "no_history", "no_baseline"} and the numbers behind it;
    ``reasons`` lists every tripped dimension.
    """
    cands = rows if metric is None \
        else [r for r in rows if r.get("metric") == metric]
    if not cands:
        return {"status": "no_history", "metric": metric, "n_history": 0}
    key = _series_key(cands[-1])
    metric = cands[-1]["metric"]
    group = [r for r in rows if _series_key(r) == key]
    newest, prior = group[-1], group[:-1][-window:]
    out: dict = {"status": "pass", "metric": metric,
                 "per_device_batch": newest.get("per_device_batch"),
                 "value": newest["value"],
                 "n_history": len(group) - 1, "window": len(prior),
                 "threshold": threshold, "reasons": [],
                 "measured_at": newest.get("measured_at")}
    if len(prior) < min_history:
        out["status"] = "no_baseline"
        return out
    base_v = _median([r["value"] for r in prior])
    out["baseline_value"] = round(base_v, 2)
    out["ratio"] = round(newest["value"] / base_v, 4) if base_v else None
    # Gate direction follows the series' unit: throughput series
    # (images/sec, MFU) regress DOWNWARD; latency series (the bench_flash
    # ``unit: ms`` rows) regress UPWARD. A row may also state it outright
    # (``lower_is_better``) for units this heuristic doesn't know.
    lower_better = bool(newest.get("lower_is_better",
                                   newest.get("unit") == "ms"))
    out["lower_is_better"] = lower_better
    if lower_better:
        if base_v and newest["value"] > (1.0 + threshold) * base_v:
            out["status"] = "regression"
            out["reasons"].append(
                f"{newest.get('unit', 'value')} {newest['value']:.3f} is "
                f"{(newest['value'] / base_v - 1):.1%} above the trailing "
                f"median {base_v:.3f} (n={len(prior)})")
    elif base_v and newest["value"] < (1.0 - threshold) * base_v:
        out["status"] = "regression"
        # Name the series' own unit (req/s for the serving saturation
        # rows, images/sec for the throughput default) so the banner
        # reads correctly for every higher-is-better series.
        out["reasons"].append(
            f"{newest.get('unit') or 'images/sec'} {newest['value']:.1f} "
            f"is {(1 - newest['value'] / base_v):.1%} below the trailing "
            f"median {base_v:.1f} (n={len(prior)})")
    prior_mfu = [r["mfu"] for r in prior
                 if isinstance(r.get("mfu"), (int, float))]
    if isinstance(newest.get("mfu"), (int, float)) and \
            len(prior_mfu) >= min_history:
        base_m = _median(prior_mfu)
        out["mfu"] = newest["mfu"]
        out["baseline_mfu"] = round(base_m, 4)
        if base_m and newest["mfu"] < (1.0 - threshold) * base_m:
            out["status"] = "regression"
            out["reasons"].append(
                f"MFU {newest['mfu']:.4f} is "
                f"{(1 - newest['mfu'] / base_m):.1%} below the trailing "
                f"median {base_m:.4f} (n={len(prior_mfu)})")
    # Collective-bytes gate (PR 11: communication is a first-class gated
    # dimension beside img/s and MFU): the census bytes are a deterministic
    # property of the compiled program, so a rise above the trailing median
    # means the program grew its comms — a step-builder change silently
    # re-densifying a compressed exchange, or a sharding change widening a
    # gather. Bytes regress UPWARD regardless of the series' value unit.
    prior_cb = [r["collective_bytes_per_step"] for r in prior
                if isinstance(r.get("collective_bytes_per_step"),
                              (int, float))]
    if isinstance(newest.get("collective_bytes_per_step"), (int, float)) \
            and len(prior_cb) >= min_history:
        base_b = _median(prior_cb)
        out["collective_bytes_per_step"] = newest[
            "collective_bytes_per_step"]
        out["baseline_collective_bytes"] = round(base_b, 1)
        if base_b and newest["collective_bytes_per_step"] \
                > (1.0 + threshold) * base_b:
            out["status"] = "regression"
            out["reasons"].append(
                f"collective bytes/step "
                f"{newest['collective_bytes_per_step']:.3e} is "
                f"{(newest['collective_bytes_per_step'] / base_b - 1):.1%} "
                f"above the trailing median {base_b:.3e} "
                f"(n={len(prior_cb)})")
    return out


def format_verdict(v: dict) -> str:
    m = v.get("metric") or "<no rows>"
    if v["status"] == "no_history":
        return f"[regress] no history for {m} — nothing to gate"
    if v["status"] == "no_baseline":
        return (f"[regress] {m}: {v['n_history']} prior row(s) — below "
                f"min history, gate not armed (value {v['value']})")
    head = (f"[regress] {m}: value {v['value']} vs trailing median "
            f"{v.get('baseline_value')} (ratio {v.get('ratio')}"
            + (f", mfu {v['mfu']} vs {v['baseline_mfu']}"
               if "mfu" in v else "") + ")")
    if v["status"] == "regression":
        return ("REGRESSION: " + "; ".join(v["reasons"]) + "\n" + head)
    return head + " — PASS"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Gate the newest bench row against its trailing-median "
                    "history (exit 2 on >threshold regression)")
    # default=None, resolved below at CALL time: an argparse default of
    # history_path() would re-freeze the env var at parse time — the exact
    # dual-path bug the old module-level DEFAULT_HISTORY snapshot had
    # (a caller setting TPUDIST_BENCH_HISTORY after import gated against
    # the wrong file).
    p.add_argument("--history", default=None,
                   help="bench_history.jsonl path "
                        "(env TPUDIST_BENCH_HISTORY)")
    p.add_argument("--metric", default=None,
                   help="workload metric name to gate (default: the "
                        "history's newest row)")
    p.add_argument("--window", type=int, default=5,
                   help="trailing rows the baseline median is taken over")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="fractional drop in images/sec or MFU that fails "
                        "the gate")
    p.add_argument("--min-history", type=int, default=1, dest="min_history",
                   help="prior rows required before the gate arms "
                        "(below it: informational pass)")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict as JSON (status still drives the "
                        "exit code)")
    args = p.parse_args(argv)

    rows = load_history(args.history or history_path())
    v = analyze_history(rows, metric=args.metric, window=args.window,
                        threshold=args.threshold,
                        min_history=args.min_history)
    if args.json:
        print(json.dumps(v))
    else:
        print(format_verdict(v))
    return 2 if v["status"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
