"""Version shim: expose the jax>=0.8 surface this package codes against on
older jax installs (no new deps — ROADMAP environments pin different jax
versions and the container cannot pip install).

The one load-bearing gap today is top-level ``jax.shard_map`` (jax 0.8
promoted ``jax.experimental.shard_map.shard_map`` and renamed two kwargs:
``check_rep`` → ``check_vma``, and the *auto* axis set became its complement
``axis_names`` — the axes the body IS manual over). Everything else this
repo uses (``jax.distributed.initialize(initialization_timeout=...)``,
``NamedSharding``, ``multihost_utils``) exists back to 0.4.x.

Imported for its side effect from ``tpudist/__init__.py`` so every
``from jax import shard_map`` / ``jax.shard_map(...)`` site in the package
and its tests works unchanged on either version. On jax>=0.8 this module is
a no-op.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        kwargs = {}
        if axis_names is not None:
            # New API names the MANUAL axes; the old one names the AUTO
            # (complement) set.
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs,
                                       check_rep=check_vma, **kwargs)

    jax.shard_map = shard_map

if not hasattr(jax.lax, "axis_size"):
    # jax<0.6 spells "static size of a bound axis" as core.axis_frame(name)
    # (an int on 0.4.x; earlier versions return a frame with .size).
    def _axis_size(axis_name):
        frame = jax.core.axis_frame(axis_name)
        return getattr(frame, "size", frame)

    jax.lax.axis_size = _axis_size

if not hasattr(jax.sharding, "set_mesh"):
    # jax<0.8 has no jax.sharding.set_mesh; the GSPMD step builders use it
    # to provide the ambient mesh for trace-time consumers (the Pallas
    # flash kernel's nested manual region). On these versions entering the
    # Mesh itself is the ambient-mesh context manager.
    jax.sharding.set_mesh = lambda mesh: mesh
