"""Version shim: expose the jax>=0.8 surface this package codes against on
older jax installs (no new deps — ROADMAP environments pin different jax
versions and the container cannot pip install).

The one load-bearing gap today is top-level ``jax.shard_map`` (jax 0.8
promoted ``jax.experimental.shard_map.shard_map`` and renamed two kwargs:
``check_rep`` → ``check_vma``, and the *auto* axis set became its complement
``axis_names`` — the axes the body IS manual over). Everything else this
repo uses (``jax.distributed.initialize(initialization_timeout=...)``,
``NamedSharding``, ``multihost_utils``) exists back to 0.4.x.

Imported for its side effect from ``tpudist/__init__.py`` so every
``from jax import shard_map`` / ``jax.shard_map(...)`` site in the package
and its tests works unchanged on either version. On jax>=0.8 this module is
a no-op.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        kwargs = {}
        if axis_names is not None:
            # New API names the MANUAL axes; the old one names the AUTO
            # (complement) set.
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs,
                                       check_rep=check_vma, **kwargs)

    jax.shard_map = shard_map

if not hasattr(jax.lax, "axis_size"):
    # jax<0.6 spells "static size of a bound axis" as core.axis_frame(name)
    # (an int on 0.4.x; earlier versions return a frame with .size).
    def _axis_size(axis_name):
        frame = jax.core.axis_frame(axis_name)
        return getattr(frame, "size", frame)

    jax.lax.axis_size = _axis_size

if not hasattr(jax.sharding, "set_mesh"):
    # jax<0.8 has no jax.sharding.set_mesh; the GSPMD step builders use it
    # to provide the ambient mesh for trace-time consumers (the Pallas
    # flash kernel's nested manual region). On these versions entering the
    # Mesh itself is the ambient-mesh context manager.
    jax.sharding.set_mesh = lambda mesh: mesh

if not hasattr(jax.sharding, "AxisType"):
    # jax<0.8 spells mesh axis kinds jax._src.mesh.AxisTypes with different
    # members (Auto/User/Collective vs the new Auto/Explicit/Manual). The
    # shim only needs identity semantics for `t == AxisType.Auto` checks,
    # so expose a tiny enum-alike with the one member the package compares
    # against.
    class _AxisType:
        class Auto:
            pass

        class Explicit:
            pass

        class Manual:
            pass

    jax.sharding.AxisType = _AxisType


class _AbstractMeshShim:
    """jax<0.8 stand-in for ``jax.sharding.get_abstract_mesh()``'s result:
    wraps the thread-resources physical mesh (the ``with mesh:`` context
    that ``set_mesh`` resolves to on these versions) and reports every axis
    as Auto — on old jax the ambient-context mesh IS the partitioner-managed
    (GSPMD) mesh; manual (shard_map-bound) axes never appear here because
    they live in the axis environment, not the context mesh (see
    ``ambient_auto_axes``, which subtracts them). ``physical_mesh`` is the
    real ``Mesh`` a nested ``shard_map`` needs."""

    def __init__(self, mesh):
        self.physical_mesh = mesh

    @property
    def empty(self):
        return self.physical_mesh.empty

    @property
    def axis_names(self):
        return self.physical_mesh.axis_names

    @property
    def shape(self):
        return self.physical_mesh.shape

    @property
    def axis_types(self):
        return (jax.sharding.AxisType.Auto,) * len(
            self.physical_mesh.axis_names)


if not hasattr(jax.sharding, "get_abstract_mesh"):
    # jax<0.8: the ambient mesh is the entered-Mesh thread resource (what
    # the shimmed set_mesh provides). Exposing it under the jax>=0.8 name
    # lets flash_attention_spmd / fused_bn_act_spmd compose with the GSPMD
    # path on old jax instead of standing down to gather-and-replicate —
    # the off-TPU environment-reason failure of
    # test_gspmd_step_composes_with_flash at clean HEAD since PR 5.
    def _get_abstract_mesh():
        from jax._src import mesh as _mesh_lib
        return _AbstractMeshShim(_mesh_lib.thread_resources.env.physical_mesh)

    jax.sharding.get_abstract_mesh = _get_abstract_mesh


def _axis_is_bound(name: str) -> bool:
    """True when ``name`` is currently bound as a MANUAL axis (we are
    tracing inside a shard_map/pmap body over it)."""
    try:
        jax.lax.axis_size(name)
        return True
    except Exception:
        return False


def ambient_auto_axes(axes=("data", "model")):
    """``(mesh, auto)``: the ambient mesh usable for a nested manual
    ``shard_map`` and the subset of ``axes`` that are partitioner-managed
    (Auto) in it — i.e. the axes a trace-time kernel wrapper may claim.
    ``mesh`` is a concrete ``Mesh`` on jax<0.8 and the abstract mesh on
    jax>=0.8 (both accepted by ``jax.shard_map``). Returns
    ``(None, frozenset())`` when there is no ambient mesh (eager, plain
    jit) or every candidate axis is already manual (inside a shard_map
    body — the DP/SP/EP/PP step paths), so callers degrade to the plain
    kernel exactly where wrapping would be wrong."""
    am = jax.sharding.get_abstract_mesh()
    if am.empty:
        return None, frozenset()
    if isinstance(am, _AbstractMeshShim):
        auto = frozenset(a for a in am.axis_names
                         if a in axes and not _axis_is_bound(a))
        return am.physical_mesh, auto
    auto = frozenset(
        a for a, t in zip(am.axis_names, am.axis_types)
        if t == jax.sharding.AxisType.Auto and a in axes)
    return am, auto
