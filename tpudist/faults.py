"""Deterministic fault injection — the failure chain made testable.

Production TPU fleets live with preemption, flaky storage, and corrupt
bytes as the COMMON case; the reference template has zero failure handling
(a dead rank hangs every NCCL collective forever, SURVEY.md §5). This
module makes every failure path in tpudist *injectable* so the tests can
drive the full chain end-to-end: inject → detect → abort/degrade →
restart → resume.

Injections are armed by spec string (``--inject`` on the launcher/trainer
CLI, or the ``TPUDIST_INJECT`` env var the launcher propagates to every
rank). The spec is a comma-free ``;``-joined list of items::

    rank_exit@step=7                     # os._exit mid-step at global step 7
    rank_exit@step=7@rank=1@attempt=0    # only rank 1, only launch attempt 0
    checkpoint_corrupt                   # flip bytes in the next saved ckpt
    decode_fail:p=0.25,fails=1           # 25% of samples fail 1 decode, then heal
    decode_fail:p=0.1                    # 10% of samples fail EVERY decode
    init_hang:ms=30000                   # sleep 30s inside runtime init
    slow_peer:ms=500                     # 500ms stall per training step
    watchdog_expire                      # force the stall watchdog to fire
    nanbomb@step=5                       # NaN-poison step 5's input batch
    lossbomb:factor=100@step=5           # poison the head: finite loss spike
    bitflip@step=5@rank=1                # flip bits in rank 1's live params

Grammar: ``name[:k=v[,k=v...]][@gate[@gate...]]`` where each gate is
``step=N`` / ``rank=N`` / ``attempt=N`` / ``once``. Gates select WHEN the
fault fires (``attempt`` matches ``TPUDIST_RESTART_COUNT``, so a fault can
be armed for launch attempt 0 only — the restarted job must then recover
cleanly); params after ``:`` parameterize the fault itself.

Determinism: no wall-clock or RNG state — probabilistic faults
(``decode_fail:p=...``) hash the sample key, so the same samples fail on
every run and every rank, and ``fails=N`` heals a key after N failures
(transient-fault shape) by counting attempts in-process.

The consult API is cheap when nothing is armed (one dict lookup, no jax
import): each fault point calls ``should_fire(name, ...)`` or one of the
typed helpers below.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional

# Exit code a preempted (SIGTERM'd) trainer uses after draining the step and
# writing its emergency checkpoint: tells the launcher "resumable, not a
# crash". 75 = BSD EX_TEMPFAIL ("temp failure; user is invited to retry").
PREEMPTED_EXIT_CODE = 75

# Exit code a rank uses when tpudist.doctor's cross-replica SDC probe finds
# ITS replicated state minority-divergent (silent data corruption on this
# host): the rank self-quarantines WITHOUT writing any checkpoint — its
# state is the corruption — and the elastic launcher reforms the gang
# around it. Distinct from PREEMPTED so classify_exit / post-mortems can
# tell a lying chip from a preempted one.
SDC_EXIT_CODE = 76

ENV_SPEC = "TPUDIST_INJECT"
ENV_ATTEMPT = "TPUDIST_RESTART_COUNT"
ENV_RANK = "TPUDIST_PROCESS_ID"

_GATE_KEYS = ("step", "rank", "attempt", "once")


@dataclass
class Injection:
    """One armed fault: a point name, firing gates, and fault params."""
    name: str
    step: Optional[int] = None       # fire only at this global step
    rank: Optional[int] = None       # fire only on this process id
    attempt: Optional[int] = None    # fire only on this launch attempt
    once: bool = False               # disarm after the first firing
    params: dict = field(default_factory=dict)
    fired: int = 0                   # times this injection has fired
    _attempt_counts: dict = field(default_factory=dict)  # decode heal counter

    def param_float(self, key: str, default: float = 0.0) -> float:
        return float(self.params.get(key, default))

    def param_int(self, key: str, default: int = 0) -> int:
        return int(float(self.params.get(key, default)))


def parse_spec(spec: str) -> list[Injection]:
    """Parse an injection spec string (see module docstring for grammar).

    Items separate on ``;`` (commas belong to the param list). Unknown gate
    keys raise — a typo'd gate that silently never fires would defeat the
    whole point of deterministic injection.
    """
    out: list[Injection] = []
    for item in (spec or "").split(";"):
        item = item.strip()
        if not item:
            continue
        head, *gates = item.split("@")
        name, _, paramstr = head.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"--inject item has no fault name: {item!r}")
        inj = Injection(name=name)
        for kv in paramstr.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(
                    f"--inject param {kv!r} in {item!r} is not key=value")
            inj.params[k.strip()] = v.strip()
        for gate in gates:
            gate = gate.strip()
            if gate == "once":
                inj.once = True
                continue
            k, sep, v = gate.partition("=")
            k = k.strip()
            if not sep or k not in ("step", "rank", "attempt"):
                raise ValueError(
                    f"--inject gate {gate!r} in {item!r} must be one of "
                    f"step=N / rank=N / attempt=N / once")
            setattr(inj, k, int(v))
        out.append(inj)
    return out


class FaultInjector:
    """Per-process registry of armed injections."""

    def __init__(self, injections: list[Injection]):
        self.injections = injections
        self._by_name: dict[str, list[Injection]] = {}
        for inj in injections:
            self._by_name.setdefault(inj.name, []).append(inj)

    def should_fire(self, point: str, step: Optional[int] = None,
                    consume: bool = True) -> Optional[Injection]:
        """The armed injection for ``point`` whose gates all match, else
        None. Marks the injection fired (honoring ``once``) — pass
        ``consume=False`` when the caller applies its own post-filter
        (e.g. ``decode_fail``'s probability hash) and will mark ``fired``
        itself only on an actual firing; otherwise a ``@once`` injection
        would disarm on a consult that ended up not firing."""
        for inj in self._by_name.get(point, ()):
            if inj.once and inj.fired:
                continue
            if inj.step is not None and step != inj.step:
                continue
            if inj.rank is not None and _env_int(ENV_RANK, 0) != inj.rank:
                continue
            if inj.attempt is not None \
                    and _env_int(ENV_ATTEMPT, 0) != inj.attempt:
                continue
            if consume:
                inj.fired += 1
            return inj
        return None

    def armed(self, point: str) -> bool:
        return point in self._by_name


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, default))
    except ValueError:
        return default


_injector: Optional[FaultInjector] = None
_observer = None


def set_observer(fn) -> None:
    """Register a callable ``fn(point, step, info: dict)`` invoked whenever
    an injection actually FIRES (telemetry wiring: the trainer points this
    at its event stream so every injected fault lands in events.*.jsonl).
    Pass None to clear. Observer errors are swallowed — a broken telemetry
    sink must not change fault semantics."""
    global _observer
    _observer = fn


def _notify(point: str, step: Optional[int] = None, **info) -> None:
    if _observer is None:
        return
    try:
        _observer(point, step, info)
    except Exception:
        pass


def configure(spec: Optional[str] = None) -> FaultInjector:
    """(Re)arm the process-wide injector. ``None`` reads ``TPUDIST_INJECT``;
    an empty spec disarms everything (the common production state)."""
    global _injector
    if spec is None:
        spec = os.environ.get(ENV_SPEC, "")
    _injector = FaultInjector(parse_spec(spec))
    return _injector


def get_injector() -> FaultInjector:
    global _injector
    if _injector is None:
        configure()
    return _injector


def should_fire(point: str, step: Optional[int] = None) -> Optional[Injection]:
    inj = get_injector().should_fire(point, step=step)
    if inj is not None:
        _notify(point, step=step)
    return inj


def armed(point: str) -> bool:
    return get_injector().armed(point)


# -- typed fault points ------------------------------------------------------
# Each helper is called from exactly one named place in the stack; the
# docstring names it so docs/FAULT_TOLERANCE.md's table stays greppable.

def maybe_rank_exit(step: int) -> None:
    """Fault point ``rank_exit`` — trainer hot loop (trainer.train_epoch):
    hard-kill this rank mid-step, the preemption/OOM/segfault shape (no
    atexit, no jax shutdown hooks — exactly what a SIGKILL'd rank skips)."""
    inj = should_fire("rank_exit", step=step)
    if inj is not None:
        code = inj.param_int("code", 41)
        print(f"[tpudist.faults] rank_exit firing at step {step} "
              f"(os._exit({code}))", flush=True)
        os._exit(code)


def maybe_slow_peer(step: int) -> None:
    """Fault point ``slow_peer`` — trainer hot loop: stall this rank
    ``ms`` per step (straggler/contended-host shape; with a stall_timeout
    armed, the watchdog converts a long enough stall into an abort)."""
    inj = should_fire("slow_peer", step=step)
    if inj is not None:
        time.sleep(inj.param_float("ms", 500.0) / 1e3)


def maybe_straggle(step: int) -> None:
    """Fault point ``straggle`` — trainer hot loop: a SUSTAINED ``ms``
    stall on every step from step ``from`` onward (params: ``ms``
    per-step delay, ``from`` first affected step; gates: rank/attempt/
    once as usual — the ``step=`` gate is meaningless here and ``from=``
    replaces it). This is the persistent-straggler shape the launcher's
    ``--evict-stragglers`` path detects and drains: unlike ``slow_peer``
    (one step, or every step), it lets a rank run healthy for a warm-up
    window and THEN degrade, so the eviction e2e is deterministic in
    steps, not wall-clock."""
    inj = get_injector().should_fire("straggle", consume=False)
    if inj is None or step < inj.param_int("from", 0):
        return
    inj.fired += 1                             # an ACTUAL firing (see consume)
    _notify("straggle", step=step)
    time.sleep(inj.param_float("ms", 400.0) / 1e3)


def maybe_init_hang() -> None:
    """Fault point ``init_hang`` — dist.initialize_runtime: sleep ``ms``
    BEFORE joining the coordinator barrier, so the other ranks' init
    deadline (initialization_timeout) is what breaks the job, proving a
    lost coordinator/peer cannot hang init forever."""
    inj = should_fire("init_hang")
    if inj is not None:
        ms = inj.param_float("ms", 60_000.0)
        print(f"[tpudist.faults] init_hang firing ({ms:.0f}ms)", flush=True)
        time.sleep(ms / 1e3)


def maybe_corrupt_checkpoint(paths: list[str],
                             epoch: Optional[int] = None) -> bool:
    """Fault point ``checkpoint_corrupt`` — checkpoint.save_checkpoint /
    checkpoint_orbax save: flip bytes mid-file in every path of the save
    that just completed (the torn-write/bitrot shape the sha256 sidecar
    must catch on load). The ``step`` gate, for this point, matches the
    checkpoint's STORED epoch (``checkpoint_corrupt@step=2`` corrupts the
    save whose resume point is epoch 2). Returns True when it fired."""
    inj = should_fire("checkpoint_corrupt", step=epoch)
    if inj is None:
        return False
    for path in paths:
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.seek(size // 2)
                chunk = f.read(64)
                f.seek(size // 2)
                f.write(bytes(b ^ 0xFF for b in chunk))
            print(f"[tpudist.faults] checkpoint_corrupt flipped "
                  f"{len(chunk)} bytes in {path}", flush=True)
        except OSError as e:
            print(f"[tpudist.faults] checkpoint_corrupt could not corrupt "
                  f"{path}: {e}", flush=True)
    return True


def decode_should_fail(key: int) -> bool:
    """Fault point ``decode_fail`` — data loader worker (data/loader.py):
    deterministic pseudo-random sample failure. ``p`` selects a stable
    subset of sample keys (splitmix-style integer hash, identical on every
    rank/run); ``fails=N`` heals a key after N failures (transient-storage
    shape), omitted/0 means the key fails forever (corrupt-file shape)."""
    inj = get_injector().should_fire("decode_fail", consume=False)
    if inj is None:
        return False
    p = inj.param_float("p", 1.0)
    # splitmix64 finalizer: cheap, well-mixed, dependency-free.
    h = (int(key) + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    h = ((h ^ (h >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    h = ((h ^ (h >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 31
    if (h % 10_000) / 10_000.0 >= p:
        return False
    fails = inj.param_int("fails", 0)
    if fails > 0:
        seen = inj._attempt_counts.get(key, 0)
        if seen >= fails:
            return False                       # healed: transient fault over
        inj._attempt_counts[key] = seen + 1
    inj.fired += 1                             # an ACTUAL firing (see consume)
    _notify("decode_fail", key=int(key))
    return True


def maybe_nanbomb(step: int, images):
    """Fault point ``nanbomb`` — trainer hot loop, after the batch is
    placed: poison the ENTIRE input batch with NaN (the bad-record /
    overflowed-preprocessing shape). The guarded step's fused finiteness
    sentinel must flag the step and the skip-step policy must zero the
    update — weights after the step are bit-identical to before it."""
    inj = should_fire("nanbomb", step=step)
    if inj is None:
        return images
    import jax.numpy as jnp
    print(f"[tpudist.faults] nanbomb firing at step {step}", flush=True)
    # Multiply-by-NaN preserves shape, dtype and (under GSPMD) sharding.
    return images * jnp.asarray(float("nan"), images.dtype)


def maybe_lossbomb(step: int, state):
    """Fault point ``lossbomb`` — trainer hot loop: scale the model's
    final dense kernel (the classifier head — the last 2-D param leaf) by
    ``factor`` (default 100). Logits scale with it, so the next step's
    loss spikes hard but stays FINITE — the diverging-LR / poisoned-update
    shape the in-step finiteness sentinel can NOT see and the host-side
    EWMA detector must catch, answered by rollback-to-last-good + replay.
    (Scaling the *inputs* would be laundered away by the first BatchNorm;
    the head sits after every normalization.) Fires identically on every
    rank (no rank gate in the spec) so replicas stay consistent — this is
    a health fault, not an SDC fault. Returns the (possibly mutated)
    state."""
    inj = should_fire("lossbomb", step=step)
    if inj is None:
        return state
    factor = inj.param_float("factor", 100.0)
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    idx = next((i for i in reversed(range(len(leaves)))
                if getattr(leaves[i], "ndim", 0) == 2), None)
    if idx is None:
        print("[tpudist.faults] lossbomb armed but no 2-D param leaf "
              "found", flush=True)
        return state
    print(f"[tpudist.faults] lossbomb firing at step {step} "
          f"(head kernel x{factor:g})", flush=True)
    leaves[idx] = leaves[idx] * factor
    return state.replace(params=jax.tree_util.tree_unflatten(treedef, leaves))


def maybe_bitflip(step: int, state):
    """Fault point ``bitflip`` — trainer hot loop: flip a high mantissa/
    exponent bit in one element of this rank's live params (param ``bit``,
    default 23 — the f32 exponent LSB). This is silent data corruption:
    nothing is non-finite, the step keeps running, and only the doctor's
    cross-replica digest probe can see that this rank's replicated state
    now disagrees with the majority. Returns the (possibly mutated)
    state."""
    inj = should_fire("bitflip", step=step)
    if inj is None:
        return state
    import jax
    import numpy as np
    bit = inj.param_int("bit", 23)
    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    idx = next((i for i, leaf in enumerate(leaves)
                if getattr(leaf, "size", 0) > 0
                and getattr(leaf, "dtype", None) == np.float32), None)
    if idx is None:
        print("[tpudist.faults] bitflip armed but no f32 param leaf found",
              flush=True)
        return state
    host = np.array(jax.device_get(leaves[idx]), dtype=np.float32, copy=True)
    flat = host.reshape(-1)
    flat[: 1].view(np.uint32)[0] ^= np.uint32(1 << bit)
    print(f"[tpudist.faults] bitflip firing at step {step} "
          f"(param leaf {idx}, bit {bit})", flush=True)
    leaves[idx] = host
    return state.replace(params=jax.tree_util.tree_unflatten(treedef, leaves))


def maybe_watchdog_expire() -> bool:
    """Fault point ``watchdog_expire`` — utils.watchdog poll loop: treat the
    budget as already blown, so the watchdog→abort→relaunch chain is
    testable in milliseconds instead of a real timeout's wall-clock."""
    return should_fire("watchdog_expire") is not None


def classify_exit(code: int) -> str:
    """Human label for a rank's exit code, used by the launcher's logs (and
    docs/FAULT_TOLERANCE.md's table). Imports stay local so the launcher
    needs no jax."""
    from tpudist.utils.watchdog import STALL_EXIT_CODE
    if code == 0:
        return "clean"
    if code == PREEMPTED_EXIT_CODE:
        return "preempted (emergency checkpoint written; resumable)"
    if code == SDC_EXIT_CODE:
        return ("sdc (doctor probe: replicated state minority-divergent; "
                "rank self-quarantined, no checkpoint written)")
    if code == STALL_EXIT_CODE:
        return "stalled (watchdog abort; peer loss or hung collective)"
    if code < 0:
        return f"killed by signal {-code}"
    return f"crash (exit {code})"
