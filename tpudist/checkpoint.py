"""Checkpointing (reference C15, ``utils.py:114-118``) — save AND load.

The reference ``torch.save``s ``{epoch+1, arch, model.module.state_dict(),
best_acc1}`` to ``checkpoint.pth.tar`` each epoch, copying to
``model_best.pth.tar`` on a new best (rank-0 only, ``distributed.py:210-218``)
— and has NO load path (bug ledger #8). Here:

- the state dict is a plain nested-dict pytree of numpy arrays (msgpack via
  flax.serialization) — topology-independent exactly like the reference's
  unwrapped ``model.module.state_dict()``: it can be restored onto any mesh
  because replicated params gather to plain host arrays;
- same two-file scheme: ``checkpoint.msgpack`` every epoch,
  ``model_best.msgpack`` on best;
- ``load_checkpoint``/``restore_train_state`` provide the resume path the
  reference lacks, making ``--start-epoch`` real.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import shutil
import time
from typing import Any, Callable, Optional

import jax
import numpy as np
from flax import serialization

CKPT_NAME = "checkpoint.msgpack"
BEST_NAME = "model_best.msgpack"
SIDECAR_SUFFIX = ".sha256"
CORRUPT_SUFFIX = ".corrupt"
# Doctor probe verdicts (tpudist/doctor/): a second sidecar stamped by the
# SDC probe, binding a health verdict to the payload's sha256 — "intact"
# (sidecar) and "verified good" (verdict) are different claims, and the
# rollback walk needs the second one.
VERDICT_SUFFIX = ".verdict"
VERDICT_GOOD = "good"
VERDICT_SUSPECT = "suspect"
# History copies for keep-last-K fallback: checkpoint-ep00003.msgpack.
_HISTORY_RE = re.compile(r"checkpoint-ep(\d+)\.msgpack$")


def _to_host(tree: Any) -> Any:
    def conv(x):
        if hasattr(x, "dtype") and hasattr(x, "shape"):
            return np.asarray(x)     # device array → host
        return x                     # str/int/float metadata stays as-is
    return jax.tree_util.tree_map(conv, tree)


def _sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


def _write_atomic(path: str, payload: bytes) -> None:
    # pid-unique tmp: the CPU gang sims run every rank as primary against
    # one shared outpath (identical bytes) — a shared tmp name would let
    # writer A rename writer B's half-written file out from under it.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:          # atomic rename: no torn checkpoints
        f.write(payload)
    os.replace(tmp, path)


def _write_sidecar(path: str, digest: str) -> None:
    # sha256sum-compatible line; written AFTER the payload rename so a crash
    # between the two leaves a payload with no sidecar (treated as legacy /
    # unverifiable), never a sidecar attesting bytes that aren't there.
    _write_atomic(_sidecar_path(path),
                  f"{digest}  {os.path.basename(path)}\n".encode())


def verify_checkpoint(path: str) -> bool:
    """True when ``path``'s bytes match its sha256 sidecar. A MISSING sidecar
    verifies HERE — ``load_checkpoint`` on an explicit path keeps legacy
    pre-integrity files loadable — but the FALLBACK WALK
    (``load_checkpoint_with_fallback``) independently skips sidecar-less
    candidates before ever calling this: an integrity walk must not be won
    by unattested bytes (the crash-between-payload-rename-and-sidecar
    window). A present but mismatching sidecar is a torn/corrupt file."""
    sidecar = _sidecar_path(path)
    if not os.path.exists(sidecar):
        return True
    with open(sidecar) as f:
        parts = f.read().split()
    if not parts:
        # A truncated/empty sidecar is itself storage damage: the payload
        # is unverifiable — treat as corrupt so the fallback walk
        # quarantines it rather than trusting unattested bytes.
        return False
    want = parts[0]
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == want


def _verdict_path(path: str) -> str:
    return path + VERDICT_SUFFIX


def _sidecar_digest(path: str) -> Optional[str]:
    """The sha256 a payload's sidecar attests, or None (missing/torn)."""
    try:
        with open(_sidecar_path(path)) as f:
            parts = f.read().split()
    except OSError:
        return None
    return parts[0] if parts else None


def stamp_verdict(path: str, verdict: str, step: int) -> Optional[str]:
    """Stamp a probe verdict (``good``/``suspect``) onto a checkpoint
    payload, bound to the payload's CURRENT sidecar digest — the live file
    is rewritten every epoch, and a verdict must never outlive the bytes
    it judged. No sidecar → no stamp (an unattested payload cannot be
    attested healthy). Returns the verdict path, or None when not stamped.
    """
    digest = _sidecar_digest(path)
    if digest is None or not os.path.exists(path):
        return None
    vp = _verdict_path(path)
    _write_atomic(vp, json.dumps(
        {"verdict": verdict, "step": int(step), "payload_sha256": digest,
         "t": time.time()}).encode())
    return vp


def read_verdict(path: str) -> Optional[dict]:
    """The probe verdict bound to ``path``'s current bytes, or None when
    absent, torn, or stamped for a DIFFERENT payload revision (digest
    mismatch against the current sidecar — a stale verdict is no
    verdict)."""
    try:
        with open(_verdict_path(path)) as f:
            v = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(v, dict) or v.get("verdict") not in (VERDICT_GOOD,
                                                           VERDICT_SUSPECT):
        return None
    if v.get("payload_sha256") != _sidecar_digest(path):
        return None
    return v


def stamp_outpath_verdicts(outpath: str, verdict: str, step: int
                           ) -> list[str]:
    """Stamp every UNSTAMPED checkpoint payload in ``outpath`` (live file
    + history copies) with ``verdict``. Called by the doctor after each
    probe: a clean probe at step t attests everything written up to t; a
    divergent one marks the same set suspect — a checkpoint written after
    an undetected-at-save-time corruption is thereby never verified-good.
    Payloads already carrying a verdict for their current bytes keep it
    (a later suspect probe must not retroactively un-verify an epoch a
    clean probe already attested). Returns the stamped paths."""
    stamped = []
    cands = [os.path.join(outpath, CKPT_NAME)]
    cands.extend(_history_checkpoints(outpath))
    for p in cands:
        if not os.path.exists(p) or read_verdict(p) is not None:
            continue
        if stamp_verdict(p, verdict, step):
            stamped.append(p)
    return stamped


def quarantine_checkpoint(path: str) -> str:
    """Rename a corrupt checkpoint (and its sidecar) aside with a
    ``.corrupt`` suffix — never silently delete: the bytes are evidence
    (partial recovery, storage forensics). The quarantine pool is bounded
    to the same keep-last-K as the history pool by the next pruning save
    (``_prune_quarantines`` — a crash-looping fleet must not grow
    ``.corrupt`` files forever), and each quarantine lands in the
    telemetry stream (``fault`` event, point ``checkpoint_quarantine``)
    so the obs endpoint's ``tpudist_checkpoint_quarantined_total``
    counter moves. Returns the quarantined path."""
    dest = path + CORRUPT_SUFFIX
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}{CORRUPT_SUFFIX}.{n}"
    os.replace(path, dest)
    sidecar = _sidecar_path(path)
    if os.path.exists(sidecar):
        os.replace(sidecar, _sidecar_path(dest))
    verdict = _verdict_path(path)
    if os.path.exists(verdict):
        os.replace(verdict, _verdict_path(dest))
    try:
        from tpudist import telemetry
        tel = telemetry.get()
        if tel is not None:
            tel.emit("fault", point="checkpoint_quarantine",
                     path=os.path.basename(dest))
    except Exception:
        pass                # telemetry must never change fault semantics
    return dest


def save_checkpoint(state_dict: dict, is_best: bool, outpath: str,
                    keep: int = 0) -> str:
    """Write ``checkpoint.msgpack`` + sha256 sidecar; copy to
    ``model_best.msgpack`` when best (reference ``utils.py:114-118``).
    Callers gate on process_index 0 (reference ``distributed.py:210``).

    ``keep`` > 0 additionally writes a per-epoch history copy
    (``checkpoint-ep%05d.msgpack``) and prunes history beyond the newest
    ``keep`` — the fallback pool ``load_checkpoint_with_fallback`` walks
    when the live file turns out torn/corrupt.
    """
    from tpudist import faults
    payload = serialization.msgpack_serialize(_to_host(state_dict))
    digest = hashlib.sha256(payload).hexdigest()
    filename = os.path.join(outpath, CKPT_NAME)
    epoch = int(state_dict.get("epoch", 0))
    written = [filename]
    _write_atomic(filename, payload)
    _write_sidecar(filename, digest)
    if keep > 0:
        hist = os.path.join(outpath, f"checkpoint-ep{epoch:05d}.msgpack")
        _write_atomic(hist, payload)
        _write_sidecar(hist, digest)
        written.append(hist)
        _prune_history(outpath, keep)
    if is_best:
        best = os.path.join(outpath, BEST_NAME)
        shutil.copyfile(filename, best)
        _write_sidecar(best, digest)
    # Fault point: a torn write / bitrot lands AFTER the sidecar attested the
    # good bytes — exactly the mismatch the load-side verify must catch.
    faults.maybe_corrupt_checkpoint(written, epoch=epoch)
    return filename


def _history_checkpoints(outpath: str) -> list[str]:
    """History copies, NEWEST epoch first."""
    hits = []
    for p in glob.glob(os.path.join(outpath, "checkpoint-ep*.msgpack")):
        m = _HISTORY_RE.search(p)
        if m:
            hits.append((int(m.group(1)), p))
    return [p for _, p in sorted(hits, reverse=True)]


def _quarantined_checkpoints(outpath: str) -> list[str]:
    """Quarantined (``*.corrupt[.N]``) checkpoint payloads, newest
    (by mtime) first — sidecars excluded (they ride with their payload)."""
    hits = []
    for p in glob.glob(os.path.join(outpath, f"*{CORRUPT_SUFFIX}*")):
        if p.endswith(SIDECAR_SUFFIX):
            continue
        try:
            hits.append((os.path.getmtime(p), p))
        except OSError:
            continue
    return [p for _, p in sorted(hits, reverse=True)]


def _prune_quarantines(outpath: str, keep: int) -> None:
    """Bound the ``.corrupt`` quarantine pool to the same keep-last-K as
    the history pool (ISSUE 13 satellite: keep-K pruning previously left
    quarantines behind forever — a crash-looping run on bad storage
    accumulated one per attempt). The newest K stay as evidence."""
    for p in _quarantined_checkpoints(outpath)[keep:]:
        try:
            os.remove(p)
        except OSError:
            continue
        for side in (_sidecar_path(p), _verdict_path(p)):
            if os.path.exists(side):
                try:
                    os.remove(side)
                except OSError:
                    pass


def _prune_history(outpath: str, keep: int) -> None:
    for p in _history_checkpoints(outpath)[keep:]:
        os.remove(p)
        for side in (_sidecar_path(p), _verdict_path(p)):
            if os.path.exists(side):
                os.remove(side)
    _prune_quarantines(outpath, keep)


def load_checkpoint(path: str) -> dict:
    """Restore the raw nested dict (numpy leaves). A checkpoint whose sha256
    sidecar mismatches raises — use ``load_checkpoint_with_fallback`` for
    the quarantine-and-fall-back behavior."""
    if os.path.isdir(path):
        path = os.path.join(path, CKPT_NAME)
    if not verify_checkpoint(path):
        raise ValueError(
            f"checkpoint {path} fails sha256 sidecar verification "
            f"(torn write or storage corruption)")
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def load_checkpoint_with_fallback(
        outpath: str,
        log: Optional[Callable[[str], None]] = None,
        keep: Optional[int] = None,
        require_verified: bool = False) -> tuple[dict, str]:
    """Load the newest VALID checkpoint in ``outpath``.

    Candidate order: the live ``checkpoint.msgpack``, then history copies
    newest-epoch-first. Each candidate is sha256-verified (and parse-checked)
    before winning; a failing candidate is quarantined via a ``.corrupt``
    rename and the walk continues. Raises ``FileNotFoundError`` when no
    valid checkpoint remains.

    Candidates whose sha256 sidecar is MISSING are skipped, not loaded:
    every save writes payload-then-sidecar, so a payload without one is
    the crash-between-rename-and-sidecar window (or foreign bytes) — an
    unattested file must not win a walk whose whole point is integrity.
    (It is skipped rather than quarantined: the bytes may be fine, they
    just cannot be verified; ``load_checkpoint`` on an explicit path still
    loads legacy sidecar-less files.) Candidates stamped ``suspect`` by a
    doctor SDC probe (``read_verdict``) are likewise skipped — a probe
    already judged those exact bytes.

    ``require_verified`` (the doctor's rollback-to-last-GOOD path): prefer
    candidates whose probe verdict is ``good`` for their current bytes;
    only when no verified-good candidate exists does the walk fall back to
    merely-intact ones (logged loudly — a doctor-less run dir has no
    verdicts at all and must still resume).

    ``keep`` (the run's keep-last-K) additionally bounds the quarantine
    pool HERE, after the walk — a crash-looping run on bad storage
    quarantines one file per attempt and may never reach an epoch-boundary
    pruning save, so restore time is the only pruning point it is
    guaranteed to pass; at least the newest quarantine always survives as
    evidence (``max(1, keep)``).

    Returns ``(state_dict, path_loaded)``.
    """
    emit = log or (lambda m: None)
    if keep is not None:
        _prune_quarantines(outpath, max(1, keep))
    candidates = []
    live = os.path.join(outpath, CKPT_NAME)
    if os.path.exists(live):
        candidates.append(live)
    candidates.extend(_history_checkpoints(outpath))

    def _walk(cands: list[str]) -> Optional[tuple[dict, str]]:
        for cand in cands:
            if not os.path.exists(_sidecar_path(cand)):
                emit(f"=> checkpoint {cand} has no sha256 sidecar "
                     f"(torn save: crash between payload rename and "
                     f"sidecar write?) — unverifiable, skipping")
                continue
            verdict = read_verdict(cand)
            if verdict is not None and verdict["verdict"] != VERDICT_GOOD:
                emit(f"=> checkpoint {cand} stamped '{verdict['verdict']}' "
                     f"by a doctor probe (step {verdict.get('step')}) — "
                     f"skipping")
                continue
            try:
                valid = verify_checkpoint(cand)
            except OSError:
                # A concurrent rank already quarantined this candidate
                # (elastic restarts resume on every process): just walk on.
                continue
            if not valid:
                try:
                    q = quarantine_checkpoint(cand)
                except OSError:
                    continue                  # lost the quarantine race
                emit(f"=> checkpoint {cand} fails sha256 verification — "
                     f"quarantined to {q}, falling back to the next newest")
                continue
            try:
                with open(cand, "rb") as f:
                    ckpt = serialization.msgpack_restore(f.read())
            except OSError:
                continue                      # raced: quarantined under us
            except Exception as e:
                # Verifies but does not parse: same quarantine path.
                try:
                    q = quarantine_checkpoint(cand)
                except OSError:
                    continue
                emit(f"=> checkpoint {cand} unreadable ({e}) — quarantined "
                     f"to {q}, falling back to the next newest")
                continue
            return ckpt, cand
        return None

    if require_verified:
        verified = [c for c in candidates
                    if (read_verdict(c) or {}).get("verdict") == VERDICT_GOOD]
        got = _walk(verified)
        if got is not None:
            emit(f"=> rollback target: {got[1]} (probe-verified good)")
            return got
        emit("=> no probe-verified-good checkpoint available — falling "
             "back to the newest merely-intact candidate")
    got = _walk(candidates)
    if got is not None:
        return got
    raise FileNotFoundError(
        f"no valid checkpoint in {outpath}: every candidate failed "
        f"integrity verification (quarantined as *{CORRUPT_SUFFIX}) or "
        f"was unverifiable/suspect")


def tree_digest(tree: Any) -> str:
    """Content-level sha256 of a host pytree: sorted (path, dtype, shape,
    bytes) per leaf. Used by the orbax backend, whose on-disk layout is
    written asynchronously by orbax itself — hashing the LOGICAL content at
    save time and re-hashing what load returns catches torn/corrupt files
    regardless of the directory format."""
    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves_with_path(_to_host(tree))
    for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
        h.update(str(path).encode())
        if hasattr(leaf, "dtype") or isinstance(leaf, (int, float, bool)):
            arr = np.asarray(leaf)
            h.update(arr.dtype.str.encode())
            h.update(str(arr.shape).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        else:
            h.update(repr(leaf).encode())
    return h.hexdigest()


# Parameter-layout revision stamped into checkpoints. Bumped to 2 when swin's
# fused qkv switched from qkv-major to head-major columns (r3, for tensor-
# parallel head sharding) — restore migrates older swin checkpoints.
LAYOUT_VERSION = 2


def state_to_dict(train_state, arch: str, epoch: int, best_acc1: float,
                  topology: Optional[dict] = None,
                  data_cursor: Optional[dict] = None,
                  doctor: Optional[dict] = None) -> dict:
    """The reference's checkpoint schema (``distributed.py:211-216``):
    epoch, arch, model state, best_acc1 — plus optimizer/BN state so resume is
    exact (the reference couldn't resume at all).

    ``topology`` (``elastic.reshard.topology_tag``) stamps the world/mesh
    that wrote the checkpoint so a restore at a DIFFERENT world size can
    plan its reshard; ``data_cursor`` (emergency saves only) records the
    interrupted epoch's global sample cursor —
    ``{"epoch": e, "consumed": n, "samples_skipped": s,
    "samples_retried": r}`` — so an elastic continuation resumes the
    epoch's deterministic sample order mid-way instead of replaying it.
    ``doctor`` (emergency saves under ``--doctor``, after a rollback)
    carries the replay state that must survive a restart —
    ``{"rollbacks": n, "poison_windows": {"<epoch>": [[a, b], ...]}}`` —
    so the excised-order cursor mapping stays exact and the
    ``--doctor-max-rollbacks`` budget cannot reset per-process
    (tpudist/doctor/, docs/DOCTOR.md)."""
    out = {
        "epoch": epoch + 1,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "layout_version": LAYOUT_VERSION,
        "state": serialization.to_state_dict(train_state),
    }
    if topology is not None:
        out["topology"] = dict(topology)
    if data_cursor is not None:
        out["data_cursor"] = dict(data_cursor)
    if doctor is not None:
        out["doctor"] = dict(doctor)
    return out


def _migrate_swin_qkv_layout(state_dict: dict, arch: str) -> None:
    """In-place v1→v2 migration: permute every ``…/attn/qkv`` kernel/bias
    (params, EMA copy, optimizer moments — any subtree mirroring the param
    names) from the old qkv-major column order to head-major, so pre-r3 swin
    checkpoints resume onto the repacked model instead of silently reading
    scrambled q/k/v (``models/swin.py:WindowAttention``)."""
    import re as _re

    from tpudist.compat.torch_checkpoint import _vit_inproj_perm
    from tpudist.models.swin import _VARIANTS
    heads_list = _VARIANTS[arch][2]

    def walk(node, stage):
        if not isinstance(node, dict):
            return
        for key, child in node.items():
            m = _re.match(r"features_(\d+)_", str(key))
            child_stage = ((int(m.group(1)) - 1) // 2 if m else stage)
            if key == "qkv" and isinstance(child, dict) \
                    and child_stage is not None:
                heads = heads_list[child_stage]
                k = child.get("kernel")
                if k is not None and getattr(k, "ndim", 0) == 2:
                    if (k.shape[1] // 3) % heads:
                        # A custom swin whose widths don't match the named
                        # variant: heads can't be inferred — refuse rather
                        # than scramble.
                        raise ValueError(
                            f"cannot auto-migrate pre-r3 swin qkv layout: "
                            f"width {k.shape[1] // 3} at a stage-"
                            f"{child_stage} qkv is not divisible by "
                            f"'{arch}'s expected {heads} heads")
                    perm = _vit_inproj_perm(k.shape[1] // 3, heads)
                    child["kernel"] = np.ascontiguousarray(
                        np.asarray(k)[:, perm])
                b = child.get("bias")
                if b is not None and getattr(b, "ndim", 0) == 1:
                    perm = _vit_inproj_perm(b.shape[0] // 3, heads)
                    child["bias"] = np.ascontiguousarray(np.asarray(b)[perm])
            walk(child, child_stage)

    walk(state_dict, None)


def restore_train_state(template_state, ckpt: dict,
                        target_topology: Optional[dict] = None,
                        log: Optional[Callable[[str], None]] = None):
    """Restore onto a freshly-built TrainState (any mesh/topology).

    RESHARD PATH (``target_topology``, an ``elastic.reshard.topology_tag``
    for the restoring run): when the checkpoint carries a topology tag and
    the worlds differ, the restore is planned via
    ``elastic.reshard.plan_reshard`` and the plan logged — params
    re-replicate onto the new mesh for free (checkpoint leaves are full
    host arrays, like the reference's unwrapped
    ``model.module.state_dict()``) and zero1 optimizer partitions are
    re-cut when the trainer places the restored state on its mesh
    (``shard_tree``); leaves whose leading dim no longer divides the new
    world fall back to replicated, which the plan calls out.

    ``ema_params`` cross-compat: resuming an EMA run from a checkpoint
    without one (pre-EMA file, or a run with EMA off — the field serializes
    as None) seeds the average at the restored weights; resuming WITHOUT the
    flag from an EMA checkpoint drops the stale EMA copy (flax's
    from_state_dict would otherwise resurrect it verbatim onto the None
    target and silently re-enable EMA eval).

    ``comm_state`` (the ``--compress-grads`` error-feedback residual,
    ``{"residual": (world, n)}``) follows the same cross-compat rules —
    dropped when compression is off now, zero-seeded when the checkpoint
    predates it — plus the elastic remap: a residual saved at a different
    world mean-folds onto the template's world
    (``elastic.reshard.remap_comm_state``), preserving the pending
    gradient mass exactly; a same-world restore is bit-exact."""
    if target_topology is not None:
        from tpudist.elastic.reshard import plan_reshard
        plan = plan_reshard(ckpt.get("topology"), target_topology,
                            state_dict=ckpt.get("state"))
        if plan.changed and log is not None:
            log(f"=> cross-topology restore: {plan.describe()}")
    state_dict = dict(ckpt["state"])
    if str(ckpt.get("arch", "")).startswith("swin") \
            and int(ckpt.get("layout_version", 1)) < 2:
        _migrate_swin_qkv_layout(state_dict, ckpt["arch"])
    if getattr(template_state, "ema_params", None) is not None:
        ema_sd = state_dict.get("ema_params")
        if ema_sd is None:
            state_dict["ema_params"] = {
                "params": state_dict.get("params"),
                "batch_stats": state_dict.get("batch_stats", {})}
        elif "params" not in ema_sd:
            # params-only EMA from before buffers were averaged: seed the
            # stats half from the live running stats.
            state_dict["ema_params"] = {
                "params": ema_sd,
                "batch_stats": state_dict.get("batch_stats", {})}
    else:
        state_dict["ema_params"] = None
    tgt_comm = getattr(template_state, "comm_state", None)
    if tgt_comm is not None:
        saved_comm = state_dict.get("comm_state")
        if not isinstance(saved_comm, dict) \
                or saved_comm.get("residual") is None:
            # Pre-compression checkpoint (or compression newly turned on):
            # start with zero pending error, shaped for THIS world.
            state_dict["comm_state"] = {"residual": np.zeros(
                tuple(tgt_comm["residual"].shape), np.float32)}
        else:
            from tpudist.elastic.reshard import remap_comm_state
            to_parts = int(tgt_comm["residual"].shape[0])
            state_dict["comm_state"] = remap_comm_state(
                dict(saved_comm), to_parts)
    else:
        state_dict["comm_state"] = None
    try:
        return serialization.from_state_dict(template_state, state_dict)
    except ValueError as e:
        if "opt_state" in str(e):
            # Classic cause: resuming an adamw checkpoint into an sgd
            # template (mu/nu/count vs trace) or vice versa — the raw flax
            # error ("field names ... do not match") doesn't say why.
            raise ValueError(
                "checkpoint optimizer state does not match the trainer's "
                "optimizer — was this checkpoint written with a different "
                "--optimizer (sgd vs adamw)? Pass the same --optimizer used "
                f"for training. Underlying error: {e}") from e
        raise
