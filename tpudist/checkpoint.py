"""Checkpointing (reference C15, ``utils.py:114-118``) — save AND load.

The reference ``torch.save``s ``{epoch+1, arch, model.module.state_dict(),
best_acc1}`` to ``checkpoint.pth.tar`` each epoch, copying to
``model_best.pth.tar`` on a new best (rank-0 only, ``distributed.py:210-218``)
— and has NO load path (bug ledger #8). Here:

- the state dict is a plain nested-dict pytree of numpy arrays (msgpack via
  flax.serialization) — topology-independent exactly like the reference's
  unwrapped ``model.module.state_dict()``: it can be restored onto any mesh
  because replicated params gather to plain host arrays;
- same two-file scheme: ``checkpoint.msgpack`` every epoch,
  ``model_best.msgpack`` on best;
- ``load_checkpoint``/``restore_train_state`` provide the resume path the
  reference lacks, making ``--start-epoch`` real.
"""

from __future__ import annotations

import os
import shutil
from typing import Any

import jax
import numpy as np
from flax import serialization

CKPT_NAME = "checkpoint.msgpack"
BEST_NAME = "model_best.msgpack"


def _to_host(tree: Any) -> Any:
    def conv(x):
        if hasattr(x, "dtype") and hasattr(x, "shape"):
            return np.asarray(x)     # device array → host
        return x                     # str/int/float metadata stays as-is
    return jax.tree_util.tree_map(conv, tree)


def save_checkpoint(state_dict: dict, is_best: bool, outpath: str) -> str:
    """Write ``checkpoint.msgpack``; copy to ``model_best.msgpack`` when best
    (reference ``utils.py:114-118``). Callers gate on process_index 0
    (reference ``distributed.py:210``)."""
    payload = serialization.msgpack_serialize(_to_host(state_dict))
    filename = os.path.join(outpath, CKPT_NAME)
    tmp = filename + ".tmp"
    with open(tmp, "wb") as f:          # atomic rename: no torn checkpoints
        f.write(payload)
    os.replace(tmp, filename)
    if is_best:
        shutil.copyfile(filename, os.path.join(outpath, BEST_NAME))
    return filename


def load_checkpoint(path: str) -> dict:
    """Restore the raw nested dict (numpy leaves)."""
    if os.path.isdir(path):
        path = os.path.join(path, CKPT_NAME)
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


# Parameter-layout revision stamped into checkpoints. Bumped to 2 when swin's
# fused qkv switched from qkv-major to head-major columns (r3, for tensor-
# parallel head sharding) — restore migrates older swin checkpoints.
LAYOUT_VERSION = 2


def state_to_dict(train_state, arch: str, epoch: int, best_acc1: float) -> dict:
    """The reference's checkpoint schema (``distributed.py:211-216``):
    epoch, arch, model state, best_acc1 — plus optimizer/BN state so resume is
    exact (the reference couldn't resume at all)."""
    return {
        "epoch": epoch + 1,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "layout_version": LAYOUT_VERSION,
        "state": serialization.to_state_dict(train_state),
    }


def _migrate_swin_qkv_layout(state_dict: dict, arch: str) -> None:
    """In-place v1→v2 migration: permute every ``…/attn/qkv`` kernel/bias
    (params, EMA copy, optimizer moments — any subtree mirroring the param
    names) from the old qkv-major column order to head-major, so pre-r3 swin
    checkpoints resume onto the repacked model instead of silently reading
    scrambled q/k/v (``models/swin.py:WindowAttention``)."""
    import re as _re

    from tpudist.compat.torch_checkpoint import _vit_inproj_perm
    from tpudist.models.swin import _VARIANTS
    heads_list = _VARIANTS[arch][2]

    def walk(node, stage):
        if not isinstance(node, dict):
            return
        for key, child in node.items():
            m = _re.match(r"features_(\d+)_", str(key))
            child_stage = ((int(m.group(1)) - 1) // 2 if m else stage)
            if key == "qkv" and isinstance(child, dict) \
                    and child_stage is not None:
                heads = heads_list[child_stage]
                k = child.get("kernel")
                if k is not None and getattr(k, "ndim", 0) == 2:
                    if (k.shape[1] // 3) % heads:
                        # A custom swin whose widths don't match the named
                        # variant: heads can't be inferred — refuse rather
                        # than scramble.
                        raise ValueError(
                            f"cannot auto-migrate pre-r3 swin qkv layout: "
                            f"width {k.shape[1] // 3} at a stage-"
                            f"{child_stage} qkv is not divisible by "
                            f"'{arch}'s expected {heads} heads")
                    perm = _vit_inproj_perm(k.shape[1] // 3, heads)
                    child["kernel"] = np.ascontiguousarray(
                        np.asarray(k)[:, perm])
                b = child.get("bias")
                if b is not None and getattr(b, "ndim", 0) == 1:
                    perm = _vit_inproj_perm(b.shape[0] // 3, heads)
                    child["bias"] = np.ascontiguousarray(np.asarray(b)[perm])
            walk(child, child_stage)

    walk(state_dict, None)


def restore_train_state(template_state, ckpt: dict):
    """Restore onto a freshly-built TrainState (any mesh/topology).

    ``ema_params`` cross-compat: resuming an EMA run from a checkpoint
    without one (pre-EMA file, or a run with EMA off — the field serializes
    as None) seeds the average at the restored weights; resuming WITHOUT the
    flag from an EMA checkpoint drops the stale EMA copy (flax's
    from_state_dict would otherwise resurrect it verbatim onto the None
    target and silently re-enable EMA eval)."""
    state_dict = dict(ckpt["state"])
    if str(ckpt.get("arch", "")).startswith("swin") \
            and int(ckpt.get("layout_version", 1)) < 2:
        _migrate_swin_qkv_layout(state_dict, ckpt["arch"])
    if getattr(template_state, "ema_params", None) is not None:
        ema_sd = state_dict.get("ema_params")
        if ema_sd is None:
            state_dict["ema_params"] = {
                "params": state_dict.get("params"),
                "batch_stats": state_dict.get("batch_stats", {})}
        elif "params" not in ema_sd:
            # params-only EMA from before buffers were averaged: seed the
            # stats half from the live running stats.
            state_dict["ema_params"] = {
                "params": ema_sd,
                "batch_stats": state_dict.get("batch_stats", {})}
    else:
        state_dict["ema_params"] = None
    try:
        return serialization.from_state_dict(template_state, state_dict)
    except ValueError as e:
        if "opt_state" in str(e):
            # Classic cause: resuming an adamw checkpoint into an sgd
            # template (mu/nu/count vs trace) or vice versa — the raw flax
            # error ("field names ... do not match") doesn't say why.
            raise ValueError(
                "checkpoint optimizer state does not match the trainer's "
                "optimizer — was this checkpoint written with a different "
                "--optimizer (sgd vs adamw)? Pass the same --optimizer used "
                f"for training. Underlying error: {e}") from e
        raise
