"""Input pipeline (reference C7: ImageFolder + transforms + DataLoader +
DistributedSampler, ``distributed.py:156-179``)."""

from tpudist.data.imagefolder import ImageFolder                     # noqa: F401
from tpudist.data.synthetic import SyntheticDataset                  # noqa: F401
from tpudist.data.sampler import ShardedSampler                      # noqa: F401
from tpudist.data.loader import DataLoader                           # noqa: F401
from tpudist.data import transforms                                  # noqa: F401
from tpudist.data.pipeline import build_train_val_loaders            # noqa: F401
