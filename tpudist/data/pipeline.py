"""Assemble the full train/val input pipeline for a run (reference
``distributed.py:156-179``): datasets + per-process sharding + loaders.

Per-host sharding: with P processes each owning D local devices, process p is
"rank p of P" at the DATA level (its loader yields global_batch/P samples) and
the global SPMD step sees the assembled global batch — the TPU analogue of
DistributedSampler rank/world_size (``distributed.py:167``).
"""

from __future__ import annotations

from functools import partial

import jax

from tpudist.config import Config
from tpudist.data.imagefolder import ImageFolder
from tpudist.data.loader import DataLoader
from tpudist.data.sampler import ShardedSampler
from tpudist.data.synthetic import SyntheticDataset
from tpudist.data import transforms


def build_train_val_loaders(cfg: Config):
    import os

    # Data rank/world from the distributed runtime, or — in the launcher's
    # elastic CPU simulation (independent jit ranks, TPUDIST_ELASTIC=1) —
    # from the launcher-assigned env identity, so each rank loads its 1/W
    # shard and the elastic sample cursor counts global samples correctly.
    from tpudist.dist import data_rank_world
    pid, nproc = data_rank_world()
    host_batch = cfg.batch_size // nproc
    seed = cfg.seed if cfg.seed is not None else 0

    if cfg.synthetic or not cfg.data:
        n_train = getattr(cfg, "synthetic_size", 0) \
            or max(host_batch * nproc * 4, 256)
        train_ds = SyntheticDataset(n_train, cfg.image_size,
                                    cfg.num_classes, seed)
        val_ds = SyntheticDataset(max(n_train // 2, host_batch),
                                  cfg.image_size, cfg.num_classes, seed + 1)
        train_tf = val_tf = None
    else:
        # Prefer the fused C++ kernels (native/transforms.cc + jpeg.cc); fall
        # back to the pure PIL/numpy stack when the library isn't available.
        from tpudist.data import autoaugment, native
        aa = autoaugment.build(getattr(cfg, "auto_augment", ""))
        re_p = getattr(cfg, "random_erase", 0.0)
        # The fused C++ kernels cover the reference's crop/flip/normalize
        # stack only; auto-augment/random-erasing move the TRAIN transform
        # onto the PIL path. Each split picks its loader independently: val
        # never runs those train-only transforms, so it keeps the fully-
        # native raw-bytes path (fused JPEG decode) regardless.
        train_loader_fn = val_loader_fn = None
        if native.jpeg_available() and aa is None and re_p == 0.0:
            # Fully-native path: the dataset yields raw bytes and JPEG decode
            # happens inside the fused kernel (partial, DCT-scaled decode);
            # the transforms PIL-decode any non-JPEG bytes themselves.
            train_loader_fn = ImageFolder.raw_loader
            train_tf = partial(_native_jpeg_train_tf, size=cfg.image_size)
        elif native.available() and aa is None and re_p == 0.0:
            train_tf = partial(_native_train_tf, size=cfg.image_size)
        else:
            train_tf = partial(_train_tf, size=cfg.image_size, aa=aa,
                               random_erase=re_p)
        if native.jpeg_available():
            val_loader_fn = ImageFolder.raw_loader
            val_tf = partial(_native_jpeg_val_tf, size=cfg.image_size,
                             resize=cfg.val_resize)
        elif native.available():
            val_tf = partial(_native_val_tf, size=cfg.image_size,
                             resize=cfg.val_resize)
        else:
            val_tf = partial(_val_tf, size=cfg.image_size,
                             resize=cfg.val_resize)
        train_ds = ImageFolder(os.path.join(cfg.data, "train"),
                               loader=train_loader_fn)
        val_ds = ImageFolder(os.path.join(cfg.data, "val"),
                             loader=val_loader_fn)

    # DistributedSampler for BOTH train and val, like the reference
    # (distributed.py:167,177 — including the padded-val quirk).
    train_sampler = ShardedSampler(len(train_ds), nproc, pid, shuffle=True, seed=seed)
    val_sampler = ShardedSampler(len(val_ds), nproc, pid, shuffle=False, seed=seed)

    degrade = dict(retries=getattr(cfg, "data_retries", 2),
                   retry_backoff=getattr(cfg, "data_retry_backoff", 0.05),
                   skip_budget=getattr(cfg, "data_skip_budget", 0))
    train_loader = DataLoader(train_ds, host_batch, sampler=train_sampler,
                              transform=train_tf, num_workers=cfg.workers,
                              drop_last=True, seed=seed, **degrade)
    # Val must see EVERY sample (torch DataLoader default drop_last=False):
    # the final partial batch is padded by wrapping to a device-count multiple
    # (≤ local_device_count-1 duplicates) instead of dropping up to
    # host_batch-1 images, which would skew best-model selection.
    val_loader = DataLoader(val_ds, host_batch, sampler=val_sampler,
                            transform=val_tf, num_workers=cfg.workers,
                            drop_last=False,
                            round_up_to=jax.local_device_count(), seed=seed,
                            **degrade)
    return train_loader, val_loader


def _train_tf(img, rng, size, aa=None, random_erase=0.0):
    return transforms.train_transform(img, size, rng, aa=aa,
                                      random_erase=random_erase)


def _val_tf(img, rng, size, resize):
    return transforms.val_transform(img, size, resize)


def _native_train_tf(img, rng, size):
    from tpudist.data import native
    return native.train_transform(img, size, rng)


def _native_val_tf(img, rng, size, resize):
    from tpudist.data import native
    return native.val_transform(img, size, resize)


def _pil_decode(data):
    import io

    from PIL import Image
    return Image.open(io.BytesIO(data)).convert("RGB")


def _native_jpeg_train_tf(data, rng, size):
    from tpudist.data import native
    out = native.decode_train_transform(data, size, rng)
    if out is not None:
        return out
    return native.train_transform(_pil_decode(data), size, rng)


def _native_jpeg_val_tf(data, rng, size, resize):
    from tpudist.data import native
    out = native.decode_val_transform(data, size, resize)
    if out is not None:
        return out
    return native.val_transform(_pil_decode(data), size, resize)
