"""Assemble the full train/val input pipeline for a run (reference
``distributed.py:156-179``): datasets + per-process sharding + loaders.

Per-host sharding: with P processes each owning D local devices, process p is
"rank p of P" at the DATA level (its loader yields global_batch/P samples) and
the global SPMD step sees the assembled global batch — the TPU analogue of
DistributedSampler rank/world_size (``distributed.py:167``).
"""

from __future__ import annotations

from functools import partial

import jax

from tpudist.config import Config
from tpudist.data.imagefolder import ImageFolder
from tpudist.data.loader import DataLoader
from tpudist.data.sampler import ShardedSampler
from tpudist.data.synthetic import SyntheticDataset
from tpudist.data import transforms


def build_train_val_loaders(cfg: Config):
    import os
    nproc = jax.process_count()
    pid = jax.process_index()
    host_batch = cfg.batch_size // nproc
    seed = cfg.seed if cfg.seed is not None else 0

    if cfg.synthetic or not cfg.data:
        n_train = getattr(cfg, "synthetic_size", 0) \
            or max(host_batch * nproc * 4, 256)
        train_ds = SyntheticDataset(n_train, cfg.image_size,
                                    cfg.num_classes, seed)
        val_ds = SyntheticDataset(max(n_train // 2, host_batch),
                                  cfg.image_size, cfg.num_classes, seed + 1)
        train_tf = val_tf = None
    else:
        train_ds = ImageFolder(os.path.join(cfg.data, "train"))
        val_ds = ImageFolder(os.path.join(cfg.data, "val"))
        # Prefer the fused C++ kernels (native/transforms.cc); fall back to
        # the pure PIL/numpy stack when the library isn't available.
        from tpudist.data import autoaugment, native
        aa = autoaugment.build(getattr(cfg, "auto_augment", ""))
        re_p = getattr(cfg, "random_erase", 0.0)
        # The fused C++ kernel covers the reference's crop/flip/normalize
        # stack only; auto-augment/random-erasing move the TRAIN transform
        # onto the PIL path while val keeps the native kernels.
        if native.available():
            train_tf = (partial(_native_train_tf, size=cfg.image_size)
                        if aa is None and re_p == 0.0
                        else partial(_train_tf, size=cfg.image_size, aa=aa,
                                     random_erase=re_p))
            val_tf = partial(_native_val_tf, size=cfg.image_size,
                             resize=cfg.val_resize)
        else:
            train_tf = partial(_train_tf, size=cfg.image_size, aa=aa,
                               random_erase=re_p)
            val_tf = partial(_val_tf, size=cfg.image_size, resize=cfg.val_resize)

    # DistributedSampler for BOTH train and val, like the reference
    # (distributed.py:167,177 — including the padded-val quirk).
    train_sampler = ShardedSampler(len(train_ds), nproc, pid, shuffle=True, seed=seed)
    val_sampler = ShardedSampler(len(val_ds), nproc, pid, shuffle=False, seed=seed)

    train_loader = DataLoader(train_ds, host_batch, sampler=train_sampler,
                              transform=train_tf, num_workers=cfg.workers,
                              drop_last=True, seed=seed)
    # Val must see EVERY sample (torch DataLoader default drop_last=False):
    # the final partial batch is padded by wrapping to a device-count multiple
    # (≤ local_device_count-1 duplicates) instead of dropping up to
    # host_batch-1 images, which would skew best-model selection.
    val_loader = DataLoader(val_ds, host_batch, sampler=val_sampler,
                            transform=val_tf, num_workers=cfg.workers,
                            drop_last=False,
                            round_up_to=jax.local_device_count(), seed=seed)
    return train_loader, val_loader


def _train_tf(img, rng, size, aa=None, random_erase=0.0):
    return transforms.train_transform(img, size, rng, aa=aa,
                                      random_erase=random_erase)


def _val_tf(img, rng, size, resize):
    return transforms.val_transform(img, size, resize)


def _native_train_tf(img, rng, size):
    from tpudist.data import native
    return native.train_transform(img, size, rng)


def _native_val_tf(img, rng, size, resize):
    from tpudist.data import native
    return native.val_transform(img, size, resize)
