"""Image transforms (reference ``distributed.py:158-176``).

Reimplementation of the exact torchvision stacks the reference uses:

- train: RandomResizedCrop(224) → RandomHorizontalFlip → ToTensor → Normalize
  (``distributed.py:161-166``)
- val:   Resize(256) → CenterCrop(224) → ToTensor → Normalize
  (``distributed.py:171-176``)

with the ImageNet mean/std from ``distributed.py:159``. All output is NHWC
float32 (TPU-native layout), normalized. Randomness is an explicit
``np.random.Generator`` so sample augmentation is reproducible given
(seed, epoch, index) — the functional-RNG answer to torch's global RNG state.
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)  # distributed.py:159
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


def _to_pil(img):
    from PIL import Image
    if isinstance(img, np.ndarray):
        return Image.fromarray(img)
    return img


def random_resized_crop(img, size: int, rng: np.random.Generator,
                        scale: Tuple[float, float] = (0.08, 1.0),
                        ratio: Tuple[float, float] = (3 / 4, 4 / 3)):
    """torchvision RandomResizedCrop: sample area/aspect 10 times, fall back to
    a center crop clamped to the valid ratio range."""
    from PIL import Image
    img = _to_pil(img)
    w, h = img.size
    area = w * h
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            i = int(rng.integers(0, h - ch + 1))
            j = int(rng.integers(0, w - cw + 1))
            return img.resize((size, size), Image.BILINEAR,
                              box=(j, i, j + cw, i + ch))
    # Fallback: center crop at the nearest valid aspect ratio.
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = h, int(round(h * ratio[1]))
    else:
        cw, ch = w, h
    i, j = (h - ch) // 2, (w - cw) // 2
    return img.resize((size, size), Image.BILINEAR, box=(j, i, j + cw, i + ch))


def resize_shorter(img, size: int):
    """torchvision Resize(int): scale so the SHORTER edge == size."""
    from PIL import Image
    img = _to_pil(img)
    w, h = img.size
    if w <= h:
        nw, nh = size, max(1, int(round(h * size / w)))
    else:
        nh, nw = size, max(1, int(round(w * size / h)))
    return img.resize((nw, nh), Image.BILINEAR)


def center_crop(img, size: int):
    img = _to_pil(img)
    w, h = img.size
    j = (w - size) // 2
    i = (h - size) // 2
    return img.crop((j, i, j + size, i + size))


def to_normalized_array(img, mean: np.ndarray = IMAGENET_MEAN,
                        std: np.ndarray = IMAGENET_STD) -> np.ndarray:
    """ToTensor + Normalize, but NHWC (TPU layout) instead of NCHW."""
    arr = np.asarray(img, dtype=np.float32)
    if arr.ndim == 2:                       # grayscale → 3-channel
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[-1] == 4:                  # drop alpha
        arr = arr[..., :3]
    arr = arr / 255.0
    return (arr - mean) / std


def random_erasing(arr: np.ndarray, rng: np.random.Generator,
                   scale=(0.02, 0.33), ratio=(0.3, 3.3)) -> np.ndarray:
    """torchvision ``RandomErasing(value=0)`` body (the caller rolls the
    apply-probability): sample an erase box 10 times (area/aspect like
    RandomResizedCrop), zero it; give up silently if none fits."""
    h, w = arr.shape[:2]
    area = h * w
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        eh = int(round(math.sqrt(target * aspect)))
        ew = int(round(math.sqrt(target / aspect)))
        if eh < h and ew < w:
            i = int(rng.integers(0, h - eh + 1))
            j = int(rng.integers(0, w - ew + 1))
            arr = arr.copy()
            arr[i:i + eh, j:j + ew] = 0.0
            return arr
    return arr


def train_transform(img, size: int, rng: np.random.Generator,
                    aa=None, random_erase: float = 0.0) -> np.ndarray:
    """The reference's train stack (``distributed.py:161-166``); ``aa`` is an
    optional auto-augment policy fn applied after the flip, before
    normalization — where torchvision's recipes slot RandAugment/
    TrivialAugmentWide. ``random_erase`` is the RandomErasing probability
    (applied after normalization, on the array, like torchvision's
    tensor-stage placement)."""
    img = random_resized_crop(img, size, rng)
    if rng.random() < 0.5:                  # RandomHorizontalFlip
        img = img.transpose(0)              # PIL FLIP_LEFT_RIGHT == 0
    if aa is not None:
        img = aa(img, rng)
    arr = to_normalized_array(img)
    if random_erase > 0.0 and rng.random() < random_erase:
        arr = random_erasing(arr, rng)
    return arr


def val_transform(img, size: int, resize: int) -> np.ndarray:
    """The reference's val stack (``distributed.py:171-176``)."""
    return to_normalized_array(center_crop(resize_shorter(img, resize), size))
