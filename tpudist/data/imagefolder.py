"""ImageFolder dataset (torchvision-compatible directory layout).

The reference uses ``torchvision.datasets.ImageFolder`` (``distributed.py:160,
170``): ``root/class_x/xxx.png`` → (image, class_index), classes sorted
alphabetically. Same contract here, without torchvision: directory scan +
PIL decode.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


class ImageFolder:
    """``root/<class>/<image>`` dataset with torchvision's class ordering
    (sorted) and sample ordering (per-class, sorted)."""

    def __init__(self, root: str, loader: Optional[Callable] = None):
        self.root = root
        self.classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not self.classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples: list[tuple[str, int]] = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for dirpath, _, filenames in sorted(os.walk(cdir)):
                for fn in sorted(filenames):
                    if fn.lower().endswith(IMG_EXTENSIONS):
                        self.samples.append((os.path.join(dirpath, fn),
                                             self.class_to_idx[c]))
        self.loader = loader or self._pil_loader

    @staticmethod
    def _pil_loader(path: str):
        from PIL import Image
        with open(path, "rb") as f:
            img = Image.open(f)
            return img.convert("RGB")

    @staticmethod
    def raw_loader(path: str) -> bytes:
        """Raw file bytes — for transforms that decode natively (the
        ``data/native.py`` JPEG kernels); their PIL fallback decodes any
        non-JPEG bytes."""
        with open(path, "rb") as f:
            return f.read()

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, index: int):
        path, target = self.samples[index]
        return self.loader(path), target
