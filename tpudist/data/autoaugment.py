"""RandAugment / TrivialAugmentWide (torchvision ``autoaugment.py`` semantics).

No reference equivalent (the reference's recipe predates both), but they are
the augmentation halves of the modern recipes the transformer-era zoo trains
under (``--optimizer adamw`` etc.). Implemented over PIL — the same backend
torchvision's functional ops use for PIL inputs, so the photometric ops
(posterize/solarize/equalize/autocontrast/brightness/color/contrast/
sharpness) are bit-identical; the geometric ops use PIL affine transforms
with nearest resampling. Magnitudes are drawn with an explicit
``np.random.Generator`` (reproducible per (seed, epoch, index), like the
rest of the pipeline — the functional-RNG answer to torch's global RNG).

- RandAugment: ``num_ops`` sequential ops, fixed ``magnitude`` bin (default
  2 ops @ bin 9 of 31 — torchvision defaults); signed magnitudes flip with
  p=0.5.
- TrivialAugmentWide: ONE op, uniformly random bin in [0, 30], wider ranges.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Dict, Tuple

import numpy as np

_NUM_BINS = 31


def _affine(img, coeffs):
    from PIL import Image
    return img.transform(img.size, Image.AFFINE, coeffs, Image.NEAREST)


def _apply_op(img, name: str, mag: float):
    from PIL import ImageEnhance, ImageOps
    if name == "Identity":
        return img
    if name == "ShearX":
        # Top-left-anchored coeffs (1, level, 0, 0, 1, 0): the official
        # AutoAugment PIL implementation, which torchvision reproduces by
        # passing center=[0, 0] to F.affine for the shear ops.
        return _affine(img, (1.0, mag, 0.0, 0.0, 1.0, 0.0))
    if name == "ShearY":
        return _affine(img, (1.0, 0.0, 0.0, mag, 1.0, 0.0))
    if name == "TranslateX":
        return _affine(img, (1.0, 0.0, mag, 0.0, 1.0, 0.0))
    if name == "TranslateY":
        return _affine(img, (1.0, 0.0, 0.0, 0.0, 1.0, mag))
    if name == "Rotate":
        from PIL import Image
        return img.rotate(mag, Image.NEAREST)
    if name == "Brightness":
        return ImageEnhance.Brightness(img).enhance(1.0 + mag)
    if name == "Color":
        return ImageEnhance.Color(img).enhance(1.0 + mag)
    if name == "Contrast":
        return ImageEnhance.Contrast(img).enhance(1.0 + mag)
    if name == "Sharpness":
        return ImageEnhance.Sharpness(img).enhance(1.0 + mag)
    if name == "Posterize":
        return ImageOps.posterize(img, int(mag))
    if name == "Solarize":
        # float threshold passes through (torchvision hands PIL the raw
        # linspace value; int() would shift odd magnitude bins by one level)
        return ImageOps.solarize(img, mag)
    if name == "AutoContrast":
        return ImageOps.autocontrast(img)
    if name == "Equalize":
        return ImageOps.equalize(img)
    raise ValueError(f"unknown augmentation op '{name}'")


@lru_cache(maxsize=None)
def _randaugment_space(width: int, height: int) -> Dict[str, Tuple[np.ndarray, bool]]:
    """torchvision RandAugment._augmentation_space (31 bins). Translate
    magnitudes are per-axis like torchvision's (X from width =
    its ``image_size[1]``, Y from height = ``image_size[0]`` of the
    (height, width) tuple) — identical for the trainer's square crops,
    different for non-square images via the standalone API."""
    bins = _NUM_BINS
    return {
        "Identity": (np.zeros(bins), False),
        "ShearX": (np.linspace(0.0, 0.3, bins), True),
        "ShearY": (np.linspace(0.0, 0.3, bins), True),
        "TranslateX": (np.linspace(0.0, 150.0 / 331.0 * width, bins), True),
        "TranslateY": (np.linspace(0.0, 150.0 / 331.0 * height, bins), True),
        "Rotate": (np.linspace(0.0, 30.0, bins), True),
        "Brightness": (np.linspace(0.0, 0.9, bins), True),
        "Color": (np.linspace(0.0, 0.9, bins), True),
        "Contrast": (np.linspace(0.0, 0.9, bins), True),
        "Sharpness": (np.linspace(0.0, 0.9, bins), True),
        "Posterize": (8 - np.round(np.arange(bins) / ((bins - 1) / 4)), False),
        "Solarize": (np.linspace(255.0, 0.0, bins), False),
        "AutoContrast": (np.zeros(bins), False),
        "Equalize": (np.zeros(bins), False),
    }


@lru_cache(maxsize=None)
def _trivial_wide_space(size: int) -> Dict[str, Tuple[np.ndarray, bool]]:
    """torchvision TrivialAugmentWide._augmentation_space (31 bins)."""
    bins = _NUM_BINS
    return {
        "Identity": (np.zeros(bins), False),
        "ShearX": (np.linspace(0.0, 0.99, bins), True),
        "ShearY": (np.linspace(0.0, 0.99, bins), True),
        "TranslateX": (np.linspace(0.0, 32.0, bins), True),
        "TranslateY": (np.linspace(0.0, 32.0, bins), True),
        "Rotate": (np.linspace(0.0, 135.0, bins), True),
        "Brightness": (np.linspace(0.0, 0.99, bins), True),
        "Color": (np.linspace(0.0, 0.99, bins), True),
        "Contrast": (np.linspace(0.0, 0.99, bins), True),
        "Sharpness": (np.linspace(0.0, 0.99, bins), True),
        "Posterize": (8 - np.round(np.arange(bins) / ((bins - 1) / 6)), False),
        "Solarize": (np.linspace(255.0, 0.0, bins), False),
        "AutoContrast": (np.zeros(bins), False),
        "Equalize": (np.zeros(bins), False),
    }


def _pick(space, name, bin_idx, rng):
    mags, signed = space[name]
    mag = float(mags[bin_idx])
    if signed and rng.random() < 0.5:
        mag = -mag
    return mag


def rand_augment(img, rng: np.random.Generator, num_ops: int = 2,
                 magnitude: int = 9):
    """torchvision ``RandAugment(num_ops=2, magnitude=9)``."""
    space = _randaugment_space(*img.size)
    names = list(space)
    for _ in range(num_ops):
        name = names[int(rng.integers(0, len(names)))]
        img = _apply_op(img, name, _pick(space, name, magnitude, rng))
    return img


def trivial_augment_wide(img, rng: np.random.Generator):
    """torchvision ``TrivialAugmentWide()`` — one op, random magnitude bin."""
    space = _trivial_wide_space(min(img.size))
    names = list(space)
    name = names[int(rng.integers(0, len(names)))]
    bin_idx = int(rng.integers(0, _NUM_BINS))
    return _apply_op(img, name, _pick(space, name, bin_idx, rng))


def build(policy: str) -> Callable | None:
    """'' → None; 'ra' → RandAugment; 'ta_wide' → TrivialAugmentWide."""
    if not policy:
        return None
    if policy == "ra":
        return rand_augment
    if policy == "ta_wide":
        return trivial_augment_wide
    raise ValueError(f"unknown --auto-augment policy '{policy}' "
                     f"(expected '', 'ra', or 'ta_wide')")
