"""Batched, prefetching data loader (reference ``DataLoader(num_workers=8,
pin_memory=True)``, ``distributed.py:168-169``).

torch's DataLoader forks worker PROCESSES and pins host memory for async H2D.
The TPU-native shape is different: the hot path is host→TPU transfer of one
fused batch per step, so this loader uses a THREAD pool (PIL/numpy release the
GIL for decode/resize) assembling samples directly into a preallocated batch
buffer, plus a bounded prefetch queue so batch N+1 decodes while N trains —
the same overlap DataLoader's workers + pin_memory provide. A C++ decode/
augment path can be slotted in as ``loader`` without changing this class.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional, Sequence

import numpy as np


class DataLoader:
    def __init__(self, dataset, batch_size: int,
                 sampler=None,
                 transform: Optional[Callable] = None,
                 num_workers: int = 4,
                 prefetch: int = 2,
                 drop_last: bool = True,
                 round_up_to: Optional[int] = None,
                 seed: int = 0):
        """``transform(sample, rng) -> np.ndarray`` runs in worker threads.
        ``sampler`` yields dataset indices (ShardedSampler for DDP parity);
        None = sequential. With ``drop_last=False``, ``round_up_to=k`` pads the
        final partial batch by wrapping to a multiple of k (SPMD needs batches
        divisible by the device count; ≤k-1 duplicate samples — same class of
        skew as DistributedSampler's padding, reference quirk #12 — instead of
        dropping up to batch_size-1 samples)."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler
        self.transform = transform
        self.num_workers = max(1, num_workers)
        self.prefetch = max(1, prefetch)
        self.drop_last = drop_last
        self.round_up_to = round_up_to
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None and hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def _index_batches(self) -> list[np.ndarray]:
        if self.sampler is not None:
            idx = np.fromiter(iter(self.sampler), dtype=np.int64)
        else:
            idx = np.arange(len(self.dataset))
        n_full = len(idx) // self.batch_size
        batches = [idx[i * self.batch_size:(i + 1) * self.batch_size]
                   for i in range(n_full)]
        rest = idx[n_full * self.batch_size:]
        if not self.drop_last and len(rest):
            if self.round_up_to and len(rest) % self.round_up_to:
                pad = self.round_up_to - len(rest) % self.round_up_to
                rest = np.concatenate([rest, idx[:pad]])
            batches.append(rest)
        return batches

    def __len__(self) -> int:
        return len(self._index_batches())

    def _assemble(self, batch_idx: np.ndarray, batch_no: int):
        images = None
        labels = np.empty((len(batch_idx),), dtype=np.int32)
        lock = threading.Lock()
        positions = list(enumerate(batch_idx))
        cursor = [0]

        def worker():
            nonlocal images
            while True:
                with lock:
                    if cursor[0] >= len(positions):
                        return
                    pos, ds_index = positions[cursor[0]]
                    cursor[0] += 1
                sample, label = self.dataset[int(ds_index)]
                if self.transform is not None:
                    rng = np.random.default_rng(
                        (self.seed, self.epoch, int(ds_index)))
                    sample = self.transform(sample, rng)
                sample = np.asarray(sample, dtype=np.float32)
                with lock:
                    if images is None:
                        images = np.empty((len(batch_idx),) + sample.shape,
                                          dtype=np.float32)
                images[pos] = sample
                labels[pos] = label

        threads = [threading.Thread(target=worker)
                   for _ in range(min(self.num_workers, len(positions)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return images, labels

    def __iter__(self) -> Iterator:
        batches = self._index_batches()
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def put(item) -> bool:
            # Bounded put that notices consumer abandonment: a plain q.put on
            # a full queue would park this thread forever (leaking it plus the
            # prefetched batches) if the consumer exits mid-epoch.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            for bno, b in enumerate(batches):
                if stop.is_set() or not put(self._assemble(b, bno)):
                    return
            put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                yield item
        finally:
            stop.set()
