"""Batched, prefetching data loader (reference ``DataLoader(num_workers=8,
pin_memory=True)``, ``distributed.py:168-169``).

torch's DataLoader forks worker PROCESSES and pins host memory for async H2D.
The TPU-native shape is different: the hot path is host→TPU transfer of one
fused batch per step, so this loader uses a THREAD pool (PIL/numpy release the
GIL for decode/resize) assembling samples directly into a preallocated batch
buffer, plus a bounded prefetch queue so batch N+1 decodes while N trains —
the same overlap DataLoader's workers + pin_memory provide. A C++ decode/
augment path can be slotted in as ``loader`` without changing this class.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional, Sequence

import numpy as np


class DataLoader:
    def __init__(self, dataset, batch_size: int,
                 sampler=None,
                 transform: Optional[Callable] = None,
                 num_workers: int = 4,
                 prefetch: int = 2,
                 drop_last: bool = True,
                 round_up_to: Optional[int] = None,
                 seed: int = 0,
                 retries: int = 2,
                 retry_backoff: float = 0.05,
                 skip_budget: int = 0):
        """``transform(sample, rng) -> np.ndarray`` runs in worker threads.
        ``sampler`` yields dataset indices (ShardedSampler for DDP parity);
        None = sequential. With ``drop_last=False``, ``round_up_to=k`` pads the
        final partial batch by wrapping to a multiple of k (SPMD needs batches
        divisible by the device count; ≤k-1 duplicate samples — same class of
        skew as DistributedSampler's padding, reference quirk #12 — instead of
        dropping up to batch_size-1 samples).

        Degradation under storage faults (fleet-scale reads WILL hit flaky
        NFS/GCS and the odd corrupt JPEG): a failing read/decode/transform is
        retried ``retries`` times with linear ``retry_backoff`` (transient
        shape), then the sample is SKIPPED — counted in ``samples_skipped``,
        its batch slot refilled with a neighbor from the same batch (the same
        class of duplicate-sample skew as the padding above) — and only past
        ``skip_budget`` skips in one epoch does the loader fail loudly.
        ``skip_budget=0`` (default) means strict: the first persistent
        failure raises. ``samples_retried`` counts retry-healed loads; both
        meters reset per epoch."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler
        self.transform = transform
        self.num_workers = max(1, num_workers)
        self.prefetch = max(1, prefetch)
        self.drop_last = drop_last
        self.round_up_to = round_up_to
        self.seed = seed
        self.epoch = 0
        self.retries = max(0, retries)
        self.retry_backoff = max(0.0, retry_backoff)
        self.skip_budget = max(0, skip_budget)
        self.samples_skipped = 0
        self.samples_retried = 0
        self._stats_lock = threading.Lock()
        self._failed_keys: set[int] = set()   # distinct bad samples, per epoch
        # Elastic continuation: meter baselines carried over a reform (the
        # pre-reform attempt's skip/retry counts must survive into the
        # resumed epoch's accounting) — consumed by the next __iter__.
        self._carry_skipped = 0
        self._carry_retried = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None and hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def set_cursor(self, consumed: int, samples_skipped: int = 0,
                   samples_retried: int = 0) -> None:
        """Elastic continuation of an interrupted epoch: resume this epoch's
        deterministic global order at position ``consumed`` (delegates to
        ``ShardedSampler.set_cursor``; call AFTER ``set_epoch``) and seed
        the per-epoch degradation meters with the interrupted attempt's
        checkpointed counts so skip/retry accounting spans the reform."""
        if self.sampler is not None and hasattr(self.sampler, "set_cursor"):
            self.sampler.set_cursor(consumed)
        self._carry_skipped = max(0, int(samples_skipped))
        self._carry_retried = max(0, int(samples_retried))

    def set_skip_windows(self, windows) -> None:
        """Doctor rollback replay: excise the poisoned global-position
        windows from this epoch's order (delegates to
        ``ShardedSampler.set_skip_windows``; call AFTER ``set_epoch``)."""
        if self.sampler is not None and hasattr(self.sampler,
                                                "set_skip_windows"):
            self.sampler.set_skip_windows(windows)

    def _index_batches(self) -> list[np.ndarray]:
        if self.sampler is not None:
            idx = np.fromiter(iter(self.sampler), dtype=np.int64)
        else:
            idx = np.arange(len(self.dataset))
        n_full = len(idx) // self.batch_size
        batches = [idx[i * self.batch_size:(i + 1) * self.batch_size]
                   for i in range(n_full)]
        rest = idx[n_full * self.batch_size:]
        if not self.drop_last and len(rest):
            if self.round_up_to and len(rest) % self.round_up_to:
                pad = self.round_up_to - len(rest) % self.round_up_to
                rest = np.concatenate([rest, idx[:pad]])
            batches.append(rest)
        return batches

    def __len__(self) -> int:
        return len(self._index_batches())

    def _load_sample(self, ds_index: int):
        """One sample through read→decode→transform with bounded retry.
        Transient failures (injected via the ``decode_fail`` fault point, or
        real IO flake) heal on retry and count in ``samples_retried``;
        exhausting the budget re-raises the last error for the caller's
        skip-and-count path."""
        from tpudist import faults
        last_err = None
        for attempt in range(self.retries + 1):
            try:
                if faults.decode_should_fail(ds_index):
                    raise IOError(
                        f"injected decode failure (sample {ds_index})")
                sample, label = self.dataset[ds_index]
                if self.transform is not None:
                    rng = np.random.default_rng(
                        (self.seed, self.epoch, ds_index))
                    sample = self.transform(sample, rng)
                sample = np.asarray(sample, dtype=np.float32)
                if attempt:
                    with self._stats_lock:
                        self.samples_retried += 1
                return sample, label
            except Exception as e:           # noqa: BLE001 — re-raised below
                last_err = e
                if attempt < self.retries and self.retry_backoff > 0:
                    time.sleep(self.retry_backoff * (attempt + 1))
        raise last_err

    def _assemble(self, batch_idx: np.ndarray, batch_no: int):
        images = None
        labels = np.empty((len(batch_idx),), dtype=np.int32)
        lock = threading.Lock()
        positions = list(enumerate(batch_idx))
        cursor = [0]
        errors: list[BaseException] = []

        def worker():
            nonlocal images
            while True:
                with lock:
                    if errors or cursor[0] >= len(positions):
                        return
                    pos, ds_index = positions[cursor[0]]
                    cursor[0] += 1
                # Walk the batch starting at this slot's own index: the
                # first loadable sample fills the slot. Each DISTINCT bad
                # sample is charged against the corruption budget exactly
                # once per epoch (a neighbor walking over an already-known-
                # bad index must neither re-charge the budget nor re-pay
                # the retry backoff).
                sample = label = None
                for k in range(len(batch_idx)):
                    cand = int(batch_idx[(pos + k) % len(batch_idx)])
                    with self._stats_lock:
                        if cand in self._failed_keys:
                            continue
                    try:
                        sample, label = self._load_sample(cand)
                        break
                    except Exception as e:   # noqa: BLE001
                        with self._stats_lock:
                            if cand not in self._failed_keys:
                                self._failed_keys.add(cand)
                                self.samples_skipped += 1
                            skipped = self.samples_skipped
                        if skipped > self.skip_budget:
                            with lock:
                                errors.append(RuntimeError(
                                    f"data-path corruption budget exceeded: "
                                    f"{skipped} sample(s) still failing "
                                    f"after {self.retries} retries "
                                    f"(budget {self.skip_budget}); last "
                                    f"error on sample {cand}: {e}"))
                            return
                if sample is None:
                    with lock:
                        errors.append(RuntimeError(
                            f"no loadable sample in batch {batch_no}: all "
                            f"{len(batch_idx)} candidates failed"))
                    return
                with lock:
                    if images is None:
                        images = np.empty((len(batch_idx),) + sample.shape,
                                          dtype=np.float32)
                images[pos] = sample
                labels[pos] = label

        threads = [threading.Thread(target=worker)
                   for _ in range(min(self.num_workers, len(positions)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return images, labels

    def __iter__(self) -> Iterator:
        batches = self._index_batches()
        with self._stats_lock:      # per-epoch meters (carry spans a reform)
            self.samples_skipped = self._carry_skipped
            self.samples_retried = self._carry_retried
            self._carry_skipped = 0
            self._carry_retried = 0
            self._failed_keys = set()
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def put(item) -> bool:
            # Bounded put that notices consumer abandonment: a plain q.put on
            # a full queue would park this thread forever (leaking it plus the
            # prefetched batches) if the consumer exits mid-epoch.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            for bno, b in enumerate(batches):
                if stop.is_set():
                    return
                try:
                    batch = self._assemble(b, bno)
                except BaseException as e:   # noqa: BLE001 — crosses threads
                    # Fail LOUDLY on the consumer side: a producer that dies
                    # silently would end the epoch early and silently train
                    # on a truncated dataset.
                    put(e)
                    return
                if not put(batch):
                    return
            put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
