"""Synthetic dataset: deterministic random images for benchmarks and tests
(no reference equivalent — the reference hard-requires an ImageNet mount,
``distributed.py:44``; this removes that requirement)."""

from __future__ import annotations

import numpy as np


class SyntheticDataset:
    """Index-addressable fake ImageFolder: image i is deterministic in
    (seed, i), so runs are reproducible and loss decrease is testable."""

    def __init__(self, num_samples: int = 1024, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0):
        self.num_samples = num_samples
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, index: int):
        rng = np.random.default_rng((self.seed, index))
        img = rng.standard_normal(
            (self.image_size, self.image_size, 3)).astype(np.float32)
        label = int(rng.integers(0, self.num_classes))
        # Plant a weak class-dependent signal so training can learn it.
        img[:4, :4, :] += label % 7
        return img, label
