"""ctypes bindings for the native (C++) transform kernels.

The reference's data path runs on native code it inherits from torch/PIL
(SURVEY.md §2.3); ours lives in ``native/transforms.cc`` — a fused
crop→bilinear-resize→flip→normalize kernel. Loader worker threads call it
with the GIL released (ctypes drops the GIL around foreign calls), so batch
assembly parallelizes across cores.

``available()`` gates everything: if the shared library isn't built (or the
platform lacks a toolchain), callers fall back to the pure-PIL/numpy path —
same results, fewer images/sec.
"""

from __future__ import annotations

import ctypes
import math
import os
import subprocess
from typing import Optional

import numpy as np

from tpudist.data.transforms import IMAGENET_MEAN, IMAGENET_STD

_LIB_NAME = "libtpudist_native.so"
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_MEAN = IMAGENET_MEAN.astype(np.float32)
_STD = IMAGENET_STD.astype(np.float32)
_F32P = ctypes.POINTER(ctypes.c_float)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def build(timeout: float = 300.0) -> bool:
    """Compile the shared library (out-of-band; e.g. from launch/start.sh or
    a test fixture). Import/first-batch NEVER builds implicitly — a 120 s
    ``make`` stall inside first-batch latency was VERDICT r1 weak #5."""
    global _load_attempted
    try:
        subprocess.run(["make", "-s", "-C", _NATIVE_DIR],
                       check=True, capture_output=True, timeout=timeout)
    except Exception:
        return False
    _load_attempted = False          # allow a retry now that the .so exists
    return available()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if os.environ.get("TPUDIST_DISABLE_NATIVE"):
        # Degradation escape hatch: force the pure PIL/numpy stack when the
        # native build is suspect on this runtime (the fused kernels are an
        # optimization, never a correctness dependency — the fault tests
        # use this to pin the portable decode path).
        return None
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    path = os.path.join(_NATIVE_DIR, _LIB_NAME)
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.crop_resize_normalize.argtypes = [
        _U8P, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, _F32P, _F32P, _F32P]
    lib.crop_resize_normalize.restype = None
    lib.val_resize_crop_normalize.argtypes = [
        _U8P, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _F32P, _F32P, _F32P]
    lib.val_resize_crop_normalize.restype = None
    # JPEG kernels (native/jpeg.cc) — absent from a stale pre-r3 build.
    if hasattr(lib, "jpeg_header_dims"):
        _IP = ctypes.POINTER(ctypes.c_int)
        lib.jpeg_header_dims.argtypes = [_U8P, ctypes.c_size_t, _IP, _IP]
        lib.jpeg_header_dims.restype = ctypes.c_int
        lib.jpeg_decode_crop_resize_normalize.argtypes = [
            _U8P, ctypes.c_size_t,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, _F32P, _F32P, _F32P]
        lib.jpeg_decode_crop_resize_normalize.restype = ctypes.c_int
        lib.jpeg_decode_val.argtypes = [
            _U8P, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            _F32P, _F32P, _F32P]
        lib.jpeg_decode_val.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def jpeg_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "jpeg_header_dims")


def _as_u8_hwc(img) -> np.ndarray:
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[-1] == 4:
        arr = arr[..., :3]
    return np.ascontiguousarray(arr)


def crop_resize_normalize(img, box, out_size: int, flip: bool) -> np.ndarray:
    """Fused native version of crop→resize(out_size)→flip→normalize.
    ``box`` = (x0, y0, w, h) in source pixels."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    arr = _as_u8_hwc(img)
    h, w = arr.shape[:2]
    out = np.empty((out_size, out_size, 3), np.float32)
    x0, y0, cw, ch = (int(v) for v in box)
    lib.crop_resize_normalize(
        arr.ctypes.data_as(_U8P), h, w, x0, y0, cw, ch,
        out_size, int(flip),
        _MEAN.ctypes.data_as(_F32P), _STD.ctypes.data_as(_F32P),
        out.ctypes.data_as(_F32P))
    return out


def val_transform(img, size: int, resize: int) -> np.ndarray:
    """Fused native val stack (Resize(shorter)→CenterCrop→Normalize)."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    arr = _as_u8_hwc(img)
    h, w = arr.shape[:2]
    out = np.empty((size, size, 3), np.float32)
    lib.val_resize_crop_normalize(
        arr.ctypes.data_as(_U8P), h, w, resize, size,
        _MEAN.ctypes.data_as(_F32P), _STD.ctypes.data_as(_F32P),
        out.ctypes.data_as(_F32P))
    return out


def sample_rrc_box(src_w: int, src_h: int, rng: np.random.Generator,
                   scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """RandomResizedCrop's box sampling (same algorithm as
    transforms.random_resized_crop), returned as (x0, y0, w, h)."""
    area = src_w * src_h
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= src_w and 0 < ch <= src_h:
            x0 = int(rng.integers(0, src_w - cw + 1))
            y0 = int(rng.integers(0, src_h - ch + 1))
            return x0, y0, cw, ch
    in_ratio = src_w / src_h
    if in_ratio < ratio[0]:
        cw, ch = src_w, int(round(src_w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = src_h, int(round(src_h * ratio[1]))
    else:
        cw, ch = src_w, src_h
    return (src_w - cw) // 2, (src_h - ch) // 2, cw, ch


def train_transform(img, size: int, rng: np.random.Generator) -> np.ndarray:
    """Fused native train stack (RandomResizedCrop→flip→Normalize)."""
    arr = _as_u8_hwc(img)
    h, w = arr.shape[:2]
    box = sample_rrc_box(w, h, rng)
    return crop_resize_normalize(arr, box, size, bool(rng.random() < 0.5))


def _as_u8_buffer(data) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8)     # zero-copy view


def decode_train_transform(data, size: int,
                           rng: np.random.Generator) -> Optional[np.ndarray]:
    """Fully-fused native train stack from raw JPEG bytes: header-only dims
    → sample the RandomResizedCrop box at FULL resolution → partial decode
    (DCT-scaled, scanline-cropped, native/jpeg.cc) → fused
    crop→resize→flip→normalize. Returns None when the bytes are not a JPEG
    the fast path can decode (caller falls back to PIL). Draws the same rng
    stream (box, then flip) as the PIL/transform-only paths."""
    lib = _load()
    if lib is None or not hasattr(lib, "jpeg_header_dims"):
        return None
    buf = _as_u8_buffer(data)
    h, w = ctypes.c_int(), ctypes.c_int()
    if lib.jpeg_header_dims(buf.ctypes.data_as(_U8P), buf.size,
                            ctypes.byref(h), ctypes.byref(w)):
        return None
    box = sample_rrc_box(w.value, h.value, rng)
    flip = bool(rng.random() < 0.5)
    out = np.empty((size, size, 3), np.float32)
    rc = lib.jpeg_decode_crop_resize_normalize(
        buf.ctypes.data_as(_U8P), buf.size, *(int(v) for v in box),
        size, int(flip),
        _MEAN.ctypes.data_as(_F32P), _STD.ctypes.data_as(_F32P),
        out.ctypes.data_as(_F32P))
    return out if rc == 0 else None


def decode_val_transform(data, size: int,
                         resize: int) -> Optional[np.ndarray]:
    """Fully-fused native val stack from raw JPEG bytes (decode at the
    largest 1/2^k scale covering Resize(shorter=resize), then the fused
    resize→center-crop→normalize kernel). None → caller falls back to PIL."""
    lib = _load()
    if lib is None or not hasattr(lib, "jpeg_header_dims"):
        return None
    buf = _as_u8_buffer(data)
    out = np.empty((size, size, 3), np.float32)
    rc = lib.jpeg_decode_val(
        buf.ctypes.data_as(_U8P), buf.size, resize, size,
        _MEAN.ctypes.data_as(_F32P), _STD.ctypes.data_as(_F32P),
        out.ctypes.data_as(_F32P))
    return out if rc == 0 else None
