"""Per-process data sharding (reference ``DistributedSampler``,
``distributed.py:167,177`` + ``set_epoch`` at ``distributed.py:188-189``).

Same semantics as torch's DistributedSampler: pad the index list to a multiple
of ``num_replicas`` by repeating from the front, shuffle deterministically by
(seed, epoch), then each replica takes a strided slice. The padding-duplicate
val-accuracy skew (reference quirk #12, SURVEY.md) is preserved by default for
parity but can be disabled with ``pad=False`` (last shard shorter).
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(self, dataset_len: int, num_replicas: int, rank: int,
                 shuffle: bool = True, seed: int = 0, pad: bool = True):
        assert 0 <= rank < num_replicas
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.pad = pad
        self.epoch = 0
        self.num_samples = -(-dataset_len // num_replicas)   # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle per epoch (reference ``sampler.set_epoch(epoch)``,
        ``distributed.py:188-189``)."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        idx = np.arange(self.dataset_len)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            rng.shuffle(idx)
        if self.pad:
            if self.total_size > len(idx):
                idx = np.concatenate([idx, idx[: self.total_size - len(idx)]])
            return idx[self.rank:self.total_size:self.num_replicas]
        return idx[self.rank::self.num_replicas]

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.num_samples if self.pad else len(self.indices())
