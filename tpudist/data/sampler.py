"""Per-process data sharding (reference ``DistributedSampler``,
``distributed.py:167,177`` + ``set_epoch`` at ``distributed.py:188-189``).

Same semantics as torch's DistributedSampler: pad the index list to a multiple
of ``num_replicas`` by repeating from the front, shuffle deterministically by
(seed, epoch), then each replica takes a strided slice. The padding-duplicate
val-accuracy skew (reference quirk #12, SURVEY.md) is preserved by default for
parity but can be disabled with ``pad=False`` (last shard shorter).

ELASTIC CONTINUATION (``set_cursor``): the epoch's GLOBAL order — the
(seed, epoch) permutation before any rank takes its slice — is world-size
independent, and with the strided slice above, global step ``j`` consumes
exactly positions ``[j*B, (j+1)*B)`` of it (B = global batch): rank r's
batch j covers positions ``{r + (j*hb + i)*W}``. So a checkpointed cursor
of N consumed samples lets a RESUMED run — at the same or a DIFFERENT
world size — drop the first N positions and redistribute the remainder
over the new (rank, world): no sample dropped, none double-seen, and when
the new world divides the same global batch, the continuation's global
batches are bit-identical slices of the same order. The cursor counts
positions of the UNPADDED permutation; padding duplicates live at the very
tail only (train runs drop_last anyway). ``set_epoch`` clears the cursor —
only the interrupted epoch continues mid-way.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(self, dataset_len: int, num_replicas: int, rank: int,
                 shuffle: bool = True, seed: int = 0, pad: bool = True):
        assert 0 <= rank < num_replicas
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.pad = pad
        self.epoch = 0
        self.cursor = 0
        self.skip_windows: list[tuple[int, int]] = []
        self.num_samples = -(-dataset_len // num_replicas)   # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle per epoch (reference ``sampler.set_epoch(epoch)``,
        ``distributed.py:188-189``). Clears any elastic cursor and any
        doctor skip windows: only the epoch a checkpoint interrupted
        resumes mid-way, and only the epoch being replayed skips its
        poisoned window (the trainer re-applies both AFTER set_epoch)."""
        self.epoch = epoch
        self.cursor = 0
        self.skip_windows = []

    def set_skip_windows(self, windows) -> None:
        """Doctor rollback replay (tpudist/doctor/): excise the poisoned
        ``[start, end)`` position windows from this epoch's global order.
        Positions index the (seed, epoch) permutation BEFORE padding and
        striding, exactly like the elastic cursor — so the replayed epoch
        re-delivers the checkpoint-onward batch sequence bit-identically,
        minus the quarantined samples, at any world size. Windows apply
        IN ORDER, each indexing the order as already excised by its
        predecessors: a second rollback's window was measured on the
        first replay's (already-shortened) order, and applying it to the
        same intermediate order keeps the mapping exact. Call AFTER
        ``set_epoch`` (which clears windows)."""
        self.skip_windows = [
            (max(0, int(a)), int(b)) for a, b in windows if int(b) > int(a)]

    def set_cursor(self, consumed: int) -> None:
        """Elastic continuation: skip the first ``consumed`` positions of
        this epoch's global order and redistribute the remainder over
        (rank, num_replicas) — which may differ from the world that
        consumed them. Call AFTER ``set_epoch`` (set_epoch clears it)."""
        self.cursor = min(max(0, int(consumed)), self.dataset_len)

    def global_order(self) -> np.ndarray:
        """The epoch's world-size-independent global sample order (the
        permutation every rank slices; padding is applied after)."""
        idx = np.arange(self.dataset_len)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            rng.shuffle(idx)
        return idx

    def _pad_stride(self, idx: np.ndarray) -> np.ndarray:
        if self.pad:
            total = -(-len(idx) // self.num_replicas) * self.num_replicas \
                if len(idx) else 0
            if total > len(idx):
                idx = np.concatenate([idx, idx[: total - len(idx)]])
            return idx[self.rank:total:self.num_replicas]
        return idx[self.rank::self.num_replicas]

    def _apply_skip_windows(self, idx: np.ndarray) -> np.ndarray:
        for a, b in self.skip_windows:     # sequential: see set_skip_windows
            idx = np.concatenate([idx[:a], idx[b:]])
        return idx

    def indices(self) -> np.ndarray:
        # Windows first (they are positions of the pristine order), then
        # the cursor over what remains — matching the replay semantics: a
        # continuation of a replayed epoch counts consumed positions of
        # the already-excised order.
        idx = self._apply_skip_windows(self.global_order())
        if self.cursor:
            idx = idx[self.cursor:]
        return self._pad_stride(idx)

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        if self.cursor or self.skip_windows:
            remaining = len(self._apply_skip_windows(
                np.arange(self.dataset_len)))
            remaining = max(0, remaining - self.cursor)
            if self.pad:
                return -(-remaining // self.num_replicas) if remaining else 0
            return max(0, -(-(remaining - self.rank) // self.num_replicas))
        return self.num_samples if self.pad else len(self.indices())
