"""CLI: ``python -m tpudist.serve`` — export a checkpoint and serve it.

One process = one serving replica. The replica AOT-compiles its bucket set
(persistent-cache-backed), starts the continuous batcher, and — in this
repo's harness form — drives itself with synthetic open-loop traffic
(``--load-rate``/``--load-duration``); a zero rate just warms the cache
and reports the AOT numbers (the "pre-warm a replica" mode). Telemetry and
the per-rank metrics endpoint work exactly as in training (``--telemetry``
``--metrics-port``), so ``summarize`` prints the serving section and the
launcher's fleet view aggregates replicas.

Multi-replica: ``python -m tpudist.launch -n 1 --scale-up 2@10 -- python
-m tpudist.serve ... --telemetry --outpath <shared>`` — the launcher
spawns the second replica under load and the fleet endpoint shows both
(the 2-replica e2e in ``tests/test_serve.py``). Rank identity comes from
``TPUDIST_PROCESS_ID`` like a training rank's.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.serve",
        description="Serve a tpudist checkpoint: AOT bucket compilation + "
                    "continuous batching + telemetry (docs/SERVING.md)")
    p.add_argument("-a", "--arch", default="resnet18")
    p.add_argument("--checkpoint", default="",
                   help="checkpoint.msgpack file or run dir; '' = fresh "
                        "init weights (bench/smoke)")
    p.add_argument("--num-classes", type=int, default=1000,
                   dest="num_classes")
    p.add_argument("--image-size", type=int, default=224, dest="image_size")
    p.add_argument("--buckets", default="1,2,4,8",
                   help="comma-separated micro-batch bucket sizes; every "
                        "request batch is padded to the smallest fitting "
                        "bucket, so steady-state traffic compiles exactly "
                        "len(buckets) programs — at startup")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   dest="max_wait_ms",
                   help="how long the batcher holds a micro-batch open for "
                        "more requests to coalesce (latency vs occupancy "
                        "knob)")
    p.add_argument("--compile-cache", default="", dest="compile_cache",
                   help="persistent XLA compilation cache dir (env "
                        "TPUDIST_COMPILE_CACHE): a warm replica AOT-starts "
                        "in seconds instead of minutes")
    p.add_argument("--flash", default="auto", choices=("auto", "on", "off"),
                   help="attention backend for vit archs, resolved through "
                        "the measurement-honest dispatch layer with the "
                        "eval-mode (train=False) workload key")
    p.add_argument("--load-rate", type=float, default=0.0, dest="load_rate",
                   help="synthetic open-loop arrivals per second (0 = no "
                        "load: warm the cache, report AOT numbers, exit)")
    p.add_argument("--load-duration", type=float, default=10.0,
                   dest="load_duration",
                   help="seconds of synthetic load")
    p.add_argument("--load-batch", type=int, default=1, dest="load_batch",
                   help="rows per synthetic request")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--outpath", default="",
                   help="run dir for telemetry/portfiles (required with "
                        "--telemetry)")
    p.add_argument("--telemetry", action="store_true",
                   help="write events.<rank>.jsonl (serve_start/request/"
                        "serve_batch + compile events) + heartbeats")
    p.add_argument("--metrics-port", type=int, default=-1,
                   dest="metrics_port",
                   help="with --telemetry: per-replica Prometheus endpoint "
                        "(request p50/p99 latency, queue depth, batch "
                        "occupancy, req/s); 0 = ephemeral, written to "
                        "<outpath>/metrics.<rank>.port")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.telemetry and not args.outpath:
        build_parser().error("--telemetry needs --outpath")
    if args.metrics_port >= 0 and not args.telemetry:
        build_parser().error("--metrics-port requires --telemetry (the "
                             "endpoint serves gauges derived from the "
                             "telemetry event stream)")

    from tpudist.serve.batching import parse_buckets
    buckets = parse_buckets(args.buckets)

    # Cache config BEFORE any jax compilation.
    from tpudist.serve.cache import configure_compile_cache, resolve_cache_dir
    cache_dir = resolve_cache_dir(args.compile_cache)
    cache = configure_compile_cache(cache_dir) if cache_dir else "off"

    import jax

    def log(msg: str) -> None:
        print(msg, flush=True)

    telemetry = None
    metrics_server = None
    rank = 0
    try:
        rank = int(os.environ.get("TPUDIST_PROCESS_ID", "0"))
    except ValueError:
        pass
    if args.telemetry:
        from tpudist import telemetry as telemetry_lib
        os.makedirs(args.outpath, exist_ok=True)
        telemetry = telemetry_lib.Telemetry(args.outpath, rank=rank)
        telemetry.emit("run_start", platform=jax.default_backend(),
                       n_devices=jax.device_count(),
                       device_kind=jax.devices()[0].device_kind,
                       arch=args.arch, global_batch=buckets[-1],
                       mode="serve")
        if args.metrics_port >= 0:
            from tpudist.obs.server import MetricsRegistry, MetricsServer
            reg = MetricsRegistry(rank=rank)
            telemetry.add_sink(reg.observe)
            try:
                metrics_server = MetricsServer(
                    reg, port=args.metrics_port).start()
            except OSError as e:
                # --scale-up hands every replica the SAME command line,
                # fixed --metrics-port included; the newcomer losing the
                # bind race must degrade to an ephemeral port
                # (discoverable via the port file), not die and silently
                # yield a one-replica fleet (trainer's pattern).
                log(f"=> serve metrics port {args.metrics_port} "
                    f"unavailable ({e!r}) — falling back to an ephemeral "
                    f"port")
                metrics_server = MetricsServer(reg, port=0).start()
            metrics_server.write_portfile(args.outpath, rank)
            log(f"=> serve metrics on :{metrics_server.port} (/metrics)")

    from tpudist.serve.batching import ContinuousBatcher, open_loop_load
    from tpudist.serve.engine import ServeEngine
    from tpudist.serve.export import load_serve_state

    model, variables = load_serve_state(
        args.arch, args.checkpoint, num_classes=args.num_classes,
        image_size=args.image_size, max_batch=buckets[-1],
        flash=args.flash, seed=args.seed, telemetry=telemetry, log=log)
    engine = ServeEngine(model, variables, image_size=args.image_size,
                         buckets=buckets, telemetry=telemetry, cache=cache,
                         log=log)

    summary = {"arch": args.arch, "buckets": list(buckets),
               "aot_s": round(engine.aot_s, 3),
               "aot_compile_s": round(engine.aot_compile_s, 3),
               "cache": cache, "rank": rank}
    t_serve0 = time.perf_counter()
    if args.load_rate > 0:
        import numpy as np
        batcher = ContinuousBatcher(engine,
                                    max_wait_s=args.max_wait_ms / 1e3,
                                    telemetry=telemetry)
        shape = (args.load_batch, args.image_size, args.image_size, 3)

        def make_images(rng):
            return rng.standard_normal(shape).astype(np.float32)

        log(f"=> serving synthetic open-loop load: {args.load_rate} req/s "
            f"for {args.load_duration}s")
        results = open_loop_load(batcher, args.load_rate,
                                 args.load_duration, make_images,
                                 seed=args.seed)
        batcher.close()
        # Engine errors complete the future with .error set instead of
        # raising out of the load run — the replica's shutdown path
        # (telemetry.close → run_end, SERVE_SUMMARY) must run even when
        # requests failed, or the operator loses the evidence exactly
        # when diagnosing the failure.
        ok = [r for r in results if r.error is None]
        n_errors = len(results) - len(ok)
        lats = sorted(r.latency_s for r in ok)
        from tpudist.telemetry import percentile
        span = max(time.perf_counter() - t_serve0, 1e-9)
        summary.update(
            n_requests=len(results), n_errors=n_errors,
            achieved_req_s=round(len(ok) / span, 2),
            latency_p50_ms=(round(percentile(lats, 50) * 1e3, 3)
                            if lats else None),
            latency_p99_ms=(round(percentile(lats, 99) * 1e3, 3)
                            if lats else None))
        if lats:
            log(f"=> served {len(ok)} requests: p50 "
                f"{summary['latency_p50_ms']:.1f} ms, p99 "
                f"{summary['latency_p99_ms']:.1f} ms, "
                f"{summary['achieved_req_s']:.1f} req/s"
                + (f" ({n_errors} errored)" if n_errors else ""))
        else:
            first_err = next(r.error for r in results
                             if r.error is not None)
            log(f"=> every request errored ({n_errors} of {n_errors}; "
                f"first: {first_err!r})")

    if telemetry is not None:
        telemetry.close(mode="serve")
    if metrics_server is not None:
        metrics_server.close()
    print("SERVE_SUMMARY " + json.dumps(summary), flush=True)
    # Partial errors still count as a served run (reported above); a run
    # where NOTHING succeeded is a failure — after clean shutdown.
    if summary.get("n_requests") and not (summary["n_requests"]
                                          - summary.get("n_errors", 0)):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
