"""Checkpoint → compiled eval-mode inference step (the export half of
``tpudist.serve``).

A training checkpoint (``checkpoint.msgpack``, the trainer's native
format) holds the full TrainState; serving needs exactly two trees —
``params`` and ``batch_stats`` — applied in eval mode. ``load_serve_state``
extracts them (EMA weights win when the checkpoint carries them: they are
the weights ``validate()`` selected 'best' with, i.e. what a user of the
EMA recipe would deploy), builds the arch with a bf16 compute dtype, and
resolves ``--flash`` through the SAME measurement-honest dispatch client
the trainer uses (``ops/attention_dispatch``) — with ``train=False`` in
the workload key, so an eval-mode verdict measured once on a device kind
carries over to every replica that serves that shape.

``make_infer_step`` is the one jitted callable the engine AOT-compiles per
bucket: variables in, logits out, input buffer donated (the padded batch
is dead after the forward — donation halves the step's activation-input
footprint; the ``TPUDIST_NO_DONATE`` escape hatch applies, same as
training).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from tpudist import _jaxshim  # noqa: F401  (jax<0.8 surface backfill)
import jax
import jax.numpy as jnp

from tpudist.models import create_model


def make_infer_step(model) -> Callable:
    """The jitted eval forward: ``(variables, images) -> logits``.

    The engine never calls this wrapper blind — it AOT-compiles it per
    bucket shape (``.lower().compile()``) and serves from the compiled
    executables, which structurally cannot recompile. The images buffer is
    donated (argnum 1): a request batch is dead once the logits exist."""
    def step(variables: dict, images: jax.Array) -> jax.Array:
        with jax.named_scope("tpudist_serve_forward"):
            return model.apply(variables, images, train=False)

    from tpudist.parallel._common import donated_jit
    return donated_jit(step, donate_argnums=(1,))


def _extract_serving_variables(ckpt: dict, log=None) -> dict:
    """``{"params", "batch_stats"}`` from a raw checkpoint dict, preferring
    the EMA copy when present (``--model-ema-decay`` runs measured their
    best_acc1 ON the EMA weights — serving the live weights would deploy a
    model that never achieved the recorded metric)."""
    state = ckpt.get("state") or {}
    params = state.get("params")
    if params is None:
        raise ValueError("checkpoint has no state.params — not a tpudist "
                         "training checkpoint")
    batch_stats = state.get("batch_stats") or {}
    ema = state.get("ema_params")
    if isinstance(ema, dict) and ema.get("params"):
        if log is not None:
            log("=> serving the EMA weights (checkpoint carries "
                "ema_params — the copy 'best' was measured on)")
        params = ema["params"]
        batch_stats = ema.get("batch_stats") or batch_stats
    return {"params": params, "batch_stats": batch_stats}


def resolve_serve_flash(model, *, batch: int, image_size: int,
                        mode: str = "auto", telemetry=None,
                        log=None) -> Optional[dict]:
    """Resolve ``--flash`` for the serving workload through
    ``ops/attention_dispatch`` — the trainer's ``_resolve_flash_dispatch``
    with ``train=False`` and the LARGEST bucket as the batch (the shape
    that dominates steady-state throughput). Returns the decision dict and
    the possibly-cloned model as ``decision["model"]``; ``None`` when the
    arch has no derivable attention shape (conv families)."""
    patch = getattr(model, "patch_size", None)
    heads = getattr(model, "num_heads", None)
    hidden = getattr(model, "hidden_dim", None)
    if not (patch and heads and hidden) or image_size % patch:
        return None
    from tpudist.ops import attention_dispatch
    tokens = (image_size // patch) ** 2
    if getattr(model, "pool", "token") == "token":
        tokens += 1
    dt = getattr(model, "dtype", jnp.bfloat16)
    try:
        dec = attention_dispatch.decide(
            batch, tokens, heads, hidden // heads, dt,
            train=False, mode=mode)
    except Exception as e:
        if log is not None:
            log(f"=> serve attention dispatch probe failed ({e!r}) — "
                f"model-level lookup decides")
        return None
    out = dict(dec)
    # Clone in EVERY mode, not just auto: a forced --flash on/off must
    # reach the model the same way the trainer forces it
    # (model_kwargs["flash"]) — otherwise the built model keeps
    # flash=None, the trace-time lookup decides on its own, and the
    # emitted attention_dispatch verdict lies about the kernel served.
    out["model"] = model.clone(flash=dec["kernel"] == "flash")
    if log is not None:
        msg = (f"=> serve attention dispatch: {dec['kernel']} attention "
               f"(mode {dec['mode']}, {dec['source']}")
        if dec.get("flash_ms") is not None:
            msg += (f"; flash {dec['flash_ms']:.3f} ms vs "
                    f"xla {dec['xla_ms']:.3f} ms")
        log(msg + ")")
    if telemetry is not None:
        telemetry.emit("attention_dispatch",
                       **attention_dispatch.event_fields(dec))
    return out


def load_serve_state(arch: str, checkpoint: str = "", *,
                     num_classes: int = 1000, image_size: int = 224,
                     max_batch: int = 8, flash: str = "auto",
                     dtype: Any = jnp.bfloat16, seed: int = 0,
                     telemetry=None, log=None) -> tuple[Any, dict]:
    """Build the serving model + variables.

    ``checkpoint`` may be a ``.msgpack`` file or a run dir (the live
    ``checkpoint.msgpack`` inside it); '' initializes fresh weights — the
    bench/smoke path, where serving PERFORMANCE is the measured quantity
    and weights are irrelevant. Compute dtype defaults to bf16 (eval has
    no master-weight concern; the checkpoint's f32 params are cast by the
    model's dtype policy at apply time, exactly like training's forward).
    """
    model = create_model(arch, num_classes=num_classes, dtype=dtype)
    dec = None
    if arch.startswith("vit"):
        dec = resolve_serve_flash(model, batch=max_batch,
                                  image_size=image_size, mode=flash,
                                  telemetry=telemetry, log=log)
        if dec is not None:
            model = dec["model"]
    if checkpoint:
        from tpudist import checkpoint as ckpt_lib
        ckpt = ckpt_lib.load_checkpoint(checkpoint)
        if ckpt.get("arch") and ckpt["arch"] != arch:
            raise ValueError(
                f"checkpoint was trained as '{ckpt['arch']}' but serving "
                f"was asked for '{arch}' — refusing to apply mismatched "
                f"weights")
        variables = _extract_serving_variables(ckpt, log=log)
        if log is not None:
            log(f"=> exported '{arch}' from {checkpoint} "
                f"(epoch {ckpt.get('epoch', '?')}, "
                f"best_acc1 {float(ckpt.get('best_acc1', 0.0)):.3f})")
    else:
        init = model.init(jax.random.PRNGKey(seed),
                          jnp.ones((1, image_size, image_size, 3),
                                   jnp.float32), train=False)
        variables = {"params": init["params"],
                     "batch_stats": init.get("batch_stats", {})}
        if log is not None:
            log(f"=> serving fresh-init '{arch}' weights (no checkpoint — "
                f"bench/smoke mode)")
    return model, variables
