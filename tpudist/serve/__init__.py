"""tpudist.serve — the serving plane (ISSUE 14, ROADMAP item 1).

The repo trains; production scale means inference traffic. This package
turns a trained checkpoint into a compiled eval-mode inference step and
fronts it with a continuous-batching request queue whose micro-batches are
padded to a FIXED set of bucket shapes, so steady-state traffic never
triggers an XLA recompile:

- ``serve.cache``      — persistent XLA compilation cache config
  (``--compile-cache`` / ``TPUDIST_COMPILE_CACHE``), shared with the
  trainer: a scaled-up replica (or an elastic reform) pays cache-hit
  seconds instead of the 25-45 s compile every bench row shows;
- ``serve.export``     — checkpoint → (model, variables) in eval mode
  (bf16 compute), with ``--flash`` resolved through the SAME
  measurement-honest dispatch client the trainer uses (train=False key);
- ``serve.engine``     — ``ServeEngine``: AOT-compiles the whole bucket
  set at startup (``jit(...).lower().compile()`` per bucket, cache-backed)
  and serves every request from those executables — a compiled executable
  CANNOT recompile, so the zero-recompile property is structural and the
  telemetry compile-event stream proves it (exactly ``len(buckets)``
  events, all phase ``serve_aot``);
- ``serve.batching``   — ``ContinuousBatcher`` (open-loop request queue →
  bucket-padded micro-batches, per-request latency accounting) and the
  synthetic open-loop load generator ``benchmarks/bench_serve.py`` and the
  2-replica e2e drive.

CLI: ``python -m tpudist.serve`` (see ``serve/__main__.py``);
docs: ``docs/SERVING.md``.
"""

# Lazy re-exports: importing the PACKAGE (which `import
# tpudist.serve.cache` does implicitly) must stay cheap and jax-free —
# the trainer reads cache config on every construction, and serve.cache's
# contract is that launcher-side config parsing never drags jax in. The
# engine/export/batching modules load only when their names are touched.
_EXPORTS = {
    "ContinuousBatcher": "batching", "open_loop_load": "batching",
    "pad_to_bucket": "batching", "parse_buckets": "batching",
    "pick_bucket": "batching",
    "configure_compile_cache": "cache", "resolve_cache_dir": "cache",
    "ServeEngine": "engine",
    "load_serve_state": "export", "make_infer_step": "export",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib
        mod = importlib.import_module(f"tpudist.serve.{_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module 'tpudist.serve' has no attribute "
                         f"{name!r}")
