"""``ServeEngine`` — AOT-compiled bucket set + zero-recompile inference.

The cold-start kill (ISSUE 14 tentpole (c)): a serving replica's startup
cost is the XLA compilation of its bucket set, 25-45 s per program on the
bench rows. The engine attacks it twice:

1. **AOT, up front**: every bucket shape is compiled at construction
   (``jit(step).lower(vars, spec).compile()``) instead of lazily on the
   first request of each size — the replica is either NOT serving or
   serving at full speed, never limping through a compile storm under
   live traffic.
2. **Persistent cache underneath** (``serve/cache.py``): the AOT pass is
   backed by ``jax_compilation_cache_dir``, so a scaled-up replica (the
   launcher's ``--scale-up`` path) or a restarted one pays cache-hit
   deserialization instead of compilation. The engine measures and emits
   both ``aot_s`` (trace+lower+compile wall) and ``aot_compile_s`` (the
   ``.compile()`` slice — the part the cache accelerates; tracing cost is
   cache-immune), plus warm/cold provenance, so the cold-start claim is a
   number in the telemetry stream, not an adjective.

Zero recompiles are STRUCTURAL: steady-state inference calls the
already-compiled executables directly (``self._compiled[bucket]``), and a
compiled executable cannot retrace or recompile — a shape outside the
bucket set is chunked/padded into it by construction. The telemetry proof:
a serving run's compile-event stream holds exactly ``len(buckets)`` events,
all phase ``serve_aot`` (asserted in ``tests/test_serve.py`` over a
mixed-size request stream).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from tpudist import _jaxshim  # noqa: F401  (jax<0.8 surface backfill)
import jax
import numpy as np

from tpudist.serve.batching import pad_to_bucket, pick_bucket
from tpudist.serve.export import make_infer_step


class ServeEngine:
    """Compiled eval-mode inference over a fixed bucket set.

    ``infer(images)`` accepts any row count: it chunks to the largest
    bucket, pads each chunk to its bucket shape, runs the chunk's
    AOT-compiled executable, and returns the valid rows' logits as one
    float32 array. ``last_info`` describes the bucket calls the most
    recent ``infer`` made (the batcher's ``serve_batch`` event source).
    """

    def __init__(self, model, variables: dict, *, image_size: int,
                 buckets: Sequence[int] = (1, 2, 4, 8), channels: int = 3,
                 telemetry=None, cache: str = "off", log=None):
        self.model = model
        self.variables = variables
        self.image_size = int(image_size)
        self.channels = int(channels)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] <= 0:
            raise ValueError(f"buckets must be positive, got {buckets!r}")
        self.telemetry = telemetry
        self.cache = cache                  # "warm" | "cold" | "off"
        self._log = log
        self._step = make_infer_step(model)
        self._compiled: dict[int, object] = {}
        self.aot_s = 0.0                    # trace + lower + compile wall
        self.aot_compile_s = 0.0            # the .compile() slice alone —
        #                                     what the persistent cache
        #                                     accelerates (tracing is not
        #                                     cacheable)
        self.last_info: list[dict] = []
        self._warmup()

    # -- AOT bucket compilation --------------------------------------------
    def _warmup(self) -> None:
        tel = self.telemetry
        if tel is not None and self.cache != "off":
            # Tag every compile event with the persistent-cache provenance
            # (the same field the trainer's --compile-cache stamps).
            tel.compile_cache = self.cache
        t_all = time.perf_counter()
        for b in self.buckets:
            spec = jax.ShapeDtypeStruct(
                (b, self.image_size, self.image_size, self.channels),
                jax.numpy.float32)
            t0 = time.perf_counter()
            lowered = self._step.lower(self.variables, spec)
            t1 = time.perf_counter()
            self._compiled[b] = lowered.compile()
            t2 = time.perf_counter()
            self.aot_compile_s += t2 - t1
            if tel is not None:
                tel.note_compile(t2 - t0, phase="serve_aot", bucket=b)
        self.aot_s = time.perf_counter() - t_all
        if self._log is not None:
            self._log(f"=> serve AOT: {len(self.buckets)} bucket programs "
                      f"{list(self.buckets)} in {self.aot_s:.2f}s "
                      f"(XLA compile {self.aot_compile_s:.2f}s, "
                      f"persistent cache {self.cache})")
        if tel is not None:
            tel.emit("serve_start", n_buckets=len(self.buckets),
                     aot_s=round(self.aot_s, 6),
                     aot_compile_s=round(self.aot_compile_s, 6),
                     cache=self.cache,
                     buckets=",".join(str(b) for b in self.buckets),
                     image_size=self.image_size, arch=type(self.model).__name__)

    # -- steady-state inference --------------------------------------------
    def infer(self, images: np.ndarray) -> np.ndarray:
        """Logits for ``images`` (``(n, H, W, C)`` float32, any n ≥ 1),
        served exclusively from the AOT bucket executables. Blocks until
        the result is host-resident (serving latency must be a real
        number, not an enqueue ack)."""
        images = np.asarray(images, dtype=np.float32)
        n = images.shape[0]
        if n < 1:
            raise ValueError("infer needs at least one row")
        max_b = self.buckets[-1]
        outs: list[np.ndarray] = []
        info: list[dict] = []
        i = 0
        while i < n:
            chunk = images[i:i + max_b]
            valid = chunk.shape[0]
            bucket = pick_bucket(valid, self.buckets)
            padded = pad_to_bucket(chunk, bucket)
            t0 = time.perf_counter()
            logits = self._compiled[bucket](self.variables, padded)
            host = np.asarray(logits)       # forces completion
            info.append({"bucket": bucket, "n_valid": valid,
                         "seconds": time.perf_counter() - t0})
            outs.append(host[:valid])
            i += valid
        self.last_info = info
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    # -- introspection ------------------------------------------------------
    def compiled_buckets(self) -> tuple[int, ...]:
        return tuple(sorted(self._compiled))
