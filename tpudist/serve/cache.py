"""Persistent XLA compilation cache configuration (serving AND training).

``compile_s`` is 25-45 s in every bench row (BENCH_r04/r05) — fatal for
autoscaling a serving replica under load, and re-paid in full by every
elastic reform/restart of the trainer. jax already ships the fix (a
content-addressed on-disk executable cache, ``jax_compilation_cache_dir``);
this module is the repo's ONE place that turns it on, so the serve engine,
the trainer (``--compile-cache``), and the tests all configure it the same
way:

- the cache dir comes from the explicit flag, else ``TPUDIST_COMPILE_CACHE``;
- the min-compile-time floor is dropped to 0 so every bucket executable
  persists (the default 1 s floor would silently skip exactly the small
  eval-mode programs a serving bucket set is made of);
- provenance is reported (``"warm"`` = the dir already held entries,
  ``"cold"`` = first fill) and stamped on telemetry ``compile`` events and
  the ``serve_start`` event, so ``summarize`` and the warm-vs-cold startup
  measurement can attribute where the compile seconds went.

Deliberately NOT the run dir (``--overwrite delete`` would discard the
warm cache the next replica needs) and NOT auto-enabled: the cache is
keyed on serialized HLO + compile options + jaxlib version, and operators
should choose a location with the right sharing/eviction semantics
(docs/SERVING.md covers format and invalidation).
"""

from __future__ import annotations

import os

ENV_COMPILE_CACHE = "TPUDIST_COMPILE_CACHE"


def resolve_cache_dir(explicit: str = "") -> str:
    """The configured persistent-cache dir: the explicit flag wins, else
    ``TPUDIST_COMPILE_CACHE``, else '' (disabled)."""
    return explicit or os.environ.get(ENV_COMPILE_CACHE, "")


def cache_state(cache_dir: str) -> str:
    """``"warm"`` when the dir already holds cache entries, else
    ``"cold"``. A heuristic by necessity (jax exposes no per-compile
    hit/miss API), but an honest one: a warm dir's entries are exactly
    what the next AOT pass will be served from, and the measured
    ``aot_compile_s`` beside it is the ground truth."""
    try:
        return "warm" if any(os.scandir(cache_dir)) else "cold"
    except OSError:
        return "cold"


def configure_compile_cache(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at ``cache_dir``
    (process-global, like the cache itself) and return the provenance
    (``"warm"``/``"cold"``) BEFORE this process adds entries.

    Imports jax lazily so the launcher-side consumers of serve config
    parsing stay jax-free."""
    if not cache_dir:
        raise ValueError("configure_compile_cache needs a directory "
                         "(resolve_cache_dir returned '')")
    os.makedirs(cache_dir, exist_ok=True)
    state = cache_state(cache_dir)
    import jax
    changed = jax.config.jax_compilation_cache_dir != cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Persist EVERY executable: the default 1 s floor skips small programs,
    # and a serving bucket set is made of exactly those — a "warm" cache
    # that silently never stored the buckets would defeat the cold-start
    # kill this exists for.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if changed:
        # jax initializes its on-disk cache object at most once per
        # process: a config update AFTER the first compile would silently
        # keep writing to the old dir. reset_cache() returns it to the
        # uninitialized state so the next compile binds the new dir
        # (private API, so best-effort: a fresh process — the normal
        # serving/trainer path — never needs it).
        try:
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception:
            pass
    return state
