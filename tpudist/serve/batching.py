"""Continuous batching: an open-loop request queue packed into
bucket-padded micro-batches.

Serving traffic arrives one request at a time at arbitrary rates; XLA
wants a handful of FIXED shapes. The bridge is the classic bucket scheme:

- ``pick_bucket`` quantizes a request-batch size to the smallest
  configured bucket that fits (the largest bucket caps one engine call —
  oversize batches chunk);
- ``pad_to_bucket`` zero-pads the rows up to the bucket (eval-mode
  forward passes are row-independent — BN normalizes with running stats,
  attention mixes within a row's tokens — so padding rows cannot perturb
  the valid rows' logits; pinned by test);
- ``ContinuousBatcher`` runs the serving loop: pull every queued request
  (waiting up to ``max_wait_s`` for stragglers to coalesce), concatenate
  up to the largest bucket's rows, run ONE engine call, scatter the
  results back to each request's future, and account per-request latency
  (submit → result) plus batch occupancy (valid rows ÷ bucket = padding
  waste).

The quantization is what makes serving recompile-free: every engine call
lands on one of ``len(buckets)`` shapes the engine AOT-compiled at
startup. ``tpudist-check``'s RECOMP02 rule knows ``pick_bucket``/
``pad_to_bucket`` as the sanctioned quantizers — a jitted call keyed on a
raw ``len(batch)``/``.shape`` Python value in a serving loop is exactly
the per-request-recompile hazard it flags.

``open_loop_load`` is the synthetic traffic source (Poisson arrivals at a
target rate, submission times independent of completion — open loop, so
saturation shows up as latency growth instead of silently throttled
offered load); ``benchmarks/bench_serve.py`` sweeps it into the
latency/throughput curve.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np


def parse_buckets(spec) -> tuple[int, ...]:
    """'1,2,4,8' (or an int sequence) → sorted unique positive bucket
    sizes. At least one bucket; zero/negative entries are config errors."""
    if isinstance(spec, str):
        vals = [int(tok) for tok in spec.replace(",", " ").split()]
    else:
        vals = [int(v) for v in spec]
    if not vals or any(v <= 0 for v in vals):
        raise ValueError(f"buckets must be positive ints, got {spec!r}")
    return tuple(sorted(set(vals)))


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """The smallest bucket ≥ n, else the largest (callers chunk oversize
    batches down to it)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pad_to_bucket(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad rows up to ``bucket`` (no-op at exact fit). Oversize input
    is a caller bug — the engine chunks BEFORE padding."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError(f"batch of {n} rows exceeds bucket {bucket} — "
                         f"chunk before padding")
    pad = np.zeros((bucket - n,) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


class ServeResult:
    """One request's future: ``wait()`` blocks until the batcher scatters
    the logits back; latency is stamped submit → result-ready."""

    __slots__ = ("images", "n", "t_submit", "latency_s", "value", "error",
                 "_done")

    def __init__(self, images: np.ndarray):
        self.images = images
        self.n = int(images.shape[0])
        self.t_submit = time.time()
        self.latency_s: Optional[float] = None
        self.value: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()

    def _set(self, value=None, error=None) -> None:
        self.value = value
        self.error = error
        self.latency_s = time.time() - self.t_submit
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("serve request did not complete in time")
        if self.error is not None:
            raise self.error
        return self.value


class ContinuousBatcher:
    """The serving loop: queue → coalesce → one bucketed engine call →
    scatter. Single consumer thread (one device pipeline); thread-safe
    ``submit`` from any number of producers.

    Telemetry (optional): a ``serve_batch`` event per bucket program the
    engine executed (bucket, valid rows, call seconds, queue depth behind
    it) and a ``request`` event per completed request (latency) — the
    SAME stream the rank metrics endpoint derives its latency/queue/
    occupancy gauges from, so a scrape and the events file cannot
    disagree. A heartbeat (``Telemetry.beat``, self-throttled) keeps the
    launcher's fleet view tracking serving replicas' liveness without
    train steps.
    """

    def __init__(self, engine, max_wait_s: float = 0.002, telemetry=None):
        self.engine = engine
        self.max_wait_s = max(0.0, float(max_wait_s))
        self.telemetry = telemetry
        self.n_requests = 0
        self.n_batches = 0
        self.n_errors = 0
        self._q: deque[ServeResult] = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._loop,
                                        name="tpudist-serve-batcher",
                                        daemon=True)
        self._thread.start()

    # -- producer side -----------------------------------------------------
    def submit(self, images: np.ndarray) -> ServeResult:
        """Enqueue one request (``(n, H, W, C)`` float32 rows); returns its
        future. Raises after ``close()`` — a drained batcher must not
        accept work it will never run."""
        req = ServeResult(np.asarray(images))
        with self._cv:
            if self._closed:
                raise RuntimeError("ContinuousBatcher is closed")
            self._q.append(req)
            self._cv.notify()
        return req

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    # -- consumer loop -----------------------------------------------------
    def _gather(self) -> tuple[list[ServeResult], int]:
        """Pull the next micro-batch: block for the first request, then
        coalesce more up to the largest bucket's rows, waiting at most
        ``max_wait_s`` for stragglers. Returns ``([], depth)`` at
        shutdown."""
        max_rows = self.engine.buckets[-1]
        with self._cv:
            while not self._q and not self._closed:
                self._cv.wait()
            if not self._q:
                return [], 0
            batch = [self._q.popleft()]
            rows = batch[0].n
            deadline = time.monotonic() + self.max_wait_s
            while rows < max_rows:
                if self._q:
                    if rows + self._q[0].n > max_rows:
                        break
                    nxt = self._q.popleft()
                    batch.append(nxt)
                    rows += nxt.n
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cv.wait(remaining)
            return batch, len(self._q)

    def _loop(self) -> None:
        tel = self.telemetry
        while True:
            batch, depth = self._gather()
            if not batch:
                return
            images = (batch[0].images if len(batch) == 1 else
                      np.concatenate([r.images for r in batch], axis=0))
            n_valid = int(images.shape[0])
            t0 = time.perf_counter()
            try:
                out = self.engine.infer(images)
                err = None
            except Exception as e:          # scatter the failure, keep serving
                out, err = None, e
            batch_s = time.perf_counter() - t0
            offset = 0
            for req in batch:
                if err is not None:
                    req._set(error=err)
                else:
                    req._set(value=out[offset:offset + req.n])
                offset += req.n
            self.n_requests += len(batch)
            info = self.engine.last_info if err is None else []
            # One serve_batch event per BUCKET CALL the engine made: a
            # single oversize request chunks into several bucket programs,
            # and reporting the total rows against the first chunk's
            # bucket would fabricate occupancy > 1 (the padding-waste
            # gauge must stay a true ratio per executed program).
            self.n_batches += max(1, len(info)) if err is None else 0
            if err is not None:
                self.n_errors += len(batch)
            if tel is not None:
                if err is None:
                    # Serving compute IS this plane's productive time: the
                    # run_end goodput then reads as serving seconds / wall,
                    # with the AOT compile attributed to its bucket.
                    tel.productive_s += batch_s
                    for j, call in enumerate(info):
                        tel.emit("serve_batch", bucket=call["bucket"],
                                 n_valid=call["n_valid"],
                                 batch_s=round(call["seconds"], 6),
                                 queue_depth=depth,
                                 **({"n_requests": len(batch)} if j == 0
                                    else {}))
                # Failed requests emit too (error=1): a replica scattering
                # errors must show its failing traffic in the stream, not
                # go dark exactly when the operator needs evidence.
                for req in batch:
                    tel.emit("request", latency_s=round(req.latency_s, 6),
                             n_images=req.n,
                             **({"error": 1} if err is not None else {}))
                # beat() self-throttles (heartbeat_interval_s), so every
                # loop pass may offer one — INCLUDING error passes: a
                # live-but-erroring replica is not a hung one, and a
                # frozen heartbeat would trip the launcher's staleness
                # watchdogs on a process that is still making decisions.
                tel.beat(self.n_batches)

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting work, drain what is queued, join the loop."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout)
        if self.telemetry is not None:
            self.telemetry.beat(self.n_batches)


def open_loop_load(batcher: ContinuousBatcher, rate_hz: float,
                   duration_s: float,
                   make_images: Callable[[np.random.Generator], np.ndarray],
                   seed: int = 0,
                   wait_timeout_s: float = 120.0) -> list[ServeResult]:
    """Synthetic OPEN-LOOP traffic: Poisson arrivals at ``rate_hz`` for
    ``duration_s``, submission times scheduled independently of
    completions (a closed loop would throttle offered load at saturation
    and hide the latency knee — the whole point of the curve). Returns
    every request's completed future (latencies stamped). Engine errors
    do NOT propagate out of the load run: a failed request completes with
    its ``.error`` set — callers inspect it — so one bad batch cannot
    abort the harness before telemetry/summary shutdown. Only a request
    that never completes at all raises (TimeoutError)."""
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    rng = np.random.default_rng(seed)
    results: list[ServeResult] = []
    t0 = time.monotonic()
    t_next = t0
    while t_next - t0 < duration_s:
        now = time.monotonic()
        if now < t_next:
            time.sleep(t_next - now)
        results.append(batcher.submit(make_images(rng)))
        t_next += rng.exponential(1.0 / rate_hz)
    for r in results:
        if not r._done.wait(wait_timeout_s):
            raise TimeoutError("serve request did not complete in time")
    return results
