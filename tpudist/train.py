"""Compiled SPMD train/eval steps (reference L2+L3: the DDP wrapper + hot loop).

The reference's per-batch hot loop (``distributed.py:237-273``) is:
H2D copy → forward → CE loss → accuracy → barrier + 2 metric allreduces +
blocking ``.item()`` → zero_grad/backward/step, with gradient allreduce done by
DDP's C++ bucketed reducer inside ``backward()``.

Here the WHOLE of that is one XLA program per step, built with ``shard_map``
over the mesh's data axis:

- forward/backward run per-shard on the local batch (DDP's per-GPU compute);
- ``lax.pmean(grads)`` is the gradient allreduce — XLA schedules it on ICI and
  overlaps it with remaining backward compute (what DDP's bucketing does by
  hand in C++, ``SURVEY.md §2.3``);
- loss/accuracy are pmean-ed *inside* the program (the reference's
  ``reduce_mean`` + barrier + ``.item()`` per step, ``distributed.py:253-257``
  — here it costs one fused collective and no host sync);
- SGD(momentum, weight_decay) and MultiStepLR reproduce torch semantics
  exactly (see ``sgd_torch`` and ``lr_for_epoch``) because the 46.83% top-1
  target (BASELINE.md) depends on them.

Mixed precision (reference autocast+GradScaler,
``distributed_syncBN_amp.py:259,275-278``): params stay fp32 (master weights),
activations/matmuls run in bf16 via the model's ``dtype``. bf16 keeps fp32's
exponent range, so no GradScaler is needed; for fp16 parity a dynamic loss
scale is supported via ``amp_dtype='float16'``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

from tpudist import _jaxshim  # noqa: F401  (jax<0.8 surface backfill)
import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.training import dynamic_scale as dynamic_scale_lib
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from tpudist.config import Config
from tpudist.ops import accuracy, cross_entropy_loss


class TrainState(struct.PyTreeNode):
    """Replicated training state: params (fp32 master), BN running stats,
    SGD momentum buffers, step counter, optional fp16 loss scale, optional
    EMA copy (``--model-ema-decay``; val and best-checkpoint selection use
    it when present). ``ema_params`` is ``{"params": ..., "batch_stats":
    ...}`` — torchvision's ExponentialMovingAverage averages BUFFERS too
    (use_buffers=True): evaluating EMA weights against live BN stats is a
    weight/statistics mismatch that tanks early-run val accuracy."""
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    dynamic_scale: dynamic_scale_lib.DynamicScale | None = struct.field(default=None)
    ema_params: Any = None
    # Gradient-communication state (``--compress-grads``): the per-rank
    # error-feedback residual, ``{"residual": (world, n) f32}`` sharded over
    # the data axis (``parallel/comm.py``). None when compression is off —
    # restore drops/seeds it exactly like ``ema_params`` cross-compat.
    comm_state: Any = None


def sgd_torch(lr_placeholder: float, momentum: float, weight_decay: float) -> optax.GradientTransformation:
    """torch.optim.SGD semantics (reference ``distributed.py:148-149``):
    ``g = g + wd*p``; ``v = mu*v + g``; ``p -= lr*v`` — weight decay folded
    into the gradient BEFORE momentum (not decoupled), applied to ALL params
    including BN scale/bias, exactly as ``model.parameters()`` does. The lr is
    injected per-step via ``optax.inject_hyperparams`` so epoch-boundary decay
    does not retrigger compilation."""
    def make(learning_rate):
        return optax.chain(
            optax.add_decayed_weights(weight_decay),
            optax.trace(decay=momentum, nesterov=False),
            optax.scale_by_learning_rate(learning_rate),
        )
    return optax.inject_hyperparams(make)(learning_rate=lr_placeholder)


def adamw_torch(lr_placeholder: float, weight_decay: float,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                mask: Any = None) -> optax.GradientTransformation:
    """torch.optim.AdamW semantics: bias-corrected moments, eps OUTSIDE the
    sqrt (optax ``eps_root=0``), and DECOUPLED weight decay applied after the
    adam scaling, i.e. ``p -= lr*(m̂/(√v̂+eps) + wd*p)`` — torch defaults
    b1=0.9 b2=0.999 eps=1e-8. ``mask=None`` decays every param exactly like a
    single torch param group; pass a mask for recipe-style param groups. The
    lr is injected per-step like sgd_torch."""
    def make(learning_rate):
        return optax.chain(
            optax.scale_by_adam(b1=b1, b2=b2, eps=eps, eps_root=0.0),
            optax.add_decayed_weights(weight_decay, mask=mask),
            optax.scale_by_learning_rate(learning_rate),
        )
    return optax.inject_hyperparams(make)(learning_rate=lr_placeholder)


def no_decay_mask(params: Any) -> Any:
    """Recipe-style AdamW param groups (ViT/Swin/ConvNeXt training recipes):
    decay matrices/convs only — biases, LN/BN scales, convnext layer_scale
    (all ndim<2), swin's relative-position bias tables, and swin v2's
    logit_scale + continuous-position-bias MLP are excluded, as the
    published recipes' torch param groups do."""
    def keep(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        in_cpb = any("cpb_mlp" in (p.key if hasattr(p, "key") else str(p))
                     for p in path)
        return (getattr(leaf, "ndim", 0) >= 2
                and name not in ("relative_position_bias_table",
                                 "logit_scale")
                and not in_cpb)
    return jax.tree_util.tree_map_with_path(keep, params)


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    """The trainer's optimizer as a config state: 'sgd' is the reference's
    recipe (``distributed.py:148-149``, uniform decay like
    ``model.parameters()``); 'adamw' serves the transformer-era zoo
    (vit/swin/convnext), with the standard no-decay mask standing in for
    those recipes' param groups."""
    if cfg.optimizer == "sgd":
        return sgd_torch(cfg.lr, cfg.momentum, cfg.weight_decay)
    if cfg.optimizer == "adamw":
        return adamw_torch(cfg.lr, cfg.weight_decay, mask=no_decay_mask)
    raise ValueError(f"unsupported optimizer '{cfg.optimizer}' (sgd|adamw)")


def lr_for_epoch(cfg: Config, epoch: int) -> float:
    """MultiStepLR with the reference's step-at-epoch-START ordering
    (``distributed.py:192`` calls ``scheduler.step(epoch)`` before training):
    lr(e) = lr0 * gamma^(#milestones <= e). Milestones default [3,4]
    (``distributed.py:52``). 'cosine' is an additive extra."""
    warm = getattr(cfg, "warmup_epochs", 0)
    # Linear warmup (transformer recipes) MULTIPLIES the scheduled lr, so a
    # steplr milestone inside the warmup window still takes effect (no spike
    # + cliff at the handoff); cosine runs on the post-warmup timeline.
    ramp = (epoch + 1) / warm if (warm and epoch < warm) else 1.0
    if cfg.lr_scheduler == "steplr":
        factor = cfg.gamma ** sum(1 for m in cfg.step if epoch >= m)
        return cfg.lr * factor * ramp
    if cfg.lr_scheduler == "cosine":
        import math
        t = max(epoch - warm, 0) / max(cfg.epochs - warm, 1)
        return 0.5 * cfg.lr * (1 + math.cos(math.pi * t)) * ramp
    raise AssertionError(f"unsupported lr scheduler: {cfg.lr_scheduler}")  # distributed.py:153-154


def compute_dtype(cfg: Config):
    if not cfg.use_amp:
        return jnp.float32
    return jnp.bfloat16 if cfg.amp_dtype == "bfloat16" else jnp.float16


def create_train_state(rng: jax.Array, model: nn.Module, cfg: Config,
                       input_shape: Sequence[int] | None = None) -> TrainState:
    """Init params/BN stats (DDP's rank0-broadcast init is implicit: the same
    seed produces identical params everywhere; under pjit they are one
    replicated global array)."""
    shape = tuple(input_shape or (1, cfg.image_size, cfg.image_size, 3))
    variables = model.init(rng, jnp.ones(shape, jnp.float32), train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = make_optimizer(cfg)
    opt_state = tx.init(params)
    ds = (dynamic_scale_lib.DynamicScale()
          if cfg.use_amp and cfg.amp_dtype == "float16" else None)
    ema = (jax.tree_util.tree_map(jnp.copy, {"params": params,
                                             "batch_stats": batch_stats})
           if getattr(cfg, "model_ema_decay", 0.0) > 0.0 else None)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      batch_stats=batch_stats, opt_state=opt_state,
                      dynamic_scale=ds, ema_params=ema)


def update_ema(cfg: Config, ema: Any, new_params: Any,
               new_stats: Any) -> Any:
    """torchvision-style model EMA over params AND BN buffers
    (ExponentialMovingAverage(use_buffers=True)): e = d*e + (1-d)*x after
    each optimizer step (no-op when EMA is off). Shared by the DP and GSPMD
    train steps."""
    if ema is None:
        return None
    d = cfg.model_ema_decay
    return jax.tree_util.tree_map(
        lambda e, x: d * e + (1.0 - d) * x, ema,
        {"params": new_params, "batch_stats": new_stats})


def _loss_fn(model: nn.Module, rng, params, batch_stats, images, labels,
             smoothing: float = 0.0, labels2=None, lam=None):
    # named_scope labels the HLO so --profile captures group the forward's
    # device ops under "tpudist_forward" in XProf (metadata only: the
    # compiled program's FLOPs/memory are unchanged — test_compiled_cost
    # pins that).
    with jax.named_scope("tpudist_forward"):
        outputs, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            images, train=True, mutable=["batch_stats", "intermediates"],
            rngs={"dropout": rng})
    from tpudist.ops.mixup import mixed_ce
    loss = mixed_ce(outputs, labels, labels2, lam, smoothing)
    # Aux classifier heads (googlenet 0.3, inception_v3 0.4): their logits are
    # sown to 'intermediates' during training; weight them into the loss so
    # the aux params actually receive gradient (torchvision's train recipe —
    # without this they'd only be decayed noise, ADVICE r1 #2).
    aux_w = getattr(model, "aux_loss_weight", 0.0)
    if aux_w:
        for aux_logits in jax.tree_util.tree_leaves(
                mutated.get("intermediates", {})):
            loss = loss + aux_w * mixed_ce(aux_logits, labels, labels2,
                                           lam, smoothing)
    return loss, (outputs, mutated.get("batch_stats", {}))


def global_grad_norm(grads) -> jax.Array:
    """Global L2 norm over a gradient pytree — the doctor sentinel's second
    signal (a diverging run's grad norm explodes steps before the loss
    does; a non-finite one means the backward already blew up). Cheap: one
    fused reduction over buffers the step already holds."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree_util.tree_leaves(grads)]
    total = leaves[0]
    for x in leaves[1:]:
        total = total + x
    return jnp.sqrt(total)


def make_train_step(mesh: Mesh, model: nn.Module, cfg: Config,
                    data_axis: str = "data",
                    compress: str | None = None,
                    guard: bool = False) -> Callable:
    """Build the jitted SPMD train step: (state, images, labels, lr) →
    (state, metrics). ``images`` NHWC float32/uint8-normalized, sharded on the
    batch dim; state replicated; metrics are global means (already
    ``reduce_mean``-ed, reference ``distributed.py:254-255``).

    ``compress`` (resolved by the Trainer through ``ops/comm_dispatch`` —
    never raw config) swaps THE single gradient-reduction choke point:
    ``None`` keeps the dense ``lax.pmean`` bit-for-bit (same HLO as before
    the knob existed); ``"int8"`` runs the quantized two-phase exchange
    with the error-feedback residual carried in ``state.comm_state``
    (``parallel/comm.py``). Metric and BN-stat pmeans stay dense — they are
    bytes-trivial and their exactness is load-bearing.

    ``guard`` (``--doctor``, tpudist/doctor/): fuse the anomaly sentinels
    into the compiled step. The step additionally computes the global
    gradient L2 norm and a finiteness flag over (loss, grad norm); when the
    flag trips, the ENTIRE update is skipped GradScaler-style (params,
    optimizer moments, BN stats, EMA and comm residual all keep their
    pre-step values — a NaN batch must not poison the weights OR the
    running statistics) while ``state.step`` still advances. The flag and
    the norm ride the metrics dict, i.e. the existing deferred async
    metric drain — the guard adds NO host sync to the hot loop; the
    host-side policy engine reads them one step late from the drain."""
    tx = make_optimizer(cfg)
    base_rng = jax.random.PRNGKey(cfg.seed if cfg.seed is not None else 0)

    accum = max(1, int(getattr(cfg, "accum_steps", 1)))
    mixing = (getattr(cfg, "mixup_alpha", 0.0) > 0.0
              or getattr(cfg, "cutmix_alpha", 0.0) > 0.0)
    if compress not in (None, "int8"):
        raise ValueError(f"compress must be None or 'int8', got {compress!r}")
    if compress and cfg.use_amp and cfg.amp_dtype == "float16":
        # The fp16 GradScaler path reduces inside flax's DynamicScale
        # grad_fn, where there is no choke point to swap (config.finalize
        # rejects this combination loudly; this guards library callers).
        raise ValueError("--compress-grads does not compose with float16 "
                         "dynamic loss scaling; use bfloat16")

    def reduce_grads(grads, comm_state):
        """THE gradient-reduction choke point (DDP's C++ bucketed
        allreduce): dense pmean, or the compressed twin threading the
        error-feedback residual."""
        if compress is None:
            return jax.lax.pmean(grads, axis_name=data_axis), comm_state
        from tpudist.parallel.comm import compressed_pmean
        red, e_new = compressed_pmean(grads, comm_state["residual"][0],
                                      data_axis)
        return red, {"residual": e_new[None]}

    def step(state: TrainState, images, labels, lr):
        # Per-step, per-shard dropout key (torch: each DDP rank has its own
        # CPU/CUDA RNG stream; here it's derived, so runs are reproducible).
        rng = jax.random.fold_in(jax.random.fold_in(base_rng, state.step),
                                 jax.lax.axis_index(data_axis))
        labels2, lam = None, None
        if mixing:
            from tpudist.ops.mixup import mix_batch
            k_mix, rng = jax.random.split(rng)
            images, labels, labels2, lam = mix_batch(
                k_mix, images, labels, cfg.mixup_alpha, cfg.cutmix_alpha)

        if accum > 1:
            # Gradient accumulation: scan over microbatches so a global batch
            # far beyond one chip's activation memory (e.g. the reference's
            # 1200, distributed.py:52) still takes ONE optimizer step —
            # the shared accum_scan (parallel/_common.py) implements the
            # torch semantics (grads/metrics average, BN stats sequential);
            # one mixing draw per OPTIMIZER step, pair labels ride the scan.
            # fp16: GradScaler-with-accumulation ordering (torch.amp —
            # scale each microbatch's backward, ONE unscale/check/step):
            # the step's scale is FIXED across the scan, the finite check
            # and scale adjustment run once on the averaged grads below.
            from tpudist.parallel._common import (accum_scan, ds_finite,
                                                  ds_update,
                                                  scaled_value_and_grad)
            ds0 = state.dynamic_scale

            def per_mb(rng_i, stats, im_i, lb_i, *lb2_i):
                lf_i = partial(
                    _loss_fn, model, rng_i, smoothing=cfg.label_smoothing,
                    labels2=lb2_i[0] if lb2_i else None, lam=lam)
                if ds0 is not None:
                    loss_i, (outputs, stats), grads_i = scaled_value_and_grad(
                        lf_i, ds0.scale, state.params, stats, im_i, lb_i)
                else:
                    (loss_i, (outputs, stats)), grads_i = jax.value_and_grad(
                        lf_i, has_aux=True)(state.params, stats, im_i, lb_i)
                return grads_i, stats, (loss_i,
                                        accuracy(outputs, lb_i, topk=1))

            batch = (images, labels) + ((labels2,) if labels2 is not None
                                        else ())
            grads, new_stats, (loss, acc1) = accum_scan(
                per_mb, batch, state.batch_stats, rng, accum)
            grads, new_comm = reduce_grads(grads, state.comm_state)
            if ds0 is not None:
                # Post-pmean: the flag (and so the skip/scale decision) is
                # identical on every replica by construction.
                is_finite = ds_finite(grads)
                ds = ds_update(ds0, is_finite)
            else:
                ds, is_finite = None, None
        else:
            lf = partial(_loss_fn, model, rng, smoothing=cfg.label_smoothing,
                         labels2=labels2, lam=lam)
            if state.dynamic_scale is not None:
                # fp16 GradScaler parity (distributed_syncBN_amp.py:275-278):
                # scale → backward → unscale/check-finite → conditional step.
                grad_fn = state.dynamic_scale.value_and_grad(
                    lf, has_aux=True, axis_name=data_axis)
                ds, is_finite, (loss, aux), grads = grad_fn(
                    state.params, state.batch_stats, images, labels)
                outputs, new_stats = aux
                new_comm = state.comm_state
            else:
                grad_fn = jax.value_and_grad(lf, has_aux=True)
                (loss, (outputs, new_stats)), grads = grad_fn(
                    state.params, state.batch_stats, images, labels)
                # DDP gradient allreduce (distributed.py:144 → C++ Reducer):
                grads, new_comm = reduce_grads(grads, state.comm_state)
                ds, is_finite = None, None
            acc1 = accuracy(outputs, labels, topk=1)

        # Shared tail: BN-stat sync, SGD update, overflow skip, metric means.
        # Sync BN running stats across replicas so the replicated state stays
        # consistent (torch DDP keeps per-GPU stats and checkpoints rank 0's;
        # averaging is strictly more faithful to the data).
        # (named_scope = trace label only; see _loss_fn.)
        with jax.named_scope("tpudist_optimizer"):
            new_stats = jax.lax.pmean(new_stats, axis_name=data_axis)

            tx_state = state.opt_state
            tx_state.hyperparams["learning_rate"] = lr
            updates, new_opt_state = tx.update(grads, tx_state, state.params)
            new_params = optax.apply_updates(state.params, updates)

        if ds is not None:
            # Skip the update when grads overflowed (GradScaler.step behavior).
            new_params = jax.tree_util.tree_map(
                partial(jnp.where, is_finite), new_params, state.params)
            new_opt_state = jax.tree_util.tree_map(
                partial(jnp.where, is_finite), new_opt_state, state.opt_state)

        # reduce_mean of loss/acc (distributed.py:78-82,254-255), fused in-program.
        metrics = {
            "loss": jax.lax.pmean(loss, axis_name=data_axis),
            "acc1": jax.lax.pmean(acc1, axis_name=data_axis),
        }
        if guard:
            # Doctor sentinels: global grad norm + finiteness of (mean loss,
            # grad norm). ``grads`` is post-reduction, so both signals are
            # identical on every replica by construction — the skip decision
            # can never diverge the gang. On a tripped flag the whole update
            # is zeroed (GradScaler-style): params, moments, BN stats, and
            # the error-feedback residual all keep their pre-step values.
            gnorm = global_grad_norm(grads)
            ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(gnorm)
            if ds is not None:
                # fp16 dynamic loss scaling: an overflow step is the
                # scaler's jurisdiction — it already skipped params/opt
                # and halved the scale (GradScaler semantics predate the
                # doctor; torch's scaler doesn't flag them either).
                # Counting scale-search overflows as doctor skips would
                # escalate a healthy warm-up into a spurious
                # persistent_nonfinite rollback. The sentinel only flags
                # anomalies the scaler calls finite — but the overflow is
                # still REPORTED (scaler_skip) so the host can tell a
                # bounded scale search from data that is NaN at any scale
                # (the doctor escalates those on a larger budget).
                ok = ok | jnp.logical_not(is_finite)
                metrics["scaler_skip"] = 1.0 - is_finite.astype(jnp.float32)
            new_params = jax.tree_util.tree_map(
                partial(jnp.where, ok), new_params, state.params)
            new_opt_state = jax.tree_util.tree_map(
                partial(jnp.where, ok), new_opt_state, state.opt_state)
            new_stats = jax.tree_util.tree_map(
                partial(jnp.where, ok), new_stats, state.batch_stats)
            if new_comm is not None:
                new_comm = jax.tree_util.tree_map(
                    partial(jnp.where, ok), new_comm, state.comm_state)
            metrics["notfinite"] = 1.0 - ok.astype(jnp.float32)
            metrics["gnorm"] = gnorm
        ema = update_ema(cfg, state.ema_params, new_params, new_stats)
        if guard and ema is not None:
            # A skipped step must not advance the EMA either (averaging the
            # unchanged params would still decay the average).
            ema = jax.tree_util.tree_map(
                partial(jnp.where, ok), ema, state.ema_params)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  batch_stats=new_stats, opt_state=new_opt_state,
                                  dynamic_scale=ds, ema_params=ema,
                                  comm_state=new_comm)
        return new_state, metrics

    from tpudist.parallel._common import donated_jit
    if compress is None:
        # Bit-compat with the pre-compression builder: same specs, same HLO.
        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(data_axis), P(data_axis), P()),
            out_specs=(P(), P()),
            check_vma=False)
        return donated_jit(sharded)

    # Compressed path: comm_state shards its (world, n) residual over the
    # data axis while everything else stays replicated — the spec tree
    # depends on the concrete state structure, so the wrapper is built
    # lazily on first call (parallel/_common.lazy_step: one wrapper = one
    # compile cache, with .lower forwarded for telemetry introspection).
    from tpudist.parallel._common import lazy_step

    def build(state):
        if state.comm_state is None:
            raise ValueError(
                "compress='int8' needs state.comm_state (the "
                "error-feedback residual) — seed it with "
                "parallel.comm.init_comm_state(params, world)")
        from tpudist.parallel.tensor_parallel import tree_specs
        specs = tree_specs(mesh, state, (), opt_shard_axis=data_axis,
                           zero_mode="comm")
        return donated_jit(shard_map(
            step, mesh=mesh,
            in_specs=(specs, P(data_axis), P(data_axis), P()),
            out_specs=(specs, P()),
            check_vma=False))

    return lazy_step(build)


def make_eval_step(mesh: Mesh, model: nn.Module, cfg: Config,
                   data_axis: str = "data",
                   state_specs: Any = None) -> Callable:
    """Jitted eval step (reference ``validate``, ``distributed.py:286-334``):
    forward with running BN stats, no_grad, global-mean loss/acc.

    ``state_specs``: optional full-structure PartitionSpec tree for the state
    (default: fully replicated). The expert-parallel path passes its split
    layout (expert FFN leaves sharded over the batch/expert axis)."""
    def step(state: TrainState, images, labels):
        with jax.named_scope("tpudist_eval_forward"):
            outputs = model.apply(
                {"params": state.params, "batch_stats": state.batch_stats},
                images, train=False)
        loss = cross_entropy_loss(outputs, labels)
        acc1 = accuracy(outputs, labels, topk=1)
        return {
            "loss": jax.lax.pmean(loss, axis_name=data_axis),
            "acc1": jax.lax.pmean(acc1, axis_name=data_axis),
        }

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P() if state_specs is None else state_specs,
                  P(data_axis), P(data_axis)),
        out_specs=P(),
        check_vma=False)
    return jax.jit(sharded)
