"""Distributed runtime: the reference's ``torch.distributed``/NCCL layer, TPU-native.

The reference (``distributed.py:123-125``) does::

    args.nprocs = torch.cuda.device_count()
    dist.init_process_group(backend='nccl')
    torch.cuda.set_device(local_rank)

and then synchronizes metrics with ``reduce_mean`` (clone → all_reduce(SUM) →
/nprocs, ``distributed.py:78-82``) behind a per-step ``dist.barrier()``
(``distributed.py:253``).

The TPU-native equivalents here:

- process bootstrap → ``jax.distributed.initialize`` (coordinator service over
  DCN replaces the TCPStore rendezvous of ``torch.distributed.launch``,
  ``start.sh:3``);
- device binding → automatic: each host owns its local chips; no
  ``set_device``;
- NCCL allreduce → XLA collectives (``lax.pmean``) compiled onto ICI/DCN and
  fused into the step program — ``reduce_mean`` below IS ``lax.pmean``;
- ``dist.barrier`` → unnecessary: SPMD programs execute in lockstep, the
  collective itself is the synchronization point. We expose ``barrier()`` for
  host-side coordination (e.g. "rank 0 writes the dir, others wait").
"""

from __future__ import annotations

import os
import time
from typing import Sequence

from tpudist import _jaxshim  # noqa: F401  (jax<0.8 surface backfill)
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_runtime(coordinator_address: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None,
                       timeout_s: float | None = None,
                       retries: int | None = None) -> None:
    """Multi-host bootstrap (replaces ``dist.init_process_group('nccl')``,
    ``distributed.py:124``). On a TPU pod each host calls this once; the
    coordinator address / topology come from args or the environment the
    launcher sets (``TPUDIST_COORDINATOR`` / ``TPUDIST_NUM_PROCESSES`` /
    ``TPUDIST_PROCESS_ID``, see ``launch/``).

    Failure hardening (the reference bug one layer down: a lost coordinator
    hung TCPStore rendezvous forever, SURVEY.md §5):

    - a DEADLINE bounds the coordinator connect + init barrier
      (``timeout_s``, default env ``TPUDIST_INIT_TIMEOUT`` or 300s) — a
      dead/unreachable coordinator raises instead of hanging;
    - BOUNDED retries with linear backoff (``retries``, default env
      ``TPUDIST_INIT_RETRIES`` or 0) cover the transient shape (coordinator
      restarting, DNS blip) without masking a dead cluster;
    - the ``init_hang`` fault point simulates a lost peer sleeping through
      rendezvous, so tests can drive deadline→abort→relaunch end-to-end.
    """
    from tpudist import faults
    kwargs = {}
    if coordinator_address or os.environ.get("TPUDIST_COORDINATOR"):
        kwargs["coordinator_address"] = coordinator_address or os.environ["TPUDIST_COORDINATOR"]
    if num_processes is None and os.environ.get("TPUDIST_NUM_PROCESSES"):
        num_processes = int(os.environ["TPUDIST_NUM_PROCESSES"])
    if process_id is None and os.environ.get("TPUDIST_PROCESS_ID"):
        process_id = int(os.environ["TPUDIST_PROCESS_ID"])
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if timeout_s is None:
        timeout_s = float(os.environ.get("TPUDIST_INIT_TIMEOUT", 300.0))
    if timeout_s > 0:
        # jax's own deadline on the connect + init barrier (it polls the
        # coordinator; an int is required).
        kwargs["initialization_timeout"] = max(1, int(timeout_s))
    if retries is None:
        retries = int(os.environ.get("TPUDIST_INIT_RETRIES", 0))

    import time as _time
    t_init0 = _time.monotonic()
    faults.maybe_init_hang()
    for attempt in range(retries + 1):
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            if attempt >= retries:
                raise RuntimeError(
                    f"distributed runtime init failed after "
                    f"{attempt + 1} attempt(s) "
                    f"(deadline {timeout_s:.0f}s per attempt, coordinator "
                    f"{kwargs.get('coordinator_address', '<auto>')}): {e}"
                ) from e
            # Linear backoff, bounded: transient coordinator churn heals in
            # seconds; anything longer is the launcher/restart layer's job.
            import sys
            import time
            wait = min(5.0 * (attempt + 1), 30.0)
            print(f"[tpudist.dist] init attempt {attempt + 1} failed ({e}); "
                  f"retrying in {wait:.0f}s "
                  f"({retries - attempt} retries left)",
                  file=sys.stderr, flush=True)
            time.sleep(wait)
        else:
            # Goodput accounting: runtime init happens before the Trainer
            # (and its Telemetry) exists, so stash the duration for the
            # telemetry layer to pick up. OUTSIDE the try: a broken
            # telemetry sink after a SUCCESSFUL init must not look like an
            # init failure and re-initialize an already-initialized runtime.
            try:
                from tpudist import telemetry
                telemetry.record_phase("init", _time.monotonic() - t_init0)
            except Exception:
                pass
            return


def process_index() -> int:
    """The rank-0 gate (reference ``local_rank == 0`` checks,
    ``distributed.py:117``): on TPU, the per-host process index."""
    return jax.process_index()


def data_rank_world() -> tuple[int, int]:
    """``(rank, world)`` for the DATA plane — what ``ShardedSampler`` shards
    over and what the elastic sample cursor counts in.

    With the jax.distributed runtime up this is just
    ``(process_index, process_count)``. Under the launcher's ELASTIC mode
    (``TPUDIST_ELASTIC=1``) without ``--distributed`` — the CPU gang
    simulation, where ranks are independent jit processes whose
    ``process_count`` is uniformly 1 — the launcher-assigned env identity
    supplies the data topology instead, so each rank loads its 1/W shard
    and the gang's sample accounting matches a real pod's. Env fallback is
    gated on TPUDIST_ELASTIC so non-elastic local sims keep their
    every-rank-sees-all-data behavior."""
    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    if os.environ.get("TPUDIST_ELASTIC") == "1":
        try:
            world = int(os.environ.get("TPUDIST_NUM_PROCESSES", "1"))
            rank = int(os.environ.get("TPUDIST_PROCESS_ID", "0"))
        except ValueError:
            return jax.process_index(), jax.process_count()
        if world > 1 and 0 <= rank < world:
            return rank, world
    return jax.process_index(), jax.process_count()


def replica_rank_world() -> tuple[int, int]:
    """``(rank, world)`` for the REPLICA plane — which processes hold
    nominally bit-identical (dp-replicated) state. This is what the
    doctor's cross-replica SDC probe compares over (tpudist/doctor/).

    With the jax.distributed runtime up, replicas ARE processes:
    ``(process_index, process_count)`` — same as the data plane. Under
    the launcher's CPU gang sims (independent jit ranks), the launcher
    env identity applies REGARDLESS of elastic mode — unlike
    ``data_rank_world``, which is gated on ``TPUDIST_ELASTIC``:

    - NON-elastic sim: every rank trains ALL the data from the same seed,
      so ranks really are bit-identical replicas — the honest CPU stand-in
      for a pod's replication invariant, and the mode the SDC-probe e2es
      run in (``env TPUDIST_ELASTIC=0`` under an elastic launcher).
    - ELASTIC sim: ranks train disjoint shards with no cross-process
      collectives, so their states legitimately differ and a probe reports
      unattributable divergence — probes there belong to real
      ``--distributed`` gangs (docs/DOCTOR.md).
    """
    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    try:
        world = int(os.environ.get("TPUDIST_NUM_PROCESSES", "1"))
        rank = int(os.environ.get("TPUDIST_PROCESS_ID", "0"))
    except ValueError:
        return jax.process_index(), jax.process_count()
    if world > 1 and 0 <= rank < world:
        return rank, world
    return jax.process_index(), jax.process_count()


def is_primary() -> bool:
    return jax.process_index() == 0


def device_count() -> int:
    """Reference ``torch.cuda.device_count()`` (``distributed.py:123``) but
    global: total chips across all hosts (SPMD spans the whole mesh)."""
    return jax.device_count()


def make_mesh(mesh_shape: Sequence[int] | None = None,
              axis_names: Sequence[str] = ("data",),
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the device mesh the trainer shards over.

    Default is a 1-D ``('data',)`` mesh over all devices — the reference only
    implements data parallelism (SURVEY.md §2.2) — but any shape/axes can be
    given (e.g. ``(4, 2), ('data', 'model')``) so TP/SP/PP axes slot in without
    reshaping the trainer.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if mesh_shape is None:
        mesh_shape = (devs.size,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(tuple(mesh_shape)), tuple(axis_names))


def batch_sharding(mesh: Mesh, data_axis: str = "data") -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis (the
    DistributedSampler equivalent at the array level, ``distributed.py:167``)."""
    return NamedSharding(mesh, P(data_axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated params — data-parallel training replicates the model,
    like DDP's init broadcast (``distributed.py:144``)."""
    return NamedSharding(mesh, P())


def reduce_mean(tensor: jax.Array, axis_name: str = "data") -> jax.Array:
    """Reference ``reduce_mean`` (``distributed.py:78-82``): allreduce(SUM)/nprocs.
    Inside a shard_map'd/pmapped step this is exactly ``lax.pmean``; XLA fuses
    it into the compiled program (no clone, no barrier, no host sync)."""
    return jax.lax.pmean(tensor, axis_name=axis_name)


def barrier(tag: str = "tpudist_barrier",
            timeout_s: float | None = None) -> None:
    """Host-side barrier (reference ``dist.barrier()``, ``distributed.py:253``).

    NOT needed in the hot loop — SPMD program order synchronizes devices — but
    useful for host-side filesystem coordination across processes ("rank 0
    writes the dir, others wait"). Single-process: no-op. Failures propagate —
    a barrier that silently doesn't synchronize is worse than a crash.

    A DEADLINE bounds the wait (``timeout_s``, default env
    ``TPUDIST_BARRIER_TIMEOUT`` or 600s; <=0 disables): a peer that died
    before reaching the barrier must surface as a raise this process's
    watchdog/launcher can act on, not an indefinite hang. The barrier runs
    on a worker thread so the deadline can fire while the collective is
    blocked; the abandoned thread is daemonic (the process is about to exit
    through the failure chain anyway).
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    if timeout_s is None:
        timeout_s = float(os.environ.get("TPUDIST_BARRIER_TIMEOUT", 600.0))
    if timeout_s <= 0:
        multihost_utils.sync_global_devices(tag)
        return
    import threading
    err: list[BaseException] = []

    def _sync():
        try:
            multihost_utils.sync_global_devices(tag)
        except BaseException as e:          # noqa: BLE001 — re-raised below
            err.append(e)

    t = threading.Thread(target=_sync, daemon=True,
                         name=f"tpudist-barrier-{tag}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise TimeoutError(
            f"host barrier '{tag}' did not complete within {timeout_s:.0f}s "
            f"— a peer likely died before reaching it; aborting so the "
            f"launcher can tear the job down")
    if err:
        raise err[0]


class DevicePrefetcher:
    """Double-buffered device prefetch: keep up to ``depth`` batches already
    placed on the mesh so batch N+1's host→device copy overlaps step N's
    device compute.

    The trainer's serial loop pays the loader wait AND the ``device_put``
    staging copy on the critical path of every step (the telemetry
    data/h2d buckets PR 5's attribution table names). ``jax.device_put`` is
    asynchronous — the copy engine runs it concurrently with compute — so
    all the host has to do is ISSUE it before blocking on the step. This
    wrapper does exactly that:

    - ``__next__`` pops the oldest device-resident batch; only an EMPTY
      queue blocks (loader slower than the chip), and that exposed wait is
      what the step event's data/h2d fields then show;
    - ``poke()`` — called by the trainer right after dispatching the step —
      tops the queue back up (loader pull + device_put issue) while the
      device is busy; its duration is recorded as ``hidden_s`` and reported
      as the step's ``prefetch_s`` telemetry field, NOT as data/h2d wait
      (overlap-aware phase accounting: summarize must not double-count
      transfer time that compute hid).

    ``last_local_bs`` is the HOST-LOCAL batch size of the batch ``__next__``
    just returned — after ``shard_host_batch`` the arrays are global, so
    the trainer's sample-cursor accounting cannot read it off the shapes
    on a multi-host gang.
    """

    def __init__(self, loader, mesh: Mesh, data_axis="data", depth: int = 2):
        self._it = iter(loader)
        self.mesh = mesh
        self.data_axis = data_axis
        self.depth = max(1, int(depth))
        self._q: list = []
        self._exhausted = False
        # Per-__next__ accounting. The trainer reads last_local_bs (sample
        # cursor) and books hidden time from poke()'s return value; the
        # wait/hidden fields are the diagnostic surface that pins the
        # exposed-vs-overlapped split (tests/test_telemetry.py).
        self.last_wait_s = 0.0     # exposed: blocked with an empty queue
        self.last_hidden_s = 0.0   # overlapped: spent inside poke()
        self.last_local_bs = 0
        self._pending_hidden = 0.0

    def _fill_one(self) -> float:
        """Pull one host batch and issue its device placement; returns the
        time spent (0.0 at source exhaustion)."""
        if self._exhausted:
            return 0.0
        t0 = time.perf_counter()
        try:
            batch = next(self._it)
        except StopIteration:
            self._exhausted = True
            return 0.0
        local_bs = int(batch[0].shape[0])
        with jax.profiler.TraceAnnotation("tpudist.prefetch"):
            dev = shard_host_batch(self.mesh, batch, self.data_axis)
        self._q.append((dev, local_bs))
        return time.perf_counter() - t0

    def poke(self) -> float:
        """Top the queue up to ``depth`` — the trainer calls this right
        after dispatching the step, so the loader pull + H2D issue overlap
        the in-flight device compute. Returns the time spent (also
        accumulated into the NEXT ``__next__``'s ``last_hidden_s``)."""
        spent = 0.0
        while len(self._q) < self.depth and not self._exhausted:
            spent += self._fill_one()
        self._pending_hidden += spent
        return spent

    def __iter__(self):
        return self

    def __next__(self):
        wait = 0.0
        while not self._q and not self._exhausted:
            wait += self._fill_one()     # exposed: the chip is waiting
        if not self._q:
            raise StopIteration
        dev, local_bs = self._q.pop(0)
        self.last_wait_s = wait
        self.last_hidden_s = self._pending_hidden
        self._pending_hidden = 0.0
        self.last_local_bs = local_bs
        return dev


def shard_host_batch(mesh: Mesh, batch, data_axis: str = "data"):
    """Place a host-local numpy batch onto the mesh, sharded along the batch dim.

    Single-host: a straight device_put with a batch sharding. Multi-host: each
    process provides its local shard and we assemble the global array
    (the DataLoader+DistributedSampler H2D path, ``distributed.py:242-243``).
    """
    sharding = batch_sharding(mesh, data_axis)
    # Label the copy so --profile traces attribute H2D time to this phase
    # (XProf/Perfetto show "tpudist.h2d" rows); no-op when no trace is live.
    with jax.profiler.TraceAnnotation("tpudist.h2d"):
        if jax.process_count() == 1:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), batch)
        from jax.experimental import multihost_utils
        return jax.tree_util.tree_map(
            lambda x: multihost_utils.host_local_array_to_global_array(
                x, mesh, P(data_axis)),
            batch)
