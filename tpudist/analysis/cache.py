"""Per-file result cache for tpudist-check — the CI-economics layer.

A full-tree analysis is pure: per-file findings are a function of (that
file's content, the whole-program context). The cache exploits exactly
that factorization:

- every entry is keyed by the file's content sha1;
- every entry is guarded by the run's **global digest** — a deterministic
  hash of all cross-module facts a per-file result can depend on (declared
  axes, telemetry schema + docs text, the callgraph's traced/performer/
  donated/wrapper/arity signatures, the sharding harvest). A change that
  alters any cross-module fact flips the digest and invalidates every
  entry; a change that doesn't (comments, line drift, local edits) leaves
  other files' cached findings valid;
- a fully-unchanged tree short-circuits before parsing anything: content
  hashes match, the cached findings ARE the run (the warm path the smoke
  test times).

Storage follows the dispatch-cache conventions (``tpudist/ops/dispatch.py``):
one JSON per analyzed root under ``TPUDIST_CHECK_CACHE`` or
``~/.cache/tpudist``, atomic tmp+rename writes, corrupt or version-skewed
files silently rebuilt, never an error path — a broken cache costs a cold
run, nothing else. Stdlib only, no jax import.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

ENV_CACHE_DIR = "TPUDIST_CHECK_CACHE"
CACHE_SCHEMA = 1


def default_cache_dir() -> str:
    env = os.environ.get(ENV_CACHE_DIR, "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "tpudist")


def cache_file(root: str, cache_dir: Optional[str] = None) -> str:
    tag = hashlib.sha1(os.path.abspath(root).encode()).hexdigest()[:12]
    return os.path.join(cache_dir or default_cache_dir(),
                        f"check.{tag}.json")


def content_sha(src: str) -> str:
    return hashlib.sha1(src.encode("utf-8", "surrogatepass")).hexdigest()


def load(root: str, cache_dir: Optional[str] = None,
         analysis_version: Optional[int] = None) -> Optional[dict]:
    """The cached run for this root, or None (absent / corrupt / schema or
    analyzer-version skew — all mean 'cold run', never an error)."""
    try:
        with open(cache_file(root, cache_dir), encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(obj, dict) or obj.get("schema") != CACHE_SCHEMA:
        return None
    if analysis_version is not None \
            and obj.get("analysis_version") != analysis_version:
        return None
    files = obj.get("files")
    if not isinstance(files, dict):
        return None
    # Entry-shape validation: a truncated or hand-mangled entry must mean
    # 'cold run', never an internal-error exit — the whole-file JSON parse
    # above doesn't guarantee per-entry shape.
    required = ("rule", "path", "line", "col", "message")
    for ent in files.values():
        if not isinstance(ent, dict) or not isinstance(ent.get("sha"), str) \
                or not isinstance(ent.get("findings"), list) \
                or not all(isinstance(d, dict)
                           and all(k in d for k in required)
                           for d in ent["findings"]):
            return None
    return obj


def save(root: str, data: dict, cache_dir: Optional[str] = None) -> bool:
    """Atomic write (tmp + rename), best-effort: a read-only cache dir
    degrades to always-cold, it never fails the gate."""
    path = cache_file(root, cache_dir)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def global_digest(parts: dict) -> str:
    """Deterministic digest of the whole-program context; ``parts`` must be
    JSON-serializable with stable ordering handled by the caller."""
    blob = json.dumps(parts, sort_keys=True, default=sorted)
    return hashlib.sha1(blob.encode()).hexdigest()
