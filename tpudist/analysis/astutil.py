"""Shared AST machinery for the tpudist-check rules.

The load-bearing piece is the *traced-reachability* index: which function
bodies can execute under a jax trace (``jit`` / ``shard_map`` /
``pallas_call`` / ``grad`` / control-flow combinators / flax ``__call__``
methods), resolved statically per module. The trace-purity and recompile
rules consume it; the other rules share the cheaper helpers (dotted-name
resolution, scope tests, literal extraction).

Everything here is conservative-by-construction and *intra-module*: a
function passed across module boundaries is not followed (the rules
document this; the fixture corpus in tests/test_check.py pins what is and
is not in reach). Over-approximation is acceptable — the pragma mechanism
exists — silent under-approximation of an invariant is not, so the edge
set errs toward inclusion (function-reference arguments of tracing and
control-flow calls count as edges, not just direct calls).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

# Wrappers whose function-typed argument(s) are traced by jax. ``vmap`` and
# ``grad`` trace exactly like ``jit`` for purity purposes (the Python body
# runs once with tracers); ``donated_jit`` is this repo's jit choke point.
TRACING_WRAPPERS = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "remat", "checkpoint",
    "donated_jit", "shard_map", "pallas_call", "custom_vjp", "custom_jvp",
    "eval_shape", "linearize", "vjp", "jvp", "hessian", "jacfwd", "jacrev",
}

# Control-flow / tree combinators: their callable arguments execute inside
# whatever trace the *call site* lives in.
CONTROL_FLOW = {
    "scan", "while_loop", "fori_loop", "cond", "switch", "map",
    "associative_scan", "tree_map", "tree_map_with_path",
}

# Host escape hatches: callables passed here run OUTSIDE the trace on the
# host — they are exempt from trace-purity by definition.
HOST_CALLBACKS = {"pure_callback", "io_callback", "callback", "debug_callback"}

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.expr) -> Optional[str]:
    d = dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing(node: ast.AST, parents: dict, kinds) -> Optional[ast.AST]:
    """Nearest ancestor of the given node kinds (node itself excluded)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def at_module_level(node: ast.AST, parents: dict) -> bool:
    """True when no function scope encloses ``node`` (class bodies and
    module-level ``if``/``try`` still count as module level — they execute
    at import time)."""
    return enclosing(node, parents, FUNC_NODES) is None


def under_type_checking(node: ast.AST, parents: dict) -> bool:
    """Inside an ``if TYPE_CHECKING:`` block (never executed at runtime)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If):
            try:
                if "TYPE_CHECKING" in ast.unparse(cur.test):
                    return True
            except Exception:
                pass
        cur = parents.get(cur)
    return False


def int_literals(node: ast.expr) -> Optional[tuple[int, ...]]:
    """``0`` / ``(0, 2)`` / ``[1]`` → tuple of ints; None when dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def str_literals(node: ast.expr) -> list[str]:
    """All string constants in ``node``'s subtree (axis-name harvesting)."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def donated_positions(call: ast.Call) -> Optional[tuple]:
    """Donated argnums/argnames for a jit-constructing call, else None:
    ``jax.jit(f, donate_argnums=…)`` / ``donate_argnames=…`` and this
    repo's ``donated_jit`` choke point (default ``(0,)``). Shared between
    the intra-module DONATE01 pass and the callgraph's donated-factory
    harvest so the two cannot drift on what counts as donation."""
    seg = last_segment(call.func)
    nums: list = []
    saw_donate = False
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            got = int_literals(kw.value)
            if got is None:
                return None          # dynamic spec — out of reach
            nums.extend(got)
            saw_donate = True
        elif kw.arg == "donate_argnames":
            names = str_literals(kw.value)
            if not names:
                return None
            nums.extend(names)
            saw_donate = True
    if seg == "donated_jit":
        return tuple(nums) if saw_donate else (0,)
    if seg in ("jit", "pmap") and saw_donate:
        return tuple(nums)
    return None


def return_tuple_info(fn) -> tuple[int, tuple, bool]:
    """(number of value-returning returns, sorted distinct literal-tuple
    lengths among them, every-return-is-a-literal-tuple). THE single copy
    of the return-shape fact: SHARD02's out_specs check consumes it, and
    the cache digest records it per function — one implementation, so the
    rule and the invalidation key cannot drift."""
    if isinstance(fn, ast.Lambda):
        rets = [fn.body]
    else:
        rets = [n.value for n in walk_scope(fn)
                if isinstance(n, ast.Return) and n.value is not None]
    lens = sorted({len(r.elts) for r in rets if isinstance(r, ast.Tuple)})
    all_tuples = bool(rets) and all(isinstance(r, ast.Tuple) for r in rets)
    return len(rets), tuple(lens), all_tuples


def has_exit(body: list, kinds: tuple) -> bool:
    """A direct statement of ``body`` is one of the given exit kinds
    (Return/Raise escape the function; Continue/Break only the loop)."""
    return any(isinstance(stmt, kinds) for stmt in body)


def walk_scope(fn_or_stmts) -> Iterator[ast.AST]:
    """Walk a function body — or an explicit statement list — WITHOUT
    descending into nested function/class definitions (those are separate
    scopes: separate reachability entries, separate rank-guard/donation
    state). THE single copy of this walk; every rule shares it so the
    skip-nested-scope rule cannot drift per rule."""
    if isinstance(fn_or_stmts, list):
        stack = list(fn_or_stmts)
    elif isinstance(fn_or_stmts, ast.Lambda):
        stack = [fn_or_stmts.body]
    else:
        stack = list(fn_or_stmts.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FUNC_NODES + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class TraceIndex:
    """Per-module index of function definitions and which of them are
    statically reachable from a jax trace."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.parents = parent_map(tree)
        # bare name -> [function nodes] (module, nested, and method defs all
        # indexed; over-approximate resolution is intentional)
        self.by_name: dict[str, list[ast.AST]] = {}
        self.functions: list[ast.AST] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.by_name.setdefault(node.name, []).append(node)
                self.functions.append(node)
            elif isinstance(node, ast.Lambda):
                self.functions.append(node)
        # local aliases: name = partial(f, ...) / name = f — the repo's
        # `lf = partial(_loss_fn, ...)` then value_and_grad(lf) pattern
        # would otherwise hide _loss_fn from the index.
        self.aliases: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                val = node.value
                resolved: list[ast.AST] = []
                if isinstance(val, ast.Call) \
                        and last_segment(val.func) == "partial" and val.args:
                    resolved = self.by_name.get(
                        last_segment(val.args[0]) or "", [])
                elif isinstance(val, ast.Name):
                    resolved = self.by_name.get(val.id, [])
                if resolved:
                    self.aliases.setdefault(tgt, []).extend(resolved)
        self.traced: set[ast.AST] = set()
        self._seed_roots()
        self._propagate()

    # -- root discovery ----------------------------------------------------
    def _callable_args(self, call: ast.Call) -> list[ast.expr]:
        """Positional args of ``call`` that may be the traced callable(s)."""
        name = last_segment(call.func)
        if name in ("cond", "switch"):
            return call.args[1:]          # pred/index first, branches after
        if name == "while_loop":
            return call.args[:2]          # cond_fun, body_fun
        if name == "fori_loop":
            return call.args[2:3]         # body
        return call.args[:1]

    def _resolve_funcs(self, node: ast.expr) -> list[ast.AST]:
        """Function nodes an expression may denote (Name / self.attr /
        lambda / partial(f, ...))."""
        if isinstance(node, ast.Lambda):
            return [node]
        if isinstance(node, ast.Call) and last_segment(node.func) == "partial":
            return self._resolve_funcs(node.args[0]) if node.args else []
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr              # self.foo / module.foo -> "foo"
        if not name:
            return []
        return self.by_name.get(name, []) + self.aliases.get(name, [])

    def _seed_roots(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                if last_segment(node.func) in TRACING_WRAPPERS:
                    for arg in self._callable_args(node):
                        self.traced.update(self._resolve_funcs(arg))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    tgt = dec.func if isinstance(dec, ast.Call) else dec
                    seg = last_segment(tgt)
                    if seg in TRACING_WRAPPERS:
                        self.traced.add(node)
                    elif seg == "partial" and isinstance(dec, ast.Call) \
                            and dec.args \
                            and last_segment(dec.args[0]) in TRACING_WRAPPERS:
                        self.traced.add(node)
                    elif seg == "compact":   # flax nn.compact forward body
                        self.traced.add(node)
            elif isinstance(node, ast.ClassDef):
                # flax modules: __call__/setup execute under model.init/apply
                # inside the jitted step — the dynamic dispatch a static call
                # graph cannot see, special-cased because model files are
                # where stray np.random/print hazards live.
                if any(last_segment(b) == "Module" for b in node.bases
                       if isinstance(b, (ast.Name, ast.Attribute))):
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)) \
                                and item.name in ("__call__", "setup"):
                            self.traced.add(item)

    # -- edge propagation --------------------------------------------------
    def _edges_from(self, fn: ast.AST) -> set[ast.AST]:
        out: set[ast.AST] = set()
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            seg = last_segment(node.func)
            if seg in HOST_CALLBACKS:
                continue                  # callee runs on the host
            # direct call of a known function (f(...) / self.f(...))
            out.update(self._resolve_funcs(node.func))
            # function-reference args of tracing / control-flow calls
            if seg in TRACING_WRAPPERS or seg in CONTROL_FLOW:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    out.update(self._resolve_funcs(arg))
        # nested defs lexically inside a traced body are part of its closure
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        for stmt in (body if isinstance(body, list) else [body]):
            for node in ast.walk(stmt):
                if isinstance(node, FUNC_NODES) and node is not fn:
                    nearest = enclosing(node, self.parents, FUNC_NODES)
                    if nearest is fn:
                        out.add(node)
        return out

    def _propagate(self) -> None:
        work = list(self.traced)
        while work:
            fn = work.pop()
            for callee in self._edges_from(fn):
                if callee not in self.traced:
                    self.traced.add(callee)
                    work.append(callee)

    def traced_functions(self) -> list[ast.AST]:
        return [f for f in self.functions if f in self.traced]
