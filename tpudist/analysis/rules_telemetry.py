"""TELEM01/02/03 — telemetry schema sync.

``telemetry.validate_event`` already rejects a schema-invalid event at
RUNTIME — but only on the code path that fires, so a drifted emit site in
an error handler or an elastic-only branch rots silently until the one run
that needed it. These rules move the check to lint time:

- TELEM01: ``*.emit("<type>", …)`` with a type absent from
  ``telemetry.SCHEMA``;
- TELEM02: an emit site whose literal keyword arguments are missing
  required fields for its type — only when the call has no ``**fields``
  splat (a splat makes the field set dynamic; such sites stay covered by
  the runtime validator);
- TELEM03 (warning): a SCHEMA event type that never appears in
  docs/OBSERVABILITY.md — the signal matrix is the contract consumers
  read, and PR 3's review round found it drifting from the schema by hand.

The SCHEMA is read from the analyzed tree's own ``tpudist/telemetry.py``
via ``ast.literal_eval`` (no import, no jax): the checker always judges
emit sites against the exact schema revision in the same checkout.
"""

from __future__ import annotations

import ast
import os

from tpudist.analysis.core import Module, finding

_DOCS_REL = os.path.join("docs", "OBSERVABILITY.md")


def _schema_from_tree(tree: ast.AST):
    schema = None
    schema_lines: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(isinstance(t, ast.Name) and t.id == "SCHEMA"
                   for t in tgts) and node.value is not None:
                try:
                    schema = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    schema = None
                if isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant):
                            schema_lines[k.value] = k.lineno
    return schema, schema_lines


def collect(ctx: dict) -> None:
    schema = None
    schema_lines: dict[str, int] = {}
    tel_mod = None
    for mod in ctx["modules"]:
        if mod.relpath.endswith("tpudist/telemetry.py") \
                or mod.relpath == "telemetry.py":
            tel_mod = mod
            schema, schema_lines = _schema_from_tree(mod.tree)
            break
    if schema is None:
        # Explicit-path runs (fixtures, --paths) don't include telemetry.py
        # in the module set — the schema still comes from the analyzed
        # tree's checkout, read from disk.
        try:
            with open(os.path.join(ctx["root"], "tpudist", "telemetry.py"),
                      encoding="utf-8") as f:
                schema, schema_lines = _schema_from_tree(ast.parse(f.read()))
        except (OSError, SyntaxError, ValueError):
            schema = None
    ctx["telemetry_schema"] = schema if isinstance(schema, dict) else None
    ctx["telemetry_schema_lines"] = schema_lines
    ctx["telemetry_module"] = tel_mod
    docs_path = os.path.join(ctx["root"], _DOCS_REL)
    try:
        with open(docs_path, encoding="utf-8") as f:
            ctx["obs_docs_text"] = f.read()
    except OSError:
        ctx["obs_docs_text"] = None


def check(ctx: dict, mod: Module) -> list:
    schema = ctx.get("telemetry_schema")
    if schema is None:
        return []
    out = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit" and node.args):
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue                      # dynamic event type: runtime's job
        etype = first.value
        if etype not in schema:
            out.append(finding(
                mod, "TELEM01", node.lineno, node.col_offset,
                f"emit of unknown telemetry event type '{etype}' — not in "
                f"telemetry.SCHEMA (known: {sorted(schema)[:6]}…); this "
                f"raises ValueError the first time the code path fires"))
            continue
        has_splat = any(kw.arg is None for kw in node.keywords)
        if has_splat:
            continue                      # dynamic fields: runtime's job
        provided = {kw.arg for kw in node.keywords}
        missing = [f for f in schema[etype] if f not in provided]
        if missing:
            out.append(finding(
                mod, "TELEM02", node.lineno, node.col_offset,
                f"emit('{etype}') missing required schema fields "
                f"{missing} — validate_event raises the first time this "
                f"path fires"))
    # TELEM03: reported once, attached to the schema's own lines.
    if mod is ctx.get("telemetry_module") and ctx.get("obs_docs_text"):
        docs = ctx["obs_docs_text"]
        for etype in schema:
            if etype not in docs:
                line = ctx["telemetry_schema_lines"].get(etype, 1)
                out.append(finding(
                    mod, "TELEM03", line, 0,
                    f"schema event type '{etype}' is absent from "
                    f"docs/OBSERVABILITY.md — the signal matrix is the "
                    f"contract consumers read; document it (or drop the "
                    f"dead type)"))
    return out
