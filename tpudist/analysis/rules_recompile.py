"""RECOMP01/RECOMP02 — recompile hazards.

jax's compile cache is keyed on the *function object* plus the abstract
signature. Two hot-loop shapes defeat it:

- RECOMP01: ``jax.jit`` / ``pmap`` / ``donated_jit`` *constructed* inside
  a ``for``/``while`` body — every iteration makes a fresh wrapper with an
  empty cache, so every iteration pays a full trace+compile. Build the
  jitted callable once, outside the loop (or memoize it, as
  tensor_parallel's per-config step cache does).

- RECOMP02 (warning — heuristic): a call to a *known jitted callable*
  inside a loop where an argument is Python arithmetic over the loop
  variable or a ``.shape``/``len()``-derived value. Python scalars hash
  into the compile-cache key by VALUE: a fresh float per iteration (the
  classic hand-rolled lr schedule) or a data-dependent int recompiles the
  program every distinct value. ``len()`` is in the shape class since
  ISSUE 14: the SERVING request loop's canonical hazard is a jitted step
  keyed on ``len(batch)`` — every distinct request-batch size compiles a
  fresh program under live traffic, exactly what the bucket scheme
  exists to prevent. ``len()`` fires only when its operand VARIES per
  iteration (it names something bound inside the loop — the ``batch =
  queue.pop()`` pump shape, where loop-variable analysis alone sees
  nothing because a ``while True`` pump has no loop variable — or is
  itself a call producing a fresh value); ``len()`` of a loop-invariant
  collection is one compile, not a hazard, and stays clean.
  The repo's own conventions are the fixes this rule
  points at: lr rides ``optax.inject_hyperparams`` and crosses the jit
  boundary as a jnp array (trainer.py's ``lr_arr``); serving sizes
  quantize through ``tpudist.serve``'s bucket helpers. The rule stands
  down when the value visibly crosses as an array — a literal
  ``jnp.asarray``/``array`` call, or a repo-local helper (resolved
  through the call graph, one or more modules away) whose every return
  wraps in one (the known false-positive shape PR 7 documented, now
  downgraded) — or is quantized by a recognized bucket helper
  (``pick_bucket``/``pad_to_bucket``: the result takes at most
  ``len(buckets)`` distinct values, all AOT-compiled at startup).

"Known jitted callable" = assigned from jit/donated_jit/pmap in this
module, or from a ``make_*_step`` factory (the repo's naming convention
for compiled-step builders, which ``serve.export.make_infer_step``
follows — how ``self.train_step`` is recognized without cross-module
analysis).
"""

from __future__ import annotations

import ast
import re

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

_JIT_MAKERS = {"jit", "donated_jit", "pmap"}
_STEP_FACTORY = re.compile(r"^make_\w*step$")

# The serving plane's sanctioned shape quantizers (tpudist/serve/batching):
# a value that passed through one takes at most len(buckets) distinct
# values, every one of which the engine AOT-compiled at startup — the
# crossing is recompile-safe by construction, like an asarray wrap.
_BUCKET_QUANTIZERS = {"pick_bucket", "pad_to_bucket"}


def _known_jitted(tree: ast.Module, parents: dict) -> set[str]:
    """Dotted target names holding jitted callables in this module."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        seg = astutil.last_segment(node.func)
        if seg in _JIT_MAKERS or (seg and _STEP_FACTORY.match(seg)):
            parent = parents.get(node)
            if isinstance(parent, ast.Assign) and parent.value is node:
                for tgt in parent.targets:
                    d = astutil.dotted(tgt)
                    if d:
                        out.add(d)
    return out


def _loop_vars(loop: ast.stmt) -> set[str]:
    if isinstance(loop, ast.For):
        return {n.id for n in ast.walk(loop.target)
                if isinstance(n, ast.Name)}
    return set()


def _loop_bound(loop: ast.stmt) -> set[str]:
    """Names (re)bound inside the loop body — values that genuinely vary
    per iteration (the ``batch = queue.pop()`` pump shape). Nested
    function bodies are out of scope: their locals don't feed this
    loop's jitted calls."""
    names: set[str] = set()
    for node in astutil.walk_scope(
            list(loop.body) + list(getattr(loop, "orelse", []))):
        if isinstance(node, ast.Assign):
            tgts = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.For)):
            tgts = [node.target]
        elif isinstance(node, ast.NamedExpr):
            tgts = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            tgts = [i.optional_vars for i in node.items
                    if i.optional_vars is not None]
        else:
            continue
        for t in tgts:
            names |= {n.id for n in ast.walk(t) if isinstance(n, ast.Name)}
    return names


def _len_operand_varying(call: ast.Call, varying: set[str]) -> bool:
    """True when the ``len()`` operand can change between iterations: it
    references a name bound in the loop, or is itself a call producing a
    fresh value. ``len()`` of a loop-invariant collection hashes to ONE
    compile-cache key — flagging it would gate correct code."""
    for arg in call.args:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in varying:
                return True
            if isinstance(sub, ast.Call):
                return True
    return False


def _arg_hazard(arg: ast.expr, loop_vars: set[str],
                wraps_in_array=None,
                loop_bound: set[str] = frozenset()) -> str | None:
    """Why this argument recompiles per iteration, or None.
    ``wraps_in_array``: predicate for calls that resolve (via the call
    graph) to a repo-local helper whose returns all wrap in asarray/array
    — such a crossing is safe one level deep too."""
    has_arith = False
    uses_loop_var = False
    uses_shape = False
    uses_len = False
    for node in ast.walk(arg):
        if isinstance(node, ast.BinOp):
            has_arith = True
        elif isinstance(node, ast.Name) and node.id in loop_vars:
            uses_loop_var = True
        elif isinstance(node, ast.Attribute) and node.attr == "shape":
            uses_shape = True
        elif isinstance(node, ast.Call) \
                and astutil.last_segment(node.func) == "len":
            # len() of a runtime collection is a data-dependent Python int
            # — the serving request loop's hazard class (a jitted step
            # keyed on len(batch) compiles per distinct batch size). Only
            # a LOOP-VARYING operand is the hazard; a loop-invariant
            # collection's len() is one value, one compile.
            has_arith = True
            if _len_operand_varying(node, loop_vars | loop_bound):
                uses_len = True
        elif isinstance(node, ast.Call) \
                and astutil.last_segment(node.func) in ("int", "float"):
            has_arith = True
        elif isinstance(node, ast.Call) and astutil.last_segment(
                node.func) in ("asarray", "array", "float32", "int32"):
            return None                   # crosses the boundary as an array
        elif isinstance(node, ast.Call) and astutil.last_segment(
                node.func) in _BUCKET_QUANTIZERS:
            return None                   # bucket-quantized: bounded set of
            #                               values, all AOT-compiled
        elif isinstance(node, ast.Call) and wraps_in_array is not None \
                and wraps_in_array(node):
            return None                   # repo helper wraps it for us
    if uses_loop_var and has_arith:
        return ("Python arithmetic over the loop variable — a fresh scalar "
                "value every iteration, and scalars key the compile cache "
                "by value")
    if uses_shape and has_arith:
        return (".shape-derived Python arithmetic — shape changes recompile "
                "silently per distinct value")
    if uses_len:
        return ("keyed on len() of a loop-varying collection — a data-"
                "dependent Python int recompiles per distinct value (the "
                "serving-loop hazard: quantize it through the serve bucket "
                "helpers, or pass it as a jnp array)")
    return None


def check(ctx: dict, mod: Module) -> list:
    out: list = []
    parents = astutil.parent_map(mod.tree)
    jitted = _known_jitted(mod.tree, parents)
    cg = ctx.get("callgraph")
    symtab = ctx.get("symtab")
    wrappers = ctx.get("array_wrappers") or set()
    ms = symtab.module_for(mod) if symtab else None

    def wraps_in_array(call: ast.Call) -> bool:
        if cg is None or ms is None or not wrappers:
            return False
        cls_node = astutil.enclosing(call, parents, (ast.ClassDef,))
        fn = astutil.enclosing(call, parents, astutil.FUNC_NODES)
        targets = cg.resolve_invoked(
            ms, call,
            cls_node.name if isinstance(cls_node, ast.ClassDef) else None,
            fn)
        return bool(targets) and all(id(t.node) in wrappers for t in targets)

    for loop in ast.walk(mod.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        lvars = _loop_vars(loop)
        lbound = _loop_bound(loop)
        for node in astutil.walk_scope(
                list(loop.body) + list(getattr(loop, "orelse", []))):
            if isinstance(node, ast.Call):
                seg = astutil.last_segment(node.func)
                if seg in _JIT_MAKERS:
                    out.append(finding(
                        mod, "RECOMP01", node.lineno, node.col_offset,
                        f"'{seg}' constructed inside a loop — each "
                        f"iteration builds a fresh wrapper with an empty "
                        f"compile cache and pays a full trace+compile; "
                        f"hoist it out of the loop (or memoize per "
                        f"config, like tensor_parallel's step cache)"))
                callee = astutil.dotted(node.func)
                if callee in jitted:
                    for arg in list(node.args) + [kw.value
                                                  for kw in node.keywords]:
                        why = _arg_hazard(arg, lvars, wraps_in_array,
                                          loop_bound=lbound)
                        if why:
                            out.append(finding(
                                mod, "RECOMP02", node.lineno,
                                node.col_offset,
                                f"argument to jitted '{callee}' is {why} "
                                f"— pass it as a jnp array (trainer's "
                                f"lr_arr pattern) or mark it static"))
    return out
