"""tpudist-check — JAX/SPMD-aware static analysis for this repo's invariants.

Every hazard class this package checks was first caught *by hand* in a
review round (docs/STATIC_ANALYSIS.md names each rule's origin): host-side
effects leaking into traced code, rank-guarded collectives that deadlock a
gang, Pallas imports reachable on CPU-auto paths, telemetry emit sites
drifting from the schema, donated buffers read after donation (the
``TPUDIST_NO_DONATE`` seed bug), and recompile bombs in hot loops.
veScale's argument (arXiv:2509.07003) applies directly: SPMD consistency
should be checked by the *system*, not by reviewer vigilance — especially
before the MPMD-pipeline direction multiplies the number of rank-asymmetric
code paths.

Whole-program since ISSUE 10: a repo-wide symbol table (``symbols.py``)
and import-resolving call graph (``callgraph.py``) follow calls,
constants, and donated callables across module boundaries — cross-module
donation (DONATE01), transitively-collective rank-guarded calls (COLL03),
and the sharding/mesh consistency family (SHARD01-03 in
``rules_sharding.py``) all resolve tree-wide, with documented
conservative stops at dynamic dispatch. Per-file results cache under
``~/.cache/tpudist`` (``cache.py``) and ``--diff <ref>`` gates only
changed-line findings (the pre-commit surface).

Zero-dependency by design: pure stdlib ``ast`` — no jax import, so the
checker runs in CI images, pre-commit hooks, and the launcher's
no-jax-allowed supervisor environment alike.

Entry points: ``python -m tpudist.check`` / console script
``tpudist-check`` (tpudist/check.py). Library surface:

    from tpudist.analysis import run_check
    findings, stats = run_check(root)

Suppression is an inline pragma with a mandatory reason::

    x = host_clock()  # tpudist: ignore[TRACE01] — measured outside the jit

plus a committed baseline (``tools/check_baseline.json``) so the gate fails
only on *new* findings.
"""

from tpudist.analysis.core import (  # noqa: F401
    Finding, RULES, run_check, load_baseline, write_baseline, gate,
)
