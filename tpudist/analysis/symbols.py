"""Repo-wide symbol table for tpudist-check.

One ``ModuleSymbols`` per parsed file: the module's import map (local name
→ absolute dotted target, relative imports resolved against the file's own
package), top-level functions, classes with their methods, and module-level
constants. ``SymbolTable`` stitches them into a tree-wide namespace so a
dotted name used in one module (``make_train_step``, ``dist.barrier``,
``_regnet_mod._VARIANTS``) resolves to the *definition node* in another.

Resolution is exact-or-nothing: a name that cannot be traced through the
import graph resolves to nothing, and callers treat that as the documented
conservative stop (dynamic dispatch, external libraries). The one deliberate
over-approximation — bare-name matching for traced-reachability — stays in
``astutil.TraceIndex``; this table never guesses.

Stdlib only, no jax import (the analyzer-wide invariant).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from tpudist.analysis import astutil
from tpudist.analysis.core import Module

# Bound on chained resolution (import-of-import, alias-of-alias): a cycle or
# a pathological re-export chain terminates instead of recursing forever.
MAX_RESOLVE_DEPTH = 8


def module_dotted(relpath: str) -> str:
    """Dotted module name for a repo-relative path: ``tpudist/train.py`` →
    ``tpudist.train``; ``pkg/__init__.py`` → ``pkg``; root scripts keep
    their stem (``bench.py`` → ``bench``)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "_root_"


@dataclasses.dataclass
class FuncInfo:
    """One function definition anywhere in the tree (top-level, method,
    nested, lambda)."""
    module: str                  # dotted module name
    qual: str                    # "fn" / "Cls.fn" / "outer.<locals>.fn"
    node: ast.AST
    cls: Optional[str] = None    # enclosing class name for methods

    @property
    def label(self) -> str:
        return f"{self.module}.{self.qual}"


@dataclasses.dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    methods: dict                # method name -> ast function node
    bases: list                  # dotted base names as written


class ModuleSymbols:
    """Top-level namespace of one module."""

    def __init__(self, mod: Module, dotted: str):
        self.mod = mod
        self.dotted = dotted
        self.imports: dict[str, str] = {}       # local name -> absolute dotted
        self.functions: dict[str, ast.AST] = {}  # top-level def name -> node
        self.classes: dict[str, ClassInfo] = {}
        self.constants: dict[str, ast.expr] = {}  # module-level name -> value
        self._build()

    def _resolve_relative(self, node: ast.ImportFrom) -> str:
        """Absolute dotted module of an ImportFrom, resolving ``level``
        against this file's own package (same rule as rules_pallas)."""
        if not node.level:
            return node.module or ""
        pkg = self.dotted.split(".")
        # __init__ modules: dotted IS the package; plain modules: drop the
        # file's own segment first.
        if not self.mod.relpath.endswith("/__init__.py") \
                and self.mod.relpath != "__init__.py":
            pkg = pkg[:-1]
        if node.level > 1:
            pkg = pkg[:len(pkg) - (node.level - 1)]
        return ".".join(pkg + ([node.module] if node.module else []))

    def _build(self) -> None:
        for stmt in self.mod.tree.body:
            self._index_stmt(stmt)
        # Module-level `if`/`try` blocks execute at import time — index
        # their direct children too (TYPE_CHECKING imports included: for
        # *name resolution* they still tell us what a name means).
        for stmt in self.mod.tree.body:
            if isinstance(stmt, (ast.If, ast.Try)):
                for seq in (getattr(stmt, "body", []),
                            getattr(stmt, "orelse", []),
                            getattr(stmt, "finalbody", [])):
                    for s in seq:
                        self._index_stmt(s)

    def _index_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                if alias.asname:
                    self.imports[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    self.imports[root] = root
        elif isinstance(stmt, ast.ImportFrom):
            base = self._resolve_relative(stmt)
            for alias in stmt.names:
                if alias.name == "*":
                    continue                  # star imports: out of reach
                target = f"{base}.{alias.name}" if base else alias.name
                self.imports[alias.asname or alias.name] = target
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.functions[stmt.name] = stmt
        elif isinstance(stmt, ast.ClassDef):
            methods = {
                item.name: item for item in stmt.body
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))}
            bases = [d for d in (astutil.dotted(b) for b in stmt.bases) if d]
            self.classes[stmt.name] = ClassInfo(
                self.dotted, stmt.name, stmt, methods, bases)
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            self.constants[stmt.targets[0].id] = stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            self.constants[stmt.target.id] = stmt.value


class SymbolTable:
    """Tree-wide namespace over every parsed module."""

    def __init__(self, modules: list[Module]):
        self.mods: dict[str, ModuleSymbols] = {}
        self.by_relpath: dict[str, ModuleSymbols] = {}
        for m in modules:
            ms = ModuleSymbols(m, module_dotted(m.relpath))
            self.mods[ms.dotted] = ms
            self.by_relpath[m.relpath] = ms

    def module_for(self, mod: Module) -> Optional[ModuleSymbols]:
        return self.by_relpath.get(mod.relpath)

    # -- name resolution ---------------------------------------------------
    def resolve(self, ms: ModuleSymbols, name: str,
                depth: int = 0) -> list[tuple]:
        """Resolve a dotted name used inside ``ms`` to its definitions.
        Returns tagged targets: ``("func", FuncInfo)`` / ``("class",
        ClassInfo)`` / ``("const", (value_expr, owner ModuleSymbols))`` /
        ``("module", ModuleSymbols)``. Empty list = unresolved (the
        conservative stop)."""
        if depth > MAX_RESOLVE_DEPTH or not name:
            return []
        head, _, rest = name.partition(".")
        if head in ms.functions:
            if rest:
                return []
            node = ms.functions[head]
            return [("func", FuncInfo(ms.dotted, head, node))]
        if head in ms.classes:
            ci = ms.classes[head]
            if not rest:
                return [("class", ci)]
            if "." not in rest:
                return self.class_method(ci, rest, depth + 1)
            return []
        if head in ms.constants:
            if rest:
                return []
            expr = ms.constants[head]
            chased = self._chase_expr(ms, expr, depth + 1)
            return chased or [("const", (expr, ms))]
        if head in ms.imports:
            target = ms.imports[head] + (f".{rest}" if rest else "")
            return self.resolve_absolute(target, depth + 1)
        return []

    def resolve_absolute(self, dotted: str, depth: int = 0) -> list[tuple]:
        """Resolve an absolute dotted path: longest module prefix, then the
        remainder through that module's namespace."""
        if depth > MAX_RESOLVE_DEPTH:
            return []
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            prefix = ".".join(parts[:i])
            ms = self.mods.get(prefix)
            if ms is None:
                continue
            rest = ".".join(parts[i:])
            if not rest:
                return [("module", ms)]
            return self.resolve(ms, rest, depth + 1)
        return []                             # external (jax, stdlib, …)

    def class_method(self, ci: ClassInfo, meth: str,
                     depth: int = 0) -> list[tuple]:
        """Method lookup with repo-defined base classes followed."""
        if depth > MAX_RESOLVE_DEPTH:
            return []
        node = ci.methods.get(meth)
        if node is not None:
            return [("func", FuncInfo(ci.module, f"{ci.name}.{meth}",
                                      node, cls=ci.name))]
        owner = self.mods.get(ci.module)
        if owner is None:
            return []
        for base in ci.bases:
            for kind, tgt in self.resolve(owner, base, depth + 1):
                if kind == "class":
                    got = self.class_method(tgt, meth, depth + 1)
                    if got:
                        return got
        return []

    def resolve_funcs(self, ms: ModuleSymbols, name: str) -> list[FuncInfo]:
        out = []
        for kind, tgt in self.resolve(ms, name):
            if kind == "func":
                out.append(tgt)
            elif kind == "class":
                # Calling a class runs its __init__.
                out.extend(fi for k, fi in
                           self.class_method(tgt, "__init__") if k == "func")
        return out

    def _chase_expr(self, ms: ModuleSymbols, expr: ast.expr,
                    depth: int) -> list[tuple]:
        """Chase an alias-shaped constant value (``x = f`` / ``x = mod.f``)
        to its definition. ``partial(...)`` constants are deliberately NOT
        chased — the binding count would be lost, and an arity rule acting
        on the unbound signature would lie."""
        if depth > MAX_RESOLVE_DEPTH:
            return []
        if isinstance(expr, (ast.Name, ast.Attribute)):
            d = astutil.dotted(expr)
            if d:
                return self.resolve(ms, d, depth)
        return []

    # -- literal string resolution ------------------------------------------
    def str_values(self, ms: ModuleSymbols, expr: Optional[ast.expr],
                   local_env: Optional[dict] = None,
                   depth: int = 0) -> Optional[list[str]]:
        """The string value(s) an expression statically denotes, following
        straight-line local assignments (``local_env``), module constants,
        and cross-module constants. ``None`` = dynamic (caller must skip);
        a ``None`` literal inside a tuple contributes nothing (PartitionSpec
        entries). Dict literals yield their string KEYS (the ``_VARIANTS``
        registry shape)."""
        if expr is None or depth > MAX_RESOLVE_DEPTH:
            return None
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, str):
                return [expr.value]
            if expr.value is None:
                return []
            return None
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out: list[str] = []
            for e in expr.elts:
                got = self.str_values(ms, e, local_env, depth + 1)
                if got is None:
                    return None
                out.extend(got)
            return out
        if isinstance(expr, ast.Dict):
            out = []
            for k in expr.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append(k.value)
                else:
                    return None
            return out
        if isinstance(expr, ast.Name):
            if local_env is not None and expr.id in local_env:
                val = local_env[expr.id]
                if val is None:               # reassigned: poisoned
                    return None
                return self.str_values(ms, val, None, depth + 1)
            if expr.id in ms.constants:
                return self.str_values(ms, ms.constants[expr.id], None,
                                       depth + 1)
            if expr.id in ms.imports:
                return self._str_values_absolute(
                    ms.imports[expr.id], depth + 1)
            return None
        if isinstance(expr, ast.Attribute):
            d = astutil.dotted(expr)
            if d:
                for kind, tgt in self.resolve(ms, d, depth + 1):
                    if kind == "const":
                        value, owner = tgt
                        return self.str_values(owner, value, None, depth + 1)
            return None
        return None

    def _str_values_absolute(self, dotted: str,
                             depth: int) -> Optional[list[str]]:
        for kind, tgt in self.resolve_absolute(dotted, depth):
            if kind == "const":
                value, owner = tgt
                return self.str_values(owner, value, None, depth + 1)
        return None


def local_str_env(fn: ast.AST) -> dict[str, Optional[ast.expr]]:
    """Straight-line single-assignment map for one function scope: name →
    value expr when assigned exactly ONCE via a simple ``name = <expr>``;
    name → None (poisoned) when reassigned, augmented, a loop target, or a
    parameter. Feeds ``SymbolTable.str_values`` for axis-name propagation."""
    env: dict[str, Optional[ast.expr]] = {}

    def poison(target: ast.expr) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                env[n.id] = None

    if not isinstance(fn, ast.Lambda):
        a = fn.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs
                  + ([a.vararg] if a.vararg else [])
                  + ([a.kwarg] if a.kwarg else [])):
            env[p.arg] = None
    for node in astutil.walk_scope(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            env[name] = None if name in env else node.value
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            for tgt in getattr(node, "targets", None) \
                    or [getattr(node, "target", None)]:
                if tgt is not None:
                    poison(tgt)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            poison(node.target)
        elif isinstance(node, (ast.withitem,)) \
                and node.optional_vars is not None:
            poison(node.optional_vars)
        elif isinstance(node, ast.NamedExpr):
            poison(node.target)
    return env
