"""tpudist-check core: file walking, rule orchestration, pragma
suppression, fingerprinted baseline, and the gate contract.

Pipeline: parse every target file once → run each rule module's
``collect`` pass (repo-wide context: declared mesh axes, the telemetry
SCHEMA, docs text) → run each ``check`` pass → apply pragmas → diff the
surviving gating findings against the committed baseline.

Fingerprints are content-addressed (rule + relpath + normalized source
line + same-line occurrence index), NOT line-number-addressed, so an
unrelated edit above a baselined finding does not resurrect it.

Exit-code contract (tools/check_smoke.sh pins it):
  0 — no new gating findings
  1 — new gating findings (errors; warnings too under --strict)
  2 — usage / internal error
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Iterable, Optional

# -- rule catalog ------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str            # "error" | "warning"
    title: str
    origin: str              # which PR/review round hand-enforced this

RULES: dict[str, Rule] = {r.id: r for r in [
    Rule("TRACE01", "error",
         "host side effect inside traced code (time/np.random/.item()/"
         "device_get/print reachable from jit/shard_map/pallas_call)",
         "PR 2/3 review rounds: hot-loop clocks must stay outside the step"),
    Rule("TRACE02", "error",
         "closure/global mutation inside traced code (global/nonlocal "
         "rebinding under a trace executes once, at trace time)",
         "PR 5 dispatch layer: trace-safe lookup() discipline"),
    Rule("COLL01", "error",
         "collective under a rank-dependent conditional (asymmetric "
         "execution deadlocks the gang)",
         "PR 4 elastic reviews: orbax save under is_primary deadlocked"),
    Rule("COLL02", "error",
         "axis_name names no mesh/shard_map axis declared anywhere in the "
         "analyzed tree (typo'd axis fails only at trace time)",
         "PR 4/5: per-path axis plumbing (data/model/seq/pipe/expert)"),
    Rule("COLL03", "error",
         "rank-guarded call whose callee TRANSITIVELY performs a "
         "collective (the cross-module form of the orbax-save deadlock: "
         "the guard is in one module, the barrier in another)",
         "PR 4: the orbax deadlock was exactly this shape before the "
         "by-hand fix; PR 7 could only see it intra-module"),
    Rule("DONATE01", "error",
         "buffer read after being donated to a jitted call "
         "(donate_argnums aliases it away; the read sees garbage)",
         "seed bug: TPUDIST_NO_DONATE heap corruption, PR 1"),
    Rule("PALLAS01", "error",
         "module-level Pallas import outside tpudist/ops/pallas/ "
         "(CPU auto paths must never import Pallas — measurement honesty)",
         "PR 5/6: 'CPU auto never imports Pallas' dryrun invariant"),
    Rule("TELEM01", "error",
         "telemetry emit site uses an event type absent from "
         "telemetry.SCHEMA (would raise at runtime, caught at lint time)",
         "PR 2: schema-enforced event stream"),
    Rule("TELEM02", "error",
         "telemetry emit site missing required schema fields for its "
         "event type",
         "PR 2/3: emit-time validation moved to lint time"),
    Rule("TELEM03", "warning",
         "schema event type undocumented in docs/OBSERVABILITY.md's "
         "signal matrix",
         "PR 3: the matrix is the contract consumers read"),
    Rule("RECOMP01", "error",
         "jit/pmap constructed inside a loop (a fresh wrapper per "
         "iteration defeats the compile cache)",
         "PR 5: dispatch probes build jits once, outside loops"),
    Rule("RECOMP02", "warning",
         "loop-varying or shape-derived Python scalar passed to a jitted "
         "callable (every distinct value recompiles the program)",
         "PR 2 telemetry: lr injected via inject_hyperparams for this "
         "exact reason"),
    Rule("SHARD01", "error",
         "PartitionSpec names an axis no Mesh/make_mesh in the analyzed "
         "tree declares (the spec silently replicates — or dies at trace "
         "time — depending on the consumer)",
         "ROADMAP 1-2 prep: full weight-update sharding and MPMD pipeline "
         "stages re-cut specs far from their mesh"),
    Rule("SHARD02", "error",
         "shard_map in_specs/out_specs arity cannot match the wrapped "
         "function's signature (fails only when the step first traces)",
         "PR 4/5: five shard_map step builders, each hand-checked until "
         "now"),
    Rule("SHARD03", "error",
         "model family registered in models/__init__.py reaches a "
         "'model'-axis mesh with an EMPTY tensor-parallel rule table and "
         "no NO_TP_FAMILIES annotation (silent pure-DP)",
         "VERDICT r5 weak #3: RESNET_RULES = () ran pure DP with no "
         "signal; require_rules made it a runtime warn, this makes it "
         "structural"),
    Rule("SHARD04", "error",
         "reduce-scatter/all-gather axis inconsistency: one function "
         "pairs psum_scatter and all_gather over DIFFERENT literal mesh "
         "axes, or over different tensor dims (scatter_dimension vs "
         "axis=) — the weight-update-sharding round trip silently "
         "mis-tiles the state",
         "PR 11 ZeRO-full: the wus step's gather/scatter pair must agree "
         "on axis and dim, previously hand-checked"),
    Rule("SHARD05", "error",
         "rule-table/plane/mesh consistency: a tensor-parallel rule table "
         "names a spec axis the parallelism plane's AXIS_BINDING does not "
         "bind (or the binding names a mesh axis no Mesh declares), or a "
         "shard_map-wrapped pallas_call site's out_specs name an axis its "
         "in_specs never shard (a shard-local kernel cannot manufacture "
         "sharding)",
         "ISSUE 12 single-plane refactor: rule tables, the plane binding, "
         "and the kernel shard_map wrappers must agree end to end"),
    Rule("ELASTIC01", "error",
         "elastic/reshard.py host-side cut/merge contract: the module is "
         "numpy-only — no jax import (direct, or via a repo module that "
         "imports jax at module level) may be reachable from "
         "cut_state/merge_state (the jax-free launcher image plans "
         "reshards; the round-trip tests run deviceless)",
         "PR 4 wrote the contract as a docstring; ISSUE 13's mesh-aware "
         "cut/merge (dp×tp×zero) makes the import-a-parallel-helper "
         "refactor tempting enough to need a gate"),
    Rule("NUM01", "error",
         "per-step host sync in the training hot loop (float()/.item()/"
         "device_get/np.asarray/block_until_ready inside a loader-iterating "
         "loop, outside the deferred metric drain)",
         "ISSUE 15 doctor plane: the guard sentinels ride the async drain "
         "precisely so the hot loop never blocks on a device value — and "
         "guard code is one float(loss) away from reintroducing the "
         "reference's per-step sync (distributed.py:253-257)"),
    Rule("PRAGMA01", "warning",
         "suppression pragma without a reason (policy: every ignore "
         "carries a one-line why)",
         "this PR's suppression policy"),
    Rule("PRAGMA02", "warning",
         "suppression pragma that matched no finding (stale ignore — "
         "delete it or the rule regressed)",
         "this PR's suppression policy"),
]}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str                # repo-relative posix path
    line: int
    col: int
    message: str
    snippet: str = ""
    severity: str = ""       # filled from RULES when empty
    suppressed: bool = False
    suppress_reason: str = ""
    fingerprint: str = ""

    def __post_init__(self):
        if not self.severity:
            self.severity = RULES[self.rule].severity

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "fingerprint": self.fingerprint,
                "suppressed": self.suppressed,
                **({"suppress_reason": self.suppress_reason}
                   if self.suppressed else {})}

    def to_cache(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_cache(cls, d: dict) -> "Finding":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)
                      if f.name in d})


@dataclasses.dataclass
class Module:
    """One parsed target file."""
    path: str                # absolute
    relpath: str             # posix, relative to root
    tree: ast.Module
    src: str
    lines: list[str]


def finding(mod: "Module", rule: str, line: int, col: int,
            message: str) -> Finding:
    """Finding with the snippet filled from the module source (the snippet
    feeds the content-addressed fingerprint)."""
    snippet = mod.lines[line - 1].strip() if 0 < line <= len(mod.lines) else ""
    return Finding(rule, mod.relpath, line, col, message, snippet=snippet)


# -- file walking ------------------------------------------------------------

SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", ".claude", "runs",
             "output_ddp_test", ".tpudist", "node_modules", ".venv", "venv",
             ".eggs", "build", "dist"}


def _is_test_path(relpath: str) -> bool:
    parts = relpath.split("/")
    return "tests" in parts or parts[-1].startswith("test_")


def iter_target_files(root: str, include_tests: bool = False):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in SKIP_DIRS
                             and not d.startswith("output"))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if not include_tests and _is_test_path(rel):
                continue
            yield path, rel


def read_targets(root: str, paths: Optional[Iterable[str]] = None,
                 include_tests: bool = False
                 ) -> tuple[list[tuple[str, str, str]], list[str]]:
    """Read target sources without parsing: [(abspath, relpath, src)], plus
    the unreadable-path list. Split from parsing so the cache's fully-warm
    path can hash contents without paying ``ast.parse`` for the tree."""
    out, bad = [], []
    if paths is not None:
        pairs = [(os.path.abspath(p),
                  os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/"))
                 for p in paths]
    else:
        pairs = list(iter_target_files(root, include_tests))
    for path, rel in pairs:
        try:
            with open(path, encoding="utf-8") as f:
                out.append((path, rel, f.read()))
        except OSError as e:
            bad.append(f"{rel}: {e}")
    return out, bad


def parse_sources(sources: list[tuple[str, str, str]]
                  ) -> tuple[list[Module], list[str]]:
    mods, bad = [], []
    for path, rel, src in sources:
        try:
            tree = ast.parse(src, filename=path)
        except (SyntaxError, ValueError) as e:
            bad.append(f"{rel}: {e}")
            continue
        mods.append(Module(path=path, relpath=rel, tree=tree, src=src,
                           lines=src.splitlines()))
    return mods, bad


def parse_modules(root: str, paths: Optional[Iterable[str]] = None,
                  include_tests: bool = False) -> tuple[list[Module], list[str]]:
    """Parse target files; returns (modules, unparseable-path list).
    ``paths``: explicit file list (fixtures, --paths); else walk ``root``."""
    sources, bad_read = read_targets(root, paths, include_tests)
    mods, bad_parse = parse_sources(sources)
    return mods, bad_read + bad_parse


# -- pragma suppression ------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*tpudist:\s*ignore\[([A-Za-z0-9_*,\s-]+)\]"
    r"(?:\s*(?:[-—–:]|--)\s*(\S.*))?")


def _comment_lines(mod: Module) -> set[int]:
    """Line numbers carrying a real ``#`` comment token — tokenized, so a
    pragma EXAMPLE inside a docstring or string literal is never treated
    as live suppression."""
    import io
    import tokenize
    out: set[int] = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(mod.src).readline):
            if tok.type == tokenize.COMMENT:
                out.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Fall back to treating every line as comment-bearing (the file
        # parsed as AST, so this is a tokenizer corner case).
        return set(range(1, len(mod.lines) + 1))
    return out


def _parse_pragmas(mod: Module) -> list[dict]:
    """All pragmas in a file: line, rule set (or {'*'}), reason, and the
    line range they cover (their own line; a comment-only pragma line also
    covers the next line)."""
    out = []
    comments = _comment_lines(mod)
    for i, line in enumerate(mod.lines, start=1):
        if i not in comments:
            continue
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        covers = {i}
        if line.strip().startswith("#"):
            covers.add(i + 1)
        out.append({"line": i, "rules": rules,
                    "reason": (m.group(2) or "").strip(),
                    "covers": covers, "used": False})
    return out


def apply_pragmas(mods: list[Module], findings: list[Finding],
                  stale_check: bool = True) -> list[Finding]:
    """Mark suppressed findings; append PRAGMA01/PRAGMA02 findings.
    ``stale_check=False`` skips PRAGMA02 (a restricted --rules run cannot
    tell a stale pragma from one whose rule simply wasn't run)."""
    by_path = {m.relpath: m for m in mods}
    pragmas = {rel: _parse_pragmas(m) for rel, m in by_path.items()}
    for f in findings:
        for p in pragmas.get(f.path, []):
            if f.line in p["covers"] and \
                    ("*" in p["rules"] or f.rule in p["rules"]):
                f.suppressed = True
                f.suppress_reason = p["reason"]
                p["used"] = True
    extra = []
    for rel, plist in pragmas.items():
        for p in plist:
            snippet = by_path[rel].lines[p["line"] - 1].strip()
            if not p["reason"]:
                extra.append(Finding(
                    "PRAGMA01", rel, p["line"], 0,
                    f"suppression of {sorted(p['rules'])} has no reason — "
                    f"append '— <why>' to the pragma", snippet=snippet))
            if stale_check and not p["used"]:
                extra.append(Finding(
                    "PRAGMA02", rel, p["line"], 0,
                    f"pragma suppresses {sorted(p['rules'])} but no such "
                    f"finding fires here — stale ignore (delete it) or the "
                    f"rule regressed", snippet=snippet))
    return findings + extra


# -- fingerprints + baseline -------------------------------------------------

def assign_fingerprints(findings: list[Finding]) -> None:
    """Content-addressed identity, stable across line drift. Same-content
    duplicates within a file disambiguate by in-file order."""
    seen: dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        norm = " ".join(f.snippet.split())
        key = f"{f.rule}|{f.path}|{norm}"
        n = seen.get(key, 0)
        seen[key] = n + 1
        f.fingerprint = hashlib.sha1(
            f"{key}|{n}".encode()).hexdigest()[:16]


def load_baseline(path: str) -> set[str]:
    """Fingerprint set from a baseline file; empty set when absent (an
    absent baseline gates everything — the honest default)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return set()
    return {e.get("fingerprint", "") for e in data.get("entries", [])}


def write_baseline(path: str, findings: list[Finding],
                   analyzed_paths: Optional[set[str]] = None
                   ) -> tuple[dict, int]:
    """Persist every unsuppressed finding as accepted debt, PRUNING stale
    entries: a previously-baselined fingerprint that no longer exists on
    the analyzed tree is dropped (and counted) instead of lingering as
    dead debt forever. ``analyzed_paths``: the relpaths this run actually
    covered — entries for *other* paths are kept untouched (a --paths
    subset run must not eat the rest of the baseline); None means the run
    covered everything. Returns (written data, pruned entry count).

    The committed baseline is *supposed* to be empty — writing a non-empty
    one is an explicit, diffable act of deferral."""
    try:
        with open(path, encoding="utf-8") as f:
            old_entries = json.load(f).get("entries", [])
    except (OSError, ValueError):
        old_entries = []
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "fingerprint": f.fingerprint, "message": f.message}
               for f in findings if not f.suppressed]
    new_fps = {e["fingerprint"] for e in entries}
    pruned = 0
    for e in old_entries:
        if analyzed_paths is not None \
                and e.get("path") not in analyzed_paths:
            entries.append(e)             # outside this run's coverage: keep
        elif e.get("fingerprint", "") not in new_fps:
            pruned += 1                   # stale: the finding is gone
    data = {"version": 1, "tool": "tpudist-check",
            "entries": sorted(entries, key=lambda e: (e["path"], e["line"],
                                                      e["rule"]))}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data, pruned


def gate(findings: list[Finding], baseline: set[str],
         strict: bool = False) -> list[Finding]:
    """Findings that FAIL the run: unsuppressed, error severity (warnings
    too under strict), and not already in the baseline."""
    sevs = ("error", "warning") if strict else ("error",)
    return [f for f in findings
            if not f.suppressed and f.severity in sevs
            and f.fingerprint not in baseline]


# -- the runner --------------------------------------------------------------

# Bumped whenever rule behavior changes: invalidates every cached result
# (the cache must never replay a previous analyzer's verdicts).
ANALYSIS_VERSION = 4


def _rule_modules():
    from tpudist.analysis import (rules_collective, rules_donation,
                                  rules_elastic, rules_numerics,
                                  rules_pallas, rules_purity,
                                  rules_recompile, rules_sharding,
                                  rules_telemetry)
    return [rules_purity, rules_collective, rules_donation, rules_pallas,
            rules_telemetry, rules_recompile, rules_sharding,
            rules_elastic, rules_numerics]


def _check_one(ctx: dict, mod: Module,
               rules: Optional[set[str]] = None) -> list[Finding]:
    """All rules over ONE file: check passes, dedupe, pragmas, fingerprints.
    Per-file by construction — the result depends only on this file's
    content plus the whole-program context, which is what makes the result
    cache sound (cache.py documents the factorization)."""
    findings: list[Finding] = []
    for rmod in _rule_modules():
        findings.extend(rmod.check(ctx, mod))
    # Dedupe: nested loops / overlapping scope walks can visit one node
    # twice; a finding is identified by what and where, not by which walk
    # reached it.
    uniq: dict[tuple, Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.col, f.message), f)
    findings = list(uniq.values())
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    findings = apply_pragmas([mod], findings, stale_check=rules is None)
    if rules is not None:
        findings = [f for f in findings
                    if f.rule in rules or f.rule.startswith("PRAGMA")]
    assign_fingerprints(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def build_context(root: str, mods: list[Module],
                  max_call_depth: Optional[int] = None) -> dict:
    """Whole-program context every rule shares: the symbol table, the
    import-resolving call graph, and each rule module's ``collect`` pass."""
    from tpudist.analysis import callgraph as cg_mod
    from tpudist.analysis import symbols as sym_mod
    symtab = sym_mod.SymbolTable(mods)
    cg = cg_mod.CallGraph(symtab,
                          max_call_depth or cg_mod.DEFAULT_MAX_DEPTH)
    ctx: dict = {"root": root, "modules": mods, "symtab": symtab,
                 "callgraph": cg,
                 "traced_nodes": cg.traced_nodes(),
                 "collective_performers": cg.collective_performers(),
                 "donated_factories": cg.donated_factories(),
                 "array_wrappers": cg.array_wrappers()}
    for rmod in _rule_modules():
        collect = getattr(rmod, "collect", None)
        if collect is not None:
            collect(ctx)
    return ctx


def _str_constants_signature(ctx: dict) -> dict:
    """Per-module map of string-resolvable module constants. COLL02/SHARD01
    resolve axis names THROUGH these across modules, so an edit to a
    constant's VALUE (consts.py: ``REDUCE_OVER = "data"`` → ``"dat"``)
    must flip the digest even when the harvest sets don't change —
    otherwise a cached consumer file replays a stale green verdict."""
    symtab = ctx.get("symtab")
    out: dict = {}
    if symtab is None:
        return out
    for dotted, ms in sorted(symtab.mods.items()):
        vals = {}
        for name, expr in ms.constants.items():
            got = symtab.str_values(ms, expr)
            if got:
                vals[name] = got
        if vals:
            out[dotted] = vals
    return out


def _context_digest(ctx: dict, include_tests: bool) -> str:
    from tpudist.analysis import cache as cache_mod
    sharding = ctx.get("sharding_harvest") or {}
    parts = {
        "analysis_version": ANALYSIS_VERSION,
        "include_tests": include_tests,
        "declared_axes": sorted(ctx.get("declared_axes", ())),
        "mesh_axes": sorted(ctx.get("mesh_axes", ())),
        "telemetry_schema": ctx.get("telemetry_schema"),
        "obs_docs_sha": cache_mod.content_sha(ctx.get("obs_docs_text") or ""),
        "str_constants": _str_constants_signature(ctx),
        "callgraph": ctx["callgraph"].signature(),
        "sharding": {k: v for k, v in sorted(sharding.items())
                     if k != "register_lines"},
        "register_lines": sorted(
            (sharding.get("register_lines") or {}).items()),
    }
    return cache_mod.global_digest(parts)


def _non_py_inputs_sha(root: str) -> str:
    """Content sha of every NON-.py input a rule reads (currently the
    TELEM03 docs matrix). The fully-warm short-circuit runs before any
    parse or collect, so these must be part of the tree snapshot — a docs
    edit with no .py change must not replay stale TELEM03 verdicts."""
    from tpudist.analysis import cache as cache_mod
    try:
        with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
                  encoding="utf-8") as f:
            docs = f.read()
    except OSError:
        docs = ""
    return cache_mod.content_sha(docs)


def _stats_for(findings: list[Finding], n_files: int, bad: list[str],
               relpaths: list[str], cache_info: Optional[dict]) -> dict:
    stats = {"files": n_files, "unparseable": bad, "relpaths": relpaths,
             "errors": sum(1 for f in findings
                           if f.severity == "error" and not f.suppressed),
             "warnings": sum(1 for f in findings
                             if f.severity == "warning" and not f.suppressed),
             "suppressed": sum(1 for f in findings if f.suppressed)}
    if cache_info is not None:
        stats["cache"] = cache_info
    return stats


def run_check(root: str, paths: Optional[Iterable[str]] = None,
              include_tests: bool = False,
              rules: Optional[set[str]] = None,
              use_cache: bool = False,
              cache_dir: Optional[str] = None,
              max_call_depth: Optional[int] = None
              ) -> tuple[list[Finding], dict]:
    """Run every rule over the tree (or an explicit file list). Returns
    (findings sorted by location, stats). ``rules``: restrict to a subset
    of rule IDs (pragma bookkeeping always runs). ``use_cache``: reuse
    per-file results keyed by content hash + whole-program digest (full
    tree runs only; the library default stays cache-free so tests and
    fixtures never touch user state)."""
    from tpudist.analysis import cache as cache_mod
    from tpudist.analysis import callgraph as cg_mod
    root = os.path.abspath(root)
    sources, bad_read = read_targets(root, paths, include_tests)
    shas = {rel: cache_mod.content_sha(src) for _, rel, src in sources}
    # The effective depth is part of every cached verdict's identity: a
    # depth-limited run sees FEWER cross-module facts, and its (weaker)
    # results must never be replayed by a default-depth run.
    depth = max_call_depth or cg_mod.DEFAULT_MAX_DEPTH
    cacheable = use_cache and paths is None and rules is None
    cached = cache_mod.load(root, cache_dir, ANALYSIS_VERSION) \
        if cacheable else None
    non_py_sha = _non_py_inputs_sha(root) if cacheable else ""
    if cached is not None and cached.get("include_tests") == include_tests \
            and cached.get("max_call_depth") == depth:
        cfiles = cached["files"]
        if not bad_read and not cached.get("unparseable") \
                and cached.get("non_py_sha") == non_py_sha \
                and set(cfiles) == set(shas) \
                and all(cfiles[r].get("sha") == shas[r] for r in shas):
            # Fully warm: nothing changed since the cached run — the cached
            # findings ARE the run; no parse, no callgraph, no checks.
            findings = [Finding.from_cache(d)
                        for r in sorted(cfiles)
                        for d in cfiles[r]["findings"]]
            findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
            return findings, _stats_for(
                findings, len(sources), [], sorted(shas),
                {"mode": "warm", "reused": len(sources), "analyzed": 0})
    mods, bad_parse = parse_sources(sources)
    bad = bad_read + bad_parse
    ctx = build_context(root, mods, max_call_depth)
    digest = _context_digest(ctx, include_tests) if cacheable else ""
    reuse = {}
    if cached is not None and cached.get("global_digest") == digest \
            and cached.get("include_tests") == include_tests:
        reuse = cached["files"]
    findings = []
    new_files: dict = {}
    hits = 0
    for mod in mods:
        sha = shas[mod.relpath]
        ent = reuse.get(mod.relpath)
        if ent is not None and ent.get("sha") == sha:
            fs = [Finding.from_cache(d) for d in ent["findings"]]
            hits += 1
        else:
            fs = _check_one(ctx, mod, rules)
        if cacheable:
            new_files[mod.relpath] = {
                "sha": sha, "findings": [f.to_cache() for f in fs]}
        findings.extend(fs)
    if cacheable:
        cache_mod.save(root, {
            "schema": cache_mod.CACHE_SCHEMA,
            "analysis_version": ANALYSIS_VERSION,
            "include_tests": include_tests, "global_digest": digest,
            "non_py_sha": non_py_sha, "max_call_depth": depth,
            "unparseable": bad, "files": new_files}, cache_dir)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    cache_info = {"mode": "cold" if not hits else "partial",
                  "reused": hits,
                  "analyzed": len(mods) - hits} if cacheable else None
    return findings, _stats_for(findings, len(mods), bad,
                                [m.relpath for m in mods], cache_info)
