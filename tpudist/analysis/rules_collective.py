"""COLL01/02/03 — collective symmetry.

COLL01: a collective (``lax.psum``/``pmean``/``all_gather``/…) or host
barrier (``dist.barrier``, ``sync_global_devices``) that executes on SOME
ranks only deadlocks the gang — the participating ranks block forever in
the collective waiting for the ranks the conditional excluded. Flagged
shapes:

- a collective lexically inside a rank-dependent ``if``/``while`` branch;
- a collective *after* a rank-dependent early exit (``if is_primary():
  return`` … ``barrier()``) in the same function — including a ``return``
  buried inside a loop/with/try body: the exit escapes the *function*, so
  it pairs with collectives after the whole compound statement, not just
  within it (the false negative PR 7's honesty section documented, now
  closed). ``continue``/``break`` exit only their loop and pair only
  within it.

Rank-DEPENDENT means rank identity: ``process_index``/``is_primary``/
``axis_index``/``rank`` variables. ``process_count``/world size are the
same on every rank — conditionals on them are symmetric and exempt.

COLL02: an ``axis_name`` that names no axis declared anywhere in the
analyzed tree (mesh axis_names, shard_map/pmap axis_name, PartitionSpec
entries, ``*_axis`` defaults/constants). Both the harvest and the consumer
check now propagate through straight-line variable assignments, module
constants, and cross-module constants (the symbol table) — closing the
literal-only limit PR 7 documented. Harvest still deliberately excludes
CONSUMER axis kwargs so a typo cannot self-declare.

COLL03: a rank-guarded *call* whose callee TRANSITIVELY performs a
collective (resolved through the import-following call graph, bounded at
its call depth) — the PR 4 orbax-deadlock shape in its real cross-module
form: the guard lives in the trainer, the barrier two modules away.
Fires only on positive resolution; dynamic dispatch is the documented
conservative stop. Calls whose own name is a collective stay COLL01's.
"""

from __future__ import annotations

import ast
from typing import Optional

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

# In-program collectives + host-side gang barriers: everything that BLOCKS
# until all ranks (or all mesh members along an axis) arrive.
SYNC_OPS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "pshuffle", "psum_scatter", "pbroadcast",
    "barrier", "sync_global_devices", "broadcast_one_to_all",
    "process_allgather",
}

# Calls whose result IS rank identity.
_RANK_CALLS = {"process_index", "is_primary", "axis_index", "data_rank_world"}
# Variable/attribute names conventionally holding rank identity.
_RANK_NAMES = {"rank", "local_rank", "global_rank", "process_id", "proc_id",
               "rank_id", "is_primary", "primary", "tel_rank"}

# axis_name-taking ops (superset of SYNC_OPS) and the positional slot the
# axis occupies: lax collectives take (operand, axis_name, ...);
# axis_index takes (axis_name,).
_AXIS_POS = {**{op: 1 for op in ("psum", "pmean", "pmax", "pmin",
                                 "all_gather", "all_to_all", "ppermute",
                                 "pshuffle", "psum_scatter", "pbroadcast")},
             "axis_index": 0}

# Parameter names whose string DEFAULTS declare axes, and call kwargs that
# declare (not consume) axes.
_AXIS_PARAM_HINT = ("axis_name", "axis_names", "data_axis", "model_axis",
                    "seq_axis", "pipe_axis", "expert_axis", "batch_axes")


def _is_rank_dependent(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            if astutil.last_segment(node.func) in _RANK_CALLS:
                return True
        elif isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            return True
        elif isinstance(node, ast.Attribute) and node.attr in _RANK_NAMES:
            return True
    return False


def _sync_calls(nodes) -> list[ast.Call]:
    return [node for node in astutil.walk_scope(list(nodes))
            if isinstance(node, ast.Call)
            and astutil.last_segment(node.func) in SYNC_OPS]


def _child_stmt_seqs(stmt) -> list[list]:
    """Statement sequences nested inside a compound statement (loop/with/
    try/if bodies) — each is checked as its own ordered sequence so a
    rank guard INSIDE a train loop still pairs with the collective that
    follows it in the same iteration."""
    seqs = []
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if isinstance(val, list) and val \
                and isinstance(val[0], ast.stmt):
            seqs.append(val)
    for handler in getattr(stmt, "handlers", []) or []:
        seqs.append(handler.body)
    for case in getattr(stmt, "cases", []) or []:
        seqs.append(case.body)
    return seqs


class _ScopeChecker:
    """COLL01 + COLL03 over one function (or module) scope. Carries the
    call-graph resolution context so guarded CALLS can be checked against
    the transitive-collective performer set."""

    def __init__(self, mod: Module, ctx: dict,
                 cls: Optional[str], fn: Optional[ast.AST]):
        self.mod = mod
        self.ctx = ctx
        self.cls = cls
        self.fn = fn
        self.cg = ctx.get("callgraph")
        self.performers = ctx.get("collective_performers") or {}
        symtab = ctx.get("symtab")
        self.ms = symtab.module_for(mod) if symtab else None
        self.out: list = []

    def _performer_calls(self, nodes) -> list[tuple[ast.Call, str, str]]:
        """(call, callee text, chain) for calls resolving to a function
        that transitively performs a collective. Direct SYNC_OPS calls are
        COLL01's and excluded here."""
        if self.cg is None or self.ms is None or not self.performers:
            return []
        res = []
        for node in astutil.walk_scope(list(nodes)):
            if not isinstance(node, ast.Call):
                continue
            seg = astutil.last_segment(node.func)
            if seg in SYNC_OPS:
                continue
            for fi in self.cg.resolve_invoked(self.ms, node, self.cls,
                                              self.fn):
                chain = self.performers.get(id(fi.node))
                if chain:
                    res.append((node, seg or "<call>", chain))
                    break
        return res

    def _flag_guarded(self, nodes, why: str) -> None:
        for call in _sync_calls(nodes):
            name = astutil.last_segment(call.func)
            self.out.append(finding(
                self.mod, "COLL01", call.lineno, call.col_offset,
                f"collective '{name}' {why} — ranks excluded by the guard "
                f"never reach it and the gang deadlocks"))
        for call, name, chain in self._performer_calls(nodes):
            self.out.append(finding(
                self.mod, "COLL03", call.lineno, call.col_offset,
                f"call to '{name}' {why}, and its callee transitively "
                f"performs a collective ({chain}) — ranks excluded by the "
                f"guard never arrive and the gang deadlocks"))

    def check_seq(self, body: list) -> Optional[int]:
        """One ordered statement sequence. Returns the line of the first
        rank-dependent guard whose early exit escapes the FUNCTION
        (Return/Raise) — the caller treats everything after the enclosing
        compound statement as guarded too. Loop-local exits
        (continue/break) guard only within their own sequence."""
        guard_line = None         # any rank-dependent early exit
        func_exit = None          # Return/Raise only: escapes the function
        for stmt in body:
            if isinstance(stmt, astutil.FUNC_NODES + (ast.ClassDef,)):
                continue          # its own scope; handled separately
            if guard_line is not None:
                self._flag_guarded(
                    [stmt],
                    f"after a rank-dependent early exit (line {guard_line})")
            if isinstance(stmt, (ast.If, ast.While)) \
                    and _is_rank_dependent(stmt.test):
                self._flag_guarded(
                    stmt.body + stmt.orelse,
                    "under a rank-dependent conditional")
                if isinstance(stmt, ast.If):
                    if astutil.has_exit(stmt.body,
                                        (ast.Return, ast.Raise, ast.Continue,
                                         ast.Break)) and guard_line is None:
                        guard_line = stmt.lineno
                    if astutil.has_exit(stmt.body,
                                        (ast.Return, ast.Raise)) \
                            and func_exit is None:
                        func_exit = stmt.lineno
                continue          # its contents are already flagged
            for seq in _child_stmt_seqs(stmt):
                sub = self.check_seq(seq)
                if sub is not None:
                    # A function-escaping exit inside a nested sequence
                    # (the `for …: if rank: return` shape) guards the rest
                    # of THIS sequence too.
                    if guard_line is None:
                        guard_line = sub
                    if func_exit is None:
                        func_exit = sub
        return func_exit


def collect(ctx: dict) -> None:
    """Harvest every axis name declared anywhere in the analyzed tree,
    resolving variables and (cross-module) constants where possible."""
    axes: set[str] = set()
    symtab = ctx.get("symtab")
    cg = ctx.get("callgraph")

    def resolve_strs(mod, node, expr) -> list[str]:
        """Best-effort: the shared env-aware resolution first (variables,
        module constants), literal harvest as the fallback."""
        if symtab is not None and cg is not None:
            ms = symtab.module_for(mod)
            if ms is not None:
                got = cg.str_values_at(ms, node, expr)
                if got is not None:
                    return got
        return astutil.str_literals(expr)

    for mod in ctx["modules"]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                seg = astutil.last_segment(node.func)
                # Mesh(devs, ('data', ...)) / make_mesh(axis_names=...)
                if seg in ("Mesh", "make_mesh") and len(node.args) >= 2:
                    axes.update(resolve_strs(mod, node, node.args[1]))
                # PartitionSpec('data', ...) entries name mesh axes
                if seg in ("P", "PartitionSpec"):
                    for a in node.args:
                        axes.update(astutil.str_literals(a))
                # Axis-DECLARING wrappers only. Harvesting axis kwargs from
                # every call would let a typo'd consumer (pmean(x,
                # axis_name="dat")) self-declare its own typo and escape
                # COLL02.
                if seg in ("Mesh", "make_mesh", "shard_map", "pmap",
                           "xmap"):
                    for kw in node.keywords:
                        if kw.arg in _AXIS_PARAM_HINT:
                            axes.update(resolve_strs(mod, node, kw.value))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # def f(..., axis_name: str = "data") declares an axis
                args = node.args
                defaults = list(args.defaults)
                params = (args.posonlyargs + args.args)[-len(defaults):] \
                    if defaults else []
                for p, d in zip(params, defaults):
                    if any(h in p.arg for h in _AXIS_PARAM_HINT) \
                            or p.arg.endswith("_axis") or p.arg == "axis":
                        axes.update(astutil.str_literals(d))
                for p, d in zip(args.kwonlyargs, args.kw_defaults):
                    if d is not None and (p.arg.endswith("_axis")
                                          or p.arg in _AXIS_PARAM_HINT):
                        axes.update(astutil.str_literals(d))
            elif isinstance(node, ast.Assign):
                # PIPE_AXIS = "pipe" style module constants
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and "axis" in tgt.id.lower():
                        axes.update(astutil.str_literals(node.value))
    ctx["declared_axes"] = axes


def check(ctx: dict, mod: Module) -> list:
    out: list = []
    symtab = ctx.get("symtab")
    cg = ctx.get("callgraph")
    ms = symtab.module_for(mod) if symtab else None
    parents = cg.tindex[ms.dotted].parents if (cg and ms) \
        else astutil.parent_map(mod.tree)
    # COLL01/03 per scope: module level + each function body (nested
    # sequences — loop/with/try bodies — recursed inside check_seq).
    sc = _ScopeChecker(mod, ctx, None, None)
    sc.check_seq(mod.tree.body)
    out.extend(sc.out)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls_node = astutil.enclosing(node, parents, (ast.ClassDef,))
            cls = cls_node.name if isinstance(cls_node, ast.ClassDef) \
                else None
            sc = _ScopeChecker(mod, ctx, cls, node)
            sc.check_seq(node.body)
            out.extend(sc.out)
    # COLL02: axis args of collectives against the declared set — literal,
    # straight-line variable, or (cross-module) constant.
    axes = ctx.get("declared_axes", set())
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        seg = astutil.last_segment(node.func)
        if seg not in _AXIS_POS:
            continue
        axis_arg = None
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis_arg = kw.value
        if axis_arg is None and len(node.args) > _AXIS_POS[seg]:
            axis_arg = node.args[_AXIS_POS[seg]]
        if axis_arg is None:
            continue
        names = cg.str_values_at(ms, node, axis_arg) \
            if (cg is not None and ms is not None) else None
        if names is None:
            continue                      # dynamic axis — out of reach
        for name in names:
            if name not in axes:
                out.append(finding(
                    mod, "COLL02", node.lineno, node.col_offset,
                    f"axis_name '{name}' in '{seg}' names no mesh/"
                    f"shard_map axis declared anywhere in the analyzed "
                    f"tree (declared: {sorted(axes)[:8]}…) — typo'd axes "
                    f"fail only at trace time"))
    return out
