"""COLL01/COLL02 — collective symmetry.

COLL01: a collective (``lax.psum``/``pmean``/``all_gather``/…) or host
barrier (``dist.barrier``, ``sync_global_devices``) that executes on SOME
ranks only deadlocks the gang — the participating ranks block forever in
the collective waiting for the ranks the conditional excluded. Two shapes
are flagged:

- a collective lexically inside a rank-dependent ``if``/``while`` branch;
- a collective *after* a rank-dependent early exit (``if is_primary():
  return`` … ``barrier()``) in the same function — the asymmetry the
  lexical check alone would miss (this is exactly the orbax-save shape PR 4
  debugged by hand: trainer.py's "rank-0-only call deadlocks orbax's
  global barrier" comment).

Rank-DEPENDENT means rank identity: ``process_index``/``is_primary``/
``axis_index``/``rank`` variables. ``process_count``/world size are the
same on every rank — conditionals on them are symmetric and exempt.

COLL02: an ``axis_name`` string that names no axis declared anywhere in
the analyzed tree (mesh axis_names, shard_map/pmap axis_name, PartitionSpec
entries, ``*_axis`` defaults/constants). A typo'd axis name ("dat") parses,
imports, and fails only when the step first traces — this makes it a lint
error. Axis declarations are harvested repo-wide in ``collect`` because
axes are declared at mesh-construction sites far from their use.
"""

from __future__ import annotations

import ast

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

# In-program collectives + host-side gang barriers: everything that BLOCKS
# until all ranks (or all mesh members along an axis) arrive.
SYNC_OPS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "pshuffle", "psum_scatter", "pbroadcast",
    "barrier", "sync_global_devices", "broadcast_one_to_all",
    "process_allgather",
}

# Calls whose result IS rank identity.
_RANK_CALLS = {"process_index", "is_primary", "axis_index", "data_rank_world"}
# Variable/attribute names conventionally holding rank identity.
_RANK_NAMES = {"rank", "local_rank", "global_rank", "process_id", "proc_id",
               "rank_id", "is_primary", "primary", "tel_rank"}

# axis_name-taking ops (superset of SYNC_OPS) and the positional slot the
# axis occupies: lax collectives take (operand, axis_name, ...);
# axis_index takes (axis_name,).
_AXIS_POS = {**{op: 1 for op in ("psum", "pmean", "pmax", "pmin",
                                 "all_gather", "all_to_all", "ppermute",
                                 "pshuffle", "psum_scatter", "pbroadcast")},
             "axis_index": 0}

# Parameter names whose string DEFAULTS declare axes, and call kwargs that
# declare (not consume) axes.
_AXIS_PARAM_HINT = ("axis_name", "axis_names", "data_axis", "model_axis",
                    "seq_axis", "pipe_axis", "expert_axis", "batch_axes")


def _is_rank_dependent(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            if astutil.last_segment(node.func) in _RANK_CALLS:
                return True
        elif isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            return True
        elif isinstance(node, ast.Attribute) and node.attr in _RANK_NAMES:
            return True
    return False


def _sync_calls(nodes) -> list[ast.Call]:
    return [node for node in astutil.walk_scope(list(nodes))
            if isinstance(node, ast.Call)
            and astutil.last_segment(node.func) in SYNC_OPS]


def _has_early_exit(body) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Return, ast.Continue, ast.Break, ast.Raise)):
            return True
    return False


def _child_stmt_seqs(stmt) -> list[list]:
    """Statement sequences nested inside a compound statement (loop/with/
    try/if bodies) — each is checked as its own ordered sequence so a
    rank guard INSIDE a train loop still pairs with the collective that
    follows it in the same iteration."""
    seqs = []
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if isinstance(val, list) and val \
                and isinstance(val[0], ast.stmt):
            seqs.append(val)
    for handler in getattr(stmt, "handlers", []) or []:
        seqs.append(handler.body)
    for case in getattr(stmt, "cases", []) or []:
        seqs.append(case.body)
    return seqs


def _check_seq(mod: Module, body: list, out: list) -> None:
    """One ordered statement sequence: lexical rank-guard check + the
    early-exit-then-collective pattern; recurses into nested sequences
    (loop/with/try bodies) but never into nested function/class scopes."""
    guard_line = None           # line of the first rank-dependent early exit
    for stmt in body:
        if isinstance(stmt, astutil.FUNC_NODES + (ast.ClassDef,)):
            continue            # its own scope; handled separately
        if guard_line is not None:
            for call in _sync_calls([stmt]):
                name = astutil.last_segment(call.func)
                out.append(finding(
                    mod, "COLL01", call.lineno, call.col_offset,
                    f"collective '{name}' after a rank-dependent early "
                    f"exit (line {guard_line}) — the exiting ranks never "
                    f"reach it and the gang deadlocks"))
        if isinstance(stmt, (ast.If, ast.While)) \
                and _is_rank_dependent(stmt.test):
            for call in _sync_calls(stmt.body + stmt.orelse):
                name = astutil.last_segment(call.func)
                out.append(finding(
                    mod, "COLL01", call.lineno, call.col_offset,
                    f"collective '{name}' under a rank-dependent "
                    f"conditional — ranks on the other branch never "
                    f"enter it and the gang deadlocks; hoist the "
                    f"collective out and guard only the host-local "
                    f"work"))
            if isinstance(stmt, ast.If) and _has_early_exit(stmt.body) \
                    and guard_line is None:
                guard_line = stmt.lineno
            continue            # its collectives are already flagged
        for seq in _child_stmt_seqs(stmt):
            _check_seq(mod, seq, out)


def collect(ctx: dict) -> None:
    """Harvest every axis name declared anywhere in the analyzed tree."""
    axes: set[str] = set()
    for mod in ctx["modules"]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                seg = astutil.last_segment(node.func)
                # Mesh(devs, ('data', ...)) / make_mesh(axis_names=...)
                if seg in ("Mesh", "make_mesh") and len(node.args) >= 2:
                    axes.update(astutil.str_literals(node.args[1]))
                # PartitionSpec('data', ...) entries name mesh axes
                if seg in ("P", "PartitionSpec"):
                    for a in node.args:
                        axes.update(astutil.str_literals(a))
                # Axis-DECLARING wrappers only. Harvesting axis kwargs from
                # every call would let a typo'd consumer (pmean(x,
                # axis_name="dat")) self-declare its own typo and escape
                # COLL02.
                if seg in ("Mesh", "make_mesh", "shard_map", "pmap",
                           "xmap"):
                    for kw in node.keywords:
                        if kw.arg in _AXIS_PARAM_HINT:
                            axes.update(astutil.str_literals(kw.value))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # def f(..., axis_name: str = "data") declares an axis
                args = node.args
                defaults = list(args.defaults)
                params = (args.posonlyargs + args.args)[-len(defaults):] \
                    if defaults else []
                for p, d in zip(params, defaults):
                    if any(h in p.arg for h in _AXIS_PARAM_HINT) \
                            or p.arg.endswith("_axis") or p.arg == "axis":
                        axes.update(astutil.str_literals(d))
                for p, d in zip(args.kwonlyargs, args.kw_defaults):
                    if d is not None and (p.arg.endswith("_axis")
                                          or p.arg in _AXIS_PARAM_HINT):
                        axes.update(astutil.str_literals(d))
            elif isinstance(node, ast.Assign):
                # PIPE_AXIS = "pipe" style module constants
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and "axis" in tgt.id.lower():
                        axes.update(astutil.str_literals(node.value))
    ctx["declared_axes"] = axes


def check(ctx: dict, mod: Module) -> list:
    out: list = []
    # COLL01 per scope: module level + each function body (nested
    # sequences — loop/with/try bodies — recursed inside _check_seq).
    _check_seq(mod, mod.tree.body, out)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_seq(mod, node.body, out)
    # COLL02: literal axis args of collectives against the declared set.
    axes = ctx.get("declared_axes", set())
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        seg = astutil.last_segment(node.func)
        if seg not in _AXIS_POS:
            continue
        axis_arg = None
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis_arg = kw.value
        if axis_arg is None and len(node.args) > _AXIS_POS[seg]:
            axis_arg = node.args[_AXIS_POS[seg]]
        if axis_arg is None:
            continue
        if isinstance(axis_arg, ast.Constant) \
                and isinstance(axis_arg.value, str):
            names = [axis_arg.value]
        elif isinstance(axis_arg, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in axis_arg.elts):
            names = [e.value for e in axis_arg.elts]
        else:
            continue                      # dynamic axis — out of reach
        for name in names:
            if name not in axes:
                out.append(finding(
                    mod, "COLL02", node.lineno, node.col_offset,
                    f"axis_name '{name}' in '{seg}' names no mesh/"
                    f"shard_map axis declared anywhere in the analyzed "
                    f"tree (declared: {sorted(axes)[:8]}…) — typo'd axes "
                    f"fail only at trace time"))
    return out
