"""Import-resolving call graph over the whole analyzed tree.

Built on ``symbols.SymbolTable``, this answers the cross-module questions
the per-module ``astutil.TraceIndex`` cannot:

- ``traced_nodes()``: every function body that can execute under a jax
  trace, with calls followed **across module boundaries** (the TRACE01/02
  reachability set);
- ``collective_performers()``: functions that *transitively* call a
  collective / gang barrier (COLL03's target — the PR 4 orbax-deadlock
  shape in its real cross-module form), with the call chain recorded for
  the finding message;
- ``donated_factories()``: functions whose return value is a donated jit
  (``donated_jit(...)`` / ``jit(..., donate_argnums=…)``) — the
  ``train.py`` builds / ``trainer.py`` consumes shape DONATE01 needs;
- ``array_wrappers()``: one-level repo-local helpers whose every return
  wraps in ``jnp.asarray``/``jnp.array`` (RECOMP02's safe-crossing
  downgrade).

Everything is bounded by ``max_depth`` call hops from its seeds, and every
resolution failure (dynamic dispatch, callables stored in containers,
external libraries) is a documented conservative stop: reachability keeps
TraceIndex's intra-module over-approximation, the *accusatory* rules
(COLL03, SHARD02, DONATE01-cross-module) fire only on positive resolution.

Stdlib only, no jax import.
"""

from __future__ import annotations

import ast
from typing import Optional

from tpudist.analysis import astutil
from tpudist.analysis.symbols import (FuncInfo, ModuleSymbols, SymbolTable,
                                      local_str_env)

DEFAULT_MAX_DEPTH = 10

# jnp/np wrappers that carry a Python scalar across the jit boundary as an
# array (the RECOMP02 stand-down set, shared with rules_recompile).
ARRAY_WRAP_CALLS = {"asarray", "array", "float32", "int32", "bfloat16"}


class CallGraph:
    def __init__(self, symtab: SymbolTable,
                 max_depth: int = DEFAULT_MAX_DEPTH):
        self.symtab = symtab
        self.max_depth = max(1, int(max_depth))
        # Per-module TraceIndex: intra-module seeds/edges, parent maps, and
        # the bare-name function index reused for local resolution.
        self.tindex: dict[str, astutil.TraceIndex] = {}
        # id(function node) -> FuncInfo, for EVERY def/lambda in the tree.
        self.funcs: dict[int, FuncInfo] = {}
        self._funcs_by_module: dict[str, list[FuncInfo]] = {}
        for dotted, ms in symtab.mods.items():
            self.tindex[dotted] = astutil.TraceIndex(ms.mod.tree)
            self._funcs_by_module[dotted] = self._enumerate(ms)
        self._callees_cache: dict[int, list[FuncInfo]] = {}
        self._cls_attr_types: dict[int, dict[str, str]] = {}
        self._env_cache: dict[int, dict] = {}
        self._memo: dict[str, object] = {}

    def _local_env(self, fn: ast.AST) -> dict:
        got = self._env_cache.get(id(fn))
        if got is None:
            got = local_str_env(fn)
            self._env_cache[id(fn)] = got
        return got

    def str_values_at(self, ms: ModuleSymbols, node: ast.AST,
                      expr: Optional[ast.expr]):
        """``SymbolTable.str_values`` with the straight-line local env of
        ``node``'s enclosing function supplied — THE shared resolution
        path for every rule that reads axis names out of expressions
        (COLL02 consumer + harvest, SHARD01, mesh harvest), so the env
        handling cannot drift per rule."""
        if expr is None:
            return None
        env = None
        ti = self.tindex.get(ms.dotted)
        if ti is not None:
            fn = astutil.enclosing(node, ti.parents, astutil.FUNC_NODES)
            if fn is not None:
                env = self._local_env(fn)
        return self.symtab.str_values(ms, expr, local_env=env)

    # -- function enumeration ----------------------------------------------
    def _enumerate(self, ms: ModuleSymbols) -> list[FuncInfo]:
        out: list[FuncInfo] = []
        lam = [0]

        def visit(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    fi = FuncInfo(ms.dotted, qual, child, cls=cls)
                    self.funcs[id(child)] = fi
                    out.append(fi)
                    visit(child, f"{qual}.<locals>.", cls)
                elif isinstance(child, ast.Lambda):
                    lam[0] += 1
                    fi = FuncInfo(ms.dotted, f"{prefix}<lambda>#{lam[0]}",
                                  child, cls=cls)
                    self.funcs[id(child)] = fi
                    out.append(fi)
                    visit(child, f"{prefix}<lambda>#{lam[0]}.", cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{child.name}.", child.name)
                else:
                    visit(child, prefix, cls)

        visit(ms.mod.tree, "", None)
        return out

    def info(self, node: ast.AST) -> Optional[FuncInfo]:
        return self.funcs.get(id(node))

    def module_of(self, fi: FuncInfo) -> Optional[ModuleSymbols]:
        return self.symtab.mods.get(fi.module)

    # -- class attribute types ----------------------------------------------
    def _attr_types(self, ci) -> dict[str, str]:
        """``self.x = ClassName(...)`` assignments anywhere in a class's
        methods: attr name → dotted constructor text (resolved on use)."""
        got = self._cls_attr_types.get(id(ci.node))
        if got is not None:
            return got
        types: dict[str, str] = {}
        for meth in ci.methods.values():
            for node in astutil.walk_scope(meth):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute):
                    tgt = node.targets[0]
                    if isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" \
                            and isinstance(node.value, ast.Call):
                        d = astutil.dotted(node.value.func)
                        if d:
                            types[tgt.attr] = d
        self._cls_attr_types[id(ci.node)] = types
        return types

    # -- call resolution -----------------------------------------------------
    def _lexical_def(self, ms: ModuleSymbols, name: str,
                     at: ast.AST) -> Optional[ast.AST]:
        """Python lexical scoping for a bare function name used at ``at``:
        innermost enclosing function whose DIRECT body defines ``name``
        wins (two builders may each nest a ``step`` — each shard_map site
        must see its own)."""
        parents = self.tindex[ms.dotted].parents
        cur: Optional[ast.AST] = at
        while cur is not None:
            scope = astutil.enclosing(cur, parents, astutil.FUNC_NODES)
            if scope is None:
                break
            body = scope.body if not isinstance(scope, ast.Lambda) else []
            for stmt in body if isinstance(body, list) else []:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and stmt.name == name:
                    return stmt
            cur = scope
        return None

    def resolve_expr_funcs(self, ms: ModuleSymbols, expr: ast.expr,
                           at: Optional[ast.AST] = None) -> list[FuncInfo]:
        """Function definitions a callable-typed *expression* may denote:
        lambda, ``partial(f, …)``, plain / dotted names. ``at``: the use
        site, for lexical nested-def resolution (the shard_map-wraps-a-
        nested-step shape)."""
        if isinstance(expr, ast.Lambda):
            fi = self.info(expr)
            return [fi] if fi else []
        if isinstance(expr, ast.Call) \
                and astutil.last_segment(expr.func) == "partial" and expr.args:
            return self.resolve_expr_funcs(ms, expr.args[0], at)
        d = astutil.dotted(expr)
        if not d:
            return []
        ti = self.tindex.get(ms.dotted)
        if ti is not None and "." not in d:
            # Exact lexical scoping first; the module-wide bare-name index
            # as the unambiguous-only fallback.
            if at is not None:
                node = self._lexical_def(ms, d, at)
                if node is not None:
                    fi = self.info(node)
                    if fi:
                        return [fi]
            cands = ti.by_name.get(d, [])
            if len(cands) == 1:
                fi = self.info(cands[0])
                if fi:
                    return [fi]
        return self.symtab.resolve_funcs(ms, d)

    def resolve_invoked(self, ms: Optional[ModuleSymbols], call: ast.Call,
                        cls: Optional[str] = None,
                        fn: Optional[ast.AST] = None) -> list[FuncInfo]:
        """Definitions actually *invoked* by this call expression. Exact
        resolutions only — an unresolved callee returns [] (the documented
        conservative stop at dynamic dispatch)."""
        if ms is None:
            return []
        f = call.func
        # jit(g)(x) / shard_map(g, ...)(x): the outer call invokes g.
        if isinstance(f, ast.Call) \
                and astutil.last_segment(f.func) in astutil.TRACING_WRAPPERS:
            out: list[FuncInfo] = []
            for arg in f.args[:1]:
                out.extend(self.resolve_expr_funcs(ms, arg))
            return out
        d = astutil.dotted(f)
        if d is None:
            return []
        parts = d.split(".")
        if parts[0] in ("self", "cls") and cls and cls in ms.classes:
            ci = ms.classes[cls]
            if len(parts) == 2:
                return [fi for k, fi in
                        self.symtab.class_method(ci, parts[1]) if k == "func"]
            if len(parts) == 3:
                tname = self._attr_types(ci).get(parts[1])
                if tname:
                    for kind, tgt in self.symtab.resolve(ms, tname):
                        if kind == "class":
                            return [fi for k, fi in self.symtab.class_method(
                                tgt, parts[2]) if k == "func"]
            return []
        got = self.symtab.resolve_funcs(ms, d)
        if got:
            return got
        # obj.meth(...) where obj is a local `obj = ClassName(...)`.
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and fn is not None:
            env = self._local_env(fn)         # reuse: single-assignment map
            val = env.get(f.value.id)
            if isinstance(val, ast.Call):
                cd = astutil.dotted(val.func)
                if cd:
                    for kind, tgt in self.symtab.resolve(ms, cd):
                        if kind == "class":
                            return [fi for k, fi in self.symtab.class_method(
                                tgt, f.attr) if k == "func"]
        return []

    def callees_invoked(self, fi: FuncInfo) -> list[FuncInfo]:
        """Functions this body INVOKES (direct calls, control-flow
        combinator callables, immediately-called wrapper args). Function
        references merely *passed* to a tracing wrapper are not invoked
        here — ``jit(f)`` builds, it does not run — so a rank-guarded call
        to a step *factory* stays legal."""
        got = self._callees_cache.get(id(fi.node))
        if got is not None:
            return got
        ms = self.module_of(fi)
        out: list[FuncInfo] = []
        if ms is not None:
            for node in astutil.walk_scope(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                seg = astutil.last_segment(node.func)
                if seg in astutil.HOST_CALLBACKS:
                    continue
                out.extend(self.resolve_invoked(ms, node, fi.cls, fi.node))
                if seg in astutil.CONTROL_FLOW:
                    for arg in list(node.args) \
                            + [k.value for k in node.keywords]:
                        out.extend(self.resolve_expr_funcs(ms, arg))
        self._callees_cache[id(fi.node)] = out
        return out

    # -- derived whole-program facts -----------------------------------------
    def traced_nodes(self) -> set[int]:
        """ids of every function node reachable from a trace root, across
        modules, bounded at ``max_depth`` cross-call hops from the seeds.
        Intra-module edges keep TraceIndex's deliberate over-approximation;
        cross-module edges are exact symbol-table resolutions."""
        memo = self._memo.get("traced")
        if memo is not None:
            return memo  # type: ignore[return-value]
        traced: set[int] = set()
        work: list[tuple[ast.AST, str, int]] = []
        for dotted, ti in self.tindex.items():
            for node in ti.traced:
                if id(node) not in traced:
                    traced.add(id(node))
                    work.append((node, dotted, 0))
        # Cross-module SEEDS, not just edges: ``jax.jit(imported_fn)``
        # roots a function the importing module's TraceIndex cannot see —
        # resolve wrapper args through the symbol table too.
        for dotted, ti in self.tindex.items():
            ms = self.symtab.mods[dotted]
            for node in ast.walk(ti.tree):
                if not (isinstance(node, ast.Call) and astutil.last_segment(
                        node.func) in astutil.TRACING_WRAPPERS):
                    continue
                for arg in ti._callable_args(node):
                    for t in self.resolve_expr_funcs(ms, arg, at=node):
                        if id(t.node) not in traced:
                            traced.add(id(t.node))
                            work.append((t.node, t.module, 0))
        while work:
            node, dotted, depth = work.pop()
            if depth >= self.max_depth:
                continue
            ti = self.tindex[dotted]
            ms = self.symtab.mods[dotted]
            fi = self.info(node)
            nexts: list[tuple[ast.AST, str]] = [
                (n, dotted) for n in ti._edges_from(node)]
            for call in astutil.walk_scope(node):
                if not isinstance(call, ast.Call):
                    continue
                seg = astutil.last_segment(call.func)
                if seg in astutil.HOST_CALLBACKS:
                    continue
                targets = self.resolve_invoked(
                    ms, call, fi.cls if fi else None, node)
                if seg in astutil.TRACING_WRAPPERS \
                        or seg in astutil.CONTROL_FLOW:
                    for arg in list(call.args) \
                            + [k.value for k in call.keywords]:
                        targets = targets + self.resolve_expr_funcs(ms, arg)
                nexts.extend((t.node, t.module) for t in targets)
            for nnode, ndotted in nexts:
                if id(nnode) not in traced:
                    traced.add(id(nnode))
                    work.append((nnode, ndotted, depth + 1))
        self._memo["traced"] = traced
        return traced

    def collective_performers(self) -> dict[int, str]:
        """id(function node) → human-readable call chain ending at the
        collective, for every function that transitively performs one
        within ``max_depth`` hops."""
        memo = self._memo.get("performers")
        if memo is not None:
            return memo  # type: ignore[return-value]
        chains: dict[int, str] = {}
        allf = [fi for fis in self._funcs_by_module.values() for fi in fis]
        for fi in allf:
            for node in astutil.walk_scope(fi.node):
                if isinstance(node, ast.Call):
                    seg = astutil.last_segment(node.func)
                    if seg in SYNC_OPS_REF():
                        chains[id(fi.node)] = f"{fi.label} → {seg}"
                        break
        for _ in range(self.max_depth):
            changed = False
            for fi in allf:
                if id(fi.node) in chains:
                    continue
                for c in self.callees_invoked(fi):
                    sub = chains.get(id(c.node))
                    if sub is not None:
                        chains[id(fi.node)] = f"{fi.label} → {sub}"
                        changed = True
                        break
            if not changed:
                break
        self._memo["performers"] = chains
        return chains

    def donated_factories(self) -> dict[int, tuple[FuncInfo, tuple]]:
        """Functions whose return value is a donated jitted callable —
        calling the *result* donates by the recorded positions. Straight-
        line ``step = donated_jit(f); return step`` is followed."""
        memo = self._memo.get("donated")
        if memo is not None:
            return memo  # type: ignore[return-value]
        out: dict[int, tuple[FuncInfo, tuple]] = {}
        for fis in self._funcs_by_module.values():
            for fi in fis:
                if isinstance(fi.node, ast.Lambda):
                    continue
                env = None
                pos = None
                for node in astutil.walk_scope(fi.node):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    val = node.value
                    if isinstance(val, ast.Name):
                        if env is None:
                            env = local_str_env(fi.node)
                        bound = env.get(val.id)
                        if bound is not None:
                            val = bound
                    if isinstance(val, ast.Call):
                        got = astutil.donated_positions(val)
                        if got:
                            pos = got
                if pos:
                    out[id(fi.node)] = (fi, pos)
        self._memo["donated"] = out
        return out

    def array_wrappers(self) -> set[int]:
        """Repo-local helpers whose EVERY return statement wraps its value
        in ``asarray``/``array`` — a scalar routed through one is an array
        by the time it crosses the jit boundary (RECOMP02 stands down)."""
        memo = self._memo.get("wrappers")
        if memo is not None:
            return memo  # type: ignore[return-value]
        out: set[int] = set()
        for fis in self._funcs_by_module.values():
            for fi in fis:
                if isinstance(fi.node, ast.Lambda):
                    rets = [fi.node.body]
                else:
                    rets = [n.value for n in astutil.walk_scope(fi.node)
                            if isinstance(n, ast.Return) and n.value]
                if rets and all(
                        isinstance(r, ast.Call)
                        and astutil.last_segment(r.func) in ARRAY_WRAP_CALLS
                        for r in rets):
                    out.add(id(fi.node))
        self._memo["wrappers"] = out
        return out

    # -- digest ---------------------------------------------------------------
    def signature(self) -> dict:
        """Deterministic summary of every cross-module fact a per-file rule
        result can depend on. Two trees with equal signatures (and equal
        harvest context) give every *unchanged* file identical findings —
        the correctness contract of the per-file result cache."""
        arity = {}
        for fis in self._funcs_by_module.values():
            for fi in fis:
                a = fi.node.args
                total = len(a.posonlyargs) + len(a.args)
                required = total - len(a.defaults)
                # Return-tuple shape rides along: SHARD02's out_specs check
                # reads it cross-module, so a callee changing its return
                # arity must flip the digest (same helper as the rule).
                n_rets, lens, all_tuples = astutil.return_tuple_info(fi.node)
                arity[fi.label] = (required, total, a.vararg is not None,
                                   n_rets, list(lens), all_tuples)
        traced = sorted(self.funcs[i].label for i in self.traced_nodes()
                        if i in self.funcs)
        performers = sorted(self.collective_performers().values())
        donated = sorted((fi.label, list(pos)) for fi, pos
                         in self.donated_factories().values())
        wrappers = sorted(self.funcs[i].label for i in self.array_wrappers()
                          if i in self.funcs)
        return {"arity": arity, "traced": traced, "performers": performers,
                "donated": donated, "wrappers": wrappers}


def SYNC_OPS_REF() -> set:
    """rules_collective.SYNC_OPS without a module-level import cycle."""
    from tpudist.analysis.rules_collective import SYNC_OPS
    return SYNC_OPS
