"""SHARD01/02/03 — sharding / mesh consistency.

ROADMAP items 1–2 (full weight-update sharding per Xu et al. 2020,
arXiv:2004.13336; MPMD pipeline parallelism per arXiv:2412.14374) will put
``PartitionSpec`` re-cuts and per-stage ``shard_map`` programs far from
the mesh constructions that give their axis names meaning. These rules
make that distance safe:

- SHARD01: a ``PartitionSpec`` entry naming an axis that **no
  ``Mesh``/``make_mesh`` in the analyzed tree declares**. Unlike COLL02
  (collective *consumers*), a spec's axis must come from a mesh — a spec
  axis typo either silently replicates (GSPMD treats unknown-resolved
  specs as unconstrained at best) or dies at trace time. Axis names
  propagate through straight-line variable assignments, module-level
  constants, and cross-module constants (the symbol table); the rule
  stands down entirely when the analyzed tree declares no mesh at all
  (single-file fixture runs have no mesh to check against).
- SHARD02: a ``shard_map`` whose literal ``in_specs`` tuple cannot match
  the wrapped function's positional signature (too many specs, or fewer
  than the required parameters), or whose literal ``out_specs`` tuple
  disagrees with the arity every ``return`` statement of the wrapped
  function produces. The callee resolves through nested local defs (the
  ``make_*_step`` builder shape), ``partial`` bindings, and cross-module
  imports; an unresolved callee or a non-literal spec is the documented
  conservative stop.
- SHARD03: a model family registered in ``models/__init__.py`` that is
  reachable under a ``model``-axis mesh while its tensor-parallel rule
  table (``parallel/tensor_parallel.py::rules_for``) resolves to an EMPTY
  tuple and its family is not listed in ``NO_TP_FAMILIES`` — the
  ``RESNET_RULES = ()`` silent-pure-DP class from VERDICT r5 weak #3,
  made structural instead of runtime-warned. Registry names resolve
  through literal loops (``for _n in ("resnet18", …)``) and cross-module
  ``_VARIANTS`` dict constants.
"""

from __future__ import annotations

import ast
from typing import Optional

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

# rules_for's default-arch sentinel and the explicit no-TP annotation this
# rule recognizes (parallel/tensor_parallel.py documents both).
_NO_TP_CONST = "NO_TP_FAMILIES"


def _str_values_at(ctx, ms, node, expr):
    """The shared env-aware resolution path (CallGraph.str_values_at)."""
    cg = ctx.get("callgraph")
    if cg is None or ms is None:
        return None
    return cg.str_values_at(ms, node, expr)


# -- collect: mesh axes + registry/rule-table harvest -------------------------

def collect(ctx: dict) -> None:
    symtab = ctx.get("symtab")
    mesh_axes: set[str] = set()
    if symtab is not None:
        for ms in symtab.mods.values():
            for node in ast.walk(ms.mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                seg = astutil.last_segment(node.func)
                if seg not in ("Mesh", "make_mesh"):
                    continue
                axes_expr = None
                for kw in node.keywords:
                    if kw.arg in ("axis_names", "axis_name"):
                        axes_expr = kw.value
                if axes_expr is None and len(node.args) >= 2:
                    axes_expr = node.args[1]
                got = _str_values_at(ctx, ms, node, axes_expr)
                if got:
                    mesh_axes.update(got)
    ctx["mesh_axes"] = mesh_axes
    ctx["sharding_harvest"] = _harvest_registry(ctx)
    ctx["plane_harvest"] = _harvest_plane(ctx)


def _harvest_plane(ctx: dict) -> dict:
    """parallel/plane.py's ``AXIS_BINDING`` (logical → mesh axis, a dict of
    string literals) for SHARD05. A missing plane module or a non-literal
    binding disables the rule table half (conservative stop)."""
    symtab = ctx.get("symtab")
    if symtab is None:
        return {}
    for rel, ms in symtab.by_relpath.items():
        if not rel.endswith("parallel/plane.py"):
            continue
        expr = ms.constants.get("AXIS_BINDING")
        if not isinstance(expr, ast.Dict):
            return {}
        binding: dict = {}
        for k, v in zip(expr.keys, expr.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                return {}
            binding[k.value] = v.value
        return {"binding": binding, "relpath": rel,
                "line": expr.lineno}
    return {}


def _harvest_registry(ctx: dict) -> dict:
    """models/__init__.py registry + tensor_parallel rule tables, for
    SHARD03. Every piece that fails to resolve in the expected shape
    disables the rule for the tree (conservative stop, documented)."""
    symtab = ctx.get("symtab")
    if symtab is None:
        return {}
    reg_ms = tp_ms = None
    for rel, ms in symtab.by_relpath.items():
        if rel.endswith("models/__init__.py"):
            reg_ms = ms
        elif rel.endswith("tensor_parallel.py"):
            tp_ms = ms
    if reg_ms is None or tp_ms is None:
        return {}
    # Registered arch names: direct literal register_model("x", …) calls
    # plus `for _n in <resolvable>: register_model(_n, …)` loops.
    registered: dict[str, int] = {}          # name -> register line
    for node in ast.walk(reg_ms.mod.tree):
        if isinstance(node, ast.Call) \
                and astutil.last_segment(node.func) == "register_model" \
                and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                registered.setdefault(first.value, node.lineno)
        elif isinstance(node, (ast.For,)) \
                and isinstance(node.target, ast.Name):
            names = symtab.str_values(reg_ms, node.iter)
            if not names:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and astutil.last_segment(sub.func) == "register_model" \
                        and sub.args \
                        and isinstance(sub.args[0], ast.Name) \
                        and sub.args[0].id == node.target.id:
                    for nm in names:
                        registered.setdefault(nm, sub.lineno)
    # rules_for: `if arch.startswith("vit"): return VIT_RULES` chains plus
    # the trailing default return.
    rules_fn = tp_ms.functions.get("rules_for")
    if rules_fn is None or not registered:
        return {}
    prefix_map: list[tuple[tuple, str]] = []
    default_const: Optional[str] = None
    for stmt in rules_fn.body:
        if isinstance(stmt, ast.If) and isinstance(stmt.test, ast.Call) \
                and astutil.last_segment(stmt.test.func) == "startswith" \
                and stmt.test.args:
            prefixes = astutil.str_literals(stmt.test.args[0])
            rets = [s for s in stmt.body if isinstance(s, ast.Return)]
            if prefixes and rets and isinstance(rets[0].value, ast.Name):
                prefix_map.append((tuple(prefixes), rets[0].value.id))
        elif isinstance(stmt, ast.Return) \
                and isinstance(stmt.value, ast.Name):
            default_const = stmt.value.id
    if default_const is None:
        return {}
    empties: dict[str, bool] = {}
    for name, expr in tp_ms.constants.items():
        if isinstance(expr, (ast.Tuple, ast.List)):
            empties[name] = not expr.elts
    no_tp = symtab.str_values(
        tp_ms, tp_ms.constants.get(_NO_TP_CONST)) or []
    return {"registered": sorted(registered),
            "register_lines": registered,
            "registry_relpath": reg_ms.mod.relpath,
            "prefix_map": prefix_map, "default_const": default_const,
            "empties": empties, "no_tp": tuple(no_tp)}


# -- check --------------------------------------------------------------------

def check(ctx: dict, mod: Module) -> list:
    out: list = []
    symtab = ctx.get("symtab")
    ms = symtab.module_for(mod) if symtab else None
    mesh_axes = ctx.get("mesh_axes") or set()
    # SHARD01: spec axes against mesh-declared axes (only meaningful when
    # the tree declares a mesh at all).
    if mesh_axes and ms is not None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if astutil.last_segment(node.func) not in ("P", "PartitionSpec"):
                continue
            for arg in node.args:
                names = _str_values_at(ctx, ms, node, arg)
                if names is None:
                    continue              # dynamic entry: out of reach
                for nm in names:
                    if nm not in mesh_axes:
                        out.append(finding(
                            mod, "SHARD01", node.lineno, node.col_offset,
                            f"PartitionSpec axis '{nm}' is declared by no "
                            f"Mesh/make_mesh in the analyzed tree "
                            f"(mesh axes: {sorted(mesh_axes)}) — a typo'd "
                            f"spec axis silently replicates or dies at "
                            f"trace time"))
    # SHARD02: shard_map spec arity vs the wrapped function.
    if ms is not None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if astutil.last_segment(node.func) != "shard_map" \
                    or not node.args:
                continue
            out.extend(_check_shard_map(ctx, mod, ms, node))
    # SHARD04: reduce-scatter/all-gather pairing consistency inside one
    # (outermost) function — the weight-update-sharding round trip.
    if ms is not None:
        out.extend(_check_rs_ag_pairing(ctx, mod, ms))
    # SHARD05: rule tables ↔ the plane's axis binding ↔ the mesh, end to
    # end; plus shard_map-wrapped pallas_call spec consistency.
    out.extend(_check_plane_consistency(ctx, mod))
    if ms is not None:
        out.extend(_check_pallas_shard_map(ctx, mod, ms))
    # SHARD03: registry families vs the TP rule table, attached to the
    # registry module's register lines.
    h = ctx.get("sharding_harvest") or {}
    if h and "model" in mesh_axes \
            and mod.relpath == h.get("registry_relpath"):
        for arch in h["registered"]:
            const = h["default_const"]
            for prefixes, c in h["prefix_map"]:
                if arch.startswith(tuple(prefixes)):
                    const = c
                    break
            if not h["empties"].get(const, False):
                continue                  # non-empty rule table: sharded
            if arch.startswith(tuple(h["no_tp"])):
                continue                  # explicitly annotated pure-DP
            out.append(finding(
                mod, "SHARD03", h["register_lines"][arch], 0,
                f"arch '{arch}' resolves to EMPTY tensor-parallel rule "
                f"table '{const}' while the tree declares a 'model' mesh "
                f"axis — under a split model axis this family runs silent "
                f"pure DP; add sharding rules or list its family in "
                f"{_NO_TP_CONST} (parallel/tensor_parallel.py)"))
    return out


def _rule_table_axes(ms) -> list:
    """``(const_name, lineno, axis)`` for every string axis a ``*_RULES``
    tuple constant's ``P(...)`` entries name in this module."""
    out: list = []
    for name, expr in ms.constants.items():
        if not name.endswith("_RULES") \
                or not isinstance(expr, (ast.Tuple, ast.List)):
            continue
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Call) and astutil.last_segment(
                    node.func) in ("P", "PartitionSpec")):
                continue
            for arg in node.args:
                for lit in astutil.str_literals(arg) or []:
                    out.append((name, node.lineno, lit))
    return out


def _check_plane_consistency(ctx: dict, mod: Module) -> list:
    """SHARD05 half 1 — verify rule tables against the mesh END TO END
    through the plane: every spec axis a ``*_RULES`` table names must be a
    value of ``plane.AXIS_BINDING`` (the plane's mesh-axis vocabulary) —
    SHARD01 only checks mesh-declared-somewhere, which admits e.g. 'seq'
    (declared by the SP meshes) into a TP table — and every axis the
    binding names must itself be declared by some Mesh in the tree. A
    missing plane module or binding is the documented conservative stop."""
    out: list = []
    h = ctx.get("plane_harvest") or {}
    binding = h.get("binding")
    if not binding:
        return out
    mesh_axes = ctx.get("mesh_axes") or set()
    bound = set(binding.values())
    if mod.relpath.endswith("tensor_parallel.py"):
        symtab = ctx.get("symtab")
        ms = symtab.module_for(mod) if symtab else None
        if ms is not None:
            for const, line, axis in _rule_table_axes(ms):
                if axis not in bound:
                    out.append(finding(
                        mod, "SHARD05", line, 0,
                        f"rule table '{const}' names spec axis '{axis}', "
                        f"which the parallelism plane does not bind "
                        f"(plane.AXIS_BINDING maps onto {sorted(bound)}) "
                        f"— the step builders compose only plane-bound "
                        f"axes, so this rule can never shard what it "
                        f"claims"))
    if mod.relpath == h.get("relpath") and mesh_axes:
        for logical, axis in sorted(binding.items()):
            if axis not in mesh_axes:
                out.append(finding(
                    mod, "SHARD05", h["line"], 0,
                    f"AXIS_BINDING maps logical axis '{logical}' to mesh "
                    f"axis '{axis}', which no Mesh/make_mesh in the "
                    f"analyzed tree declares (mesh axes: "
                    f"{sorted(mesh_axes)})"))
    return out


def _pallas_performers(ctx: dict) -> set:
    """ids of function nodes that TRANSITIVELY call ``pallas_call`` within
    the call-graph depth bound (the SHARD05 shard_map-wrapped-kernel
    target set), memoized in ctx."""
    got = ctx.get("_pallas_performers")
    if got is not None:
        return got
    cg = ctx.get("callgraph")
    performers: set = set()
    if cg is not None:
        allf = [fi for fis in cg._funcs_by_module.values() for fi in fis]
        for fi in allf:
            for node in astutil.walk_scope(fi.node):
                if isinstance(node, ast.Call) and astutil.last_segment(
                        node.func) == "pallas_call":
                    performers.add(id(fi.node))
                    break
        for _ in range(cg.max_depth):
            changed = False
            for fi in allf:
                if id(fi.node) in performers:
                    continue
                if any(id(c.node) in performers
                       for c in cg.callees_invoked(fi)):
                    performers.add(id(fi.node))
                    changed = True
            if not changed:
                break
    ctx["_pallas_performers"] = performers
    return performers


def _spec_call_axes(ctx, ms, node, spec_expr):
    """Resolved axis-name set of one literal ``P(...)`` expression, or
    None when any entry is dynamic (conservative stop)."""
    if not (isinstance(spec_expr, ast.Call) and astutil.last_segment(
            spec_expr.func) in ("P", "PartitionSpec")):
        return None
    axes: set = set()
    for arg in spec_expr.args:
        if isinstance(arg, ast.Constant) and arg.value is None:
            continue
        got = _str_values_at(ctx, ms, node, arg)
        if got is None:
            return None
        axes.update(got)
    return axes


def _check_pallas_shard_map(ctx, mod: Module, ms) -> list:
    """SHARD05 half 2 — a ``shard_map`` whose wrapped callee transitively
    reaches a ``pallas_call`` must carry CONSISTENT specs: every axis its
    literal ``out_specs`` shard must appear in some ``in_specs`` entry. A
    Pallas kernel is shard-local — it runs no collectives — so an output
    sharded over an axis no input is sharded over would fabricate data the
    local kernel cannot produce (each shard would emit a *different* block
    the spec claims partitions one array). Non-literal specs or an
    unresolved callee are the documented conservative stop."""
    out: list = []
    cg = ctx.get("callgraph")
    if cg is None:
        return out
    performers = _pallas_performers(ctx)
    if not performers:
        return out
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and astutil.last_segment(
                node.func) == "shard_map" and node.args):
            continue
        fn_expr = node.args[0]
        if isinstance(fn_expr, ast.Call) and astutil.last_segment(
                fn_expr.func) == "partial" and fn_expr.args:
            fn_expr = fn_expr.args[0]
        funcs = cg.resolve_expr_funcs(ms, fn_expr, at=node)
        if not funcs or not any(id(f.node) in performers for f in funcs):
            continue
        in_specs = out_specs = None
        for kw in node.keywords:
            if kw.arg == "in_specs":
                in_specs = kw.value
            elif kw.arg == "out_specs":
                out_specs = kw.value
        if in_specs is None or out_specs is None:
            continue
        in_items = (list(in_specs.elts)
                    if isinstance(in_specs, (ast.Tuple, ast.List))
                    else [in_specs])
        out_items = (list(out_specs.elts)
                     if isinstance(out_specs, (ast.Tuple, ast.List))
                     else [out_specs])
        in_axes: set = set()
        for item in in_items:
            axes = _spec_call_axes(ctx, ms, node, item)
            if axes is None:
                in_axes = None
                break
            in_axes.update(axes)
        if in_axes is None:
            continue
        for item in out_items:
            axes = _spec_call_axes(ctx, ms, node, item)
            if axes is None:
                continue
            phantom = axes - in_axes
            if phantom:
                out.append(finding(
                    mod, "SHARD05", node.lineno, node.col_offset,
                    f"shard_map wraps a pallas_call-performing kernel "
                    f"('{funcs[0].label}') with out_specs sharding "
                    f"{sorted(phantom)} that no in_spec shards — a "
                    f"shard-local kernel runs no collectives and cannot "
                    f"manufacture that partitioning; each shard would "
                    f"emit a different block the spec claims tiles one "
                    f"array"))
    return out


def _outermost_functions(tree: ast.AST) -> list:
    """Every def not nested inside another def. SHARD04 scopes its pairing
    check to these WITH their nested defs included: step builders close
    gather/scatter helpers over the builder's axis, so the innermost-def
    scope would never see both halves of the pair."""
    funcs: list = []

    def visit(node, in_func: bool) -> None:
        for child in ast.iter_child_nodes(node):
            is_fn = isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
            if is_fn and not in_func:
                funcs.append(child)
            visit(child, in_func or is_fn)

    visit(tree, False)
    return funcs


def _check_rs_ag_pairing(ctx, mod: Module, ms) -> list:
    """SHARD04: within one outermost function, a ``psum_scatter`` paired
    with an ``all_gather`` must agree on the mesh axis and on the tensor
    dim (``scatter_dimension`` vs ``axis=``; an absent kwarg is the
    documented default 0). A mismatched pair is the weight-update-sharding
    bug class: grads scattered over one layout, params gathered over
    another — the state silently mis-tiles and trains garbage. Non-literal
    axes/dims (the spec-driven builders) are the conservative stop."""
    out: list = []
    for fn in _outermost_functions(mod.tree):
        rs: list = []
        ag: list = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            seg = astutil.last_segment(node.func)
            if seg not in ("psum_scatter", "all_gather"):
                continue
            axis_expr = None
            if len(node.args) > 1:
                axis_expr = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis_expr = kw.value
            axes = (_str_values_at(ctx, ms, node, axis_expr)
                    if axis_expr is not None else None)
            dim_kw = ("scatter_dimension" if seg == "psum_scatter"
                      else "axis")
            dim: Optional[int] = 0                # the documented default
            for kw in node.keywords:
                if kw.arg == dim_kw:
                    dim = (kw.value.value
                           if isinstance(kw.value, ast.Constant)
                           and isinstance(kw.value.value, int) else None)
            (rs if seg == "psum_scatter" else ag).append(
                (node, frozenset(axes) if axes else None, dim))
        if not rs or not ag:
            continue
        rs_axes = set().union(*[a for _, a, _ in rs if a] or [set()])
        ag_axes = set().union(*[a for _, a, _ in ag if a] or [set()])
        if rs_axes and ag_axes and not (rs_axes & ag_axes):
            node = rs[0][0]
            out.append(finding(
                mod, "SHARD04", node.lineno, node.col_offset,
                f"'{fn.name}' reduce-scatters over axis "
                f"{sorted(rs_axes)} but all-gathers over "
                f"{sorted(ag_axes)} — the scatter/gather round trip "
                f"re-tiles the state inconsistently"))
            continue
        rs_dims = {d for _, a, d in rs if d is not None and a}
        ag_dims = {d for _, a, d in ag if d is not None and a}
        if rs_axes and rs_axes == ag_axes and len(rs_dims) == 1 \
                and len(ag_dims) == 1 and rs_dims != ag_dims:
            node = rs[0][0]
            out.append(finding(
                mod, "SHARD04", node.lineno, node.col_offset,
                f"'{fn.name}' scatters dim {sorted(rs_dims)[0]} but "
                f"gathers dim {sorted(ag_dims)[0]} over the same axis "
                f"{sorted(rs_axes)} — the shard blocks come back "
                f"transposed against the cut"))
    return out


def _fn_arity(fn: ast.AST) -> tuple[int, int, bool]:
    """(required, total, has_vararg) positional arity of a def/lambda."""
    a = fn.args
    total = len(a.posonlyargs) + len(a.args)
    return total - len(a.defaults), total, a.vararg is not None


def _check_shard_map(ctx, mod: Module, ms, node: ast.Call) -> list:
    out: list = []
    cg = ctx.get("callgraph")
    if cg is None:
        return out
    in_specs = out_specs = None
    for kw in node.keywords:
        if kw.arg == "in_specs":
            in_specs = kw.value
        elif kw.arg == "out_specs":
            out_specs = kw.value
    fn_expr = node.args[0]
    nbound, kwbound = 0, False
    if isinstance(fn_expr, ast.Call) \
            and astutil.last_segment(fn_expr.func) == "partial" \
            and fn_expr.args:
        nbound = len(fn_expr.args) - 1
        kwbound = bool(fn_expr.keywords)
        fn_expr = fn_expr.args[0]
    funcs = cg.resolve_expr_funcs(ms, fn_expr, at=node)
    if not funcs or kwbound:
        return out                        # dynamic callee / kw-bound partial
    if isinstance(in_specs, (ast.Tuple, ast.List)):
        n_in = len(in_specs.elts)
        fits = []
        for fi in funcs:
            req, total, vararg = _fn_arity(fi.node)
            req, total = max(0, req - nbound), total - nbound
            fits.append(req <= n_in and (vararg or n_in <= total))
        if fits and not any(fits):
            req, total, vararg = _fn_arity(funcs[0].node)
            out.append(finding(
                mod, "SHARD02", node.lineno, node.col_offset,
                f"in_specs has {n_in} entr{'y' if n_in == 1 else 'ies'} "
                f"but '{funcs[0].label}' takes "
                f"{max(0, req - nbound)}.."
                f"{'*' if vararg else total - nbound} positional "
                f"argument(s)"
                f"{f' after {nbound} partial-bound' if nbound else ''} — "
                f"the spec tuple cannot match the wrapped function and "
                f"fails when the step first traces"))
    if isinstance(out_specs, (ast.Tuple, ast.List)) and len(funcs) == 1 \
            and not isinstance(funcs[0].node, ast.Lambda):
        n_out = len(out_specs.elts)
        n_rets, lens, all_tuples = astutil.return_tuple_info(funcs[0].node)
        if n_rets and all_tuples and len(lens) == 1 and n_out not in lens:
            out.append(finding(
                mod, "SHARD02", node.lineno, node.col_offset,
                f"out_specs has {n_out} entries but every return of "
                f"'{funcs[0].label}' produces a {lens[0]}-tuple — the "
                f"spec tuple cannot match the wrapped function's output"))
    return out
