"""DONATE01 — donation safety.

``jax.jit(f, donate_argnums=(0,))`` lets XLA alias the argument's buffer
into the output: after the call, the donor array is DEAD. Reading it again
returns garbage (or, on the jaxlib this repo's seed bug hit, corrupts the
heap — the ``TPUDIST_NO_DONATE`` escape hatch exists because of exactly
this). jax only errors on *re-donation*; a plain read of a donated buffer
is silent.

Statically tracked, per module:

- donated callables: ``name = jax.jit(f, donate_argnums=…)`` /
  ``donate_argnames=…`` and this repo's choke point
  ``name = donated_jit(f)`` (default ``(0,)``) — including method-attached
  ``self.step = …`` forms, matched by their dotted source text;
- at each call of a donated callable, the argument expressions in donated
  positions (simple names/attributes only);
- the canonical safe shape ``state = step(state, …)`` (the donor rebound
  from the call's own result) is recognized;
- any later *read* of a donated name in the same function, with no
  intervening rebind, is the finding.

CROSS-MODULE donation (the seed-bug's real shape — ``train.py`` builds the
donated step, ``trainer.py`` calls it) resolves through the call graph:
a call to a function that *returns* a donated jit (``make_train_step`` →
``donated_jit(sharded)``) marks its assignment target donated with the
factory's recorded positions; the same read-after-donate scan then applies
in the consumer module. A factory the symbol table cannot resolve is the
documented conservative stop.

Flow is approximated by line order within one function — branchy
counter-examples exist, which is why the pragma carries a reason.
"""

from __future__ import annotations

import ast

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding


def _targets_of(node: ast.AST, parents: dict) -> list[str]:
    """Dotted names this call's result is assigned to (tuple targets
    flattened): ``self.state, metrics = step(...)`` → ['self.state',
    'metrics']."""
    parent = parents.get(node)
    while isinstance(parent, (ast.Starred,)):
        parent = parents.get(parent)
    if not isinstance(parent, ast.Assign):
        # walrus / annassign
        if isinstance(parent, ast.NamedExpr):
            d = astutil.dotted(parent.target)
            return [d] if d else []
        if isinstance(parent, ast.AnnAssign) and parent.value is node:
            d = astutil.dotted(parent.target)
            return [d] if d else []
        return []
    if parent.value is not node:
        return []
    out = []
    for tgt in parent.targets:
        elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
        for e in elts:
            d = astutil.dotted(e)
            if d:
                out.append(d)
    return out


def _scan_scope(mod: Module, scope_body: list, donated: dict,
                parents: dict, out: list) -> None:
    """One function scope: find calls of donated callables, then reads of
    donated names after the call with no intervening rebind."""
    nodes = list(astutil.walk_scope(list(scope_body)))
    # (donated dotted name, donation line, callee, call-subtree node ids —
    # reads inside the donating call itself are the donation, not a bug)
    donations: list[tuple[str, int, str, set[int]]] = []
    stores: list[tuple[str, int]] = []
    reads: list[tuple[str, int, ast.AST]] = []
    for node in nodes:
        if isinstance(node, ast.Call):
            callee = astutil.dotted(node.func)
            if callee in donated:
                rebound = set(_targets_of(node, parents))
                own = {id(n) for n in ast.walk(node)}
                for pos in donated[callee]:
                    arg = None
                    if isinstance(pos, int) and pos < len(node.args):
                        arg = node.args[pos]
                    elif isinstance(pos, str):
                        for kw in node.keywords:
                            if kw.arg == pos:
                                arg = kw.value
                    if arg is None or not isinstance(
                            arg, (ast.Name, ast.Attribute)):
                        continue
                    d = astutil.dotted(arg)
                    if d is None or d in rebound:
                        continue          # state = step(state, …): safe
                    donations.append((d, node.lineno, callee, own))
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = astutil.dotted(node)
            if d is None:
                continue
            if isinstance(node.ctx, ast.Store):
                stores.append((d, node.lineno))
            elif isinstance(node.ctx, ast.Load):
                reads.append((d, node.lineno, node))
    for dname, dline, callee, own in donations:
        flagged = None
        for rname, rline, rnode in sorted(reads, key=lambda r: r[1]):
            if rname != dname or rline < dline or id(rnode) in own:
                continue
            if any(sname == dname and dline < sline <= rline
                   for sname, sline in stores):
                continue                  # rebound before this read
            flagged = (rline, rnode)
            break                         # first read is the actionable one
        if flagged:
            rline, rnode = flagged
            out.append(finding(
                mod, "DONATE01", rline, rnode.col_offset,
                f"'{dname}' was donated to '{callee}' at line {dline} "
                f"(donate_argnums) — its buffer is aliased away and this "
                f"read sees garbage; rebind it from the call's result or "
                f"drop the donation"))


def check(ctx: dict, mod: Module) -> list:
    out: list = []
    parents = astutil.parent_map(mod.tree)
    cg = ctx.get("callgraph")
    symtab = ctx.get("symtab")
    factories = ctx.get("donated_factories") or {}
    ms = symtab.module_for(mod) if symtab else None
    # Pass 1: module-wide map of donated callables by dotted target name
    # ("step", "self.train_step") → donated positions. Direct jit
    # constructions AND calls of cross-module donated factories both count.
    donated: dict[str, tuple] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            pos = astutil.donated_positions(node)
            if pos is None and cg is not None and ms is not None \
                    and factories:
                cls_node = astutil.enclosing(node, parents, (ast.ClassDef,))
                fn = astutil.enclosing(node, parents, astutil.FUNC_NODES)
                for fi in cg.resolve_invoked(
                        ms, node,
                        cls_node.name if isinstance(cls_node, ast.ClassDef)
                        else None, fn):
                    fac = factories.get(id(fi.node))
                    if fac is not None:
                        pos = fac[1]
                        break
            if pos:
                for tgt in _targets_of(node, parents):
                    donated[tgt] = pos
    if not donated:
        return out
    # Pass 2: every function scope (and the module scope) in the file.
    _scan_scope(mod, mod.tree.body, donated, parents, out)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_scope(mod, node.body, donated, parents, out)
    return out
