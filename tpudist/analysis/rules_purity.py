"""TRACE01/TRACE02 — trace purity.

A function reachable from ``jax.jit`` / ``shard_map`` / ``pallas_call``
(astutil.TraceIndex) executes its Python body ONCE, with tracers, at trace
time. Host-side effects there are one of two bugs:

- a **frozen constant**: ``time.time()`` / ``np.random.*`` evaluate during
  tracing and bake a single value into every execution of the compiled
  program (the recompile-less twin of the hazard — the run LOOKS fine and
  is silently wrong);
- a **trace-time crash or sync**: ``.item()`` / ``jax.device_get`` on a
  tracer raise ``ConcretizationTypeError`` at best, or force a blocking
  device sync when fed a committed array closed over from outside;
- ``print`` runs at trace time only (use ``jax.debug.print``);
- ``global``/``nonlocal`` rebinding (TRACE02) mutates closure state once
  per *compile*, not once per step — the classic "my counter only
  advanced twice" bug.

Functions passed to ``jax.pure_callback``/``io_callback``/``debug.callback``
are host functions by contract and exempt (astutil skips those edges).
"""

from __future__ import annotations

import ast
from typing import Optional

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

_CLOCKS = {"time", "perf_counter", "monotonic", "process_time", "sleep",
           "perf_counter_ns", "monotonic_ns", "time_ns"}
_STDLIB_RANDOM = {"random", "randint", "uniform", "choice", "shuffle",
                  "seed", "sample", "randrange", "gauss"}


def _host_effect(call: ast.Call) -> Optional[str]:
    d = astutil.dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    if d == "print":
        return ("print() runs at trace time only — use jax.debug.print "
                "for in-program output")
    if len(parts) == 2 and parts[0] == "time" and parts[1] in _CLOCKS:
        return (f"{d}() inside traced code freezes one host-clock reading "
                f"into the compiled program — time outside the jit")
    if len(parts) >= 3 and parts[0] in ("np", "numpy") \
            and parts[1] == "random":
        return (f"{d}() draws on the HOST RNG at trace time (one frozen "
                f"draw per compile, rank-divergent under SPMD) — use "
                f"jax.random with a threaded key")
    if len(parts) == 2 and parts[0] == "random" \
            and parts[1] in _STDLIB_RANDOM:
        return (f"{d}() draws on the host RNG at trace time — use "
                f"jax.random with a threaded key")
    if parts[-1] == "item" and not call.args and not call.keywords \
            and isinstance(call.func, ast.Attribute):
        return (".item() on a tracer raises ConcretizationTypeError (or "
                "forces a blocking device sync on a closed-over array) — "
                "keep values as arrays inside the program")
    if parts[-1] == "device_get" and parts[0] in ("jax", "device_get"):
        return ("jax.device_get inside traced code forces a host sync at "
                "trace time — fetch results after the step returns")
    return None


def check(ctx: dict, mod: Module) -> list:
    out = []
    # Whole-program reachability when the call graph is available (calls
    # followed across module boundaries, ctx["traced_nodes"]); per-module
    # TraceIndex as the standalone fallback.
    cg = ctx.get("callgraph")
    symtab = ctx.get("symtab")
    traced_ids = ctx.get("traced_nodes")
    ms = symtab.module_for(mod) if symtab else None
    if cg is not None and ms is not None and traced_ids is not None:
        idx = cg.tindex[ms.dotted]
        fns = [f for f in idx.functions if id(f) in traced_ids]
    else:
        idx = astutil.TraceIndex(mod.tree)
        fns = idx.traced_functions()
    for fn in fns:
        for node in astutil.walk_scope(fn):
            if isinstance(node, ast.Call):
                msg = _host_effect(node)
                if msg:
                    out.append(finding(mod, "TRACE01", node.lineno,
                                       node.col_offset, msg))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(node, ast.Global) else "nonlocal"
                out.append(finding(
                    mod, "TRACE02", node.lineno, node.col_offset,
                    f"'{kw} {', '.join(node.names)}' inside traced code — "
                    f"the rebinding happens once per COMPILE, not once per "
                    f"step; thread the value through the function's "
                    f"arguments/returns instead"))
    return out
