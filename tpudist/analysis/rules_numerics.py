"""NUM01 — per-step host syncs in the training hot loop.

The whole point of the deferred/async metric drain (``trainer._MetricDrain``)
and of riding the doctor sentinels on it is that the hot loop never blocks
on a device value: ``float(loss)`` on a freshly-dispatched step's metric
stalls the host on the in-flight program, serializing device and host and
burning the MFU the drain machinery exists to protect. The reference paid
exactly this tax every step (``distributed.py:253-257``: barrier + two
allreduces + blocking ``.item()``), and guard code is the natural place to
silently reintroduce it — "just check the flag" is one ``float()`` away.

NUM01 flags, inside a **hot loop**, the device→host materialization forms:

- ``float(x)`` / ``int(x)`` on a name/attribute/subscript (a constant or
  host-side arithmetic expression is not a sync);
- ``.item()``;
- ``jax.device_get(...)`` / ``np.asarray(...)`` / ``np.array(...)``;
- ``.block_until_ready()``.

A **hot loop** is a ``for``/``while`` loop that iterates the input
pipeline: any loop whose iterator expression mentions an identifier
containing ``loader`` or ``prefetch``, plus every loop inside a function
named ``train_epoch`` or ``validate`` (the trainer's step loops). Nested
function definitions are separate scopes and are not scanned — which is
exactly why the sanctioned sink stays legal: the drain materializes
metrics in ``_MetricDrain._apply``, a method whose entries are at least
``lag`` steps old (their async copies have landed), not inline in the
loop body.

Periodic maintenance OUTSIDE the per-step path (the doctor's every-N-steps
SDC probe, epoch-end flushes) lives in helper methods for the same reason
and is likewise out of scope by construction.
"""

from __future__ import annotations

import ast

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

_HOT_FUNCS = {"train_epoch", "validate"}
_ITER_MARKERS = ("loader", "prefetch")

_MSG = ("per-step host sync in the training hot loop — {what} blocks the "
        "host on the in-flight step's device value, serializing host and "
        "device every step (the reference's distributed.py:253-257 bug). "
        "Route the value through the deferred metric drain "
        "(trainer._MetricDrain; the doctor reads its sentinel flags there) "
        "or move the read to a periodic/epoch-boundary helper")


def _iter_mentions_pipeline(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and any(m in name.lower() for m in _ITER_MARKERS):
            return True
    return False


def _hot_loops(mod: Module):
    """(loop node, reason) for every hot loop in the module."""
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in _HOT_FUNCS:
            for sub in astutil.walk_scope(node):
                if isinstance(sub, (ast.For, ast.While)):
                    out.append(sub)
        elif isinstance(node, ast.For) and _iter_mentions_pipeline(node.iter):
            out.append(node)
    return out


def _loop_body_nodes(loop):
    """Nodes lexically inside the loop body, not descending into nested
    function/class definitions (separate scopes — the drain's sanctioned
    materialization lives in one) and not into the loop's own iterator."""
    stack = list(loop.body) + list(getattr(loop, "orelse", []) or [])
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_metadata(node: ast.expr) -> bool:
    """True for array METADATA reads (``x.shape[0]``, ``x.ndim``) — host
    attributes that never touch device memory."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "dtype"):
            return True
    return False


def _sync_call(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        # Attribute forms work on ANY receiver expression (m["loss"].item()
        # has no dotted name) — match on the attribute alone.
        if call.func.attr == "item" and not call.args and not call.keywords:
            return ".item()"
        if call.func.attr == "block_until_ready":
            return ".block_until_ready()"
    d = astutil.dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    if d in ("float", "int") and len(call.args) == 1 \
            and isinstance(call.args[0], (ast.Name, ast.Attribute,
                                          ast.Subscript)) \
            and not _is_metadata(call.args[0]):
        return f"{d}(...) on a (device-held) value"
    if parts[-1] == "device_get" and parts[0] in ("jax", "device_get"):
        return "jax.device_get(...)"
    if len(parts) == 2 and parts[0] in ("np", "numpy") \
            and parts[1] in ("asarray", "array"):
        return f"{d}(...)"
    return None


def check(ctx: dict, mod: Module) -> list:
    out = []
    seen: set[int] = set()
    for loop in _hot_loops(mod):
        if id(loop) in seen:
            continue
        seen.add(id(loop))
        for node in _loop_body_nodes(loop):
            if not isinstance(node, ast.Call):
                continue
            what = _sync_call(node)
            if what:
                out.append(finding(mod, "NUM01", node.lineno,
                                   node.col_offset, _MSG.format(what=what)))
    return out
