"""PALLAS01 — lazy-Pallas discipline.

PR 5/6's measurement-honesty invariant, made structural: on a CPU host,
``--flash auto`` / ``--fused-bn auto`` must resolve to XLA *without Pallas
ever entering ``sys.modules``* (``__graft_entry__`` dryrun modes 10/11
prove it at runtime by inspecting ``sys.modules``). That only holds if no
module outside ``tpudist/ops/pallas/`` imports Pallas — or anything from
the ``tpudist.ops.pallas`` package — at module level. Kernel access from
dispatch clients, models, and benches is function-local by convention
(``from tpudist.ops.pallas import …`` inside the branch that already
decided to use it); this rule turns the convention into a gate.

``if TYPE_CHECKING:`` imports are exempt (never executed); files under
``tpudist/ops/pallas/`` are the kernel package itself and exempt.
"""

from __future__ import annotations

import ast

from tpudist.analysis import astutil
from tpudist.analysis.core import Module, finding

_EXEMPT_PREFIX = "tpudist/ops/pallas/"


def _resolve_from(node: ast.ImportFrom, relpath: str) -> str:
    """Absolute dotted module path of an ImportFrom, resolving relative
    levels against the importing file's own package — ``from .pallas
    import x`` in tpudist/ops/ must read as tpudist.ops.pallas, or the
    natural relative refactor of a dispatch client evades the gate."""
    if not node.level:
        return node.module or ""
    pkg = relpath.split("/")[:-1]                 # the file's package dirs
    base = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 else pkg
    return ".".join(base + ([node.module] if node.module else []))


def _pallas_target(node: ast.stmt, relpath: str) -> str | None:
    """The offending import path when this statement imports Pallas."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name.startswith("jax.experimental.pallas") \
                    or alias.name.startswith("tpudist.ops.pallas"):
                return alias.name
    elif isinstance(node, ast.ImportFrom):
        m = _resolve_from(node, relpath)
        if m.startswith("jax.experimental.pallas") \
                or m.startswith("tpudist.ops.pallas"):
            return m
        if m in ("jax.experimental", "tpudist.ops"):
            for alias in node.names:
                if alias.name == "pallas":
                    return f"{m}.pallas"
    return None


def check(ctx: dict, mod: Module) -> list:
    if mod.relpath.startswith(_EXEMPT_PREFIX):
        return []
    out = []
    parents = astutil.parent_map(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        target = _pallas_target(node, mod.relpath)
        if target is None:
            continue
        if not astutil.at_module_level(node, parents):
            continue                      # lazy function-local import: fine
        if astutil.under_type_checking(node, parents):
            continue
        out.append(finding(
            mod, "PALLAS01", node.lineno, node.col_offset,
            f"module-level import of '{target}' outside tpudist/ops/pallas/ "
            f"— breaks the 'CPU auto never imports Pallas' honesty "
            f"invariant (dryrun modes 10/11); move the import inside the "
            f"function that already decided to use the kernel"))
    return out
