"""ELASTIC01 — the host-side reshard contract, held structurally.

``tpudist/elastic/reshard.py`` is the elastic plane's cut/merge math:
``cut_state``/``merge_state`` (and the mesh-aware ``cut_state_mesh`` /
``merge_state_mesh``) reassemble checkpoints across topologies on nested
dicts of NUMPY arrays, by contract jax-free — the launcher's jax-free
supervisor image plans reshards, and the round-trip property tests must
run without devices. PR 4 wrote that contract into a docstring ("No jax
imports"); ISSUE 13 makes it a gated rule, because the natural refactor
that breaks it is silent: importing a helper from ``parallel/`` (say,
``zero_full_axis``'s device twin) drags jax into the module, and nothing
fails until the supervisor image can't import the launcher.

The rule fires on:

- any import of ``jax`` (or a ``jax.*`` submodule) anywhere in
  ``elastic/reshard.py`` — module level or function-local: the whole
  module is the host-side surface, and a lazy import reachable from
  ``cut_state``/``merge_state`` still breaks the supervisor image;
- any import (module-level or function-local) of a repo module that
  itself imports jax at module level — the indirect form of the same
  break, resolved through the whole-program symbol table.

Files not named ``elastic/reshard.py`` are out of scope (the rest of the
elastic package may talk to jax; ``membership.py`` stays jax-free via the
launcher's own no-jax test).
"""

from __future__ import annotations

import ast

from tpudist.analysis.core import Module, finding

_TARGET_SUFFIX = "elastic/reshard.py"


def _imported_modules(node: ast.stmt, dotted: str) -> list[str]:
    """Absolute dotted module targets of one import statement (relative
    levels resolved against the importing module's own package)."""
    if isinstance(node, ast.Import):
        return [alias.name for alias in node.names]
    if isinstance(node, ast.ImportFrom):
        if node.level:
            pkg = dotted.split(".")[:-1]
            base = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 \
                else pkg
            mod = ".".join(base + ([node.module] if node.module else []))
        else:
            mod = node.module or ""
        return [mod]
    return []


def _module_imports_jax(symtab, dotted: str) -> bool:
    """True when the analyzed tree's module ``dotted`` imports jax at
    MODULE level (what an importer pays just by importing it)."""
    ms = symtab.mods.get(dotted) if symtab is not None else None
    if ms is None:
        return False
    for stmt in ms.mod.tree.body:
        for tgt in _imported_modules(stmt, ms.dotted):
            if tgt == "jax" or tgt.startswith("jax."):
                return True
    return False


def check(ctx: dict, mod: Module) -> list:
    if not mod.relpath.endswith(_TARGET_SUFFIX):
        return []
    symtab = ctx.get("symtab")
    ms = symtab.module_for(mod) if symtab is not None else None
    dotted = ms.dotted if ms is not None else \
        mod.relpath[:-3].replace("/", ".")
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        for tgt in _imported_modules(node, dotted):
            if tgt == "jax" or tgt.startswith("jax."):
                out.append(finding(
                    mod, "ELASTIC01", node.lineno, node.col_offset,
                    f"'{tgt}' imported in {_TARGET_SUFFIX} — the host-side "
                    f"cut/merge contract is numpy-only (the jax-free "
                    f"launcher/supervisor image plans reshards; the "
                    f"round-trip tests run deviceless). Put device-facing "
                    f"logic in parallel/plane.py and hand this module "
                    f"plain data"))
            elif symtab is not None and _module_imports_jax(symtab, tgt):
                out.append(finding(
                    mod, "ELASTIC01", node.lineno, node.col_offset,
                    f"'{tgt}' imports jax at module level, so importing "
                    f"it from {_TARGET_SUFFIX} drags jax into the "
                    f"host-side cut/merge surface — keep the dependency "
                    f"one-way (plane -> reshard, never back)"))
    return out
