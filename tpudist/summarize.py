"""MFU-budget report from a run dir's telemetry (``python -m tpudist.summarize
<rundir>``).

Answers the two questions console meters cannot (VERDICT #4): *where does
the missing MFU go* and *which rank is slow*. Reads every
``events.*.jsonl`` a run (or its launcher) wrote — see ``tpudist/telemetry.py``
for the schema — and prints:

- run **goodput** (productive step time ÷ wall time) with the non-productive
  remainder attributed to init / compile / checkpoint / eval;
- **MFU** from the compiled step's cost-analysis FLOPs against the device
  peak (``--peak-flops`` or ``TPUDIST_PEAK_FLOPS`` override the table —
  required on backends with no public spec, e.g. CPU);
- the per-step **time budget** (data wait / host→device / device compute /
  metric drain / other-host, p50 and p95);
- per-rank step-time table with straggler flags, plus the fault /
  preemption / restart timeline.

``analyze()`` is a pure function of the event list so the goodput/MFU math
is unit-testable against synthetic timelines (``tests/test_telemetry.py``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

from tpudist.telemetry import (find_stragglers, percentile,
                               resolve_peak_flops, resolve_peak_hbm,
                               validate_event)


def load_events(rundir: str, strict: bool = False) -> list[dict]:
    """Every event from every ``events.*.jsonl`` in ``rundir``, time-sorted.
    The glob also picks up size-rotated segments (``events.<rank>.1.jsonl``,
    from ``--telemetry-max-mb``) — time-sorting reassembles the stream.
    Malformed lines are counted and skipped (a rank killed mid-write leaves
    at most one torn final line) unless ``strict``."""
    events: list[dict] = []
    bad = 0
    for path in sorted(glob.glob(os.path.join(rundir, "events.*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                    validate_event(ev)
                except (ValueError, TypeError) as e:
                    if strict:
                        raise ValueError(f"{path}: {e}") from e
                    bad += 1
                    continue
                events.append(ev)
    if bad:
        print(f"[summarize] skipped {bad} malformed event line(s)",
              file=sys.stderr)
    events.sort(key=lambda e: e["t"])
    return events


def _pcts(xs: list[float]) -> Optional[dict]:
    if not xs:
        return None
    return {"p50": percentile(xs, 50), "p95": percentile(xs, 95),
            "total": sum(xs)}


def analyze(events: list[dict],
            peak_flops: Optional[float] = None) -> dict:
    """Pure goodput/MFU/budget accounting over a telemetry event list."""
    steps = [e for e in events if e["type"] == "step"]
    run_starts = [e for e in events if e["type"] == "run_start"]
    run_ends = [e for e in events if e["type"] == "run_end"]
    programs = [e for e in events if e["type"] == "program"]
    faults = [e for e in events if e["type"] in
              ("fault", "preempt", "rank_exit", "restart", "straggler",
               "eviction", "collective_deadline")]
    # -- topology timeline (elastic plane): every launch attempt's world,
    # gang reformations, and cross-world reshards, in time order ----------
    topology = []
    for e in events:
        if e["type"] == "launcher_start":
            topology.append({"t": e["t"], "kind": "launch",
                             "attempt": e["attempt"],
                             "world": e.get("nprocs"),
                             "mesh": e.get("mesh", "")})
        elif e["type"] == "topology_change":
            topology.append({"t": e["t"],
                             "kind": ("scale_up"
                                      if e.get("mesh_action") == "scale_up"
                                      else "reform"),
                             "attempt": e["attempt"],
                             "from_world": e["from_world"],
                             "to_world": e["to_world"],
                             "from_mesh": e.get("from_mesh", ""),
                             "to_mesh": e.get("to_mesh", ""),
                             "mesh_action": e.get("mesh_action", ""),
                             "lost_ranks": e.get("lost_ranks", "")})
        elif e["type"] == "eviction":
            topology.append({"t": e["t"], "kind": "evict",
                             "attempt": e["attempt"],
                             "rank": e.get("straggler_rank"),
                             "windows": e.get("windows")})
        elif e["type"] == "reshard":
            topology.append({"t": e["t"], "kind": "reshard",
                             "attempt": e["attempt"], "rank": e["rank"],
                             "from_world": e["from_world"],
                             "to_world": e["to_world"],
                             "detail": e.get("detail", "")})
    ckpts = [e for e in events if e["type"] in
             ("checkpoint_save", "checkpoint_restore")]
    attempts = sorted({e["attempt"] for e in events})

    out: dict = {
        "n_events": len(events),
        "n_steps": len(steps),
        "ranks": sorted({e["rank"] for e in events if e["rank"] >= 0}),
        "attempts": attempts,
        "arch": run_starts[0].get("arch") if run_starts else None,
        "platform": run_starts[0].get("platform") if run_starts else None,
        "device_kind": run_starts[0].get("device_kind") if run_starts
        else None,
        "n_faults": len([e for e in faults if e["type"] == "fault"]),
        "faults": faults,
        "checkpoint_events": len(ckpts),
        "topology": topology,
    }

    # -- step-time budget (one rank is representative under lockstep SPMD;
    # mixing ranks would double-count the same wall time — the same scoping
    # applies to checkpoint cost: collective saves emit one event PER rank
    # for the same wall-clock save) ----------------------------------------
    r0 = min(out["ranks"]) if out["ranks"] else 0
    r0_steps = [e for e in steps if e["rank"] == r0]
    out["checkpoint_s"] = sum(e["seconds"] for e in ckpts
                              if e["rank"] == r0)
    # First-dispatch compile rides inside that step's step_s (the step
    # event has no compile field; the paired compile event carries it) —
    # subtract it wherever productive time is reconstructed from raw
    # steps, and EXCLUDE those steps from the steady-state percentiles
    # (one 6s compile step among ten 0.5s steps would otherwise put the
    # compile into the "device compute" p95 and deflate MFU).
    r0_compile_s = sum(e["seconds"] for e in events
                       if e["type"] == "compile" and e["rank"] == r0
                       and e.get("phase") == "train_step")
    compile_step_nums = {e["step"] for e in events
                         if e["type"] == "compile" and e["rank"] == r0
                         and e.get("phase") == "train_step" and "step" in e}
    steady_steps = [e for e in r0_steps
                    if e["step"] not in compile_step_nums] or r0_steps
    budget = {}
    for key in ("data_s", "h2d_s", "compute_s", "drain_s", "step_s"):
        budget[key] = _pcts([e[key] for e in steady_steps])
    # Overlap-aware phase accounting (device prefetch): ``prefetch_s`` is
    # host time spent staging the NEXT batch while THIS step's compute was
    # in flight. It is a disjoint host interval like the others, so it gets
    # its own bucket AND is subtracted from the other-host residue — the
    # serial buckets then hold only exposed time, and the whole budget sums
    # to ≤ step_s by construction (no phase is ever counted twice).
    has_prefetch = any("prefetch_s" in e for e in steady_steps)
    if has_prefetch:
        budget["prefetch_s"] = _pcts([e.get("prefetch_s", 0.0)
                                      for e in steady_steps])
    # Async metric drain rides the same overlapped contract (its own
    # bucket, subtracted from the other-host residue — never counted
    # beside the serial drain_s it replaced).
    if any("drain_ovl_s" in e for e in steady_steps):
        budget["drain_ovl_s"] = _pcts([e.get("drain_ovl_s", 0.0)
                                       for e in steady_steps])
    other = [max(0.0, e["step_s"] - e["data_s"] - e["h2d_s"] - e["compute_s"]
                 - e["drain_s"] - e.get("prefetch_s", 0.0)
                 - e.get("drain_ovl_s", 0.0))
             for e in steady_steps]
    budget["other_host_s"] = _pcts(other)
    out["budget"] = budget

    # -- persistent-compile-cache provenance (--compile-cache): stamped on
    # compile events; surfaces beside the compile bucket so a warm restart
    # is attributable as cache-hit seconds, not a real compile -----------
    out["compile_cache"] = next(
        (e["cache"] for e in events
         if e["type"] == "compile" and e.get("cache")), None)

    # -- serving plane (tpudist/serve/): request latency/throughput and
    # the AOT cold-start numbers, from the serve event stream ------------
    reqs = [e for e in events if e["type"] == "request"]
    batches = [e for e in events if e["type"] == "serve_batch"]
    serve_start = next(
        (e for e in reversed(events) if e["type"] == "serve_start"), None)
    if serve_start is not None or reqs:
        # Errored requests (error=1) count toward traffic but not the
        # latency percentiles — p50/p99 is service latency.
        lat = [e["latency_s"] for e in reqs
               if isinstance(e.get("latency_s"), (int, float))
               and not e.get("error")]
        span = (reqs[-1]["t"] - reqs[0]["t"]) if len(reqs) > 1 else 0.0
        occ = [e["n_valid"] / e["bucket"] for e in batches
               if e.get("bucket")]
        aot_compiles = [e for e in events if e["type"] == "compile"
                        and e.get("phase") == "serve_aot"]
        out["serving"] = {
            "n_requests": len(reqs),
            "n_errors": len([e for e in reqs if e.get("error")]),
            "n_batches": len(batches),
            "latency_p50_ms": (round(percentile(lat, 50) * 1e3, 3)
                               if lat else None),
            "latency_p99_ms": (round(percentile(lat, 99) * 1e3, 3)
                               if lat else None),
            "req_per_s": (round(len(reqs) / span, 2) if span > 0 else None),
            "occupancy_p50": (round(percentile(occ, 50), 4)
                              if occ else None),
            "aot_s": (serve_start or {}).get("aot_s"),
            "aot_compile_s": (serve_start or {}).get("aot_compile_s"),
            "cache": (serve_start or {}).get("cache"),
            "n_buckets": (serve_start or {}).get("n_buckets"),
            "buckets": (serve_start or {}).get("buckets"),
            "aot_compiles": len(aot_compiles),
            # The zero-recompile proof: every compile event in a serving
            # run must be an AOT bucket compile (or the trainer-side
            # phases of a mixed run dir) — steady-state traffic through
            # the bucketed queue never compiles.
            "non_aot_compiles": len(
                [e for e in events if e["type"] == "compile"
                 and e.get("phase") not in ("serve_aot",)]),
        }
    else:
        out["serving"] = None

    # -- doctor plane (tpudist/doctor/): every intervention and every SDC
    # probe, so a run where weights were un-written says so ---------------
    doctor_evs = [e for e in events if e["type"] == "doctor"]
    sdc_evs = [e for e in events if e["type"] == "sdc_probe"]
    if doctor_evs or sdc_evs:
        by_action: dict = {}
        for e in doctor_evs:
            a_ = str(e.get("action"))
            by_action[a_] = by_action.get(a_, 0) + 1
        out["doctor"] = {
            "interventions": len(doctor_evs),
            "by_action": by_action,
            "probes": len(sdc_evs),
            "divergent_probes": len([e for e in sdc_evs
                                     if e.get("divergent") or e.get("tie")]),
            "events": doctor_evs,
        }
    else:
        out["doctor"] = None

    # -- perf-CI console (tpudist/perfci.py): unattended bench-matrix runs
    # emit one perfci_run event each into the report dir, so summarizing
    # benchmarks/results/ yields the trend-gate history -------------------
    perfci_evs = [e for e in events if e["type"] == "perfci_run"]
    if perfci_evs:
        out["perfci"] = {
            "runs": len(perfci_evs),
            "regressions": sum(int(e.get("regressions") or 0)
                               for e in perfci_evs),
            "events": perfci_evs,
        }
    else:
        out["perfci"] = None

    # -- blackbox plane (tpudist/blackbox.py): every incident trigger, by
    # class, with the capture-vs-cooldown split; bundle inventory comes
    # from the run dir at render time (analyze stays pure on events) ------
    incident_evs = [e for e in events if e["type"] == "incident"]
    if incident_evs:
        by_trigger: dict = {}
        for e in incident_evs:
            tr = str(e.get("trigger"))
            by_trigger[tr] = by_trigger.get(tr, 0) + 1
        out["incidents"] = {
            "triggers": len(incident_evs),
            "by_trigger": by_trigger,
            "captures": len([e for e in incident_evs if e.get("captured")]),
            "suppressed": len([e for e in incident_evs
                               if not e.get("captured")]),
            "events": incident_evs,
        }
    else:
        out["incidents"] = None

    # -- goodput -----------------------------------------------------------
    # Per-attempt run_end events carry the trainer's own accounting; prefer
    # the primary rank's LAST one. Across restarts, also compute the
    # whole-job view: everything from the first run_start to the last
    # run_end, so the crashed attempt's lost work shows up as lost goodput.
    r0_end = next((e for e in reversed(run_ends) if e["rank"] == r0), None)
    out["run_end"] = r0_end
    if r0_end is not None:
        out["goodput"] = r0_end["goodput"]
        out["wall_s"] = r0_end["wall_s"]
        out["productive_s"] = r0_end["productive_s"]
    elif r0_steps:
        # Crashed run (no run_end): reconstruct from the step stream. The
        # first step's step_s holds the XLA compile — subtract the paired
        # compile events or a 60s-compile/10s-train crash reads as ~1.0.
        wall = max(1e-9, r0_steps[-1]["t"] - (run_starts[0]["t"]
                                              if run_starts
                                              else r0_steps[0]["t"]))
        productive = max(0.0, sum(e["step_s"] for e in r0_steps)
                         - r0_compile_s)
        out["wall_s"] = wall
        out["productive_s"] = productive
        out["goodput"] = min(1.0, productive / wall)
    else:
        out["goodput"] = None
    if len(attempts) > 1 and run_starts and (run_ends or steps):
        t_first = run_starts[0]["t"]
        # run_ends AND steps: a final attempt that died without a run_end
        # (os._exit, OOM) still contributed steps whose productive time is
        # summed below — its wall must be in the denominator too.
        t_last = max(e["t"] for e in run_ends + steps)
        wall_all = max(1e-9, t_last - t_first)
        productive_all = max(0.0, sum(e["step_s"] for e in steps
                                      if e["rank"] == r0) - r0_compile_s)
        out["goodput_incl_restarts"] = min(1.0, productive_all / wall_all)
        out["wall_incl_restarts_s"] = wall_all

    # -- MFU ---------------------------------------------------------------
    flops = next((e["flops_per_step"] for e in reversed(programs)
                  if e.get("flops_per_step")), None)
    out["flops_per_step"] = flops
    if peak_flops is None:
        peak_flops = resolve_peak_flops(out["device_kind"])
    out["peak_flops"] = peak_flops
    out["mfu"] = None
    if flops and peak_flops and r0_steps:
        # Steady-state MFU: FLOPs per step over the p50 step time (the mean
        # would let one compile-polluted or paused step poison the number).
        out["mfu"] = round(flops / budget["step_s"]["p50"] / peak_flops, 4)
        step_mfus = [e["mfu"] for e in r0_steps if "mfu" in e]
        if step_mfus:
            out["mfu_p50"] = round(percentile(step_mfus, 50), 4)

    # -- XLA program introspection (tpudist/obs/xla_introspect.py fields
    # riding the cost_analysis compile event) ------------------------------
    xla = None
    from tpudist.obs.xla_introspect import EVENT_FIELDS
    xla_keys = EVENT_FIELDS + ("all_reduce_count", "all_reduce_bytes")
    for e in reversed(events):
        if e["type"] == "compile" and e.get("phase") == "cost_analysis" \
                and any(k in e for k in ("hbm_compiled_bytes",
                                         "collective_ops", "bytes_accessed")):
            xla = {k: e[k] for k in xla_keys if k in e}
            break
    out["xla"] = xla

    # -- kernel dispatch (the two ops/dispatch clients): which kernels
    # --flash and --fused-bn resolved to, on what evidence — the newest
    # decision of each wins ------------------------------------------------
    out["attention_dispatch"] = next(
        (e for e in reversed(events) if e["type"] == "attention_dispatch"),
        None)
    out["fused_norm_dispatch"] = next(
        (e for e in reversed(events) if e["type"] == "fused_norm_dispatch"),
        None)
    out["comm_dispatch"] = next(
        (e for e in reversed(events) if e["type"] == "comm_dispatch"), None)
    # Compression ratio (--compress-grads): the dispatch event's
    # dense-equivalent gradient payload held against the census's ACTUAL
    # per-step collective bytes — the before/after meter for ROADMAP item
    # 2's "shrink what crosses the interconnect".
    cd = out["comm_dispatch"]
    if cd and isinstance(cd.get("dense_bytes"), (int, float)) \
            and cd["dense_bytes"] > 0 and xla \
            and isinstance(xla.get("collective_bytes_per_step"),
                           (int, float)) \
            and xla["collective_bytes_per_step"] > 0:
        ratio = {"dense_bytes": cd["dense_bytes"],
                 "actual_bytes": xla["collective_bytes_per_step"],
                 "payload_ratio": round(
                     cd["dense_bytes"] / xla["collective_bytes_per_step"],
                     3)}
        w = cd.get("world")
        if isinstance(xla.get("collective_link_bytes"), (int, float)) \
                and xla["collective_link_bytes"] > 0 \
                and isinstance(w, (int, float)) and w and w > 1:
            # Dense baseline wire traffic: a ring all-reduce of the f32
            # gradients moves 2(W-1)/W x their bytes.
            dense_link = 2.0 * (w - 1) / w * cd["dense_bytes"]
            ratio["link_bytes"] = xla["collective_link_bytes"]
            ratio["link_ratio"] = round(
                dense_link / xla["collective_link_bytes"], 3)
        out["compression"] = ratio
    else:
        out["compression"] = None

    # -- op-category time attribution (first bite at VERDICT r5 weak #4:
    # where the non-MXU time goes). Roofline lower bounds from the compiled
    # program's FLOPs/bytes against device peaks, held against the measured
    # steady-state device-compute p50: the residual is host/pipeline/non-
    # roofline overhead neither bound explains. ---------------------------
    attr = None
    if xla and xla.get("flops") and peak_flops and budget.get("compute_s"):
        attr = {"mxu_ms_lb": round(xla["flops"] / peak_flops * 1e3, 3)}
        peak_hbm = resolve_peak_hbm(out["device_kind"])
        if xla.get("bytes_accessed") and peak_hbm:
            attr["hbm_ms_lb"] = round(
                xla["bytes_accessed"] / peak_hbm * 1e3, 3)
            attr["peak_hbm_bps"] = peak_hbm
        compute_ms = budget["compute_s"]["p50"] * 1e3
        attr["compute_p50_ms"] = round(compute_ms, 3)
        bound = max(attr["mxu_ms_lb"], attr.get("hbm_ms_lb", 0.0))
        attr["bound"] = ("mxu" if attr["mxu_ms_lb"]
                         >= attr.get("hbm_ms_lb", 0.0) else "hbm")
        attr["residual_ms"] = round(max(0.0, compute_ms - bound), 3)
        cats = {k[4:]: xla[k] for k in xla
                if k.startswith("ops_") and isinstance(xla[k], (int, float))}
        if cats:
            attr["op_counts"] = cats
    out["op_attribution"] = attr

    # -- per-rank straggler view ------------------------------------------
    per_rank = {}
    for rank in out["ranks"]:
        rs = [e for e in steps if e["rank"] == rank]
        if not rs:
            continue
        host = [max(0.0, e["step_s"] - e["compute_s"]) for e in rs]
        per_rank[rank] = {
            "rank": rank, "n": len(rs),
            "step_p50": round(percentile([e["step_s"] for e in rs], 50), 6),
            "host_p50": round(percentile(host, 50), 6),
            "updated_at": rs[-1]["t"], "attempt": rs[-1]["attempt"],
        }
    out["per_rank"] = per_rank
    out["stragglers"] = find_stragglers(
        per_rank, attempt=None, max_age_s=float("inf"))
    return out


def _ms(v: Optional[float]) -> str:
    return f"{v * 1e3:8.1f}" if v is not None else "       -"


def format_report(a: dict, rundir: str = "") -> str:
    L = [f"tpudist run summary — {rundir or '<events>'}"]
    L.append(f"  arch {a['arch'] or '?'} on {a['platform'] or '?'} "
             f"({a['device_kind'] or 'unknown device'}); "
             f"ranks {a['ranks'] or '[]'}; attempts {a['attempts']}; "
             f"{a['n_steps']} step events")
    # goodput budget
    if a.get("goodput") is not None:
        L.append(f"  goodput {a['goodput']:.3f}  "
                 f"(productive {a['productive_s']:.2f}s / "
                 f"wall {a['wall_s']:.2f}s)")
        re = a.get("run_end") or {}
        for name, key in (("init", "init_s"), ("compile", "compile_s"),
                          ("checkpoint", "checkpoint_s"), ("eval", "eval_s")):
            if re.get(key):
                note = ""
                if key == "compile_s" and a.get("compile_cache"):
                    note = f", persistent cache {a['compile_cache']}"
                L.append(f"    {name:<11}{re[key]:9.2f}s "
                         f"({re[key] / max(a['wall_s'], 1e-9):6.1%} of wall"
                         f"{note})")
        if a.get("goodput_incl_restarts") is not None:
            L.append(f"  goodput incl. restarts "
                     f"{a['goodput_incl_restarts']:.3f} "
                     f"(wall {a['wall_incl_restarts_s']:.2f}s across "
                     f"{len(a['attempts'])} attempts)")
    else:
        L.append("  goodput: n/a (no step events)")
    # MFU
    if a.get("mfu") is not None:
        L.append(f"  MFU {a['mfu']:.4f}  (flops/step "
                 f"{a['flops_per_step']:.3e} per device, peak "
                 f"{a['peak_flops']:.3e} FLOP/s)")
        if a["mfu"] > 1.0:
            # Same trap bench.py guards: async dispatch returned at enqueue
            # rather than execution-complete, so step_s under-measured.
            L.append("  WARNING: MFU > 1 is physically impossible — the "
                     "host-side step timing did not capture real device "
                     "execution (async dispatch without backpressure); "
                     "treat the step breakdown as dispatch-side only")
    elif a.get("flops_per_step"):
        L.append(f"  MFU: n/a — no peak FLOP/s known for "
                 f"'{a['device_kind']}' (flops/step "
                 f"{a['flops_per_step']:.3e}; set TPUDIST_PEAK_FLOPS or "
                 f"--peak-flops)")
    else:
        L.append("  MFU: n/a (no compiled-program cost analysis in events)")
    # XLA program introspection (where the HBM and FLOPs go INSIDE the step)
    x = a.get("xla")
    if x:
        from tpudist.obs.xla_introspect import format_section
        info = dict(x)
        # The compile event's only per-op detail is all-reduce (the headline
        # DP-sync op); when the program IS pure all-reduce show it per-op,
        # otherwise format_section's flat-field fallback prints the totals.
        if x.get("all_reduce_count") and \
                x.get("all_reduce_count") == x.get("collective_ops"):
            info["collectives"] = {"all-reduce": {
                "count": x["all_reduce_count"],
                "bytes": x.get("all_reduce_bytes", 0)}}
        lines = format_section(info)
        if lines:
            L.append("  XLA program (per device, compiled train step):")
            L.extend(lines)
    # attention dispatch (which kernel --flash resolved to, on what evidence)
    ad = a.get("attention_dispatch")
    if ad:
        prov = ad["source"]
        if ad["source"] == "cache":
            prov = "cache hit"
        elif ad["source"] == "measured":
            prov = "measured now, cached"
        line = (f"  attention dispatch: {ad['kernel']} attention "
                f"(mode {ad['mode']}, {prov}")
        if isinstance(ad.get("flash_ms"), (int, float)) \
                and isinstance(ad.get("xla_ms"), (int, float)):
            line += (f"; flash {ad['flash_ms']:.3f} ms vs "
                     f"xla {ad['xla_ms']:.3f} ms")
            if isinstance(ad.get("margin"), (int, float)):
                line += f", margin {ad['margin']:.1%}"
        if ad.get("shape_key"):
            line += f"; shape {ad['shape_key']}"
        L.append(line + ")")
    # fused-norm dispatch (which epilogue --fused-bn resolved to)
    fn = a.get("fused_norm_dispatch")
    if fn:
        prov = fn["source"]
        if prov == "cache":
            prov = "cache hit"
        elif prov == "measured":
            prov = "measured now, cached"
        line = (f"  fused-norm dispatch: {fn['kernel']} epilogue "
                f"(mode {fn['mode']}, {prov}")
        if isinstance(fn.get("n_sites"), (int, float)) and fn["n_sites"]:
            line += (f"; {int(fn.get('n_fused', 0))}/{int(fn['n_sites'])} "
                     f"BN workloads fused")
        if fn.get("reason"):
            line += f"; {fn['reason']}"
        L.append(line + ")")
    # comm dispatch (which gradient wire format --compress-grads resolved to)
    cd = a.get("comm_dispatch")
    if cd:
        prov = cd["source"]
        if prov == "cache":
            prov = "cache hit"
        elif prov == "measured":
            prov = "measured now, cached"
        line = (f"  comm dispatch: {cd['kernel']} gradient exchange "
                f"(mode {cd['mode']}, {prov}")
        if isinstance(cd.get("int8_ms"), (int, float)) \
                and isinstance(cd.get("dense_ms"), (int, float)):
            line += (f"; int8 {cd['int8_ms']:.3f} ms vs "
                     f"dense {cd['dense_ms']:.3f} ms")
            if isinstance(cd.get("margin"), (int, float)):
                line += f", margin {cd['margin']:.1%}"
        if cd.get("reason"):
            line += f"; {cd['reason']}"
        L.append(line + ")")
    comp = a.get("compression")
    if comp:
        line = (f"  gradient compression: dense-equivalent "
                f"{comp['dense_bytes'] / 2**20:.1f} MiB/step vs "
                f"{comp['actual_bytes'] / 2**20:.1f} MiB actual collective "
                f"payload ({comp['payload_ratio']:.2f}x)")
        if comp.get("link_ratio") is not None:
            line += (f"; est. link traffic "
                     f"{comp['link_bytes'] / 2**20:.1f} MiB "
                     f"({comp['link_ratio']:.2f}x less than the dense "
                     f"ring all-reduce)")
        L.append(line)
    # op-category attribution (where the non-MXU time goes)
    at = a.get("op_attribution")
    if at:
        comp = at["compute_p50_ms"]

        def share(ms: float) -> str:
            return f" ({ms / comp:6.1%} of compute)" if comp > 0 else ""

        L.append("  op-category attribution (steady-state compute p50 "
                 f"{comp:.1f} ms, {at['bound']}-bound):")
        L.append(f"    MXU roofline      {at['mxu_ms_lb']:8.3f} ms lower "
                 f"bound{share(at['mxu_ms_lb'])}")
        if at.get("hbm_ms_lb") is not None:
            L.append(f"    HBM roofline      {at['hbm_ms_lb']:8.3f} ms "
                     f"lower bound{share(at['hbm_ms_lb'])}")
        L.append(f"    unattributed      {at['residual_ms']:8.3f} ms "
                 f"(non-roofline: launch/layout/fusion overhead)")
        cats = at.get("op_counts")
        if cats:
            per = ", ".join(f"{k} x{int(v)}" for k, v in
                            sorted(cats.items(), key=lambda kv: -kv[1])
                            if v)
            L.append(f"    HLO ops by unit:  {per}")
    # step budget
    b = a.get("budget") or {}
    if b.get("step_s"):
        L.append("  step-time budget (rank-0 p50 / p95 ms):")
        rows = [("data wait", "data_s"), ("host→device", "h2d_s"),
                ("device compute", "compute_s"),
                ("metric drain", "drain_s")]
        if b.get("prefetch_s"):
            # Overlapped bucket (device prefetch): staged under compute —
            # in the serial sum it displaces other-host, not data/h2d.
            rows.append(("prefetch (ovl.)", "prefetch_s"))
        if b.get("drain_ovl_s"):
            rows.append(("drain (ovl.)", "drain_ovl_s"))
        rows += [("other host", "other_host_s"), ("total step", "step_s")]
        for name, key in rows:
            p = b.get(key)
            if p:
                L.append(f"    {name:<15}{_ms(p['p50'])} /{_ms(p['p95'])}")
    # serving plane (tpudist/serve/): latency/throughput + cold-start
    sv = a.get("serving")
    if sv:
        head = f"  serving: {sv['n_requests']} requests"
        if sv.get("n_errors"):
            head += f" ({sv['n_errors']} errored)"
        if sv.get("n_batches"):
            head += f" in {sv['n_batches']} bucketed batches"
        if sv.get("occupancy_p50") is not None:
            head += f" (occupancy p50 {sv['occupancy_p50']:.0%})"
        L.append(head)
        if sv.get("latency_p50_ms") is not None:
            line = (f"    latency p50 {sv['latency_p50_ms']:.1f} ms / "
                    f"p99 {sv['latency_p99_ms']:.1f} ms")
            if sv.get("req_per_s") is not None:
                line += f"; {sv['req_per_s']:.1f} req/s"
            L.append(line)
        if sv.get("aot_s") is not None:
            line = (f"    AOT startup: {sv['n_buckets']} bucket programs "
                    f"[{sv.get('buckets', '?')}] in {sv['aot_s']:.2f}s")
            if sv.get("aot_compile_s") is not None:
                line += f" (XLA compile {sv['aot_compile_s']:.2f}s)"
            if sv.get("cache"):
                line += f", persistent cache {sv['cache']}"
            L.append(line)
        if sv.get("aot_compiles"):
            extra = sv.get("non_aot_compiles") or 0
            L.append(f"    compiles: {sv['aot_compiles']} AOT bucket "
                     f"programs, {extra} other — "
                     + ("ZERO steady-state recompiles" if extra == 0
                        else "(non-AOT compiles present: mixed "
                             "train+serve run dir, or a recompile)"))
    # doctor plane: interventions + SDC probe census (docs/DOCTOR.md)
    dc = a.get("doctor")
    if dc:
        acts = ", ".join(f"{k} x{v}" for k, v in sorted(dc["by_action"].items()))
        L.append(f"  doctor: {dc['interventions']} intervention(s)"
                 + (f" ({acts})" if acts else "")
                 + (f"; SDC probes {dc['probes']} "
                    f"({dc['divergent_probes']} divergent)"
                    if dc["probes"] else ""))
        for e in dc["events"][:12]:
            act = e.get("action")
            if act == "skip_step":
                what = "non-finite step — update zeroed in-program"
            elif act == "spike":
                what = (f"loss spike {e.get('loss', '?')} vs EWMA "
                        f"{e.get('mean', '?')} (+{e.get('sigmas', '?')}σ)")
            elif act == "rollback":
                what = (f"{e.get('reason', 'rollback')} → re-entered epoch "
                        f"{e.get('to_epoch', '?')}")
                if e.get("window_start") is not None:
                    what += (f", replay minus samples "
                             f"[{e['window_start']}, {e['window_end']})")
            elif act == "sdc_divergence":
                what = ("replicated-state digest divergence"
                        + (" (2-replica tie — unattributable)"
                           if e.get("tie") else
                           f" (rank(s) {e.get('divergent_ranks', '?')})"))
            elif act == "evict":
                what = (f"rank {e.get('divergent_rank', '?')} "
                        f"self-quarantined after "
                        f"{e.get('windows', '?')} divergent probes")
            else:
                what = str(act)
            L.append(f"    [doctor] rank {e['rank']} step "
                     f"{e.get('step', '?')}: {what}")
        if len(dc["events"]) > 12:
            L.append(f"    ... {len(dc['events']) - 12} more")
    # perf-CI console: unattended bench-matrix runs (tpudist-perfci)
    pc = a.get("perfci")
    if pc:
        L.append(f"  perfci: {pc['runs']} run(s), "
                 f"{pc['regressions']} regression(s) flagged")
        for e in pc["events"][-6:]:
            L.append(f"    [perfci] {e.get('platform', '?')}: "
                     f"{e.get('stages_ok', '?')}/{e.get('stages_total', '?')}"
                     f" stages ok ({e.get('stages_failed', 0)} failed, "
                     f"{e.get('stages_skipped', 0)} skipped), "
                     f"{e.get('series_gated', 0)} series gated, "
                     f"{e.get('regressions', 0)} regression(s), "
                     f"exit {e.get('exit', '?')}")
        if len(pc["events"]) > 6:
            L.append(f"    ... {len(pc['events']) - 6} earlier run(s)")
    # blackbox plane: incident triggers + the bundles on disk
    # (docs/INCIDENTS.md). Bundles are read from the run dir here, not in
    # analyze(), which stays pure on events.
    inc = a.get("incidents")
    bundles = []
    if rundir:
        try:
            from tpudist.blackbox import list_incidents
            bundles = list_incidents(rundir)
        except Exception:
            bundles = []
    if inc or bundles:
        trig = ", ".join(f"{k} x{v}" for k, v in
                         sorted((inc or {}).get("by_trigger", {}).items()))
        L.append(f"  incidents: {(inc or {}).get('triggers', 0)} trigger(s)"
                 + (f" ({trig})" if trig else "")
                 + (f", {inc['captures']} deep capture(s), "
                    f"{inc['suppressed']} cooldown-suppressed"
                    if inc else "")
                 + f"; {len(bundles)} bundle(s) on disk")
        for m in bundles[-6:]:
            dumps = m.get("dumps") or []
            ranks = sorted({d.get("rank") for d in dumps
                            if d.get("rank") is not None})
            arts = len(m.get("artifacts") or [])
            L.append(f"    [incident] {m.get('id', '?')}: trigger "
                     f"{m.get('trigger', '?')}, suspect rank "
                     f"{m.get('suspect_rank', '?')}"
                     + (f", dumps from rank(s) {ranks}" if ranks else "")
                     + f", {arts} artifact(s)"
                     + (f", {len(m.get('captures') or [])} capture dir(s)"
                        if m.get("captures") else ""))
        if len(bundles) > 6:
            L.append(f"    ... {len(bundles) - 6} earlier bundle(s)")
        L.append("    (inspect: tpudist-incident report <rundir> [id])")
    # per-rank
    if len(a.get("per_rank", {})) > 1:
        flagged = {s["straggler_rank"] for s in a["stragglers"]}
        L.append("  per-rank (n steps, step p50 ms, host p50 ms):")
        for rank, r in sorted(a["per_rank"].items()):
            mark = "  ← STRAGGLER" if rank in flagged else ""
            L.append(f"    rank {rank}: n={r['n']:<5} "
                     f"step {_ms(r['step_p50']).strip()} ms  "
                     f"host {_ms(r['host_p50']).strip()} ms{mark}")
    # topology timeline (elastic plane): only interesting once a reform or
    # cross-world reshard happened, or the job launched more than once.
    topo = a.get("topology") or []
    if any(t["kind"] != "launch" for t in topo) or len(topo) > 1:
        L.append("  topology timeline:")
        t0 = topo[0]["t"] if topo else 0.0
        for t in topo:
            dt = f"+{t['t'] - t0:7.1f}s"
            if t["kind"] == "launch":
                mesh = (f", mesh {t['mesh']}"
                        if t.get("mesh") and t["mesh"] != "default" else "")
                L.append(f"    {dt} [launch]  attempt {t['attempt']}: "
                         f"world {t['world']}{mesh}")
            elif t["kind"] == "reform":
                lost = f" (lost rank(s) {t['lost_ranks']})" \
                    if t.get("lost_ranks") else ""
                mesh = ""
                if t.get("from_mesh") and t["from_mesh"] != "default":
                    act = f" {t['mesh_action']}" if t.get("mesh_action") \
                        else ""
                    mesh = (f", mesh {t['from_mesh']} -> "
                            f"{t['to_mesh']}{act}")
                L.append(f"    {dt} [reform]  world {t['from_world']} -> "
                         f"{t['to_world']}{mesh}{lost}")
            elif t["kind"] == "scale_up":
                L.append(f"    {dt} [scale]   world {t['from_world']} -> "
                         f"{t['to_world']} (serving replicas scaled up "
                         f"under load)")
            elif t["kind"] == "evict":
                L.append(f"    {dt} [evict]   rank {t['rank']}: persistent "
                         f"straggler drained after {t.get('windows', '?')} "
                         f"flagged windows")
            else:
                L.append(f"    {dt} [reshard] rank {t['rank']}: checkpoint "
                         f"world {t['from_world']} -> {t['to_world']}")
    # fault timeline
    if a["faults"]:
        L.append(f"  faults/restarts ({len(a['faults'])}):")
        for e in a["faults"][:20]:
            if e["type"] == "restart":
                what = f"relaunch (prev exit {e.get('prev_exit', '?')})"
            elif e["type"] == "straggler":
                # straggler_rank can be 0 — no falsy `or` chains here.
                what = (f"rank {e['straggler_rank']} at "
                        f"{e.get('factor', '?')}x the fleet median")
            elif e["type"] == "eviction":
                what = (f"rank {e['straggler_rank']} evicted "
                        f"(straggler {e.get('windows', '?')} consecutive "
                        f"windows)")
            elif e["type"] == "collective_deadline":
                what = (f"gang wedged (no heartbeat progress; suspect "
                        f"rank {e['suspect_rank']} stale "
                        f"{e.get('max_age_s', '?')}s) — draining")
            else:
                what = e.get("point") or e.get("classification") \
                    or e.get("signal") or e["type"]
            # rank_exit/straggler events come from the LAUNCHER stream
            # (envelope rank -1); the rank they are ABOUT is in their own
            # field.
            rank = e.get("exit_rank",
                         e.get("straggler_rank",
                               e.get("suspect_rank", e["rank"])))
            L.append(f"    [{e['type']}] rank {rank} attempt "
                     f"{e['attempt']}: {what}")
        if len(a["faults"]) > 20:
            L.append(f"    ... {len(a['faults']) - 20} more")
    return "\n".join(L)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Summarize a tpudist run's telemetry "
                    "(goodput, MFU budget, stragglers)")
    p.add_argument("rundir", help="run output dir containing events.*.jsonl")
    p.add_argument("--peak-flops", type=float, default=None,
                   dest="peak_flops",
                   help="peak FLOP/s for the MFU denominator (overrides the "
                        "device table and TPUDIST_PEAK_FLOPS)")
    p.add_argument("--json", action="store_true",
                   help="emit the analysis as JSON instead of the report "
                        "(goodput, MFU, percentiles, stragglers, XLA "
                        "introspection) for CI/regression-gate consumption")
    p.add_argument("--strict", action="store_true",
                   help="fail on any malformed event line")
    p.add_argument("--trace", default="", metavar="OUT.json",
                   help="also merge every rank's events (launcher + rotated "
                        "segments included) into a Chrome/Perfetto "
                        "trace-event JSON at this path — open it at "
                        "ui.perfetto.dev")
    p.add_argument("--no-align", action="store_true", dest="no_align",
                   help="with --trace: keep raw host clocks instead of "
                        "aligning each rank's run_start anchor")
    args = p.parse_args(argv)

    events = load_events(args.rundir, strict=args.strict)
    if not events:
        print(f"no events.*.jsonl found in {args.rundir} "
              f"(run with --telemetry)", file=sys.stderr)
        return 2
    if args.trace:
        from tpudist.obs.trace import export_trace_file
        obj = export_trace_file(events, args.trace, align=not args.no_align)
        print(f"[summarize] wrote {len(obj['traceEvents'])} trace events "
              f"to {args.trace} (open at ui.perfetto.dev)", file=sys.stderr)
    a = analyze(events, peak_flops=args.peak_flops)
    if args.json:
        print(json.dumps(a, indent=1, default=str))
    else:
        print(format_report(a, args.rundir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
