"""Measurement-honest fused-BN-epilogue dispatch (``--fused-bn auto``) — the
second client of the generic dispatch layer (``tpudist/ops/dispatch``),
beside ``ops/attention_dispatch``.

The kernels (``ops/pallas/fused_norm``: BN+ReLU and BN+add+ReLU single-pass
epilogues) are wired into ``models/layers.py::BatchNorm``, which every conv
family shares — so ONE dispatch question covers resnet, vgg, densenet,
regnet, mobilenet, the inception family, … without per-model logic. The
same honesty policy as attention applies, via the same generic machinery:

- ``use_fused()`` is the TRACE-SAFE call BatchNorm makes while the step is
  being traced: mode/eligibility/platform/cache only, never a measurement.
  Unmeasured ⇒ XLA; off-TPU ``auto`` ⇒ XLA without ``fused_norm`` (and its
  Pallas import) ever entering ``sys.modules``.
- the Trainer warms the cache OUTSIDE the trace: ``record_requests()``
  captures every (rows, channels, dtype, variant) workload an
  ``eval_shape`` of the model requests, and ``decide()`` micro-benchmarks
  each exactly once per device kind (cached in
  ``fused_norm.<kind>.json``, invalidated by ``KERNEL_REV``).
- multi-host gangs get ONE verdict set: the primary publishes
  ``fused_norm_dispatch.json`` into the shared run dir
  (``shared_decide_all``), and peers ADOPT it into their local cache so
  their trace-time lookups compile the same kernels — a near-tie shape
  must not mix epilogue backends inside one SPMD program.

Structural fallbacks (not measurement questions, decided at the call
site in ``models/layers.py``): SyncBN (``axis_name`` set — the stat
``pmean`` has no fused kernel) and eval-mode running-stats both take the
XLA path explicitly, even under ``--fused-bn on``.

Mode is process-global (``set_mode`` from ``Config.fused_bn``, env
``TPUDIST_FUSED_BN`` for subprocess-level forcing) because BatchNorm sits
too deep for ctor plumbing through 19 model files — the exact per-model
edits this layer exists to avoid.
"""

from __future__ import annotations

import contextlib
import os
from functools import partial
from typing import Callable, Optional

from tpudist.ops import dispatch

CLIENT = "fused_norm"
NAMES = ("pallas", "xla")
MODES = dispatch.MODES
ENV_MODE = "TPUDIST_FUSED_BN"
SHARED_FILENAME = "fused_norm_dispatch.json"

_mode: Optional[str] = None
_recording: Optional[set] = None


def set_mode(mode: Optional[str]) -> None:
    """Install the process-wide ``--fused-bn`` mode (None = back to the env/
    default resolution). Raises on anything outside auto|on|off so a Config
    typo cannot silently coerce to off."""
    if mode is not None and mode not in MODES:
        raise ValueError(f"fused-bn mode must be one of {MODES}, got "
                         f"{mode!r}")
    global _mode
    _mode = mode


def get_mode() -> str:
    if _mode is not None:
        return _mode
    env = os.environ.get(ENV_MODE, "")
    return env if env in MODES else "auto"


def kernel_rev() -> int:
    """Lazy import: the cache/decision plumbing must not drag Pallas in on
    the XLA-only path."""
    from tpudist.ops.pallas.fused_norm import KERNEL_REV
    return KERNEL_REV


def norm_key(rows: int, channels: int, dtype, residual: bool) -> str:
    """The dispatch identity: the exact epilogue workload. ``rows`` is the
    flattened non-channel extent of the activation the traced step actually
    runs (per-shard under shard_map DP — the shape a device executes)."""
    try:
        import numpy as np
        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, "name", None) or str(dtype)
    return f"m{rows}_c{channels}_{name}_{'res' if residual else 'plain'}"


def fused_eligible(*, rows: int, channels: int) -> tuple[bool, str]:
    """Static eligibility: workloads the kernel cannot (or will never
    sensibly) tile resolve to XLA before any device question is asked."""
    if rows < 1 or channels < 1:
        return False, "empty activation"
    if channels > 8192:
        return False, (f"channels {channels} exceeds the kernel's channel "
                       f"tiling")
    if rows < 8:
        return False, (f"rows {rows} is below one sublane tile — a "
                       f"streaming epilogue cannot win")
    return True, "eligible"


cache_path = partial(dispatch.cache_path, CLIENT)
clear_cache = partial(dispatch.clear_cache, CLIENT)


def epilogue_shard_axes(shape):
    """``(mesh, batch_axis, channel_axis)`` — THE single derivation of
    which ambient Auto mesh axes cut an ``(..., C)`` epilogue activation:
    the batch (leading) dim over ``data`` and the channel (trailing) dim
    over ``model``, each only when the axis exists, is Auto
    (partitioner-managed — inside a shard_map body both read as bound and
    nothing cuts, see _jaxshim.ambient_auto_axes), has size > 1, and
    divides the dim. Shared by the dispatch key
    (``shard_local_workload``) and the kernel wrapper
    (``pallas/fused_norm.fused_bn_act_spmd``) so the workload that is
    keyed/measured and the block the wrapper actually runs CANNOT drift —
    a one-sided edit here is the honesty hole this layer exists to close.
    Trace-safe: shapes and mesh context only, no device work, no Pallas
    import."""
    from tpudist._jaxshim import ambient_auto_axes
    mesh, auto = ambient_auto_axes(("data", "model"))
    batch_ax = ("data" if "data" in auto and mesh.shape["data"] > 1
                and int(shape[0]) % mesh.shape["data"] == 0 else None)
    chan_ax = ("model" if "model" in auto and mesh.shape["model"] > 1
               and int(shape[-1]) % mesh.shape["model"] == 0 else None)
    return mesh, batch_ax, chan_ax


def shard_local_workload(shape) -> tuple[int, int, bool]:
    """``(rows, channels, sharded)`` — the PER-SHARD epilogue workload a
    device actually executes for an activation of (global) ``shape``.

    Outside any ambient Auto mesh (eager, the shard_map DP path — where
    the traced shapes are already local) this is the plain
    ``(prod(shape[:-1]), shape[-1], False)``. Under a GSPMD trace (the
    step builders' ``set_mesh`` ambient mesh, jax<0.8 via the _jaxshim
    backfill) the batch dim divides by the ``data`` axis and the channel
    dim by the ``model`` axis exactly as ``fused_bn_act_spmd`` will shard
    them (both read ``epilogue_shard_axes`` — one derivation, no drift),
    so the dispatch key that is recorded, measured, and looked up at
    trace time IS the shard-local workload — probing the global shape
    would re-open the hole the honesty layer closes: a kernel winning an
    unrun shape and losing the real one."""
    rows = 1
    for d in shape[:-1]:
        rows *= int(d)
    channels = int(shape[-1])
    mesh, batch_ax, chan_ax = epilogue_shard_axes(shape)
    if batch_ax is not None:
        rows //= mesh.shape[batch_ax]
    if chan_ax is not None:
        channels //= mesh.shape[chan_ax]
    return rows, channels, batch_ax is not None or chan_ax is not None


@contextlib.contextmanager
def record_requests():
    """While active, every ``use_fused()`` call APPENDS its workload to the
    yielded set (and answers False — the recording pass is an abstract
    ``eval_shape``, its outputs are discarded). The Trainer records, then
    ``decide()``s each request outside the trace."""
    global _recording
    prev, _recording = _recording, set()
    try:
        yield _recording
    finally:
        _recording = prev


def use_fused(rows: int, channels: int, dtype, *, residual: bool,
              cache_dir: Optional[str] = None,
              platform: Optional[str] = None,
              device_kind: Optional[str] = None) -> bool:
    """THE trace-safe question BatchNorm asks: run the fused Pallas epilogue
    for this workload? Forced modes answer directly; ``auto`` consults the
    cache only — no entry (nobody measured) means XLA, and off-TPU the
    answer is False before any Pallas import can happen."""
    mode = get_mode()
    if mode == "off":
        return False
    ok, _ = fused_eligible(rows=rows, channels=channels)
    if not ok:
        return False
    if _recording is not None:
        _recording.add((rows, channels, norm_key(rows, channels, dtype,
                                                 residual), residual, dtype))
        return False
    if mode == "on":
        return True
    return dispatch.lookup(
        CLIENT, norm_key(rows, channels, dtype, residual),
        candidate="pallas", kernel_rev=kernel_rev, cache_dir=cache_dir,
        platform=platform, device_kind=device_kind)


def build_measure_fns(rows: int, channels: int, dtype, residual: bool,
                      *, interpret: bool = False):
    """THE fwd+bwd workload definition the micro-benchmark times —
    ``(pallas_fn, xla_fn, args)``, each fn jitted grad of a scalar loss over
    the epilogue at the exact workload. Shared with
    ``benchmarks/bench_fused_norm.py`` so dispatch verdicts and bench rows
    cannot drift in WHAT they measure any more than (via
    ``dispatch.measure_ms``) in how they time it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.ops.pallas.fused_norm import fused_bn_act, reference_bn_act

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((rows, channels)), dtype)
    res = (jnp.asarray(rng.standard_normal((rows, channels)), dtype)
           if residual else None)
    scale = jnp.asarray(rng.standard_normal(channels), jnp.float32)
    bias = jnp.asarray(rng.standard_normal(channels), jnp.float32)
    mean = jnp.asarray(rng.standard_normal(channels), jnp.float32)
    var = jnp.asarray(rng.random(channels) + 0.5, jnp.float32)

    def loss(fn):
        def f(x, scale, bias, res=None):
            return fn(x, scale, bias, mean, var,
                      residual=res).astype(jnp.float32).sum()
        return f

    argnums = (0, 1, 2, 3) if residual else (0, 1, 2)
    args = (x, scale, bias) + ((res,) if residual else ())

    def fused(x, scale, bias, mean, var, *, residual=None):
        return fused_bn_act(x, scale, bias, mean, var, residual=residual,
                            interpret=interpret)

    pallas_c = jax.jit(jax.grad(loss(fused), argnums=argnums))
    xla_c = jax.jit(jax.grad(loss(reference_bn_act), argnums=argnums))
    return pallas_c, xla_c, args


def measure_fused_norm(rows: int, channels: int, dtype, residual: bool,
                       steps: int = 10, warmup: int = 2
                       ) -> tuple[float, float]:
    """The on-device micro-benchmark: (pallas_ms, xla_ms) for forward +
    backward of the epilogue at the exact workload — BN epilogues only
    matter in training, so fwd+bwd IS the configuration that decides. Only
    meaningful on an accelerator — callers gate on platform."""
    pallas_c, xla_c, args = build_measure_fns(rows, channels, dtype,
                                              residual)
    pallas_ms = dispatch.measure_ms(pallas_c, args, steps, warmup)
    xla_ms = dispatch.measure_ms(xla_c, args, steps, warmup)
    return pallas_ms, xla_ms


def decide(rows: int, channels: int, dtype, *, residual: bool,
           mode: str = "auto", cache_dir: Optional[str] = None,
           measure_pair: Optional[Callable[[], tuple[float, float]]] = None,
           refresh: bool = False, platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> dict:
    """Resolve one epilogue workload through the generic honesty policy
    (``dispatch.decide``, ``names=("pallas", "xla")``): under ``auto`` the
    fused kernel is selected ONLY off the back of a measurement it won
    (fresh or cached per device_kind + key + KERNEL_REV); ties and losses
    keep the XLA epilogue; off-TPU resolves to XLA without measuring.

    Unlike attention (where forced ``on`` bypasses eligibility and the
    ineligible call sites carry tripwires), eligibility here is STRUCTURAL
    — it outranks even forced ``on``, exactly as ``use_fused`` enforces at
    the BatchNorm call site. A decision must name the kernel the trace
    actually runs, so the same rule applies on both surfaces."""
    key = norm_key(rows, channels, dtype, residual)
    ok, why = fused_eligible(rows=rows, channels=channels)
    if mode == "on" and not ok:
        return {"kernel": "xla", "mode": mode, "source": "ineligible",
                "key": key, "reason": why, "pallas_ms": None,
                "xla_ms": None, "margin": None, "cache_hit": False}
    if measure_pair is None:
        measure_pair = lambda: measure_fused_norm(  # noqa: E731
            rows, channels, dtype, residual)
    return dispatch.decide(
        CLIENT, key, mode=mode, names=NAMES, kernel_rev=kernel_rev,
        measure_pair=measure_pair, eligibility=(ok, why),
        cache_dir=cache_dir, refresh=refresh, platform=platform,
        device_kind=device_kind)


def adopt_decisions(decisions: dict, device_kind: str,
                    cache_dir: Optional[str] = None) -> int:
    """Seed the LOCAL cache with another host's measured verdicts (the
    ``shared_decide_all`` peer path): trace-time ``use_fused`` lookups read
    this host's per-device_kind file, so without adoption a peer would
    resolve every site to XLA while the primary compiles Pallas — mixed
    epilogue backends inside one SPMD program. Only measured/cache-sourced
    entries with a kernel_rev are adopted; returns the count."""
    path = cache_path(device_kind, cache_dir)
    cache = dispatch.load_cache(path)
    n = 0
    for key, d in decisions.items():
        if d.get("kernel") in NAMES and d.get("kernel_rev") is not None:
            cache["entries"][key] = {
                "kernel": d["kernel"],
                "pallas_ms": d.get("pallas_ms"),
                "xla_ms": d.get("xla_ms"),
                "margin": d.get("margin"),
                "kernel_rev": d["kernel_rev"],
                "measured_at": d.get("measured_at"),
            }
            n += 1
    if n:
        cache["device_kind"] = device_kind
        try:
            dispatch.save_cache(path, cache)
        except OSError:
            # Unwritable cache dir: the peer must STILL compile what the
            # primary decided — seed the in-process overlay lookup() falls
            # back to, or this rank would trace XLA into the gang's program.
            for key, d in decisions.items():
                if d.get("kernel") in NAMES \
                        and d.get("kernel_rev") is not None:
                    dispatch.seed_local(path, key, cache["entries"][key])
    return n


def combined_key(requests) -> str:
    """One stable key over a request set, for the shared-verdict freshness
    check (peers compute it from their OWN recording, so a stale file for a
    different model/batch never matches)."""
    return "+".join(sorted(r[2] for r in requests))


def shared_decide_all(outpath: str, primary: bool, decide_all_fn,
                      *, expect_key: Optional[str] = None,
                      timeout_s: float = 600.0, poll_s: float = 0.25,
                      log=None, device_kind: Optional[str] = None,
                      cache_dir: Optional[str] = None) -> dict:
    """One fused-norm verdict SET for the whole gang, via the generic
    ``dispatch.shared_decision`` (file ``fused_norm_dispatch.json``).
    ``decide_all_fn`` returns the aggregate dict (``kernel``/``key``/
    ``decisions``); peers adopt the published set into their local cache
    before returning it."""
    dec = dispatch.shared_decision(
        outpath, primary, decide_all_fn, filename=SHARED_FILENAME,
        kernel_rev=kernel_rev, expect_key=expect_key, timeout_s=timeout_s,
        poll_s=poll_s, log=log, what="fused-norm dispatch")
    if dec.get("shared_from_primary") and dec.get("decisions") \
            and device_kind:
        adopt_decisions(dec["decisions"], device_kind, cache_dir)
    return dec


def aggregate(decisions: dict, mode: str) -> dict:
    """Roll per-workload decisions into ONE reportable verdict: ``kernel``
    is "pallas" when every site fused, "mixed" when some did, else "xla";
    ``source`` prefers "measured" over "cache" (any fresh measurement makes
    the run's evidence fresh). The per-key dict rides along for the shared
    file and the telemetry detail."""
    n = len(decisions)
    fused = sum(1 for d in decisions.values() if d.get("kernel") == "pallas")
    if n and fused == n:
        kernel = "pallas"
    elif fused:
        kernel = "mixed"
    else:
        kernel = "xla"
    sources = {d.get("source") for d in decisions.values()}
    source = ("measured" if "measured" in sources
              else "cache" if "cache" in sources
              else next(iter(sources), "platform"))
    out = {"kernel": kernel, "mode": mode, "source": source,
           "n_sites": n, "n_fused": fused, "decisions": decisions}
    revs = {d.get("kernel_rev") for d in decisions.values()
            if d.get("kernel_rev") is not None}
    if len(revs) == 1:
        out["kernel_rev"] = revs.pop()
    return out


def event_fields(decision: dict) -> dict:
    """The aggregate decision as telemetry-event fields (type
    ``fused_norm_dispatch``, schema in tpudist/telemetry.py) so
    ``summarize`` can print the fused-norm dispatch line without re-reading
    any cache."""
    out = {"kernel": decision["kernel"], "mode": decision["mode"],
           "source": decision["source"]}
    for f in ("n_sites", "n_fused"):
        if isinstance(decision.get(f), (int, float)):
            out[f] = decision[f]
    if decision.get("reason"):
        out["reason"] = decision["reason"]
    if decision.get("shared_from_primary"):
        out["shared_from_primary"] = 1
    decs = decision.get("decisions") or {}
    if decs:
        out["detail"] = "; ".join(
            f"{k}={d.get('kernel')}"
            + (f" ({d['pallas_ms']:.3f} vs {d['xla_ms']:.3f} ms)"
               if isinstance(d.get("pallas_ms"), (int, float))
               and isinstance(d.get("xla_ms"), (int, float)) else "")
            for k, d in sorted(decs.items()))[:2000]
    return out
