"""Measurement-honest gradient-compression dispatch (``--compress-grads``)
— the third client of the generic dispatch layer (``tpudist/ops/dispatch``),
beside attention and fused-norm.

The candidate here is not a Pallas kernel but a COLLECTIVE ALGORITHM
(``parallel/comm.py``: int8 two-phase all-reduce with error feedback), so
the dispatch question is different in kind: the quantize/dequantize
arithmetic is pure VPU work that trades compute for interconnect bytes,
and whether that trade wins depends on the fabric (ICI generation, slice
size) and the gradient size — exactly the per-workload, per-device_kind
question the honesty layer answers. The same policy applies unchanged:

- ``auto`` selects int8 ONLY off the back of a measurement it won at the
  exact workload key (total gradient element count × data-axis size ×
  chunk), cached per device_kind in ``comm.<kind>.json``, invalidated by
  ``COMM_REV`` (the wire-format revision). Ties and losses keep the dense
  pmean — the compiler's collective needs no justification.
- off-TPU ``auto`` resolves to dense without measuring: CPU-sim collective
  timings say nothing about ICI. (Forced ``int8`` still works anywhere —
  the algorithm is plain jnp — which is what the CPU parity tests and the
  ≥2-device census acceptance run.)
- multi-host gangs get ONE verdict via ``shared_decision``
  (``comm_dispatch.json`` in the run dir): a near-tie must not compile a
  quantized exchange on one host and a dense pmean on another into the
  same SPMD program.

The A/B measured is the REAL exchange at the real size over the real mesh
(``build_measure_fns``): a jitted shard_map running dense ``lax.pmean``
vs the compressed twin on a synthetic flat gradient of the model's exact
element count — one timing harness (``dispatch.measure_ms``) shared with
``benchmarks/bench_comm.py`` so verdicts and bench rows cannot drift.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

from tpudist.ops import dispatch

CLIENT = "comm"
NAMES = ("int8", "dense")
MODES = ("off", "int8", "auto")
SHARED_FILENAME = "comm_dispatch.json"


def kernel_rev() -> int:
    from tpudist.parallel.comm import COMM_REV
    return COMM_REV


def comm_key(n_grads: int, world: int, chunk: int) -> str:
    """The dispatch identity: the exact reduction workload — total gradient
    element count (f32 master grads), data-axis size, quantization chunk."""
    return f"n{n_grads}_w{world}_c{chunk}"


def comm_eligible(*, n_grads: int, world: int) -> tuple[bool, str]:
    """Static eligibility: a reduction that moves no bytes across ranks can
    never win (and the exchange itself is undefined at world 1)."""
    if world < 2:
        return False, (f"data-axis size {world}: nothing crosses the "
                       f"interconnect, compression cannot win")
    if n_grads < 1:
        return False, "empty gradient"
    return True, "eligible"


cache_path = partial(dispatch.cache_path, CLIENT)
clear_cache = partial(dispatch.clear_cache, CLIENT)


def build_measure_fns(n_grads: int, mesh, data_axis: str, chunk: int):
    """``(int8_fn, dense_fn, args)`` — each a jitted shard_map reducing a
    synthetic flat f32 gradient of the model's exact element count over
    the real mesh. Shared with ``benchmarks/bench_comm.py`` (ONE workload
    definition, ONE timing harness)."""
    import numpy as np

    from tpudist import _jaxshim  # noqa: F401
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudist.parallel.comm import compressed_pmean_flat

    world = mesh.shape[data_axis]
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.standard_normal((world, n_grads)), jnp.float32)
    e = jnp.zeros((world, n_grads), jnp.float32)

    def dense(gv):
        return jax.lax.pmean(gv[0], data_axis)[None]

    def int8(gv, ev):
        red, e_new = compressed_pmean_flat(gv[0], ev[0], data_axis,
                                           chunk=chunk)
        return red[None], e_new[None]

    sh = NamedSharding(mesh, P(data_axis))
    gs, es = jax.device_put(g, sh), jax.device_put(e, sh)
    dense_c = jax.jit(shard_map(dense, mesh=mesh, in_specs=(P(data_axis),),
                                out_specs=P(data_axis), check_vma=False))
    int8_c = jax.jit(shard_map(int8, mesh=mesh,
                               in_specs=(P(data_axis), P(data_axis)),
                               out_specs=(P(data_axis), P(data_axis)),
                               check_vma=False))
    return (lambda: int8_c(gs, es)), (lambda: dense_c(gs)), ()


def measure_comm(n_grads: int, mesh, data_axis: str, chunk: int,
                 steps: int = 10, warmup: int = 2) -> tuple[float, float]:
    """The on-device micro-benchmark: (int8_ms, dense_ms) for one gradient
    exchange at the exact workload. Only meaningful on an accelerator —
    callers gate on platform (the generic layer already does)."""
    int8_fn, dense_fn, args = build_measure_fns(n_grads, mesh, data_axis,
                                                chunk)
    int8_ms = dispatch.measure_ms(int8_fn, args, steps, warmup)
    dense_ms = dispatch.measure_ms(dense_fn, args, steps, warmup)
    return int8_ms, dense_ms


def decide(n_grads: int, world: int, *, mode: str, chunk: int,
           mesh=None, data_axis: str = "data",
           cache_dir: Optional[str] = None,
           measure_pair: Optional[Callable[[], tuple[float, float]]] = None,
           refresh: bool = False, platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> dict:
    """Resolve ``--compress-grads`` for one reduction workload through the
    generic honesty policy. Mode mapping onto the generic layer:
    ``off``→forced dense, ``int8``→forced candidate, ``auto``→measured.
    Forced ``int8`` still refuses an ineligible workload (world < 2):
    there is nothing to exchange, so the decision must report dense —
    ``config.finalize``/the Trainer reject that combination loudly before
    it gets here."""
    if mode not in MODES:
        raise ValueError(f"--compress-grads must be one of {MODES}, got "
                         f"{mode!r}")
    key = comm_key(n_grads, world, chunk)
    ok, why = comm_eligible(n_grads=n_grads, world=world)
    if not ok:
        return {"kernel": "dense", "mode": mode, "source": "ineligible",
                "key": key, "reason": why, "int8_ms": None,
                "dense_ms": None, "margin": None, "cache_hit": False}
    generic_mode = {"off": "off", "int8": "on", "auto": "auto"}[mode]
    if measure_pair is None:
        if mesh is None and generic_mode == "auto":
            raise ValueError("auto needs the mesh (or an injected "
                             "measure_pair) to run the A/B")
        measure_pair = lambda: measure_comm(  # noqa: E731
            n_grads, mesh, data_axis, chunk)
    out = dispatch.decide(
        CLIENT, key, mode=generic_mode, names=NAMES, kernel_rev=kernel_rev,
        measure_pair=measure_pair, eligibility=(ok, why),
        cache_dir=cache_dir, refresh=refresh, platform=platform,
        device_kind=device_kind)
    out["mode"] = mode
    return out


def shared_decision(outpath: str, primary: bool, decide_fn,
                    *, expect_key: Optional[str] = None,
                    timeout_s: float = 300.0, log=None) -> dict:
    """One compressed-vs-dense verdict for the whole gang (file
    ``comm_dispatch.json`` in the shared run dir; same staleness rules as
    the other clients: attempt + key + COMM_REV must match)."""
    return dispatch.shared_decision(
        outpath, primary, decide_fn, filename=SHARED_FILENAME,
        kernel_rev=kernel_rev, expect_key=expect_key, timeout_s=timeout_s,
        log=log, what="comm dispatch")


def event_fields(decision: dict, *, world: int, n_grads: int,
                 dense_bytes: int) -> dict:
    """The decision as ``comm_dispatch`` telemetry-event fields (schema in
    tpudist/telemetry.py). ``dense_bytes`` is the dense-equivalent
    gradient payload (f32 bytes of the whole gradient tree) — the
    numerator of the compression-ratio line summarize prints against the
    census's actual collective bytes."""
    out = {"kernel": decision["kernel"], "mode": decision["mode"],
           "source": decision["source"], "world": world,
           "n_grads": n_grads, "dense_bytes": dense_bytes}
    for f in ("int8_ms", "dense_ms", "margin"):
        if isinstance(decision.get(f), (int, float)):
            out[f] = decision[f]
    if decision.get("reason"):
        out["reason"] = decision["reason"]
    if decision.get("key"):
        out["key"] = decision["key"]
    if decision.get("shared_from_primary"):
        out["shared_from_primary"] = 1
    return out
