"""Losses (reference criterion: ``nn.CrossEntropyLoss().cuda()``,
``distributed.py:147``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax cross-entropy over integer labels.

    Matches ``nn.CrossEntropyLoss`` (log-softmax + NLL, mean reduction,
    ``distributed.py:147,247``). Computed in float32 regardless of the compute
    dtype so the loss/grad scale is stable under the bf16 policy (the
    GradScaler-free TPU answer to ``distributed_syncBN_amp.py:275-278``).
    """
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    n_classes = logits.shape[-1]
    if label_smoothing > 0.0:
        onehot = jax.nn.one_hot(targets, n_classes, dtype=jnp.float32)
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / n_classes
        nll = -(onehot * log_probs).sum(axis=-1)
    else:
        nll = -jnp.take_along_axis(log_probs, targets[:, None], axis=-1)[:, 0]
    return nll.mean()
