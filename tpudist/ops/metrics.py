"""Classification metrics (reference ``utils.py:105-111``).

The reference computes top-k accuracy with ``scores.topk`` → eq with expanded
targets → fraction correct, and deliberately returns a 0-D tensor (not a float)
so it stays allreduce-able. Same here: these are jnp functions that fold into
the jitted step and stay on device, so the cross-replica ``pmean`` fuses in.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(scores: jax.Array, targets: jax.Array, topk: int = 1) -> jax.Array:
    """Fraction (in %) of rows whose true label is within the top-k scores.

    Matches reference ``accuracy`` with ``topk=(1,)`` (``utils.py:105-111``):
    returns a 0-D array scaled to percent (mul_(100.0 / batch_size)).
    """
    if topk == 1:
        pred = jnp.argmax(scores, axis=-1)
        correct = (pred == targets).sum()
    else:
        _, pred = jax.lax.top_k(scores, topk)          # [B, k]
        correct = (pred == targets[:, None]).any(axis=-1).sum()
    return correct.astype(jnp.float32) * (100.0 / scores.shape[0])
