"""Pallas TPU fused BatchNorm epilogues: BN+ReLU and BN+add+ReLU in one pass.

PR 5's op-category attribution table names where the missing MFU goes on the
conv families: VPU-bound normalize/activate epilogues around every conv.
XLA emits the BN-apply → (add) → relu chain as its own fusion cluster, but
each cluster still round-trips the activation tensor through HBM between the
conv that produced it and the conv that consumes it, and the backward
re-reads it twice more. These kernels collapse the whole epilogue — both
directions — into ONE streaming pass each:

- **forward**: ``y = relu(x·a + b [+ residual])`` where the per-channel
  ``a = scale·rsqrt(var+eps)`` and ``b = bias − mean·a`` are folded OUTSIDE
  the kernel (two O(C) vectors — XLA fuses them into dust). One read of x
  (+residual), one write of y; the VPU does one fma + max per element
  instead of the unfused sub/rsqrt/mul/add/add/max chain.
- **backward**: one pass reads x (+residual) and dy and emits dx
  (+dresidual) AND the per-channel partial sums ``Σ g·x`` / ``Σ g``
  (g = dy masked by the recomputed relu sign), blocked over rows so each
  grid program owns a disjoint (1, C) partial row — no cross-program
  accumulation hazard. The (grid, C) partials reduce to vectors in XLA,
  and autodiff maps them back through the a/b folding to dscale/dbias/
  dmean/dvar — so the FULL BatchNorm gradient (including the paths through
  the batch statistics) is exact without the kernel knowing BN exists.

Numerics: all kernel math in fp32 regardless of the storage dtype (bf16
under the AMP policy); relu' at exactly 0 is 0, matching ``nn.relu``'s
custom JVP. Zero-padding is exact by construction: padded rows/channels
carry a = b = x = dy = 0, so pre-activation = 0, the mask gates g to 0, and
every partial-sum contribution cancels — no in-kernel masking needed.

Whether this actually beats the XLA epilogue on a real chip is decided by
measurement, not this docstring: ``ops/norm_dispatch`` (a client of the
generic ``ops/dispatch`` honesty layer) A/Bs both per workload and caches
the winner per device kind. ``KERNEL_REV`` below invalidates those cached
verdicts whenever the kernel changes.

Falls back to interpreter mode off-TPU so CPU tests exercise the same
kernel bodies that compile on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax<0.6 names it TPUCompilerParams (same fields we use).
    pltpu.CompilerParams = pltpu.TPUCompilerParams

# Bumped whenever kernel math/scheduling changes: norm_dispatch keys its
# cached pallas-vs-XLA verdicts on this, so a rebuilt kernel re-measures
# instead of inheriting the old kernel's win/loss record.
KERNEL_REV = 1

_LANES = 128
# Target block footprint: ~512 KiB of fp32 per (bm, bc) tile keeps the
# backward's ~6 live buffers + Pallas double-buffering inside VMEM.
_BLOCK_BYTES = 512 * 1024
_MAX_BC = 2048


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _blocks(m: int, c: int) -> tuple[int, int, int, int]:
    """(bm, bc, m_pad, c_pad): channel blocks lane-aligned (≤ _MAX_BC), row
    blocks sized so one fp32 tile is ~_BLOCK_BYTES, floor 16 sublanes (the
    bf16 minimum tile)."""
    c_pad = _ceil_to(c, _LANES) if c > _LANES else c
    bc = min(c_pad, _MAX_BC)
    c_pad = _ceil_to(c_pad, bc)
    bm = max(16, min(1024, (_BLOCK_BYTES // (4 * bc)) // 8 * 8))
    bm = min(bm, _ceil_to(m, 16))
    m_pad = _ceil_to(m, bm)
    return bm, bc, m_pad, c_pad


def _fwd_kernel(x_ref, a_ref, b_ref, o_ref):
    xf = x_ref[...].astype(jnp.float32)
    pre = xf * a_ref[...] + b_ref[...]
    o_ref[...] = jnp.maximum(pre, 0.0).astype(o_ref.dtype)


def _fwd_res_kernel(x_ref, r_ref, a_ref, b_ref, o_ref):
    xf = x_ref[...].astype(jnp.float32)
    # Round the normalized value to the storage dtype BEFORE the residual
    # add, exactly as the unfused call sites did (bn output cast → bf16 add
    # → relu): the fused path must be a pure scheduling change, not a
    # numerics change the parity tests would have to special-case.
    q = (xf * a_ref[...] + b_ref[...]).astype(o_ref.dtype)
    pre = q + r_ref[...].astype(o_ref.dtype)
    o_ref[...] = jnp.maximum(pre, 0.0).astype(o_ref.dtype)


def _bwd_kernel(x_ref, dy_ref, a_ref, b_ref, dx_ref, da_ref, db_ref):
    xf = x_ref[...].astype(jnp.float32)
    a = a_ref[...]
    pre = xf * a + b_ref[...]
    g = jnp.where(pre > 0.0, dy_ref[...].astype(jnp.float32), 0.0)
    dx_ref[...] = (g * a).astype(dx_ref.dtype)
    da_ref[...] = jnp.sum(g * xf, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(g, axis=0, keepdims=True)


def _bwd_res_kernel(x_ref, r_ref, dy_ref, a_ref, b_ref, dx_ref, dr_ref,
                    da_ref, db_ref):
    xf = x_ref[...].astype(jnp.float32)
    a = a_ref[...]
    # Recompute the relu sign with the SAME storage-dtype rounding as the
    # forward (cast-then-add) — an f32 recompute could flip the mask on a
    # value that rounds across zero.
    q = (xf * a + b_ref[...]).astype(dr_ref.dtype)
    pre = q + r_ref[...].astype(dr_ref.dtype)
    g = jnp.where(pre > 0.0, dy_ref[...].astype(jnp.float32), 0.0)
    dx_ref[...] = (g * a).astype(dx_ref.dtype)
    dr_ref[...] = g.astype(dr_ref.dtype)
    da_ref[...] = jnp.sum(g * xf, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(g, axis=0, keepdims=True)


def _pad2(x, m_pad: int, c_pad: int):
    m, c = x.shape
    if m == m_pad and c == c_pad:
        return x
    return jnp.pad(x, ((0, m_pad - m), (0, c_pad - c)))


def _row_spec(bc):
    return pl.BlockSpec((1, bc), lambda im, ic: (0, ic))


def _tile_spec(bm, bc):
    return pl.BlockSpec((bm, bc), lambda im, ic: (im, ic))


def _part_spec(bc):
    return pl.BlockSpec((1, bc), lambda im, ic: (im, ic))


def _fwd_call(x2, r2, a2, b2, out_dtype, interpret):
    m, c = x2.shape
    bm, bc, m_pad, c_pad = _blocks(m, c)
    grid = (m_pad // bm, c_pad // bc)
    xp = _pad2(x2, m_pad, c_pad)
    ap = _pad2(a2, 1, c_pad)
    bp = _pad2(b2, 1, c_pad)
    operands = [xp]
    in_specs = [_tile_spec(bm, bc)]
    kernel = _fwd_kernel
    if r2 is not None:
        operands.append(_pad2(r2, m_pad, c_pad))
        in_specs.append(_tile_spec(bm, bc))
        kernel = _fwd_res_kernel
    operands += [ap, bp]
    in_specs += [_row_spec(bc), _row_spec(bc)]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=_tile_spec(bm, bc),
        out_shape=jax.ShapeDtypeStruct((m_pad, c_pad), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*operands)
    return out[:m, :c]


def _bwd_call(x2, r2, dy2, a2, b2, interpret):
    m, c = x2.shape
    bm, bc, m_pad, c_pad = _blocks(m, c)
    nm, nc = m_pad // bm, c_pad // bc
    xp = _pad2(x2, m_pad, c_pad)
    dyp = _pad2(dy2, m_pad, c_pad)
    ap = _pad2(a2, 1, c_pad)
    bp = _pad2(b2, 1, c_pad)
    tile, row, part = _tile_spec(bm, bc), _row_spec(bc), _part_spec(bc)
    if r2 is None:
        dx, da_p, db_p = pl.pallas_call(
            _bwd_kernel,
            grid=(nm, nc),
            in_specs=[tile, tile, row, row],
            out_specs=[tile, part, part],
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, c_pad), x2.dtype),
                jax.ShapeDtypeStruct((nm, c_pad), jnp.float32),
                jax.ShapeDtypeStruct((nm, c_pad), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(xp, dyp, ap, bp)
        dr = None
    else:
        rp = _pad2(r2, m_pad, c_pad)
        dx, dr, da_p, db_p = pl.pallas_call(
            _bwd_res_kernel,
            grid=(nm, nc),
            in_specs=[tile, tile, tile, row, row],
            out_specs=[tile, tile, part, part],
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, c_pad), x2.dtype),
                jax.ShapeDtypeStruct((m_pad, c_pad), r2.dtype),
                jax.ShapeDtypeStruct((nm, c_pad), jnp.float32),
                jax.ShapeDtypeStruct((nm, c_pad), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(xp, rp, dyp, ap, bp)
        dr = dr[:m, :c]
    # (grid_rows, C) partials → per-channel vectors; an O(nm·C) XLA reduce.
    da = jnp.sum(da_p, axis=0)[:c]
    db = jnp.sum(db_p, axis=0)[:c]
    return dx[:m, :c], dr, da, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_plain(x2, a, b, out_dtype_name, interpret):
    return _fwd_call(x2, None, a[None, :], b[None, :],
                     jnp.dtype(out_dtype_name), interpret)


def _fused_plain_fwd(x2, a, b, out_dtype_name, interpret):
    y = _fwd_call(x2, None, a[None, :], b[None, :],
                  jnp.dtype(out_dtype_name), interpret)
    return y, (x2, a, b)


def _fused_plain_bwd(out_dtype_name, interpret, res, g):
    x2, a, b = res
    dx, _, da, db = _bwd_call(x2, None, g, a[None, :], b[None, :], interpret)
    return dx, da, db


_fused_plain.defvjp(_fused_plain_fwd, _fused_plain_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_res(x2, r2, a, b, out_dtype_name, interpret):
    return _fwd_call(x2, r2, a[None, :], b[None, :],
                     jnp.dtype(out_dtype_name), interpret)


def _fused_res_fwd(x2, r2, a, b, out_dtype_name, interpret):
    y = _fwd_call(x2, r2, a[None, :], b[None, :],
                  jnp.dtype(out_dtype_name), interpret)
    return y, (x2, r2, a, b)


def _fused_res_bwd(out_dtype_name, interpret, res, g):
    x2, r2, a, b = res
    dx, dr, da, db = _bwd_call(x2, r2, g, a[None, :], b[None, :], interpret)
    return dx, dr, da, db


_fused_res.defvjp(_fused_res_fwd, _fused_res_bwd)


def fused_bn_act(x: jax.Array, scale: jax.Array, bias: jax.Array,
                 mean: jax.Array, var: jax.Array, *, eps: float = 1e-5,
                 residual: jax.Array | None = None, out_dtype=None,
                 interpret: bool | None = None) -> jax.Array:
    """Fused BN epilogue: ``relu(normalize(x)·scale + bias [+ residual])``.

    ``x``/``residual``: any ``(..., C)`` layout (NHWC activations);
    ``scale``/``bias``/``mean``/``var``: per-channel fp32 vectors — the
    batch (or running) statistics are computed by the CALLER, which is what
    lets one kernel serve train mode, and lets autodiff through the a/b
    folding below recover the exact full BN gradient (the dmean/dvar paths
    ride the fold, not the kernel). Returns ``out_dtype`` (default: x's).

    Differentiable via a single-pass Pallas backward (see module docstring).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    f32 = jnp.float32
    a = scale.astype(f32) * jax.lax.rsqrt(var.astype(f32) + eps)
    b = bias.astype(f32) - mean.astype(f32) * a
    shape = x.shape
    c = shape[-1]
    out_dt = jnp.dtype(out_dtype) if out_dtype is not None \
        else jnp.dtype(x.dtype)
    x2 = x.reshape(-1, c)
    if residual is None:
        y2 = _fused_plain(x2, a, b, out_dt.name, interpret)
    else:
        if residual.shape != shape:
            raise ValueError(
                f"fused residual shape {residual.shape} != x {shape}")
        y2 = _fused_res(x2, residual.reshape(-1, c), a, b, out_dt.name,
                        interpret)
    return y2.reshape(shape)


def fused_bn_act_spmd(x: jax.Array, scale: jax.Array, bias: jax.Array,
                      mean: jax.Array, var: jax.Array, *, eps: float = 1e-5,
                      residual: jax.Array | None = None, out_dtype=None,
                      interpret: bool | None = None) -> jax.Array:
    """``fused_bn_act`` that composes with the GSPMD (jit + sharding rules)
    path — the fused-epilogue twin of ``flash_attention_spmd``.

    ``pallas_call`` has no SPMD partitioning rule, so inside a partitioned
    jit XLA would gather the activation and replicate the epilogue on every
    device — the structural stand-down that pinned ``--fused-bn`` off on
    every sharded path until this PR. But the epilogue needs NO cross-shard
    math at all (``relu(x·a + b [+ r])`` is elementwise over rows ×
    channels), so under an ambient mesh with Auto ``data``/``model`` axes
    this wraps the kernel in a nested manual ``shard_map``: batch rows
    shard over ``data``, channels (and the per-channel vectors) over
    ``model`` where divisible — exactly the layout the conv TP rules
    (``parallel/tensor_parallel``) give the surrounding convs, so no
    reshard is forced on either side. Each shard runs the kernel on its
    LOCAL block — the workload ``norm_dispatch.shard_local_workload``
    keys, records, and measures, so ``auto``'s never-pick-a-loser verdict
    is about the work a device actually executes.

    With no ambient mesh, inside an already-manual region (the shard_map
    DP path — local shapes already), or when nothing divides, this is
    ``fused_bn_act`` unchanged."""
    from jax.sharding import PartitionSpec as P

    # THE shared cut derivation (norm_dispatch.epilogue_shard_axes): the
    # axes this wrapper shards are BY CONSTRUCTION the axes the dispatch
    # key divided by — key/measure/execute cannot drift.
    from tpudist.ops.norm_dispatch import epilogue_shard_axes

    plain = functools.partial(fused_bn_act, eps=eps, residual=residual,
                              out_dtype=out_dtype, interpret=interpret)
    mesh, batch_ax, chan_ax = epilogue_shard_axes(x.shape)
    if batch_ax is None and chan_ax is None:
        return plain(x, scale, bias, mean, var)
    xs = P(batch_ax, *([None] * (x.ndim - 2)), chan_ax)
    vs = P(chan_ax)
    manual = frozenset(a for a in (batch_ax, chan_ax) if a)
    fn = functools.partial(fused_bn_act, eps=eps, out_dtype=out_dtype,
                           interpret=interpret)
    if residual is None:
        body = lambda x_, s_, b_, m_, v_: fn(x_, s_, b_, m_, v_)  # noqa: E731
        return jax.shard_map(
            body, mesh=mesh, axis_names=manual,
            in_specs=(xs, vs, vs, vs, vs), out_specs=xs,
            check_vma=False)(x, scale, bias, mean, var)
    body = lambda x_, s_, b_, m_, v_, r_: fn(  # noqa: E731
        x_, s_, b_, m_, v_, residual=r_)
    return jax.shard_map(
        body, mesh=mesh, axis_names=manual,
        in_specs=(xs, vs, vs, vs, vs, xs), out_specs=xs,
        check_vma=False)(x, scale, bias, mean, var, residual)


def reference_bn_act(x: jax.Array, scale: jax.Array, bias: jax.Array,
                     mean: jax.Array, var: jax.Array, *, eps: float = 1e-5,
                     residual: jax.Array | None = None,
                     out_dtype=None) -> jax.Array:
    """The pure-XLA twin of ``fused_bn_act`` with the EXACT op order the
    model call sites historically ran (f32 normalize → cast → add → relu):
    the fallback path in ``models/layers.py::BatchNorm``, the parity
    oracle for the interpret-mode tests, and the baseline side of
    ``norm_dispatch``'s micro-benchmark."""
    f32 = jnp.float32
    y = (x.astype(f32) - mean) * jax.lax.rsqrt(var.astype(f32) + eps)
    y = y * scale + bias
    y = y.astype(out_dtype or x.dtype)
    if residual is not None:
        y = y + residual
    return jax.nn.relu(y)
