"""Pallas TPU flash attention: fused blockwise softmax-attention kernel.

No reference equivalent — the reference has no attention at all (SURVEY.md §5
"long-context: absent entirely") and delegates every fused kernel to
cudnn/ATen (SURVEY.md §2.3). This is the framework's hand-written hot-op
path: where the reference leans on closed CUDA kernels, we lean on Pallas.

Forward (flash-attention-2 schedule mapped onto the TPU memory hierarchy):

- grid = (batch, heads, q_blocks, k_blocks), k innermost and marked
  "arbitrary" (sequential) so the running-softmax state carried in VMEM
  scratch is valid across k steps; batch/head/q are "parallel".
- Q stays resident in VMEM for all k steps of a q block; K/V blocks stream
  HBM→VMEM via the BlockSpec pipeline (Pallas double-buffers automatically).
- online softmax in fp32: running max ``m`` and normalizer ``l`` live in
  (block_q, 128) VMEM scratch (lane-broadcast — TPU vregs are 8×128, a
  (bq, 1) column would occupy a full vreg anyway), the unnormalized
  accumulator ``acc`` in (block_q, head_dim) fp32 scratch.
- the two matmuls (S = QKᵀ, O += P·V) hit the MXU in the input dtype
  (bf16 under the AMP policy) with fp32 accumulation; masking/exp/rescale
  fuse into the VPU between them.
- the softmax temperature is folded into Q once on the way in (one XLA
  elementwise pass) instead of rescaling every (bq, bk) score tile on the
  VPU — S = (scale·Q)Kᵀ is already scaled.
- masking is by GLOBAL position: causal (rows ≥ cols) and key-validity
  (cols < true key length, so sequence lengths that aren't block multiples —
  ViT's 197 tokens — are padded then exactly masked). The mask is built
  ONLY under configurations that statically need one (causal, or a key
  length that isn't a block multiple) — an exact-tiling non-causal call
  (the 2k-token bench shape) runs a mask-free VPU path. k blocks that are
  fully masked are skipped with ``pl.when`` (they cost a predicate, not
  FLOPs or DMA-compute).

Backward (VERDICT r5 weak #2 — the rebuilt two-pass schedule):

FlashAttention-2's core lesson is that the backward is where naive tiling
drowns: it must be two dedicated passes with the right grid parallelism,
each recomputing probabilities from the forward's saved per-row logsumexp —
never one recompute-everything loop and never an O(T²) tensor.

- **dKV pass**: grid (batch, heads, k_blocks, q_blocks), q innermost
  sequential — each program owns one (block_k, d) dK/dV tile in fp32 VMEM
  scratch and streams Q/dO blocks past it. dK needs no epilogue scale:
  contracting dS (unscaled) against the pre-scaled Q IS the scaled dK.
- **dQ pass**: grid (batch, heads, q_blocks, k_blocks), k innermost
  sequential — each program owns one (block_q, d) dQ tile and streams K/V
  blocks; the temperature is applied once per tile in the epilogue.
- both reuse the forward's saved logsumexp and the precomputed
  ``delta = rowsum(dO ∘ O)`` (an XLA-fused elementwise+reduce outside the
  kernels) instead of rematerializing the softmax normalization per tile,
  so each pass is exactly two MXU matmuls of recompute (S and dP) plus its
  two gradient matmuls.
- accumulators are fp32 over bf16 MXU operands; block sizes default to
  128×128 (a whole MXU tile per matmul, (8, 128)-aligned) and the backward
  blocks are independently tunable (``block_q_bwd``/``block_k_bwd``) from
  the forward's, since the dKV pass wants its resident tile on the KV dim
  while the forward wants it on Q.
- zero-padded Q rows cancel exactly (their dO and delta rows are zero), so
  only key-padding and causality ever generate a mask — the same static
  specialization as the forward.

Whether this kernel actually beats XLA attention *in training* on a real
chip is decided by measurement, not by this docstring: the dispatch layer
(``tpudist/ops/attention_dispatch``) A/Bs both backends per shape and
caches the winner per device kind. ``KERNEL_REV`` below invalidates those
cached verdicts whenever the kernel changes.

Falls back to interpreter mode off-TPU so CPU tests exercise the same kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax<0.6 names it TPUCompilerParams (same fields we use).
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30
_LANES = 128

# Bumped whenever kernel math/scheduling changes: attention_dispatch keys its
# cached flash-vs-XLA verdicts on this, so a rebuilt kernel re-measures
# instead of inheriting the old kernel's win/loss record.
#   rev 2: two-pass backward rebuilt — scale folded into Q, static mask
#          specialization, independent backward block sizes.
KERNEL_REV = 2


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                  *, causal: bool, block_q: int, block_k: int,
                  num_k_blocks: int, q_len: int, k_len: int, mask_k: bool):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    # Causal convention matches the XLA `attention` (tril with offset
    # k_len - q_len): query row i attends keys ≤ i + k_len - q_len, so with
    # a key prefix (k_len > q_len) the last query still sees every key.
    offset = k_len - q_len

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Skip blocks with no unmasked column: fully beyond the true key length,
    # or (causal) strictly above the diagonal.
    run = ik * block_k < k_len
    if causal:
        run = jnp.logical_and(
            run, iq * block_q + block_q - 1 + offset >= ik * block_k)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                                     # (bq, d), scaled
        k = k_ref[0, 0]                                     # (bk, d)
        v = v_ref[0, 0]                                     # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk) f32

        # Mask only under configs that statically need one (mask_k: the key
        # length isn't a block multiple). Padded q ROWS need none: they are
        # dropped on the way out, and their lse guard below keeps them 0.
        s, valid = _masked_scores(s, iq, ik, causal=causal,
                                  block_q=block_q, block_k=block_k,
                                  q_len=q_len, k_len=k_len, mask_k=mask_k)

        m_prev = m_scr[:, :1]                               # (bq, 1)
        l_prev = l_scr[:, :1]
        m_curr = jnp.max(s, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)                             # (bq, bk)
        if valid is not None:
            p = jnp.where(valid, p, 0.0)    # exp(-1e30-m)≈0 anyway
        l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)

        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, d) f32
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(ik == num_k_blocks - 1)
    def _finish():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        # Fully-masked rows (padded q rows, dropped on the way out): emit 0,
        # not NaN.
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[...] / l).astype(o_ref.dtype)
        # Per-row logsumexp, saved for the backward recompute. Stored with a
        # trailing singleton dim, (B, H, Tq, 1): Mosaic requires the last two
        # block dims be (multiple-of-8, multiple-of-128-or-full-dim) — a
        # rank-3 (1, 1, block_q) block puts the size-1 head slice in the
        # sublane position and fails to lower on real TPU hardware.
        lse_ref[0, 0] = m + jnp.log(l)


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=(
    "causal", "block_q", "block_k", "block_q_bwd", "block_k_bwd",
    "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, block_q_bwd: int | None = None,
                    block_k_bwd: int | None = None,
                    interpret: bool | None = None):
    """Fused attention. Shapes [B, T, H, D] (sequence-major, matching
    ``tpudist.parallel.ring_attention.attention``); returns [B, T, H, D].

    Numerics: fp32 online softmax, MXU matmuls in the input dtype with fp32
    accumulation — same contract as the pure-XLA ``attention`` it replaces.

    Differentiable: the backward is flash too — two dedicated Pallas passes
    (a dKV pass parallel over KV blocks, a dQ pass parallel over Q blocks)
    recompute the probabilities blockwise from the saved per-row logsumexp
    and the precomputed ``delta = rowsum(dO ∘ O)``; no O(T²) tensor is ever
    materialized. ``block_q_bwd``/``block_k_bwd`` tune the backward blocks
    independently of the forward's (None = same as forward).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_vjp(q, k, v, causal, block_q, block_k,
                      block_q_bwd or block_q, block_k_bwd or block_k,
                      interpret)


def flash_attention_spmd(q: jax.Array, k: jax.Array, v: jax.Array,
                         causal: bool = False, **kw):
    """``flash_attention`` that composes with the GSPMD (jit + sharding
    rules) path — VERDICT r4 next #4.

    ``pallas_call`` has no SPMD partitioning rule, so inside a partitioned
    jit XLA would all-gather Q/K/V and replicate attention on every device
    (the r4 limitation that forced ``--flash off`` under TP). But the kernel
    needs no cross-shard math for batch or head shardings — TP shards whole
    heads by construction (``tensor_parallel.VIT_RULES`` column-shards the
    head-major in_proj) — so under an ambient mesh with Auto 'data'/'model'
    axes this wraps the kernel in a nested full-manual ``shard_map``: each
    shard runs the kernel on its local (batch-block, head-block), exactly
    the math the partitioner would otherwise have to reconstruct. The GSPMD
    step builders provide the ambient mesh via ``jax.sharding.set_mesh``.

    Everywhere else this is ``flash_attention`` unchanged: with no ambient
    mesh (eager, plain-jit single device) or inside an already-manual
    region (the shard_map DP/PP/SP step bodies) there is nothing to wrap.
    On jax<0.8 the ambient mesh comes through the ``_jaxshim``
    ``get_abstract_mesh`` backfill (the set_mesh context), so the nested
    manual region works on every supported jax instead of standing down
    to gather-and-replicate.
    """
    from jax.sharding import PartitionSpec as P

    from tpudist._jaxshim import ambient_auto_axes

    mesh, auto = ambient_auto_axes(("data", "model"))
    if "data" in auto and q.shape[0] % mesh.shape["data"]:
        # An undivisible batch cannot shard; drop the axis rather than die
        # (the partitioner then handles the batch dim — correct, slower).
        auto = auto - {"data"}
    if not auto:
        return flash_attention(q, k, v, causal=causal, **kw)
    if "model" in auto and q.shape[2] % mesh.shape["model"]:
        raise ValueError(
            f"flash attention under TP needs the model-axis size "
            f"{mesh.shape['model']} to divide num_heads={q.shape[2]}")
    spec = P("data" if "data" in auto else None, None,
             "model" if "model" in auto else None, None)
    fn = functools.partial(flash_attention, causal=causal, **kw)
    return jax.shard_map(fn, mesh=mesh, axis_names=frozenset(auto),
                         in_specs=(spec,) * 3, out_specs=spec,
                         check_vma=False)(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_vjp(q, k, v, causal, block_q, block_k, block_q_bwd, block_k_bwd,
               interpret):
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, block_q_bwd,
                   block_k_bwd, interpret):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, block_q, block_k, block_q_bwd, block_k_bwd,
                   interpret, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal, block_q_bwd,
                           block_k_bwd, interpret)


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _scaled_q(q, d: int):
    """Softmax temperature folded into Q once (fp32 multiply, cast back to
    the MXU input dtype) — S = (scale·Q)Kᵀ needs no per-tile VPU rescale,
    and dK = dSᵀ·(scale·Q) comes out scaled for free in the backward."""
    scale = 1.0 / (d ** 0.5)
    return (q.astype(jnp.float32) * scale).astype(q.dtype)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    tk = k.shape[1]

    block_q = min(block_q, _ceil_to(t, 8))
    block_k = min(block_k, _ceil_to(tk, 8))
    tq_pad = _ceil_to(t, block_q)
    tk_pad = _ceil_to(tk, block_k)

    # (B, T, H, D) → (B, H, T, D); pad T so the grid tiles exactly. Padded
    # keys are masked inside the kernel (k_len); padded q rows drop on exit.
    qt = jnp.moveaxis(_scaled_q(q, d), 1, 2)
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    if tq_pad != t:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, tq_pad - t), (0, 0)))
    if tk_pad != tk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, tk_pad - tk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, tk_pad - tk), (0, 0)))

    nq = tq_pad // block_q
    nk = tk_pad // block_k

    kernel = functools.partial(
        _flash_kernel, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=nk, q_len=t, k_len=tk,
        mask_k=tk_pad != tk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)

    out = out[:, :, :t, :]
    return jnp.moveaxis(out, 1, 2), lse


def _masked_scores(s, iq, ik, *, causal, block_q, block_k, q_len, k_len,
                   mask_k):
    """Static mask specialization shared by the forward and both backward
    passes: build the (bq, bk) validity mask only under configs that need
    one — key padding (``mask_k``) or causality (global-position tril with
    the k_len−q_len offset, matching the XLA ``attention``). Zero-padded q
    rows need NO mask anywhere: the forward drops them on the way out (its
    l==0 guard), and in the backward their dO and delta rows are zero, so
    every contribution they could make (dV += Pᵀ·dO, dS = P·(dP − δ))
    cancels exactly; the only hazard — exp(s − (−inf)) from their forward
    lse — is removed by the backward's lse clamp. Returns (masked scores,
    valid-or-None): the forward also zeroes its probabilities by
    ``valid``."""
    offset = k_len - q_len
    valid = None
    if mask_k:
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < k_len
    if causal:
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        c = rows + offset >= cols
        valid = c if valid is None else jnp.logical_and(valid, c)
    if valid is not None:
        s = jnp.where(valid, s, NEG_INF)
    return s, valid


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale: float, causal: bool, block_q: int,
                   block_k: int, num_k_blocks: int, q_len: int, k_len: int,
                   mask_k: bool):
    """dQ pass: parallel over q blocks, k blocks stream sequentially.

    The (block_q, d) dQ tile accumulates in fp32 scratch across the k
    stream; the temperature (folded out of dS) is applied once per tile in
    the epilogue instead of once per (bq, bk) score tile."""
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    offset = k_len - q_len

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = ik * block_k < k_len
    if causal:
        run = jnp.logical_and(
            run, iq * block_q + block_q - 1 + offset >= ik * block_k)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                                     # (bq, d), scaled
        k = k_ref[0, 0]                                     # (bk, d)
        v = v_ref[0, 0]                                     # (bk, d)
        do = do_ref[0, 0]                                   # (bq, d)
        lse = lse_ref[0, 0]                                 # (bq, 1)
        delta = delta_ref[0, 0]                             # (bq, 1)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        s, _ = _masked_scores(s, iq, ik, causal=causal, block_q=block_q,
                              block_k=block_k, q_len=q_len, k_len=k_len,
                              mask_k=mask_k)
        # p from the saved statistics — no second softmax pass.
        p = jnp.exp(s - lse)                                 # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta)                                # (bq, bk)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, d)

    @pl.when(ik == num_k_blocks - 1)
    def _finish():
        dq_ref[0, 0, :, :] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                    block_q: int, block_k: int, num_q_blocks: int, q_len: int,
                    k_len: int, mask_k: bool):
    """dKV pass: parallel over KV blocks, q blocks stream sequentially.

    Each program owns one (block_k, d) dK tile and one dV tile in fp32
    scratch and streams Q/dO past them. Everything stays (bq, bk)-oriented —
    probabilities are transposed only implicitly, by contracting over the q
    dim in the two gradient matmuls. (A materialized (1, bq) lse/delta row
    would need a sublane→lane relayout that Mosaic can't lower; a (bq, 1)
    column is native.) dK needs no epilogue scale: Q arrives pre-scaled, and
    dK = dSᵀ·(scale·Q) IS the scaled gradient."""
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    offset = k_len - q_len

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = iq * block_q < q_len
    if causal:
        # A k block contributes only to q rows at/below its diagonal.
        run = jnp.logical_and(
            run, iq * block_q + block_q - 1 + offset >= ik * block_k)

    @pl.when(run)
    def _step():
        k = k_ref[0, 0]                                     # (bk, d)
        v = v_ref[0, 0]                                     # (bk, d)
        q = q_ref[0, 0]                                     # (bq, d), scaled
        do = do_ref[0, 0]                                   # (bq, d)
        lse = lse_ref[0, 0]                                 # (bq, 1)
        delta = delta_ref[0, 0]                             # (bq, 1)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        s, _ = _masked_scores(s, iq, ik, causal=causal, block_q=block_q,
                              block_k=block_k, q_len=q_len, k_len=k_len,
                              mask_k=mask_k)
        p = jnp.exp(s - lse)                                 # (bq, bk)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta)                                # (bq, bk)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, d)

    @pl.when(iq == num_q_blocks - 1)
    def _finish():
        dk_ref[0, 0, :, :] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k, interpret):
    """Two-pass flash backward (see module docstring): a dQ pass parallel
    over q blocks and a dKV pass parallel over KV blocks, sharing the saved
    ``lse`` and the XLA-precomputed ``delta = rowsum(dO ∘ O)``."""
    b, t, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    block_q = min(block_q, _ceil_to(t, 8))
    block_k = min(block_k, _ceil_to(tk, 8))
    tq_pad = _ceil_to(t, block_q)
    tk_pad = _ceil_to(tk, block_k)
    mask_k = tk_pad != tk

    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                 # (b, t, h)
    delta = jnp.moveaxis(delta, -1, 1)                       # (b, h, t)

    qt = jnp.moveaxis(_scaled_q(q, d), 1, 2)
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    dot = jnp.moveaxis(g, 1, 2)
    # The forward's lse is padded to the FORWARD q-block multiple, which may
    # differ from this pass's (block_q_bwd): re-pad from the true length.
    # Fully-masked (padded) q rows carry lse = NEG_INF; exp(s - NEG_INF)
    # would overflow to inf → NaN via inf·0 in the matmuls, so clamp those
    # rows to 0 — with the clamp their contributions cancel exactly (zero
    # dO/delta rows), which is why the backward kernels need no q-row mask.
    # Both per-row stats ride in the (B, H, Tq, 1) layout (see _flash_kernel's
    # _finish for why rank-3 blocks don't lower on TPU).
    lse_safe = jnp.where(lse[:, :, :t] <= NEG_INF / 2, 0.0, lse[:, :, :t])
    if tq_pad != t:
        pad_q = ((0, 0), (0, 0), (0, tq_pad - t), (0, 0))
        qt = jnp.pad(qt, pad_q)
        dot = jnp.pad(dot, pad_q)
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, tq_pad - t)))
        lse_safe = jnp.pad(lse_safe, ((0, 0), (0, 0), (0, tq_pad - t),
                                      (0, 0)))
    if tk_pad != tk:
        pad_k = ((0, 0), (0, 0), (0, tk_pad - tk), (0, 0))
        kt = jnp.pad(kt, pad_k)
        vt = jnp.pad(vt, pad_k)
    delta = delta[..., None]

    nq = tq_pad // block_q
    nk = tk_pad // block_k

    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b_, h_, iq, ik: (b_, h_, iq, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          q_len=t, k_len=tk, mask_k=mask_k),
        grid=(b, h, nq, nk),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, iq, ik: (b_, h_, ik, 0)),
            q_spec, row_spec, row_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_safe, delta)

    k_spec = pl.BlockSpec((1, 1, block_k, d),
                          lambda b_, h_, ik, iq: (b_, h_, ik, 0))
    q_spec_b = pl.BlockSpec((1, 1, block_q, d),
                            lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    row_spec_b = pl.BlockSpec((1, 1, block_q, 1),
                              lambda b_, h_, ik, iq: (b_, h_, iq, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          q_len=t, k_len=tk, mask_k=mask_k),
        grid=(b, h, nk, nq),
        in_specs=[k_spec, k_spec, q_spec_b, q_spec_b, row_spec_b, row_spec_b],
        out_specs=[k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, tk_pad, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, tk_pad, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(kt, vt, qt, dot, lse_safe, delta)

    dq = jnp.moveaxis(dq[:, :, :t, :], 1, 2)
    dk = jnp.moveaxis(dk[:, :, :tk, :], 1, 2)
    dv = jnp.moveaxis(dv[:, :, :tk, :], 1, 2)
    return dq, dk, dv
