"""Hand-written Pallas TPU kernels for the framework's hot ops.

The reference consumes its fused kernels from cudnn/ATen binaries
(SURVEY.md §2.3); here they are in-repo, written against the TPU memory
hierarchy (HBM→VMEM pipelines, MXU matmuls, VPU elementwise), with
interpreter-mode fallback so the same kernels run in CPU tests.
"""

from tpudist.ops.pallas.flash_attention import (  # noqa: F401
    flash_attention, flash_attention_spmd)
