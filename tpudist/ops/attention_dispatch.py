"""Measurement-honest attention-kernel dispatch (``--flash auto``).

VERDICT r5 weak #2: the hand-written Pallas flash kernel *lost* to plain XLA
attention in training (fwd+bwd −23% at the ViT-B shape, −33% at 2k tokens,
``benchmarks/results/flash_r3_tpu.json``) while ``--flash auto`` still
selected it on TPU — default ViT training was slower than if the kernel
didn't exist. The root failure wasn't the kernel; it was *auto deciding
without a measurement*.

This module makes the decision empirical:

- ``decide()`` resolves ``--flash auto`` by running a one-time on-device
  micro-benchmark of flash-vs-XLA **for the exact attention workload**
  (batch, seq, heads, head_dim, dtype, train-vs-eval, causal), picks the
  winner, and **never selects a kernel that loses its own measurement**
  (ties go to XLA — the compiler baseline needs no justification, the
  custom kernel does).
- verdicts are cached in a per-``device_kind`` JSON file (one file per chip
  generation — a v4 verdict must never dispatch a v5e) keyed by the shape
  key AND the kernel revision (``flash_attention.KERNEL_REV``), so a
  rebuilt kernel re-measures instead of inheriting the old kernel's
  win/loss record. ``clear_cache()`` / deleting the file forces a
  re-measure.
- off-TPU, ``auto`` resolves to XLA attention immediately — no Pallas
  import, no measurement (interpreter-mode timings are meaningless).
- with **no** cache entry and no opportunity to measure (``lookup()``, the
  trace-safe path models use), auto resolves to XLA: an unmeasured custom
  kernel is never the default.
- every resolution is reportable as a schema-valid ``attention_dispatch``
  telemetry event (``event_fields``), so ``summarize`` and the bench
  history show *which* kernel trained and by what measured margin.

The micro-benchmark is injectable (``measure_pair``) so the honesty
properties are unit-testable with synthetic timings on CPU
(``tests/test_attention_dispatch.py``).
"""

from __future__ import annotations

import datetime
import json
import os
import re
import time
from typing import Callable, Optional

MODES = ("auto", "on", "off")

ENV_CACHE_DIR = "TPUDIST_DISPATCH_CACHE"
CACHE_VERSION = 1


def default_cache_dir() -> str:
    """Where dispatch verdicts persist across runs: ``TPUDIST_DISPATCH_CACHE``
    or ``~/.cache/tpudist``. Deliberately NOT the run dir — ``--overwrite
    delete`` would discard the measurement the next run needs."""
    env = os.environ.get(ENV_CACHE_DIR, "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "tpudist")


def _slug(device_kind: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "-", device_kind.strip()) or "unknown"


def cache_path(device_kind: str, cache_dir: Optional[str] = None) -> str:
    """One JSON file per device kind: ``attention_dispatch.<kind>.json``."""
    return os.path.join(cache_dir or default_cache_dir(),
                        f"attention_dispatch.{_slug(device_kind)}.json")


def shape_key(batch: int, seq: int, heads: int, head_dim: int, dtype,
              train: bool, causal: bool) -> str:
    """The dispatch identity: the exact attention workload. ``dtype`` may be
    a jnp/numpy dtype, scalar type, or string — normalized to the canonical
    dtype name so every spelling of bfloat16 keys the same cache entry."""
    try:
        import numpy as np
        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, "name", None) or str(dtype)
    return (f"b{batch}_t{seq}_h{heads}_d{head_dim}_{name}_"
            f"{'train' if train else 'eval'}_"
            f"{'causal' if causal else 'full'}")


def kernel_rev() -> int:
    """The flash kernel's revision stamp — imported lazily so the cache /
    decision plumbing never drags Pallas in on the XLA-only path."""
    from tpudist.ops.pallas.flash_attention import KERNEL_REV
    return KERNEL_REV


def load_cache(path: str) -> dict:
    """Cache file contents ({} shell on missing/corrupt — a torn write must
    degrade to a re-measure, never crash a training run)."""
    try:
        with open(path) as f:
            obj = json.load(f)
        if isinstance(obj, dict) and obj.get("version") == CACHE_VERSION \
                and isinstance(obj.get("entries"), dict):
            return obj
    except (OSError, ValueError):
        pass
    return {"version": CACHE_VERSION, "entries": {}}


def save_cache(path: str, cache: dict) -> None:
    """Atomic write (tmp + rename): a preempted rank mid-save must not leave
    a torn JSON that poisons every later run's load."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cache, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def clear_cache(device_kind: Optional[str] = None,
                cache_dir: Optional[str] = None) -> int:
    """Drop cached verdicts (all device kinds, or one). Returns the number
    of cache files removed — the documented invalidation path alongside the
    automatic ``KERNEL_REV`` mismatch."""
    d = cache_dir or default_cache_dir()
    removed = 0
    if device_kind is not None:
        paths = [cache_path(device_kind, d)]
    else:
        try:
            paths = [os.path.join(d, n) for n in os.listdir(d)
                     if n.startswith("attention_dispatch.")
                     and n.endswith(".json")]
        except OSError:
            paths = []
    for p in paths:
        try:
            os.remove(p)
            removed += 1
        except OSError:
            pass
    return removed


def flash_eligible(*, seq: int, head_dim: int, bias: bool = False,
                   dtype=None) -> tuple[bool, str]:
    """Central static-eligibility check, consulted by every attention call
    site BEFORE any dispatch question is asked. The windowed-attention
    families (swin, maxvit) carry an additive relative-position bias (and
    swin-v2 cosine attention) the Pallas kernel does not implement — for
    them eligibility is statically False and the XLA path IS the dispatched
    choice, recorded here in one place instead of five model files."""
    if bias:
        return False, ("additive attention bias is not implemented by the "
                       "flash kernel")
    if head_dim > 256:
        return False, f"head_dim {head_dim} exceeds the kernel's VMEM tiling"
    if seq < 16:
        return False, (f"seq {seq} is below one (8,128) tile — blockwise "
                       f"streaming cannot win")
    return True, "eligible"


def measure_ms(fn, args, steps: int = 10, warmup: int = 2) -> float:
    """THE on-device timing harness (mean ms/call over ``steps`` after
    ``warmup``), shared with ``benchmarks/bench_flash.py`` so dispatch
    verdicts and bench rows cannot drift in methodology. Completion is
    forced via ``device_get`` of a value depending on the full computation:
    ``block_until_ready`` returns at enqueue-ack over the remote tunnel —
    the same guard bench.py documents."""
    import jax
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / steps * 1e3


def measure_attention(batch: int, seq: int, heads: int, head_dim: int,
                      dtype, train: bool, causal: bool,
                      steps: int = 10, warmup: int = 2) -> tuple[float, float]:
    """The on-device micro-benchmark: (flash_ms, xla_ms) at the exact shape.
    ``train`` times forward+backward (grad wrt q/k/v — the configuration the
    r3 capture showed the kernel losing); eval times forward only. Only
    meaningful on an accelerator — callers gate on platform."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.ops.pallas import flash_attention
    from tpudist.parallel.ring_attention import attention

    rng = np.random.default_rng(0)
    shape = (batch, seq, heads, head_dim)
    q, k, v = (jnp.asarray(rng.standard_normal(shape), dtype)
               for _ in range(3))

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, causal=causal)

    def xla_fn(q, k, v):
        return attention(q, k, v, causal=causal)

    if train:
        def loss(fn):
            def f(q, k, v):
                return fn(q, k, v).astype(jnp.float32).sum()
            return f
        flash_c = jax.jit(jax.grad(loss(flash_fn), argnums=(0, 1, 2)))
        xla_c = jax.jit(jax.grad(loss(xla_fn), argnums=(0, 1, 2)))
    else:
        flash_c = jax.jit(flash_fn)
        xla_c = jax.jit(xla_fn)

    flash_ms = measure_ms(flash_c, (q, k, v), steps, warmup)
    xla_ms = measure_ms(xla_c, (q, k, v), steps, warmup)
    return flash_ms, xla_ms


def _now_iso() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def decide(batch: int, seq: int, heads: int, head_dim: int, dtype,
           *, train: bool = True, causal: bool = False, mode: str = "auto",
           cache_dir: Optional[str] = None,
           measure_pair: Optional[Callable[[], tuple[float, float]]] = None,
           refresh: bool = False, platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> dict:
    """Resolve the attention backend for one workload. Returns a decision
    dict: ``kernel`` ("flash"|"xla"), ``mode``, ``source`` ("forced" |
    "platform" | "cache" | "measured"), timings/margin when measured, and
    cache provenance.

    The honesty invariant: under ``auto`` the flash kernel is selected ONLY
    off the back of a measurement it won (fresh or cached for this
    device_kind + shape + kernel rev). ``measure_pair`` injects the
    benchmark (tests use synthetic timings; bench_flash reuses its own
    measured rows); default is ``measure_attention`` at the given shape.
    """
    if mode not in MODES:
        raise ValueError(f"flash mode must be one of {MODES}, got {mode!r}")
    key = shape_key(batch, seq, heads, head_dim, dtype, train, causal)
    out = {"kernel": "xla", "mode": mode, "source": "platform", "key": key,
           "flash_ms": None, "xla_ms": None, "margin": None,
           "cache_hit": False}

    if mode in ("on", "off"):
        out["kernel"] = "flash" if mode == "on" else "xla"
        out["source"] = "forced"
        return out

    # Static eligibility BEFORE anything touches a device: a shape the
    # kernel cannot tile must not reach measure_attention (where the Pallas
    # probe would just crash) — `auto` resolves it to XLA outright. Forced
    # `on` above deliberately bypasses this (A/B and tiny-shape test work).
    ok, why = flash_eligible(seq=seq, head_dim=head_dim)
    if not ok:
        out["source"] = "ineligible"
        out["reason"] = why
        return out

    if platform is None:
        import jax
        platform = jax.default_backend()
    out["platform"] = platform
    if platform != "tpu":
        # auto off-TPU IS the XLA path: no Pallas import, no measurement —
        # interpreter-mode timings would be noise dressed as data.
        return out

    import jax
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    out["device_kind"] = device_kind
    rev = kernel_rev()
    out["kernel_rev"] = rev
    path = cache_path(device_kind, cache_dir)
    out["cache_path"] = path
    cache = load_cache(path)
    entry = cache["entries"].get(key)
    if entry and entry.get("kernel_rev") == rev and not refresh:
        out.update(kernel=entry["kernel"], source="cache", cache_hit=True,
                   flash_ms=entry.get("flash_ms"),
                   xla_ms=entry.get("xla_ms"),
                   margin=entry.get("margin"),
                   measured_at=entry.get("measured_at"))
        return out

    if measure_pair is None:
        measure_pair = lambda: measure_attention(  # noqa: E731
            batch, seq, heads, head_dim, dtype, train, causal)
    flash_ms, xla_ms = measure_pair()
    # Strict win required: a tie keeps the compiler baseline. The custom
    # kernel must EARN dispatch; XLA never has to.
    winner = "flash" if flash_ms < xla_ms else "xla"
    loser_ms = max(flash_ms, xla_ms)
    margin = (loser_ms - min(flash_ms, xla_ms)) / loser_ms if loser_ms else 0.0
    out.update(kernel=winner, source="measured", flash_ms=round(flash_ms, 4),
               xla_ms=round(xla_ms, 4), margin=round(margin, 4),
               measured_at=_now_iso())
    cache["device_kind"] = device_kind
    cache["entries"][key] = {
        "kernel": winner, "flash_ms": out["flash_ms"],
        "xla_ms": out["xla_ms"], "margin": out["margin"],
        "kernel_rev": rev, "measured_at": out["measured_at"],
    }
    try:
        save_cache(path, cache)
    except OSError:
        # A read-only cache dir degrades to re-measuring next run — the
        # decision itself stands.
        out["cache_path"] = None
    return out


def lookup(batch: int, seq: int, heads: int, head_dim: int, dtype,
           *, train: bool = True, causal: bool = False,
           cache_dir: Optional[str] = None,
           platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> bool:
    """Trace-safe resolution for model call sites (``flash=None``): consults
    platform + cache only, NEVER measures (a micro-benchmark cannot run
    while the train step is being traced). No cache entry on TPU → False:
    an unmeasured custom kernel is never the default — the Trainer (or
    bench) warms the cache for the shapes it runs by calling ``decide()``
    outside the trace."""
    if not flash_eligible(seq=seq, head_dim=head_dim)[0]:
        return False
    if platform is None:
        import jax
        platform = jax.default_backend()
    if platform != "tpu":
        return False
    import jax
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    key = shape_key(batch, seq, heads, head_dim, dtype, train, causal)
    entry = load_cache(cache_path(device_kind, cache_dir))["entries"].get(key)
    return bool(entry and entry.get("kernel_rev") == kernel_rev()
                and entry.get("kernel") == "flash")


def shared_decision(outpath: str, primary: bool, decide_fn,
                    *, expect_key: Optional[str] = None,
                    timeout_s: float = 300.0, poll_s: float = 0.25,
                    log=None) -> dict:
    """One decision for the whole gang. A per-rank micro-benchmark is noisy:
    at a near-tie shape, hosts could measure opposite winners and compile
    DIFFERENT attention backends into one SPMD program — non-reproducible
    trajectories, divergent per-rank grads. So the primary rank decides and
    publishes ``attention_dispatch.json`` into the (shared-filesystem) run
    dir; every other rank reads that instead of measuring.

    The run dir can carry a decision file from a previous attempt or run
    (``--overwrite keep`` + restart, possibly across a KERNEL_REV bump), so
    peers only adopt a file stamped with THEIR launcher attempt
    (``telemetry.env_attempt``) whose shape key and kernel rev still match —
    anything else is treated as absent until the live primary overwrites
    it. A primary whose probe raises publishes the failure instead, so
    peers fail over immediately and *identically* (every rank degrades to
    the caller's model-level-lookup path) rather than burning the full
    timeout and then measuring into a possibly-split gang. A non-primary
    rank that times out (primary mid-compile over a slow tunnel) falls back
    to its own decision — logged loudly, because the gang may now be split.
    """
    from tpudist.telemetry import env_attempt
    attempt = env_attempt()
    path = os.path.join(outpath, "attention_dispatch.json")

    def _publish(obj: dict) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1)
        os.replace(tmp, path)

    if primary:
        try:
            dec = decide_fn()
        except Exception as e:
            try:
                _publish({"failed": repr(e)[:500], "key": expect_key,
                          "attempt": attempt})
            except OSError:
                pass
            raise
        try:
            _publish(dict(dec, attempt=attempt))
        except OSError as e:
            if log is not None:
                log(f"attention dispatch: could not publish decision "
                    f"({e!r}) — peers will decide independently")
        return dec

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with open(path) as f:
                dec = json.load(f)
        except (OSError, ValueError):
            dec = None
        fresh = (isinstance(dec, dict)
                 and dec.get("attempt") == attempt
                 and (expect_key is None or dec.get("key") == expect_key)
                 and ("kernel_rev" not in dec
                      or dec["kernel_rev"] == kernel_rev()))
        if fresh:
            if dec.get("failed"):
                raise RuntimeError(
                    "primary's attention dispatch probe failed: "
                    f"{dec['failed']}")
            if dec.get("kernel"):
                dec["shared_from_primary"] = 1
                return dec
        time.sleep(poll_s)
    if log is not None:
        log(f"attention dispatch: primary's decision file did not appear "
            f"within {timeout_s:.0f}s — deciding independently (gang may "
            f"mix attention backends this run)")
    return decide_fn()


def event_fields(decision: dict) -> dict:
    """The decision as telemetry-event fields (type ``attention_dispatch``,
    schema in tpudist/telemetry.py). Numeric-or-None timings; the winner,
    mode, provenance, shape key, and measured margin all ride along so
    ``summarize`` can print the dispatch line without re-reading the
    cache."""
    out = {"kernel": decision["kernel"], "mode": decision["mode"],
           "source": decision["source"], "shape_key": decision.get("key")}
    for f in ("flash_ms", "xla_ms", "margin"):
        if isinstance(decision.get(f), (int, float)):
            out[f] = decision[f]
    if decision.get("cache_hit"):
        out["cache_hit"] = 1
    if decision.get("reason"):
        out["reason"] = decision["reason"]
    if decision.get("shared_from_primary"):
        out["shared_from_primary"] = 1
    if decision.get("device_kind"):
        out["dispatch_device_kind"] = decision["device_kind"]
    return out
