"""Measurement-honest attention-kernel dispatch (``--flash auto``) — a thin
client of the generic dispatch layer (``tpudist/ops/dispatch``).

VERDICT r5 weak #2: the hand-written Pallas flash kernel *lost* to plain XLA
attention in training (fwd+bwd −23% at the ViT-B shape, −33% at 2k tokens,
``benchmarks/results/flash_r3_tpu.json``) while ``--flash auto`` still
selected it on TPU — default ViT training was slower than if the kernel
didn't exist. The root failure wasn't the kernel; it was *auto deciding
without a measurement*. PR 5 made the decision empirical; PR 6 hoisted the
machinery (cache, timing harness, never-pick-a-loser invariant, multi-host
shared verdict) into ``ops/dispatch`` so the fused-norm kernels
(``ops/norm_dispatch``) ride the SAME policy instead of a drifting copy.

What stays attention-specific here — and ONLY this:

- the workload identity (``shape_key``: batch, seq, heads, head_dim, dtype,
  train-vs-eval, causal);
- static eligibility (``flash_eligible``: the windowed-attention families'
  additive bias, head_dim/seq tiling limits);
- the on-device micro-benchmark (``measure_attention``: flash vs XLA
  attention, fwd or fwd+bwd, at the exact shape);
- the kernel revision (``flash_attention.KERNEL_REV``, imported lazily so
  the XLA-only path never drags Pallas in);
- the telemetry-event projection (``event_fields``).

Everything else — ``decide``/``lookup``/``shared_decision``/cache
round-trips — delegates to the generic layer with ``names=("flash",
"xla")``, which keeps this module's decision dicts, cache files
(``attention_dispatch.<kind>.json``) and shared-verdict file
(``attention_dispatch.json``) byte-compatible with PR 5's.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

from tpudist.ops import dispatch

CLIENT = "attention_dispatch"
NAMES = ("flash", "xla")

# Re-exported so existing callers (bench_flash's timing rows, tests, tools)
# keep ONE surface; these ARE the generic layer's objects — no copies.
MODES = dispatch.MODES
ENV_CACHE_DIR = dispatch.ENV_CACHE_DIR
CACHE_VERSION = dispatch.CACHE_VERSION
default_cache_dir = dispatch.default_cache_dir
load_cache = dispatch.load_cache
save_cache = dispatch.save_cache
measure_ms = dispatch.measure_ms

cache_path = partial(dispatch.cache_path, CLIENT)
clear_cache = partial(dispatch.clear_cache, CLIENT)


def shape_key(batch: int, seq: int, heads: int, head_dim: int, dtype,
              train: bool, causal: bool) -> str:
    """The dispatch identity: the exact attention workload. ``dtype`` may be
    a jnp/numpy dtype, scalar type, or string — normalized to the canonical
    dtype name so every spelling of bfloat16 keys the same cache entry."""
    try:
        import numpy as np
        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, "name", None) or str(dtype)
    return (f"b{batch}_t{seq}_h{heads}_d{head_dim}_{name}_"
            f"{'train' if train else 'eval'}_"
            f"{'causal' if causal else 'full'}")


def kernel_rev() -> int:
    """The flash kernel's revision stamp — imported lazily so the cache /
    decision plumbing never drags Pallas in on the XLA-only path."""
    from tpudist.ops.pallas.flash_attention import KERNEL_REV
    return KERNEL_REV


def flash_eligible(*, seq: int, head_dim: int, bias: bool = False,
                   dtype=None) -> tuple[bool, str]:
    """Central static-eligibility check, consulted by every attention call
    site BEFORE any dispatch question is asked. The windowed-attention
    families (swin, maxvit) carry an additive relative-position bias (and
    swin-v2 cosine attention) the Pallas kernel does not implement — for
    them eligibility is statically False and the XLA path IS the dispatched
    choice, recorded here in one place instead of five model files."""
    if bias:
        return False, ("additive attention bias is not implemented by the "
                       "flash kernel")
    if head_dim > 256:
        return False, f"head_dim {head_dim} exceeds the kernel's VMEM tiling"
    if seq < 16:
        return False, (f"seq {seq} is below one (8,128) tile — blockwise "
                       f"streaming cannot win")
    return True, "eligible"


def measure_attention(batch: int, seq: int, heads: int, head_dim: int,
                      dtype, train: bool, causal: bool,
                      steps: int = 10, warmup: int = 2) -> tuple[float, float]:
    """The on-device micro-benchmark: (flash_ms, xla_ms) at the exact shape.
    ``train`` times forward+backward (grad wrt q/k/v — the configuration the
    r3 capture showed the kernel losing); eval times forward only. Only
    meaningful on an accelerator — callers gate on platform."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.ops.pallas import flash_attention
    from tpudist.parallel.ring_attention import attention

    rng = np.random.default_rng(0)
    shape = (batch, seq, heads, head_dim)
    q, k, v = (jnp.asarray(rng.standard_normal(shape), dtype)
               for _ in range(3))

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, causal=causal)

    def xla_fn(q, k, v):
        return attention(q, k, v, causal=causal)

    if train:
        def loss(fn):
            def f(q, k, v):
                return fn(q, k, v).astype(jnp.float32).sum()
            return f
        flash_c = jax.jit(jax.grad(loss(flash_fn), argnums=(0, 1, 2)))
        xla_c = jax.jit(jax.grad(loss(xla_fn), argnums=(0, 1, 2)))
    else:
        flash_c = jax.jit(flash_fn)
        xla_c = jax.jit(xla_fn)

    flash_ms = measure_ms(flash_c, (q, k, v), steps, warmup)
    xla_ms = measure_ms(xla_c, (q, k, v), steps, warmup)
    return flash_ms, xla_ms


def decide(batch: int, seq: int, heads: int, head_dim: int, dtype,
           *, train: bool = True, causal: bool = False, mode: str = "auto",
           cache_dir: Optional[str] = None,
           measure_pair: Optional[Callable[[], tuple[float, float]]] = None,
           refresh: bool = False, platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> dict:
    """Resolve the attention backend for one workload through the generic
    honesty policy (``dispatch.decide``). Returns a decision dict:
    ``kernel`` ("flash"|"xla"), ``mode``, ``source`` ("forced" | "platform"
    | "ineligible" | "cache" | "measured"), timings/margin when measured,
    and cache provenance. ``measure_pair`` injects the benchmark (tests use
    synthetic timings; bench_flash reuses its own measured rows); default
    is ``measure_attention`` at the given shape."""
    if mode not in MODES:
        raise ValueError(f"flash mode must be one of {MODES}, got {mode!r}")
    key = shape_key(batch, seq, heads, head_dim, dtype, train, causal)
    if measure_pair is None:
        measure_pair = lambda: measure_attention(  # noqa: E731
            batch, seq, heads, head_dim, dtype, train, causal)
    return dispatch.decide(
        CLIENT, key, mode=mode, names=NAMES, kernel_rev=kernel_rev,
        measure_pair=measure_pair,
        eligibility=flash_eligible(seq=seq, head_dim=head_dim),
        cache_dir=cache_dir, refresh=refresh, platform=platform,
        device_kind=device_kind)


def lookup(batch: int, seq: int, heads: int, head_dim: int, dtype,
           *, train: bool = True, causal: bool = False,
           cache_dir: Optional[str] = None,
           platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> bool:
    """Trace-safe resolution for model call sites (``flash=None``): the
    generic ``dispatch.lookup`` (cache/platform only, never measures) behind
    the attention eligibility gate."""
    if not flash_eligible(seq=seq, head_dim=head_dim)[0]:
        return False
    key = shape_key(batch, seq, heads, head_dim, dtype, train, causal)
    return dispatch.lookup(CLIENT, key, candidate="flash",
                           kernel_rev=kernel_rev, cache_dir=cache_dir,
                           platform=platform, device_kind=device_kind)


def shared_decision(outpath: str, primary: bool, decide_fn,
                    *, expect_key: Optional[str] = None,
                    timeout_s: float = 300.0, poll_s: float = 0.25,
                    log=None) -> dict:
    """One attention verdict for the whole gang (``attention_dispatch.json``
    in the shared run dir) — the generic ``dispatch.shared_decision`` with
    this client's file name and kernel revision; see that docstring for the
    staleness/failure-propagation contract."""
    return dispatch.shared_decision(
        outpath, primary, decide_fn, filename="attention_dispatch.json",
        kernel_rev=kernel_rev, expect_key=expect_key, timeout_s=timeout_s,
        poll_s=poll_s, log=log, what="attention dispatch")


def event_fields(decision: dict) -> dict:
    """The decision as telemetry-event fields (type ``attention_dispatch``,
    schema in tpudist/telemetry.py). Numeric-or-None timings; the winner,
    mode, provenance, shape key, and measured margin all ride along so
    ``summarize`` can print the dispatch line without re-reading the
    cache."""
    out = {"kernel": decision["kernel"], "mode": decision["mode"],
           "source": decision["source"], "shape_key": decision.get("key")}
    for f in ("flash_ms", "xla_ms", "margin"):
        if isinstance(decision.get(f), (int, float)):
            out[f] = decision[f]
    if decision.get("cache_hit"):
        out["cache_hit"] = 1
    if decision.get("reason"):
        out["reason"] = decision["reason"]
    if decision.get("shared_from_primary"):
        out["shared_from_primary"] = 1
    if decision.get("device_kind"):
        out["dispatch_device_kind"] = decision["device_kind"]
    return out
