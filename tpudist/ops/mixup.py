"""Mixup / CutMix batch augmentation, computed INSIDE the compiled step.

No reference equivalent (the reference's recipe predates both), but they are
standard pieces of the modern recipes the zoo's transformer-era archs train
under. The TPU-first design point: mixing happens on-device inside the jitted
train step — static shapes (the CutMix box is a dynamic-bound mask built from
``broadcasted_iota`` comparisons, not a dynamic slice), one fused program, no
host-side batch rewriting.

Shapes: the permutation pairs whatever batch it is handed — per-SHARD under
the shard_map DP step (the SPMD analogue of torch's in-batch ``randperm``),
per-GLOBAL-batch under the GSPMD/TP step (plain jit over global arrays; the
partitioner lowers the permuted gather to a collective).

Loss contract: ``mixed_ce`` — ``lam * CE(out, y1) + (1-lam) * CE(out, y2)``
(label smoothing composes per-term); accuracy is reported against ``y1``
(the dominant label), as torch reference training scripts do.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpudist.ops.loss import cross_entropy_loss


def mixed_ce(logits: jax.Array, labels: jax.Array, labels2, lam,
             smoothing: float = 0.0) -> jax.Array:
    """The pair loss both step builders share: plain (smoothed) CE when no
    pair labels, else the lam-weighted two-term CE."""
    loss = cross_entropy_loss(logits, labels, label_smoothing=smoothing)
    if labels2 is not None:
        loss = lam * loss + (1.0 - lam) * cross_entropy_loss(
            logits, labels2, label_smoothing=smoothing)
    return loss


def mix_batch(rng: jax.Array, images: jax.Array, labels: jax.Array,
              mixup_alpha: float, cutmix_alpha: float):
    """Apply mixup and/or cutmix to one (per-shard) batch.

    Returns ``(mixed_images, y1, y2, lam)`` where ``y1`` is the original
    label, ``y2`` the pairing partner's, and ``lam`` the realized mixing
    weight of ``y1`` (for cutmix: 1 - realized box-area fraction). When both
    alphas are positive, each step picks one of the two uniformly
    (torchvision's ``RandomChoice([RandomMixup, RandomCutmix])``).
    """
    assert mixup_alpha > 0.0 or cutmix_alpha > 0.0
    k_perm, k_lam, k_box, k_choice = jax.random.split(rng, 4)
    n = images.shape[0]
    perm = jax.random.permutation(k_perm, n)
    y1, y2 = labels, labels[perm]
    shuffled = images[perm]

    def _mixup(_):
        lam = jax.random.beta(k_lam, mixup_alpha or 1.0, mixup_alpha or 1.0)
        mixed = lam * images + (1.0 - lam) * shuffled
        return mixed.astype(images.dtype), lam.astype(jnp.float32)

    def _cutmix(_):
        h, w = images.shape[1], images.shape[2]
        lam0 = jax.random.beta(k_box, cutmix_alpha or 1.0, cutmix_alpha or 1.0)
        # Box with area fraction (1 - lam0), centered uniformly, clipped —
        # then lam is recomputed from the clipped box (torch semantics).
        ratio = jnp.sqrt(1.0 - lam0)
        bh, bw = (ratio * h).astype(jnp.int32), (ratio * w).astype(jnp.int32)
        ky, kx = jax.random.split(k_lam)
        cy = jax.random.randint(ky, (), 0, h)
        cx = jax.random.randint(kx, (), 0, w)
        y0, y1_ = jnp.clip(cy - bh // 2, 0, h), jnp.clip(cy + bh // 2, 0, h)
        x0, x1_ = jnp.clip(cx - bw // 2, 0, w), jnp.clip(cx + bw // 2, 0, w)
        rows = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1)
        inside = ((rows >= y0) & (rows < y1_)
                  & (cols >= x0) & (cols < x1_))[None, :, :, None]
        mixed = jnp.where(inside, shuffled, images)
        area = ((y1_ - y0) * (x1_ - x0)).astype(jnp.float32)
        lam = 1.0 - area / float(h * w)
        return mixed.astype(images.dtype), lam
    if mixup_alpha > 0.0 and cutmix_alpha > 0.0:
        use_mixup = jax.random.bernoulli(k_choice, 0.5)
        mixed, lam = jax.lax.cond(use_mixup, _mixup, _cutmix, operand=None)
    elif mixup_alpha > 0.0:
        mixed, lam = _mixup(None)
    else:
        mixed, lam = _cutmix(None)
    return mixed, y1, y2, lam
