"""Measurement-honest kernel dispatch — the GENERIC layer.

PR 5 built this machinery for one client (``--flash auto``,
``ops/attention_dispatch``); PR 6 needed the identical policy for the fused
BN-epilogue kernels, and duplicating the cache/timing/shared-verdict logic
would have let the two honesty policies drift. So the policy lives HERE,
once, and each kernel family registers as a *client*:

- **attention** (``ops/attention_dispatch``): Pallas flash attention vs XLA
  attention, keyed by the exact attention workload;
- **fused_norm** (``ops/norm_dispatch``): Pallas fused BN+ReLU /
  BN+add+ReLU epilogue vs the XLA epilogue, keyed by (rows, channels,
  dtype, variant).

One timing harness, one cache format, one honesty policy:

- ``decide()`` resolves ``auto`` by a one-time on-device micro-benchmark of
  candidate-vs-baseline **at the exact workload key**, picks the winner,
  and **never selects a kernel that loses its own measurement** (ties go to
  the baseline — the compiler needs no justification, the custom kernel
  does).
- verdicts cache in a per-``device_kind`` JSON file per client
  (``<client>.<kind>.json`` — a v4 verdict must never dispatch a v5e),
  keyed by the workload key AND the client's kernel revision, so a rebuilt
  kernel re-measures instead of inheriting the old kernel's record.
  ``clear_cache()`` / deleting the file forces a re-measure.
- off-TPU, ``auto`` resolves to the baseline immediately — no Pallas
  import, no measurement (interpreter-mode timings are meaningless).
- ``lookup()`` is the trace-safe path (cache/platform only, never
  measures): no cache entry on TPU → baseline — an unmeasured custom
  kernel is never the default.
- ``shared_decision()`` gives a multi-host gang ONE verdict (the primary
  publishes into the shared run dir; peers adopt a fresh, matching file or
  fail over identically).

The micro-benchmark is injectable (``measure_pair``) so every honesty
property is unit-testable with synthetic timings on CPU.
"""

from __future__ import annotations

import datetime
import json
import os
import re
import time
from typing import Callable, Optional

MODES = ("auto", "on", "off")

ENV_CACHE_DIR = "TPUDIST_DISPATCH_CACHE"
CACHE_VERSION = 1


def default_cache_dir() -> str:
    """Where dispatch verdicts persist across runs: ``TPUDIST_DISPATCH_CACHE``
    or ``~/.cache/tpudist``. Deliberately NOT the run dir — ``--overwrite
    delete`` would discard the measurement the next run needs."""
    env = os.environ.get(ENV_CACHE_DIR, "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "tpudist")


def _slug(device_kind: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "-", device_kind.strip()) or "unknown"


def cache_path(client: str, device_kind: str,
               cache_dir: Optional[str] = None) -> str:
    """One JSON file per client per device kind: ``<client>.<kind>.json``."""
    return os.path.join(cache_dir or default_cache_dir(),
                        f"{client}.{_slug(device_kind)}.json")


def load_cache(path: str) -> dict:
    """Cache file contents ({} shell on missing/corrupt — a torn write must
    degrade to a re-measure, never crash a training run)."""
    try:
        with open(path) as f:
            obj = json.load(f)
        if isinstance(obj, dict) and obj.get("version") == CACHE_VERSION \
                and isinstance(obj.get("entries"), dict):
            return obj
    except (OSError, ValueError):
        pass
    return {"version": CACHE_VERSION, "entries": {}}


_read_memo: dict = {}

# (path, key) -> entry, populated ONLY when a measured verdict could not be
# persisted (read-only cache dir): the decision a run just reported must
# still bind its own trace-time lookup()s, or the dispatch line would name
# a kernel that never compiled. In-process only — the next run re-measures.
_local_entries: dict = {}


def seed_local(path: str, key: str, entry: dict) -> None:
    """Fallback persistence for one verdict when the cache file cannot be
    written — consulted by ``lookup()`` after the file."""
    _local_entries[(path, key)] = entry


def _load_cache_cached(path: str) -> dict:
    """Read-only ``load_cache`` memoized on (mtime_ns, size): ``lookup()``
    runs once per kernel call site per trace — ~50+ BN epilogues for a deep
    convnet — and must not re-open and re-parse the same JSON each time. A
    ``save_cache`` (os.replace) or ``clear_cache`` changes the stat key, so
    writers invalidate readers for free. Callers must not mutate the
    returned dict."""
    try:
        st = os.stat(path)
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        return {"version": CACHE_VERSION, "entries": {}}
    hit = _read_memo.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    obj = load_cache(path)
    _read_memo[path] = (key, obj)
    return obj


def save_cache(path: str, cache: dict) -> None:
    """Atomic write (tmp + rename): a preempted rank mid-save must not leave
    a torn JSON that poisons every later run's load."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cache, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def clear_cache(client: str, device_kind: Optional[str] = None,
                cache_dir: Optional[str] = None) -> int:
    """Drop one client's cached verdicts (all device kinds, or one). Returns
    the number of cache files removed — the documented invalidation path
    alongside the automatic kernel-revision mismatch."""
    d = cache_dir or default_cache_dir()
    removed = 0
    if device_kind is not None:
        paths = [cache_path(client, device_kind, d)]
    else:
        try:
            paths = [os.path.join(d, n) for n in os.listdir(d)
                     if n.startswith(f"{client}.") and n.endswith(".json")]
        except OSError:
            paths = []
    for p in paths:
        for k in [k for k in _local_entries if k[0] == p]:
            del _local_entries[k]
        try:
            os.remove(p)
            removed += 1
        except OSError:
            pass
    return removed


def measure_ms(fn, args, steps: int = 10, warmup: int = 2) -> float:
    """THE on-device timing harness (mean ms/call over ``steps`` after
    ``warmup``), shared by every dispatch client AND the kernel benchmarks
    (``benchmarks/bench_flash.py``/``bench_fused_norm.py``) so verdicts and
    bench rows cannot drift in methodology. Completion is forced via
    ``device_get`` of a value depending on the full computation:
    ``block_until_ready`` returns at enqueue-ack over the remote tunnel —
    the same guard bench.py documents."""
    import jax
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / steps * 1e3


def _now_iso() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def decide(client: str, key: str, *, mode: str,
           names: tuple[str, str],
           kernel_rev: Callable[[], int],
           measure_pair: Callable[[], tuple[float, float]],
           eligibility: Optional[tuple[bool, str]] = None,
           cache_dir: Optional[str] = None, refresh: bool = False,
           platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> dict:
    """Resolve one workload for one client. ``names = (candidate,
    baseline)`` labels the two sides: the decision dict carries ``kernel``
    (one of the names), ``mode``, ``source`` ("forced" | "platform" |
    "ineligible" | "cache" | "measured"), ``<candidate>_ms``/
    ``<baseline>_ms``/``margin`` when measured, and cache provenance.

    THE honesty invariant: under ``auto`` the candidate kernel is selected
    ONLY off the back of a measurement it won (fresh, or cached for this
    device_kind + key + kernel rev). ``measure_pair`` returns
    ``(candidate_ms, baseline_ms)``; ``kernel_rev`` is a CALLABLE so the
    revision import (which may drag Pallas in) only happens on the TPU
    path. ``eligibility`` is the client's static pre-check — a workload the
    kernel cannot run resolves to the baseline before any device question
    is asked (forced ``on`` deliberately bypasses it, for A/B work).
    """
    if mode not in MODES:
        raise ValueError(f"{client} mode must be one of {MODES}, got "
                         f"{mode!r}")
    cand, base = names
    out = {"kernel": base, "mode": mode, "source": "platform", "key": key,
           f"{cand}_ms": None, f"{base}_ms": None, "margin": None,
           "cache_hit": False}

    if mode in ("on", "off"):
        out["kernel"] = cand if mode == "on" else base
        out["source"] = "forced"
        return out

    # Static eligibility BEFORE anything touches a device: a workload the
    # kernel cannot run must not reach measure_pair (where the Pallas probe
    # would just crash) — `auto` resolves it to the baseline outright.
    if eligibility is not None and not eligibility[0]:
        out["source"] = "ineligible"
        out["reason"] = eligibility[1]
        return out

    if platform is None:
        import jax
        platform = jax.default_backend()
    out["platform"] = platform
    if platform != "tpu":
        # auto off-TPU IS the baseline path: no Pallas import, no
        # measurement — interpreter timings would be noise dressed as data.
        return out

    if device_kind is None:
        import jax
        device_kind = jax.devices()[0].device_kind
    out["device_kind"] = device_kind
    rev = kernel_rev()
    out["kernel_rev"] = rev
    path = cache_path(client, device_kind, cache_dir)
    out["cache_path"] = path
    cache = load_cache(path)
    entry = cache["entries"].get(key)
    if entry and entry.get("kernel_rev") == rev and not refresh:
        out.update(kernel=entry["kernel"], source="cache", cache_hit=True,
                   margin=entry.get("margin"),
                   measured_at=entry.get("measured_at"))
        out[f"{cand}_ms"] = entry.get(f"{cand}_ms")
        out[f"{base}_ms"] = entry.get(f"{base}_ms")
        return out

    cand_ms, base_ms = measure_pair()
    # Strict win required: a tie keeps the compiler baseline. The custom
    # kernel must EARN dispatch; the baseline never has to.
    winner = cand if cand_ms < base_ms else base
    loser_ms = max(cand_ms, base_ms)
    margin = (loser_ms - min(cand_ms, base_ms)) / loser_ms if loser_ms \
        else 0.0
    out.update(kernel=winner, source="measured", margin=round(margin, 4),
               measured_at=_now_iso())
    out[f"{cand}_ms"] = round(cand_ms, 4)
    out[f"{base}_ms"] = round(base_ms, 4)
    cache["device_kind"] = device_kind
    cache["entries"][key] = {
        "kernel": winner, f"{cand}_ms": out[f"{cand}_ms"],
        f"{base}_ms": out[f"{base}_ms"], "margin": out["margin"],
        "kernel_rev": rev, "measured_at": out["measured_at"],
    }
    try:
        save_cache(path, cache)
    except OSError:
        # A read-only cache dir degrades to re-measuring next run, but the
        # decision itself stands — seed the in-process overlay so this
        # run's trace-time lookup()s agree with the verdict just reported.
        out["cache_path"] = None
        seed_local(path, key, cache["entries"][key])
    return out


def lookup(client: str, key: str, *, candidate: str,
           kernel_rev: Callable[[], int],
           cache_dir: Optional[str] = None,
           platform: Optional[str] = None,
           device_kind: Optional[str] = None) -> bool:
    """Trace-safe resolution for model call sites: consults platform + cache
    only, NEVER measures (a micro-benchmark cannot run while the step is
    being traced). No cache entry on TPU → False: an unmeasured custom
    kernel is never the default — the Trainer (or a bench) warms the cache
    for the workloads it runs by calling ``decide()`` outside the trace."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    if platform != "tpu":
        return False
    if device_kind is None:
        import jax
        device_kind = jax.devices()[0].device_kind
    path = cache_path(client, device_kind, cache_dir)
    entry = (_load_cache_cached(path)["entries"].get(key)
             or _local_entries.get((path, key)))
    return bool(entry and entry.get("kernel_rev") == kernel_rev()
                and entry.get("kernel") == candidate)


def shared_decision(outpath: str, primary: bool, decide_fn,
                    *, filename: str,
                    kernel_rev: Optional[Callable[[], int]] = None,
                    expect_key: Optional[str] = None,
                    timeout_s: float = 300.0, poll_s: float = 0.25,
                    log=None, what: str = "dispatch") -> dict:
    """One decision for the whole gang. A per-rank micro-benchmark is noisy:
    at a near-tie workload, hosts could measure opposite winners and compile
    DIFFERENT kernels into one SPMD program — non-reproducible trajectories,
    divergent per-rank grads. So the primary rank decides and publishes
    ``<filename>`` into the (shared-filesystem) run dir; every other rank
    reads that instead of measuring.

    The run dir can carry a decision file from a previous attempt or run
    (``--overwrite keep`` + restart, possibly across a kernel-rev bump), so
    peers only adopt a file stamped with THEIR launcher attempt
    (``telemetry.env_attempt``) whose workload key and kernel rev still
    match — anything else is treated as absent until the live primary
    overwrites it. A primary whose probe raises publishes the failure
    instead, so peers fail over immediately and *identically* (every rank
    degrades to the caller's trace-safe-lookup path) rather than burning
    the full timeout and then measuring into a possibly-split gang. A
    non-primary rank that times out (primary mid-compile over a slow
    tunnel) falls back to its own decision — logged loudly, because the
    gang may now be split.
    """
    from tpudist.telemetry import env_attempt
    attempt = env_attempt()
    path = os.path.join(outpath, filename)

    def _publish(obj: dict) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1)
        os.replace(tmp, path)

    if primary:
        try:
            dec = decide_fn()
        except Exception as e:
            try:
                _publish({"failed": repr(e)[:500], "key": expect_key,
                          "attempt": attempt})
            except OSError:
                pass
            raise
        try:
            _publish(dict(dec, attempt=attempt))
        except OSError as e:
            if log is not None:
                log(f"{what}: could not publish decision ({e!r}) — peers "
                    f"will decide independently")
        return dec

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with open(path) as f:
                dec = json.load(f)
        except (OSError, ValueError):
            dec = None
        fresh = (isinstance(dec, dict)
                 and dec.get("attempt") == attempt
                 and (expect_key is None or dec.get("key") == expect_key)
                 and ("kernel_rev" not in dec or kernel_rev is None
                      or dec["kernel_rev"] == kernel_rev()))
        if fresh:
            if dec.get("failed"):
                raise RuntimeError(
                    f"primary's {what} probe failed: {dec['failed']}")
            if dec.get("kernel"):
                dec["shared_from_primary"] = 1
                return dec
        time.sleep(poll_s)
    if log is not None:
        log(f"{what}: primary's decision file did not appear within "
            f"{timeout_s:.0f}s — deciding independently (gang may mix "
            f"kernels this run)")
    return decide_fn()
