"""Numerics that fold into the compiled step (reference C14 + loss math)."""

from tpudist import _jaxshim  # noqa: F401  (jax<0.8 surface backfill)
from tpudist.ops.metrics import accuracy            # noqa: F401
from tpudist.ops.loss import cross_entropy_loss     # noqa: F401
