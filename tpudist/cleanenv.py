"""Clean-environment defense for forcing a CPU JAX backend.

A sitecustomize hook (e.g. ``/root/.axon_site`` on ``PYTHONPATH``, which
registers a remote-TPU PJRT plugin at interpreter startup) can make ``import
jax`` block on a dead tunnel REGARDLESS of ``JAX_PLATFORMS`` — so in-process
env mutation is not enough: the interpreter must be (re-)started with the
plugin path stripped. This module is the single copy of that defense, shared
by ``bench.py``, ``__graft_entry__.py`` and ``tests/conftest.py`` (it must
therefore import nothing heavier than the stdlib).
"""

from __future__ import annotations

import os
import re

# Matches a path SEGMENT starting with 'axon' or '.axon' (/root/.axon_site,
# .../axon/...), not substrings inside other names (/home/jaxon/lib).
_PLUGIN_SEGMENT = re.compile(r"(^|/)\.?axon")


def strip_plugin_paths(pythonpath: str) -> list[str]:
    return [p for p in pythonpath.split(os.pathsep)
            if p and not _PLUGIN_SEGMENT.search(p)]


def cpu_env(n_devices: int | None = None,
            base: dict | None = None) -> dict:
    """A copy of ``base`` (default ``os.environ``) reshaped for a clean CPU
    backend: ``JAX_PLATFORMS=cpu``, the virtual-device-count XLA flag set to
    ``n_devices`` (replacing any existing one), and plugin sitecustomize dirs
    stripped from ``PYTHONPATH``."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    if n_devices:
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = os.pathsep.join(
        strip_plugin_paths(env.get("PYTHONPATH", "")))
    return env
