"""Experiment directory management (reference ``utils.py:40-51,65-69``)."""

from __future__ import annotations

import os
import shutil
import sys


def output_process(output_path: str, mode: str = "prompt") -> None:
    """Create the experiment dir; if it exists, resolve per ``mode``.

    The reference (``utils.py:40-51``) interactively prompts d(elete)/q(uit) on
    stdin — which blocks headless runs (bug ledger #9). We keep that behavior
    under ``mode='prompt'`` but add non-interactive ``'delete'``/``'quit'``,
    and ``'prompt'`` itself fails fast (instead of blocking forever on
    ``input()``) when stdin is not a TTY — a headless run hitting an existing
    outpath is the exact hang class the reference shipped (VERDICT r1 weak #6).

    ``'keep'`` reuses an existing dir untouched — the elastic-restart mode
    (``launch --max-restarts`` + ``--resume auto``): a relaunched job must
    find the previous attempt's checkpoint, not an empty dir.
    """
    if os.path.exists(output_path):
        if mode == "keep":
            return
        if mode == "prompt":
            if sys.stdin is None or not sys.stdin.isatty():
                raise OSError(
                    f"Directory {output_path} exists and stdin is not a TTY; "
                    f"refusing to prompt in a headless run. Pass "
                    f"--overwrite delete or --overwrite quit (or remove the "
                    f"directory).")
            print(f"{output_path} file exist!")
            action = input("Select Action: d (delete) / q (quit):").lower().strip()
        elif mode == "delete":
            action = "d"
        else:
            action = "q"
        if action == "d":
            shutil.rmtree(output_path)
        else:
            raise OSError(f"Directory {output_path} exists!")
    os.makedirs(output_path)


def get_learning_rate(lr_value: float) -> float:
    """Reference ``utils.py:65-69`` read optimizer.param_groups[0]['lr']; our
    schedule is a pure function of the epoch so callers pass the value through.
    Kept for API parity."""
    return float(lr_value)
