"""Cross-cutting utilities (reference ``utils.py``, components C10-C13, C17)."""

from tpudist.utils.logging import get_logger, ddp_print          # noqa: F401
from tpudist.utils.meters import AverageMeter                    # noqa: F401
from tpudist.utils.experiment import output_process              # noqa: F401
