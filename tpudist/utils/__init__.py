"""Cross-cutting utilities (reference ``utils.py``, components C10-C13, C17)
plus the aux subsystems the reference lacks (SURVEY.md §5): profiling,
replica-consistency checking, stall watchdog."""

from tpudist.utils.logging import get_logger, ddp_print          # noqa: F401
from tpudist.utils.meters import AverageMeter                    # noqa: F401
from tpudist.utils.experiment import output_process              # noqa: F401
from tpudist.utils.profiling import StepProfiler, peak_hbm_gb    # noqa: F401
from tpudist.utils.debug import (check_replica_consistency,      # noqa: F401
                                 assert_replicas_consistent)
from tpudist.utils.watchdog import Watchdog                      # noqa: F401
