"""Step-window profiler hooks (SURVEY.md §5 "tracing/profiling: none" in the
reference — its only instrumentation is the data_time/batch_time meters,
``/root/reference/distributed.py:239-240,266``, which we keep; this adds the
TPU-native upgrade: ``jax.profiler`` traces viewable in
TensorBoard/Perfetto/XProf).

``StepProfiler`` captures a trace for a configured step window
(``--profile start:end``): it starts the trace when the global step enters
the window and stops it when the step leaves, writing to
``<outpath>/profile/attempt_<n>`` (one subdir per launcher restart attempt,
so a relaunch cannot overwrite the pre-crash capture). The trainer labels
the capture: ``jax.profiler.StepTraceAnnotation("train", ...)`` around each
step and ``TraceAnnotation`` rows for the data-wait/H2D/drain phases, so
XProf/Perfetto group device ops by step and phase out of the box.
Capturing a bounded window (not whole-run) is the
standard TPU practice — traces are large and the interesting steps are the
post-compilation steady state.
"""

from __future__ import annotations

import os
from typing import Optional


def parse_window(spec: str) -> Optional[tuple[int, int]]:
    """'10:20' → (10, 20); '15' → (15, 16); '' → None (off)."""
    if not spec:
        return None
    if ":" in spec:
        a, b = spec.split(":", 1)
        start, end = int(a), int(b)
    else:
        start, end = int(spec), int(spec) + 1
    if end <= start:
        raise ValueError(f"empty profile window '{spec}' (need end > start)")
    return start, end


class StepProfiler:
    """Trace global steps in [start, end). Call ``step(global_step)`` once per
    training step, ``close()`` at exit (stops a still-open trace).

    Traces land in ``<logdir>/profile/attempt_<n>`` where ``n`` is the
    launcher's restart counter (``TPUDIST_RESTART_COUNT``, 0 standalone): an
    elastic relaunch into the same outpath must not overwrite the previous
    attempt's capture — the pre-crash trace is often the interesting one.
    """

    def __init__(self, spec: str, logdir: str, enabled: bool = True,
                 attempt: Optional[int] = None):
        self.window = parse_window(spec) if enabled else None
        if attempt is None:
            # Single shared parse of TPUDIST_RESTART_COUNT: profile dirs and
            # telemetry events must agree on the attempt number.
            from tpudist.telemetry import env_attempt
            attempt = env_attempt()
        self.logdir = os.path.join(logdir, "profile", f"attempt_{attempt}")
        self.active = False

    def step(self, global_step: int) -> None:
        if self.window is None:
            return
        start, end = self.window
        if not self.active and start <= global_step < end:
            import jax
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self.active = True
        elif self.active and global_step >= end:
            self.close()

    def epoch_end(self) -> None:
        """Stop an open trace at the epoch boundary so validation/checkpoint
        work never leaks into the capture (a window past the epoch's last
        train step would otherwise only close on the NEXT epoch's first
        ``step()``). If the window extends into the next epoch, ``step()``
        restarts a fresh trace there."""
        self.close()

    def close(self) -> None:
        if self.active:
            import jax
            jax.profiler.stop_trace()
            self.active = False


def peak_hbm_gb() -> float | None:
    """Peak per-device memory high-water mark in GiB, maxed over ALL local
    devices (the reference README's per-GPU Memory column,
    ``/root/reference/README.md:9-14``). Device 0 alone under-reports on any
    multi-chip host with imbalance — uneven sharding, stage-0-heavy pipeline
    layouts — and an OOM headroom number must track the WORST chip. TPU
    runtimes expose allocator stats; backends without them (CPU) return
    None. Shared by the trainer's epoch log and bench.py."""
    import jax
    peaks = []
    try:
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                continue
            if stats and "peak_bytes_in_use" in stats:
                peaks.append(stats["peak_bytes_in_use"])
    except Exception:
        pass
    return round(max(peaks) / 2**30, 3) if peaks else None
