"""Step/epoch metric meters (reference ``utils.py:78-102``).

Same semantics as the reference ``AverageMeter``: ``update(val, n)`` is a
weighted update (``sum += val*n; count += n``), ``avg = sum/count``, and
``__str__`` renders ``"{name} {val:fmt} ({avg:fmt})"``.
"""

from __future__ import annotations


class AverageMeter:
    """Computes and stores the average and current value
    (reference ``utils.py:78-102``)."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0.0

    def update(self, val: float, n: int = 1) -> None:
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count if self.count else 0.0

    def __str__(self) -> str:
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(name=self.name, val=self.val, avg=self.avg)


class ProgressMeter:
    """Batch-progress line builder matching the reference's console format
    (``distributed.py:270-272``): 'Epoch[e]:\\t[i/N]\\tmeter\\tmeter...'."""

    def __init__(self, num_batches: int, meters: list[AverageMeter], prefix: str = ""):
        self.num_batches = num_batches
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [f"{self.prefix}[{batch}/{self.num_batches}]"]
        entries += [str(m) for m in self.meters]
        return "\t".join(entries)
