"""Experiment logging (reference ``utils.py:17-37,72-74``).

Same two-channel shape as the reference: a file handler writing
``experiment.log`` with timestamps and a bare stdout handler, INFO level.
Fixes the reference's duplicate-handler bug (``utils.py:34-35`` appended
handlers unconditionally, doubling output if called twice).
"""

from __future__ import annotations

import logging
import os
import sys


def get_logger(save_path: str, logger_name: str = "tpudist") -> logging.Logger:
    """File + stdout logger, matching the reference's formats
    (``utils.py:22-31``: timestamped file lines, bare console lines)."""
    logger = logging.getLogger(logger_name)
    target = os.path.abspath(os.path.join(save_path, "experiment.log"))
    if logger.handlers:
        # Already configured (don't double handlers — reference bug #10) …
        if any(isinstance(h, logging.FileHandler) and
               h.baseFilename == target for h in logger.handlers):
            return logger
        # … but a NEW experiment dir means the cached handlers point at the
        # previous run's file: rebuild instead of silently logging there.
        for h in list(logger.handlers):
            logger.removeHandler(h)
            h.close()
    logger.setLevel(logging.INFO)
    logger.propagate = False

    file_fmt = logging.Formatter("%(asctime)s %(levelname)s: %(message)s")
    fh = logging.FileHandler(os.path.join(save_path, "experiment.log"))
    fh.setFormatter(file_fmt)
    logger.addHandler(fh)

    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(sh)
    return logger


def ddp_print(msg: str, logger: logging.Logger | None, process_index: int) -> None:
    """Rank-0-gated logging (reference ``utils.py:72-74``): on TPU the gate is
    ``jax.process_index() == 0`` instead of ``local_rank == 0``."""
    if process_index == 0 and logger is not None:
        logger.info(msg)
