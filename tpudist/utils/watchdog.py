"""Training stall watchdog — failure detection the reference lacks.

In the reference, a dead rank hangs every NCCL collective forever with no
timeout (SURVEY.md §5 "failure detection: none"). The TPU-native failure
chain here: a lost peer stalls the SPMD step → no ``kick()`` arrives within
``timeout`` → the watchdog runs ``on_stall`` (default: log a diagnostic and
``os._exit`` non-zero) → the launcher (tpudist/launch.py) sees the dead rank
and tears down the whole job's process groups — clean abort-on-peer-loss
instead of an indefinite hang.

Thread-based, zero overhead in the hot loop (``kick`` is one time() store).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

STALL_EXIT_CODE = 117


def _default_on_stall(elapsed: float, timeout: float,
                      reason: str = "no kick within timeout") -> None:
    sys.stderr.write(
        f"[tpudist.watchdog] no training-step progress for {elapsed:.0f}s "
        f"(timeout {timeout:.0f}s; fire reason: {reason}) — a peer is likely "
        f"lost or a collective is hung; aborting so the launcher can tear "
        f"the job down.\n")
    # Dump all thread stacks: which collective/transfer is stuck.
    for tid, frame in sys._current_frames().items():
        sys.stderr.write(f"--- thread {tid} ---\n")
        sys.stderr.write("".join(traceback.format_stack(frame)))
    sys.stderr.flush()
    try:
        # Last words into the event stream: emit() flushes per line, so the
        # stall survives the os._exit below into events.<rank>.jsonl.
        from tpudist import telemetry
        tel = telemetry.get()
        if tel is not None:
            tel.emit("fault", point="watchdog_stall", detail=reason,
                     elapsed_s=round(elapsed, 3))
    except Exception:
        pass
    os._exit(STALL_EXIT_CODE)


class Watchdog:
    """``kick()`` once per completed step; if no kick lands within ``timeout``
    seconds, ``on_stall(elapsed, timeout)`` runs on the watchdog thread."""

    def __init__(self, timeout: float,
                 on_stall: Optional[Callable[[float, float], None]] = None,
                 poll_interval: Optional[float] = None):
        self.timeout = float(timeout)
        self.on_stall = on_stall or _default_on_stall
        self.poll = poll_interval or max(self.timeout / 10.0, 0.05)
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = False
        self._fire_reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        if self.timeout <= 0:
            return self                       # disabled
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpudist-watchdog")
        self._thread.start()
        return self

    def _run(self) -> None:
        # Local import: faults is dependency-free, but keep the hot path
        # free of it unless a poll actually runs.
        from tpudist import faults
        while not self._stop.wait(self.poll):
            elapsed = time.monotonic() - self._last
            reason = None
            if elapsed > self.timeout:
                reason = (f"no kick for {elapsed:.1f}s "
                          f"(budget {self.timeout:.1f}s)")
            elif faults.maybe_watchdog_expire():
                # Injected expiry (fault point ``watchdog_expire``): the
                # full watchdog→abort→relaunch chain in milliseconds.
                elapsed = self.timeout + 1.0
                reason = "injected: watchdog_expire fault"
            if reason is not None:
                self._fired = True
                self._fire_reason = reason
                self._call_on_stall(elapsed, reason)
                return

    def _call_on_stall(self, elapsed: float, reason: str) -> None:
        # Back-compat: 2-arg on_stall callbacks predate fire reasons.
        # Signature-inspected (not try/except TypeError — a TypeError raised
        # INSIDE the callback must not retrigger it with fewer args).
        import inspect
        try:
            takes_reason = len(
                inspect.signature(self.on_stall).parameters) >= 3
        except (TypeError, ValueError):
            takes_reason = False
        if takes_reason:
            self.on_stall(elapsed, self.timeout, reason)
        else:
            self.on_stall(elapsed, self.timeout)

    def kick(self) -> None:
        self._last = time.monotonic()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll)

    @property
    def fired(self) -> bool:
        return self._fired

    @property
    def fire_reason(self) -> Optional[str]:
        """Why the watchdog fired (None while healthy) — surfaced so logs
        and tests can tell a real stall from an injected one."""
        return self._fire_reason

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
