"""Training stall watchdog — failure detection the reference lacks.

In the reference, a dead rank hangs every NCCL collective forever with no
timeout (SURVEY.md §5 "failure detection: none"). The TPU-native failure
chain here: a lost peer stalls the SPMD step → no ``kick()`` arrives within
``timeout`` → the watchdog runs ``on_stall`` (default: log a diagnostic and
``os._exit`` non-zero) → the launcher (tpudist/launch.py) sees the dead rank
and tears down the whole job's process groups — clean abort-on-peer-loss
instead of an indefinite hang.

Thread-based, zero overhead in the hot loop (``kick`` is one time() store).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

STALL_EXIT_CODE = 117


def _default_on_stall(elapsed: float, timeout: float) -> None:
    sys.stderr.write(
        f"[tpudist.watchdog] no training-step progress for {elapsed:.0f}s "
        f"(timeout {timeout:.0f}s) — a peer is likely lost or a collective is "
        f"hung; aborting so the launcher can tear the job down.\n")
    # Dump all thread stacks: which collective/transfer is stuck.
    for tid, frame in sys._current_frames().items():
        sys.stderr.write(f"--- thread {tid} ---\n")
        sys.stderr.write("".join(traceback.format_stack(frame)))
    sys.stderr.flush()
    os._exit(STALL_EXIT_CODE)


class Watchdog:
    """``kick()`` once per completed step; if no kick lands within ``timeout``
    seconds, ``on_stall(elapsed, timeout)`` runs on the watchdog thread."""

    def __init__(self, timeout: float,
                 on_stall: Optional[Callable[[float, float], None]] = None,
                 poll_interval: Optional[float] = None):
        self.timeout = float(timeout)
        self.on_stall = on_stall or _default_on_stall
        self.poll = poll_interval or max(self.timeout / 10.0, 0.05)
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        if self.timeout <= 0:
            return self                       # disabled
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpudist-watchdog")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            elapsed = time.monotonic() - self._last
            if elapsed > self.timeout:
                self._fired = True
                self.on_stall(elapsed, self.timeout)
                return

    def kick(self) -> None:
        self._last = time.monotonic()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll)

    @property
    def fired(self) -> bool:
        return self._fired

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
