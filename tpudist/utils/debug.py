"""Replica-consistency checking — the SPMD analog of a data race detector.

The reference has no sanitizers (SURVEY.md §5 "race detection: none"); torch
DDP's only guard is an optional broadcast-compare of buffers. Under SPMD the
equivalent invariant is: every leaf of the replicated train state must be
bit-identical on all devices — divergence means a non-deterministic op, a
bad collective, or hardware corruption silently desyncing replicas (the
failure DDP would show as NaN-ish gradients much later).

``check_replica_consistency`` walks a pytree of jax Arrays and, for every
fully-replicated leaf, compares each device's copy against device 0's.
Cheap relative to a step (host-side memcmp of addressable shards, no
collectives), so it can run every N epochs via ``--replica-check-freq``.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import numpy as np


def _is_replicated(arr) -> bool:
    sharding = getattr(arr, "sharding", None)
    if sharding is None:
        return False
    try:
        return sharding.is_fully_replicated and len(arr.addressable_shards) > 1
    except Exception:
        return False


def check_replica_consistency(tree: Any, atol: float = 0.0) -> Tuple[List[Tuple[str, float]], int]:
    """Return ``(bad, checked)``: ``bad`` is [(path, max_abs_diff)] for every
    replicated leaf whose device copies differ by more than ``atol``
    (bit-exact expected: SPMD replicas run the same program on the same
    data); ``checked`` counts the replicated leaves inspected. ``checked == 0``
    means the state had nothing replicated to verify (single device, or fully
    sharded under TP/PP) — callers must not report that as 'passed'."""
    bad: List[Tuple[str, float]] = []
    checked = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if not _is_replicated(leaf):
            continue
        checked += 1
        shards = leaf.addressable_shards
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            other = np.asarray(s.data)
            if atol == 0.0 and np.array_equal(ref, other):
                continue                       # cheap equal-path: no casts
            diff = (np.max(np.abs(other.astype(np.float64) -
                                  ref.astype(np.float64)))
                    if ref.size else 0.0)
            if diff > atol:
                bad.append((jax.tree_util.keystr(path), float(diff)))
                break
    return bad, checked


def assert_replicas_consistent(tree: Any, atol: float = 0.0,
                               require_replicated: bool = False) -> int:
    """Raise on divergence; return the number of leaves checked. With
    ``require_replicated``, also raise if nothing was replicated (so a
    'passed' can't silently mean 'checked nothing')."""
    bad, checked = check_replica_consistency(tree, atol)
    if bad:
        lines = ", ".join(f"{p} (Δ={d:g})" for p, d in bad[:5])
        raise AssertionError(
            f"replica divergence on {len(bad)} state leaves: {lines} — "
            f"replicated SPMD state must be identical on every device")
    if require_replicated and checked == 0:
        raise AssertionError(
            "replica consistency check found no replicated leaves to verify "
            "(single-device run or fully sharded state)")
    return checked
