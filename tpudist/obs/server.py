"""Per-rank Prometheus metrics endpoint + the launcher's fleet aggregation.

Zero-dependency by contract: stdlib ``http.server`` only, no jax, no
prometheus_client — the launcher (which never initializes jax) and CPU smoke
tests must be able to serve and scrape this.

Design: the hot loop is NOT instrumented again. ``MetricsRegistry.observe``
registers as a :class:`tpudist.telemetry.Telemetry` sink, so every gauge is
derived from the SAME schema-validated events the ``events.<rank>.jsonl``
flight recorder persists — a scrape and the events file can never disagree
about what happened, and a run without ``--metrics-port`` pays nothing.

Endpoints (``GET``):

- ``/metrics``  — Prometheus text exposition (version 0.0.4);
- ``/healthz``  — one-line JSON liveness: rank, last step, heartbeat age.

``--metrics-port 0`` binds an ephemeral port; the bound port is written to
``<outpath>/metrics.<rank>.port`` so operators (and the launcher's fleet
view) can discover it after the fact.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional

from tpudist.telemetry import percentile

PORTFILE_FMT = "metrics.{rank}.port"


def portfile_path(outpath: str, rank) -> str:
    return os.path.join(outpath, PORTFILE_FMT.format(rank=rank))


def _esc(v) -> str:
    """Escape a Prometheus label value."""
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _labels(d: dict) -> str:
    if not d:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(d.items())) \
        + "}"


class PromText:
    """Tiny Prometheus text-format builder.

    Samples are grouped BY FAMILY at render time (insertion order of first
    appearance), not emitted in call order: the exposition format requires
    all lines of one metric to form a single group, and callers like the
    fleet view naturally loop per-rank across several families — strict
    parsers (OpenMetrics, promtool) reject interleaved re-appearances."""

    def __init__(self):
        self._families: dict[str, dict] = {}

    def sample(self, name: str, value, help: str = "", type: str = "gauge",
               **labels) -> None:
        if value is None:
            return
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = {"help": help, "type": type,
                                          "lines": []}
        elif help and not fam["help"]:
            fam["help"] = help
        fam["lines"].append(f"{name}{_labels(labels)} {float(value):g}")

    def render(self) -> str:
        out: list[str] = []
        for name, fam in self._families.items():
            if fam["help"]:
                out.append(f"# HELP {name} {fam['help']}")
            out.append(f"# TYPE {name} {fam['type']}")
            out.extend(fam["lines"])
        return "\n".join(out) + "\n"


class MetricsRegistry:
    """Event-driven aggregates for one rank's telemetry stream.

    Registered as the Telemetry sink: ``observe(ev)`` runs inside the
    already-taken emit lock's caller (cheap dict math, no I/O, no clocks
    beyond what the event carries), so the step loop's cost is unchanged.
    ``render()`` runs on the HTTP server thread under this registry's own
    lock — a scrape never blocks an emit for longer than the aggregate
    update itself.
    """

    def __init__(self, rank: int = 0, window: int = 128):
        self.rank = rank
        self._lock = threading.Lock()
        self._recent: deque[dict] = deque(maxlen=window)
        self._t_run_start: Optional[float] = None
        self._last_event_t: Optional[float] = None
        self._last_step: Optional[int] = None
        self._last_mfu: Optional[float] = None
        self._steps = 0
        self._productive_s = 0.0
        self._pending_compile_s = 0.0
        self._buckets = {"init": 0.0, "compile": 0.0, "checkpoint": 0.0,
                         "eval": 0.0}
        self._faults: dict[str, int] = {}
        self._quarantined = 0
        self._preempts = 0
        # Doctor plane (tpudist/doctor/): interventions by action, plus the
        # SDC probe census — derived from the same schema-validated events
        # the flight recorder persists, like every other gauge here.
        self._doctor: dict[str, int] = {}
        self._sdc_probes = 0
        self._sdc_divergent = 0
        # Blackbox plane (tpudist/blackbox.py): incident triggers by class,
        # plus how many armed a deep capture vs. were cooldown-suppressed.
        self._incidents: dict[str, int] = {}
        self._incident_captures = 0
        self._samples_skipped = 0
        self._samples_retried = 0
        self._flops_per_step: Optional[float] = None
        self._collective_bytes: Optional[float] = None
        self._collective_ops: Optional[float] = None
        self._temp_bytes: Optional[float] = None
        self._info: dict[str, str] = {}
        self._run_end: Optional[dict] = None
        # Serving plane (tpudist/serve/): derived from the same event
        # stream the batcher persists — request latencies over a recent
        # window (with timestamps, so req/s is a windowed rate, not a
        # lifetime average), batch occupancy, queue depth, AOT startup.
        self._serve_requests = 0
        self._serve_errors = 0
        self._serve_lat: deque[tuple[float, float]] = deque(maxlen=1024)
        self._serve_occ: deque[float] = deque(maxlen=256)
        self._serve_queue_depth: Optional[float] = None
        self._serve_batches = 0
        self._serve_start: Optional[dict] = None

    # -- sink --------------------------------------------------------------
    def observe(self, ev: dict) -> None:
        et = ev.get("type")
        with self._lock:
            self._last_event_t = ev.get("t")
            if et == "run_start":
                self._t_run_start = ev["t"]
                if ev.get("init_s"):
                    self._buckets["init"] = float(ev["init_s"])
                self._info = {k: str(ev[k]) for k in
                              ("platform", "arch", "device_kind") if k in ev}
            elif et == "step":
                self._steps += 1
                self._last_step = ev.get("step")
                # A compile-carrying step is preceded by its paired compile
                # event (Telemetry.step emits compile first): the stashed
                # seconds come OUT of this step's productive time, mirroring
                # Telemetry's own accounting (productive = step - compile) —
                # and the step stays OUT of the percentile window, matching
                # the heartbeat window and summarize's steady-state
                # percentiles (a minutes-long compile in the p95 would fire
                # step-time alerts at every restart).
                if self._pending_compile_s > 0.0:
                    self._productive_s += max(
                        0.0, ev["step_s"] - self._pending_compile_s)
                    self._pending_compile_s = 0.0
                else:
                    self._productive_s += ev["step_s"]
                    self._recent.append(ev)
                if "mfu" in ev:
                    self._last_mfu = ev["mfu"]
            elif et == "compile":
                self._buckets["compile"] += ev["seconds"]
                if ev.get("phase") == "train_step":
                    self._pending_compile_s += ev["seconds"]
                for src, dst in (("collective_bytes_per_step",
                                  "_collective_bytes"),
                                 ("collective_ops", "_collective_ops"),
                                 ("temp_bytes", "_temp_bytes")):
                    if ev.get(src) is not None:
                        setattr(self, dst, ev[src])
            elif et in ("checkpoint_save", "checkpoint_restore"):
                self._buckets["checkpoint"] += ev["seconds"]
            elif et == "eval":
                self._buckets["eval"] += ev["seconds"]
            elif et == "epoch":
                self._samples_skipped += ev.get("samples_skipped", 0) or 0
                self._samples_retried += ev.get("samples_retried", 0) or 0
            elif et == "fault":
                p = str(ev.get("point"))
                self._faults[p] = self._faults.get(p, 0) + 1
                if p == "checkpoint_quarantine":
                    # Storage damage deserves its own headline counter: a
                    # fleet quietly eating its keep-K fallback pool is an
                    # incident, not a per-point footnote.
                    self._quarantined += 1
            elif et == "preempt":
                self._preempts += 1
            elif et == "doctor":
                a = str(ev.get("action"))
                self._doctor[a] = self._doctor.get(a, 0) + 1
            elif et == "sdc_probe":
                self._sdc_probes += 1
                if ev.get("divergent") or ev.get("tie"):
                    self._sdc_divergent += 1
            elif et == "incident":
                tr = str(ev.get("trigger"))
                self._incidents[tr] = self._incidents.get(tr, 0) + 1
                if ev.get("captured"):
                    self._incident_captures += 1
            elif et == "request":
                self._serve_requests += 1
                if ev.get("error"):
                    # Failed requests count (and keep req/s honest about
                    # liveness via the errors counter) but stay out of the
                    # latency window — p50/p99 is SERVICE latency.
                    self._serve_errors += 1
                elif isinstance(ev.get("latency_s"), (int, float)):
                    self._serve_lat.append((ev["t"], ev["latency_s"]))
            elif et == "serve_batch":
                self._serve_batches += 1
                if ev.get("bucket"):
                    self._serve_occ.append(ev["n_valid"] / ev["bucket"])
                if ev.get("queue_depth") is not None:
                    self._serve_queue_depth = ev["queue_depth"]
            elif et == "serve_start":
                self._serve_start = ev
            elif et == "program":
                if ev.get("flops_per_step"):
                    self._flops_per_step = ev["flops_per_step"]
            elif et == "run_end":
                self._run_end = ev
                self._buckets["init"] = ev.get("init_s", self._buckets["init"])

    # -- exposition --------------------------------------------------------
    def snapshot(self) -> dict:
        """Point-in-time copy used by render() and /healthz."""
        with self._lock:
            now = time.time()
            recent = list(self._recent)
            out = {
                "rank": self.rank,
                "steps_total": self._steps,
                "last_step": self._last_step,
                "last_mfu": self._last_mfu,
                "flops_per_step": self._flops_per_step,
                "collective_bytes_per_step": self._collective_bytes,
                "collective_ops": self._collective_ops,
                "temp_bytes": self._temp_bytes,
                "productive_s": self._productive_s,
                "buckets": dict(self._buckets),
                "faults": dict(self._faults),
                "quarantined": self._quarantined,
                "preempts": self._preempts,
                "doctor": dict(self._doctor),
                "sdc_probes": self._sdc_probes,
                "sdc_divergent": self._sdc_divergent,
                "incidents": dict(self._incidents),
                "incident_captures": self._incident_captures,
                "samples_skipped": self._samples_skipped,
                "samples_retried": self._samples_retried,
                "info": dict(self._info),
                "heartbeat_age_s": (now - self._last_event_t
                                    if self._last_event_t else None),
                "run_end": self._run_end,
            }
            serve = None
            if self._serve_start is not None or self._serve_requests:
                lat = [v for _, v in self._serve_lat]
                serve = {
                    "requests_total": self._serve_requests,
                    "errors_total": self._serve_errors,
                    "batches_total": self._serve_batches,
                    "queue_depth": self._serve_queue_depth,
                    "latency_p50_s": (percentile(lat, 50) if lat else None),
                    "latency_p99_s": (percentile(lat, 99) if lat else None),
                    "occupancy": (sum(self._serve_occ)
                                  / len(self._serve_occ)
                                  if self._serve_occ else None),
                    "aot_s": (self._serve_start or {}).get("aot_s"),
                    "cache": (self._serve_start or {}).get("cache"),
                    "n_buckets": (self._serve_start or {}).get("n_buckets"),
                }
                # Windowed req/s ANCHORED TO NOW: only requests from the
                # last window count, and the span runs to the present —
                # so the gauge decays to 0 when traffic stops instead of
                # freezing at the last burst's rate forever (an
                # autoscaler reading phantom steady traffic), and a
                # lifetime average would flatten every rate change the
                # latency/throughput curve exists to show.
                window = 60.0
                recent_req = [t for t, _ in self._serve_lat
                              if now - t <= window]
                span = (now - min(recent_req)) if recent_req else 0.0
                serve["req_per_s"] = (len(recent_req) / span if span > 0
                                      else 0.0)
            out["serve"] = serve
        # goodput: the trainer's own run_end number once the run is over;
        # live runs use wall since run_start (+ init stashed before it).
        if self._run_end is not None:
            out["goodput"] = self._run_end.get("goodput")
            out["wall_s"] = self._run_end.get("wall_s")
        elif self._t_run_start is not None:
            wall = max(1e-9, now - self._t_run_start + out["buckets"]["init"])
            out["wall_s"] = wall
            out["goodput"] = min(1.0, out["productive_s"] / wall)
        else:
            out["goodput"] = None
            out["wall_s"] = None
        phases = {}
        if recent:
            for key in ("step_s", "data_s", "h2d_s", "compute_s", "drain_s"):
                xs = [e[key] for e in recent if key in e]
                if xs:
                    phases[key] = {"p50": percentile(xs, 50),
                                   "p95": percentile(xs, 95)}
        out["phases"] = phases
        return out

    def render(self) -> str:
        s = self.snapshot()
        p = PromText()
        if s["info"]:
            p.sample("tpudist_run_info", 1,
                     help="run identity labels (value is always 1)",
                     **s["info"])
        p.sample("tpudist_steps_total", s["steps_total"],
                 help="training steps completed", type="counter")
        if s["last_step"] is not None:
            p.sample("tpudist_last_step", s["last_step"],
                     help="most recent global step number")
        for key, phase in (("step_s", "step"), ("data_s", "data"),
                           ("h2d_s", "h2d"), ("compute_s", "compute"),
                           ("drain_s", "drain")):
            q = s["phases"].get(key)
            if not q:
                continue
            name = ("tpudist_step_time_seconds" if phase == "step"
                    else "tpudist_phase_time_seconds")
            hlp = ("per-step wall time over a recent window"
                   if phase == "step" else
                   "per-step phase breakdown over a recent window")
            kw = {} if phase == "step" else {"phase": phase}
            p.sample(name, q["p50"], help=hlp, quantile="0.5", **kw)
            p.sample(name, q["p95"], quantile="0.95", **kw)
        p.sample("tpudist_mfu", s["last_mfu"],
                 help="model FLOPs utilization of the most recent step")
        p.sample("tpudist_goodput", s["goodput"],
                 help="productive step time / wall time so far")
        p.sample("tpudist_productive_seconds_total", s["productive_s"],
                 help="accumulated productive step seconds", type="counter")
        for bucket, v in sorted(s["buckets"].items()):
            p.sample("tpudist_overhead_seconds_total", v,
                     help="non-productive wall attributed by bucket",
                     type="counter", bucket=bucket)
        p.sample("tpudist_flops_per_step", s["flops_per_step"],
                 help="per-device FLOPs of the compiled train step")
        p.sample("tpudist_collective_bytes_per_step",
                 s["collective_bytes_per_step"],
                 help="bytes moved by collective ops per compiled step "
                      "(XLA introspection)")
        p.sample("tpudist_collective_ops_per_step", s["collective_ops"],
                 help="collective op count in the compiled step")
        p.sample("tpudist_hbm_temp_bytes", s["temp_bytes"],
                 help="XLA buffer-assignment temp (scratch) bytes")
        p.sample("tpudist_samples_skipped_total", s["samples_skipped"],
                 help="data-path samples skipped after retries",
                 type="counter")
        p.sample("tpudist_samples_retried_total", s["samples_retried"],
                 help="data-path samples healed by retry", type="counter")
        for point, n in sorted(s["faults"].items()):
            p.sample("tpudist_faults_total", n,
                     help="fault injections/detections by point",
                     type="counter", point=point)
        p.sample("tpudist_checkpoint_quarantined_total", s["quarantined"],
                 help="checkpoints that failed integrity verification and "
                      "were quarantined aside (.corrupt)", type="counter")
        p.sample("tpudist_preemptions_total", s["preempts"],
                 help="SIGTERM/SIGINT preemption drains", type="counter")
        for action, n in sorted(s["doctor"].items()):
            p.sample("tpudist_doctor_interventions_total", n,
                     help="doctor interventions by action (skip_step / "
                          "spike / sdc_divergence / rollback / evict)",
                     type="counter", action=action)
        if s["sdc_probes"]:
            p.sample("tpudist_sdc_probes_total", s["sdc_probes"],
                     help="cross-replica replicated-state digest probes "
                          "run", type="counter")
            p.sample("tpudist_sdc_divergence_total", s["sdc_divergent"],
                     help="probes that found replicas disagreeing "
                          "(silent data corruption)", type="counter")
        for trigger, n in sorted(s["incidents"].items()):
            p.sample("tpudist_incidents_total", n,
                     help="blackbox incident triggers by class "
                          "(docs/INCIDENTS.md)", type="counter",
                     trigger=trigger)
        if s["incidents"]:
            p.sample("tpudist_incident_captures_total",
                     s["incident_captures"],
                     help="incidents that armed a deep capture (the rest "
                          "were cooldown-suppressed)", type="counter")
        p.sample("tpudist_heartbeat_age_seconds", s["heartbeat_age_s"],
                 help="seconds since this rank last emitted any event")
        sv = s.get("serve")
        if sv:
            p.sample("tpudist_serve_requests_total", sv["requests_total"],
                     help="serving requests completed", type="counter")
            p.sample("tpudist_serve_request_errors_total",
                     sv["errors_total"],
                     help="serving requests that completed with an error",
                     type="counter")
            p.sample("tpudist_serve_batches_total", sv["batches_total"],
                     help="bucketed micro-batches executed", type="counter")
            p.sample("tpudist_serve_request_latency_seconds",
                     sv["latency_p50_s"],
                     help="request latency (submit to result) over a "
                          "recent window", quantile="0.5")
            p.sample("tpudist_serve_request_latency_seconds",
                     sv["latency_p99_s"], quantile="0.99")
            p.sample("tpudist_serve_queue_depth", sv["queue_depth"],
                     help="requests waiting behind the most recent batch")
            p.sample("tpudist_serve_batch_occupancy", sv["occupancy"],
                     help="valid rows / bucket rows over a recent window "
                          "(1 - padding waste)")
            p.sample("tpudist_serve_requests_per_second", sv["req_per_s"],
                     help="completed-request rate over the latency window")
            p.sample("tpudist_serve_aot_seconds", sv["aot_s"],
                     help="startup AOT bucket-set compile wall seconds")
            if sv.get("cache") in ("warm", "cold"):
                p.sample("tpudist_serve_cache_warm",
                         1 if sv["cache"] == "warm" else 0,
                         help="1 when the persistent compile cache was "
                              "warm at AOT startup")
        p.sample("tpudist_run_ended", 1 if s["run_end"] is not None else 0,
                 help="1 once run_end was emitted (endpoint lingers briefly)")
        return p.render()


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpudist-obs/1"

    def do_GET(self):  # noqa: N802 (http.server API)
        try:
            if self.path.split("?")[0] in ("/metrics", "/"):
                body = self.server.render_metrics().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/healthz":
                body = (json.dumps(self.server.render_health())
                        + "\n").encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/dashboard" \
                    and getattr(self.server, "render_dashboard", None):
                # Attached only where there is something to draw (the
                # launcher's fleet endpoint); rank endpoints 404 here.
                body = self.server.render_dashboard().encode()
                ctype = "text/html; charset=utf-8"
            else:
                self.send_error(404)
                return
        except Exception as e:      # a scrape must never kill the server
            self.send_error(500, explain=repr(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 (http.server API)
        # POST /capture: arm the blackbox's one-shot deep capture (trigger
        # class `manual`, same per-class cooldown as SIGUSR2). POST, not
        # GET: arming a profiler trace is a state change, and a crawler or
        # dashboard prefetch hitting a GET must not burn the cooldown.
        if self.path.split("?")[0] != "/capture":
            self.send_error(404)
            return
        hook = getattr(self.server, "capture_hook", None)
        if hook is None:
            # No recorder on this endpoint (run without --blackbox, or the
            # launcher's fleet endpoint): say so, don't pretend.
            self.send_error(404, explain="no blackbox recorder attached "
                                         "(run with --blackbox)")
            return
        try:
            hook()
        except Exception as e:
            self.send_error(500, explain=repr(e))
            return
        body = b'{"ok": true, "armed": "manual"}\n'
        self.send_response(202)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):      # scrapes must not spam training stdout
        pass


class MetricsServer:
    """Threaded HTTP server around a render callable.

    ``port=0`` binds an ephemeral port (read ``.port`` after start). The
    server is a daemon thread: it can never keep a finished rank alive.
    """

    def __init__(self, registry, port: int = 0, host: str = "0.0.0.0",
                 dashboard=None):
        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.render_metrics = self._render
        self._httpd.render_health = self._health
        # ``dashboard``: () -> HTML str, served at /dashboard. Reads
        # (history file, tsdb window) happen in the HTTP handler thread —
        # never on the caller's supervision poll.
        if dashboard is not None:
            self._httpd.render_dashboard = dashboard
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpudist-metrics",
            daemon=True)
        self._portfile: Optional[str] = None

    def _render(self) -> str:
        return self.registry.render()

    def _health(self) -> dict:
        s = self.registry.snapshot() if hasattr(self.registry, "snapshot") \
            else {}
        return {"ok": True, "rank": s.get("rank"),
                "last_step": s.get("last_step"),
                "heartbeat_age_s": s.get("heartbeat_age_s")}

    def set_capture(self, hook) -> None:
        """Attach the blackbox manual-capture hook, served at
        ``POST /capture`` (``hook`` is () -> None and must be cheap — it
        runs on the HTTP handler thread; the recorder's flag-set is)."""
        self._httpd.capture_hook = hook

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def write_portfile(self, outpath: str, rank) -> str:
        """Atomically record the bound port for discovery (fleet view,
        operators, the obs smoke test)."""
        path = portfile_path(outpath, rank)
        tmp = path + ".tmp"
        os.makedirs(outpath, exist_ok=True)
        with open(tmp, "w") as f:
            f.write(str(self.port))
        os.replace(tmp, path)
        self._portfile = path
        return path

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        if self._portfile:
            try:
                os.unlink(self._portfile)
            except OSError:
                pass
            self._portfile = None


# -- launcher-side fleet view -------------------------------------------------

class FleetMetrics:
    """The launcher's aggregate view: its own supervision counters, the
    ranks' heartbeats (straggler flags as gauges), and headline samples
    scraped from each rank's discovered endpoint.

    ``refresh()`` is called from the launcher's existing ~1 s poll loop —
    the HTTP handler serves the cached text, so a scrape never does
    filesystem or network work of its own. Heartbeats work across hosts
    (shared filesystem); endpoint scraping is same-host best-effort.
    """

    def __init__(self, rundir: str, nprocs: int, straggler_factor: float = 4.0):
        self.rundir = rundir
        self.nprocs = nprocs
        self.straggler_factor = straggler_factor
        self._lock = threading.Lock()
        self._launcher_events: deque[dict] = deque(maxlen=512)
        self._rank_exits: dict[str, int] = {}
        self._restarts = 0
        self._reforms = 0
        self._evictions = 0
        self._collective_deadlines = 0
        self._incidents: dict[str, int] = {}
        self._world = nprocs
        self._attempt = 0
        self._stragglers: set[int] = set()
        self._cached = "# tpudist fleet: no refresh yet\n"
        # rank-endpoint samples, updated by a BACKGROUND scrape thread: the
        # supervision poll that calls refresh() also implements
        # abort-on-peer-loss, and a wedged rank endpoint eating its full
        # connect timeout (x nprocs, serially) must not delay dead-rank
        # detection. refresh() publishes the previous scrape's samples
        # (≤ one poll interval stale) and kicks the next scrape.
        self._rank_samples: dict[int, dict] = {}
        self._scraping = False

    # sink for the launcher's own Telemetry stream
    def observe(self, ev: dict) -> None:
        with self._lock:
            self._launcher_events.append(ev)
            et = ev.get("type")
            if et == "rank_exit":
                c = str(ev.get("classification", "?"))
                self._rank_exits[c] = self._rank_exits.get(c, 0) + 1
            elif et == "restart":
                self._restarts += 1
            elif et == "topology_change":
                # Elastic world change: reform (shrink to survivors) or
                # serve-plane scale-up (grow). Either way the scrape loop
                # and gauges must follow the new world; only genuine
                # reforms count toward the reform SLO counter.
                if ev.get("mesh_action") != "scale_up":
                    self._reforms += 1
                try:
                    self._world = int(ev.get("to_world", self._world))
                except (TypeError, ValueError):
                    pass
                self.nprocs = self._world
            elif et == "launcher_start":
                self._attempt = ev.get("attempt", self._attempt)
                try:
                    self._world = int(ev.get("nprocs", self._world))
                except (TypeError, ValueError):
                    pass
                self.nprocs = self._world
                # New attempt: the previous attempt's straggler flags must
                # not latch into the restarted job's gauges.
                self._stragglers.clear()
            elif et == "straggler":
                self._stragglers.add(int(ev.get("straggler_rank", -1)))
            elif et == "eviction":
                # Proactive drains are NOT crash restarts: their own
                # counter, so an SLO on restart rate stays honest.
                self._evictions += 1
            elif et == "collective_deadline":
                self._collective_deadlines += 1
            elif et == "incident":
                # Emitted by the launcher-side bundler as it correlates
                # rank dumps / fleet triggers into incidents/<id>/.
                tr = str(ev.get("trigger"))
                self._incidents[tr] = self._incidents.get(tr, 0) + 1

    def _scrape_rank(self, rank: int, port: int, timeout: float = 0.25):
        """Headline gauges from one rank's /metrics (same-host best-effort).
        Serving replicas contribute their request counter and latency
        quantiles, so the fleet endpoint shows every replica's serving
        headline beside the training ones."""
        import urllib.request
        want = {"tpudist_goodput": "goodput", "tpudist_mfu": "mfu",
                "tpudist_steps_total": "steps",
                "tpudist_serve_requests_total": "serve_requests",
                "tpudist_serve_requests_per_second": "serve_req_s",
                "tpudist_serve_queue_depth": "queue_depth"}
        # Labeled counter families summed across labels (fault points,
        # doctor actions): one headline number per rank for the fleet
        # gauges and the tsdb recorder.
        summed = {"tpudist_faults_total": "faults",
                  "tpudist_doctor_interventions_total": "doctor"}
        out = {}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=timeout) as r:
            for line in r.read().decode().splitlines():
                if line.startswith("#"):
                    continue
                name = line.split("{")[0].split(" ")[0]
                try:
                    val = float(line.rsplit(" ", 1)[1])
                except ValueError:
                    continue
                if name in want:
                    out[want[name]] = val
                elif name in summed:
                    key = summed[name]
                    out[key] = out.get(key, 0.0) + val
                elif name == "tpudist_serve_request_latency_seconds":
                    if 'quantile="0.5"' in line:
                        out["serve_p50"] = val
                    elif 'quantile="0.99"' in line:
                        out["serve_p99"] = val
        return out

    def _scrape_all(self) -> None:
        """Background pass over every discovered rank endpoint (daemon
        thread; at most one in flight)."""
        samples: dict[int, dict] = {}
        try:
            for rank in range(self.nprocs):
                try:
                    with open(portfile_path(self.rundir, rank)) as f:
                        port = int(f.read().strip())
                except (OSError, ValueError):
                    continue
                samples[rank] = {"port": port}
                try:
                    samples[rank].update(self._scrape_rank(rank, port))
                except Exception:
                    pass
        finally:
            with self._lock:
                self._rank_samples = samples
                self._scraping = False

    def _kick_scrape(self) -> None:
        if not (self.rundir and os.path.isdir(self.rundir)):
            return
        with self._lock:
            if self._scraping:
                return
            self._scraping = True
        threading.Thread(target=self._scrape_all,
                         name="tpudist-fleet-scrape", daemon=True).start()

    def refresh(self, attempt: Optional[int] = None, beats=None) -> None:
        """Rebuild the cached exposition from heartbeats (``beats`` lets the
        launcher share its own read) + the last background endpoint
        scrape, then kick the next scrape."""
        from tpudist.telemetry import (find_stragglers, heartbeat_dir,
                                       read_heartbeats)
        if beats is None:
            beats = read_heartbeats(heartbeat_dir(self.rundir)) \
                if self.rundir else {}
        if attempt is not None:
            # Heartbeat files persist across attempts (nothing unlinks a
            # dead rank's file): after an elastic reform the removed rank's
            # stale beat would otherwise render frozen per-rank gauges —
            # and a growing heartbeat age — forever. Gate on the CURRENT
            # attempt, the same field find_stragglers gates on.
            beats = {r: b for r, b in beats.items()
                     if b.get("attempt") == attempt}
        now = time.time()
        p = PromText()
        with self._lock:
            p.sample("tpudist_fleet_nprocs", self.nprocs,
                     help="ranks the launcher supervises")
            p.sample("tpudist_fleet_attempt",
                     attempt if attempt is not None else self._attempt,
                     help="current launch attempt (restart counter)")
            p.sample("tpudist_fleet_restarts_total", self._restarts,
                     help="elastic restarts performed", type="counter")
            p.sample("tpudist_world_size", self._world,
                     help="current gang world size (shrinks on an elastic "
                          "reform)")
            p.sample("tpudist_fleet_reforms_total", self._reforms,
                     help="gang reformations (rank loss survived at a "
                          "smaller world)", type="counter")
            p.sample("tpudist_fleet_evictions_total", self._evictions,
                     help="persistent stragglers proactively drained "
                          "(--evict-stragglers; separate from crash "
                          "restarts)", type="counter")
            p.sample("tpudist_fleet_collective_deadline_total",
                     self._collective_deadlines,
                     help="wedged-gang escalations (--collective-deadline: "
                          "every rank's heartbeat stale past the deadline)",
                     type="counter")
            for c, n in sorted(self._rank_exits.items()):
                p.sample("tpudist_fleet_rank_exits_total", n,
                         help="nonzero rank exits by classification",
                         type="counter", classification=c)
            for tr, n in sorted(self._incidents.items()):
                p.sample("tpudist_incidents_total", n,
                         help="blackbox incidents bundled, by trigger "
                              "class (incidents/<id>/ under the run dir)",
                         type="counter", trigger=tr)
            flagged = set(self._stragglers)
        # factor <= 0 means detection is DISABLED (same contract as the
        # launcher's _check_stragglers): an unguarded factor-0 comparison
        # would flag every rank with any real host overhead.
        if self.straggler_factor > 0:
            live = find_stragglers(beats, factor=self.straggler_factor,
                                   attempt=attempt)
            flagged |= {s["straggler_rank"] for s in live}
        for rank, b in sorted(beats.items()):
            p.sample("tpudist_rank_last_step", b.get("step"),
                     help="per-rank most recent step (heartbeat)",
                     rank=rank)
            p.sample("tpudist_rank_step_seconds", b.get("step_p50"),
                     help="per-rank step-time p50 over the heartbeat window",
                     rank=rank, quantile="0.5")
            p.sample("tpudist_rank_step_seconds", b.get("step_p95"),
                     rank=rank, quantile="0.95")
            p.sample("tpudist_rank_host_seconds", b.get("host_p50"),
                     help="per-rank host-overhead p50 (the straggler signal)",
                     rank=rank, quantile="0.5")
            if b.get("updated_at"):
                p.sample("tpudist_rank_heartbeat_age_seconds",
                         max(0.0, now - b["updated_at"]),
                         help="seconds since the rank's heartbeat file moved",
                         rank=rank)
        for rank in sorted(set(beats) | flagged):
            if rank < 0:
                continue
            p.sample("tpudist_straggler", 1 if rank in flagged else 0,
                     help="1 once the rank was flagged as a straggler this "
                          "attempt (cleared on restart)",
                     rank=rank)
        # endpoint aggregation: publish the BACKGROUND scrape's last pass
        # (≤ one refresh interval stale) — never block this caller on HTTP
        with self._lock:
            samples = dict(self._rank_samples)
        for rank, got in sorted(samples.items()):
            p.sample("tpudist_rank_metrics_port", got.get("port"),
                     help="per-rank metrics endpoint (same-host scrape)",
                     rank=rank)
            p.sample("tpudist_rank_goodput", got.get("goodput"),
                     help="per-rank goodput (scraped from the rank "
                          "endpoint)", rank=rank)
            p.sample("tpudist_rank_mfu", got.get("mfu"),
                     help="per-rank last-step MFU (scraped)", rank=rank)
            p.sample("tpudist_rank_steps_total", got.get("steps"),
                     help="per-rank steps completed (scraped)",
                     type="counter", rank=rank)
            p.sample("tpudist_rank_serve_requests_total",
                     got.get("serve_requests"),
                     help="per-replica serving requests completed "
                          "(scraped)", type="counter", rank=rank)
            p.sample("tpudist_rank_serve_latency_seconds",
                     got.get("serve_p50"),
                     help="per-replica request latency (scraped)",
                     rank=rank, quantile="0.5")
            p.sample("tpudist_rank_serve_latency_seconds",
                     got.get("serve_p99"), rank=rank, quantile="0.99")
            p.sample("tpudist_rank_serve_requests_per_second",
                     got.get("serve_req_s"),
                     help="per-replica completed-request rate (scraped)",
                     rank=rank)
        with self._lock:
            self._cached = p.render()
        self._kick_scrape()

    def render(self) -> str:
        with self._lock:
            return self._cached

    def gauges(self) -> dict:
        """In-memory counter + endpoint-scrape snapshot for the fleet
        time-series recorder (``obs.tsdb``). Pure memory under the fleet
        lock — no filesystem or network work, because the recorder rides
        the supervision poll, whose single heartbeat-dir pass must remain
        its only read."""
        with self._lock:
            return {
                "world": self._world,
                "attempt": self._attempt,
                "restarts": self._restarts,
                "reforms": self._reforms,
                "evictions": self._evictions,
                "collective_deadlines": self._collective_deadlines,
                "rank_exits": sum(self._rank_exits.values()),
                "stragglers": len(self._stragglers),
                "incidents": sum(self._incidents.values()),
                "rank_samples": {r: dict(s)
                                 for r, s in self._rank_samples.items()},
            }

    def snapshot(self) -> dict:           # /healthz parity with the rank side
        with self._lock:
            return {"rank": -1, "last_step": None, "heartbeat_age_s": None,
                    "nprocs": self.nprocs}
