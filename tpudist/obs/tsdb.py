"""Fleet time-series recorder: the obs plane's memory.

Every gauge the obs plane exports today (`obs/server.py` MetricsRegistry /
FleetMetrics) is scrape-time-only — no history survives the scrape, so no
controller can see a trend, and no dashboard can draw one. This module is
the append-only record: on the launcher's existing ~1 s supervision poll,
one flat JSON row snapshots the fleet's headline gauges — world/alive,
straggler and supervision counters, step/host percentiles from the
heartbeats the poll *already read*, and the per-rank endpoint samples the
background scrape *already holds in memory* — into
``fleet_ts.<attempt>.jsonl`` beside the event streams.

Two hard properties:

- **Zero added filesystem reads.** Sampling consumes the heartbeat dict
  the poll's single ``read_heartbeats`` pass produced plus
  ``FleetMetrics.gauges()`` (an in-memory snapshot under the fleet lock);
  the only I/O is the one append-write per sample. A recorder that made
  the supervision poll slower would delay dead-rank detection.
- **Size-capped.** Rotation follows the telemetry ``--telemetry-max-mb``
  convention exactly (byte count tracked from written lines, live file
  rolls to ``fleet_ts.<attempt>.1.jsonl`` replacing the previous rollover;
  disk bounded at ~2x the cap, newest data wins), so a week-long run
  cannot grow the run dir unboundedly.

Consumers read through the pure ``query(rows, window=, names=)`` API (the
dashboard's live panel; ROADMAP item 1's traffic-following controller will
read the same file). Import-light by design — no jax, usable from the
launcher.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterable, Optional

from tpudist.telemetry import percentile

# Every numeric field a row may carry, in stable order. ``query`` accepts
# any subset; the dashboard's live panel iterates this for its panels.
SERIES_FIELDS: tuple[str, ...] = (
    "world", "alive", "stragglers", "restarts", "reforms", "evictions",
    "collective_deadlines", "rank_exits", "incidents", "step_p50_s",
    "step_p95_s",
    "host_p50_s", "heartbeat_age_s", "steps", "goodput", "mfu",
    "faults", "doctor", "queue_depth", "serve_requests", "serve_req_s",
    "serve_p50_s", "serve_p99_s",
)


def ts_path(rundir: str, attempt: int) -> str:
    """``fleet_ts.<attempt>.jsonl`` under the run dir — one file per
    launch attempt, mirroring ``events.<rank>.jsonl`` naming."""
    return os.path.join(rundir, f"fleet_ts.{int(attempt)}.jsonl")


def rotated_path(path: str) -> str:
    base, ext = path.rsplit(".jsonl", 1)
    return f"{base}.1.jsonl{ext}"


def _agg(vals: list, how: str) -> Optional[float]:
    xs = [float(v) for v in vals if isinstance(v, (int, float))]
    if not xs:
        return None
    if how == "sum":
        return sum(xs)
    if how == "max":
        return max(xs)
    if how == "mean":
        return sum(xs) / len(xs)
    return percentile(xs, 50)                       # "median"


def fleet_row(fleet=None, beats=None, attempt: Optional[int] = None,
              now: Optional[float] = None) -> dict:
    """One flat sample row from in-memory state only.

    ``fleet`` is a ``FleetMetrics`` (or anything with a ``gauges()``
    returning its counter/scrape snapshot); ``beats`` is the heartbeat
    dict the supervision poll already read. Either may be None (a
    launcher without fleet metrics still records heartbeat-derived
    series). All values numeric or absent — the row is schema-light by
    design: new gauges append as new keys without a migration.
    """
    now = time.time() if now is None else now
    row: dict = {"t": now}
    g = fleet.gauges() if fleet is not None else {}
    if attempt is None:
        attempt = g.get("attempt", 0)
    row["attempt"] = int(attempt)
    for k in ("world", "restarts", "reforms", "evictions",
              "collective_deadlines", "rank_exits", "stragglers",
              "incidents"):
        if k in g:
            row[k] = g[k]
    beats = beats or {}
    live = {r: b for r, b in beats.items()
            if b.get("attempt") in (None, attempt)}
    row["alive"] = len(live)
    if live:
        bs = list(live.values())
        for key, out, how in (("step_p50", "step_p50_s", "median"),
                              ("step_p95", "step_p95_s", "max"),
                              ("host_p50", "host_p50_s", "median")):
            v = _agg([b.get(key) for b in bs], how)
            if v is not None:
                row[out] = round(v, 6)
        ages = [now - b["updated_at"] for b in bs
                if isinstance(b.get("updated_at"), (int, float))]
        if ages:
            row["heartbeat_age_s"] = round(max(0.0, max(ages)), 3)
    samples = list(g.get("rank_samples", {}).values())
    if samples:
        for key, out, how in (("steps", "steps", "sum"),
                              ("goodput", "goodput", "mean"),
                              ("mfu", "mfu", "mean"),
                              ("faults", "faults", "sum"),
                              ("doctor", "doctor", "sum"),
                              ("queue_depth", "queue_depth", "sum"),
                              ("serve_requests", "serve_requests", "sum"),
                              ("serve_req_s", "serve_req_s", "sum"),
                              ("serve_p50", "serve_p50_s", "max"),
                              ("serve_p99", "serve_p99_s", "max")):
            v = _agg([s.get(key) for s in samples], how)
            if v is not None:
                row[out] = round(v, 6)
    return row


class FleetSeriesRecorder:
    """Append-only sampler for the launcher's supervision poll.

    Not thread-safe by contract: ``sample()`` is called from the single
    supervision loop. ``min_interval_s`` throttles below the poll rate
    (0 records every call — the poll itself is already ~1 s-gated).
    """

    def __init__(self, rundir: str, attempt: int = 0,
                 max_mb: float = 16.0, min_interval_s: float = 0.0):
        self.rundir = rundir
        self.attempt = int(attempt)
        self.path = ts_path(rundir, attempt)
        os.makedirs(rundir, exist_ok=True)
        self._f = open(self.path, "a", buffering=1)
        # <= 0 means UNCAPPED — same contract as Telemetry(max_mb=...).
        self._max_bytes = max(1, int(max_mb * 2**20)) \
            if max_mb and max_mb > 0 else 0
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0
        self._min_interval = min_interval_s
        self._last_t = 0.0

    def _maybe_rotate(self) -> None:
        if not self._max_bytes or self._bytes < self._max_bytes:
            return
        try:
            self._f.close()
            os.replace(self.path, rotated_path(self.path))
            self._f = open(self.path, "a", buffering=1)
            self._bytes = 0
        except OSError:
            # Best-effort, same as Telemetry: keep appending rather than
            # losing samples.
            if self._f.closed:
                self._f = open(self.path, "a", buffering=1)

    def sample(self, fleet=None, beats=None,
               now: Optional[float] = None) -> Optional[dict]:
        """Record one row; returns it (None when throttled/closed)."""
        now = time.time() if now is None else now
        if self._min_interval and now - self._last_t < self._min_interval:
            return None
        if self._f.closed:
            return None
        row = fleet_row(fleet, beats, attempt=self.attempt, now=now)
        line = json.dumps(row)
        self._f.write(line + "\n")
        self._bytes += len(line) + 1
        self._maybe_rotate()
        self._last_t = now
        return row

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def load_rows(path: str) -> list[dict]:
    """All rows for one series file, rotated segment first (chronological),
    malformed lines skipped — a reader must survive a row the recorder was
    killed in the middle of writing."""
    rows: list[dict] = []
    for p in (rotated_path(path), path):
        try:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(r, dict) and isinstance(
                            r.get("t"), (int, float)):
                        rows.append(r)
        except OSError:
            continue
    return rows


def latest_path(rundir: str) -> Optional[str]:
    """The live series file of the HIGHEST attempt in a run dir (rotated
    segments excluded) — what an after-the-fact reader wants."""
    best, best_attempt = None, -1
    try:
        entries = os.listdir(rundir)
    except OSError:
        return None
    for name in entries:
        if not (name.startswith("fleet_ts.") and name.endswith(".jsonl")):
            continue
        mid = name[len("fleet_ts."):-len(".jsonl")]
        if not mid.isdigit():               # skips "3.1" rotated segments
            continue
        if int(mid) > best_attempt:
            best_attempt, best = int(mid), os.path.join(rundir, name)
    return best


def query(rows: Iterable[dict], window: Optional[float] = None,
          names: Optional[Iterable[str]] = None) -> dict[str, list]:
    """Pure projection of sample rows into per-series point lists.

    ``window`` keeps only rows within the trailing ``window`` seconds of
    the NEWEST row (no wall clock — same answer for a file read tomorrow);
    ``names`` selects fields (default: every SERIES_FIELDS key present).
    Returns ``{name: [(t, value), ...]}`` sorted by t, absent/non-numeric
    values dropped per-series.
    """
    rows = sorted((r for r in rows
                   if isinstance(r.get("t"), (int, float))),
                  key=lambda r: r["t"])
    if window is not None and rows:
        cutoff = rows[-1]["t"] - float(window)
        rows = [r for r in rows if r["t"] >= cutoff]
    if names is None:
        present: set[str] = set()
        for r in rows:
            present.update(r)
        names = [n for n in SERIES_FIELDS if n in present]
    out: dict[str, list] = {}
    for name in names:
        pts = [(r["t"], float(r[name])) for r in rows
               if isinstance(r.get(name), (int, float))]
        out[name] = pts
    return out
