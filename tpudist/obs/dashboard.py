"""Trend dashboard: the bench history and the fleet time-series as one
zero-dependency HTML page.

Rendering is a pure string build — inline-SVG sparklines, no external JS,
no CSS/font fetches — so the one artifact works everywhere the numbers
need to travel: served live at ``/dashboard`` by the launcher's fleet
``MetricsServer`` (a ``<meta refresh>`` is the whole "live" mechanism),
written as a static file by ``tpudist-perfci --dashboard out.html``, or
attached to a PR straight from ``benchmarks/results/``.

One panel per bench-history series (regress's identity: ``metric`` +
``per_device_batch``), each showing the value trend, the trailing-median
gate band the next row will be judged against (``regress.analyze_history``
is the single source of that math — the dashboard draws exactly what the
gate enforces), and a red flag when the newest row already trips it. The
live section draws the ``obs.tsdb`` window when a recorder is attached.
Import-light: no jax.
"""

from __future__ import annotations

import html
import json
import os
from typing import Optional

from tpudist import regress
from tpudist.obs import tsdb

_STYLE = """
body{font-family:ui-monospace,Menlo,Consolas,monospace;margin:1.2em;
     background:#11151a;color:#d7dde4}
h1{font-size:1.25em} h2{font-size:1.05em;margin:1.2em 0 .4em;
     border-bottom:1px solid #2a3340;padding-bottom:.2em}
.panels{display:flex;flex-wrap:wrap;gap:10px}
.panel{border:1px solid #2a3340;border-radius:6px;padding:8px 10px;
       background:#171c23;min-width:340px}
.panel.regression{border-color:#e05252}
.panel h3{margin:0 0 4px;font-size:.85em;font-weight:normal;color:#9fb0c0}
.panel .num{font-size:.8em;color:#7d8b99}
.flag{color:#e05252;font-weight:bold}
.ok{color:#5fb86a} .noband{color:#b8a15f}
svg{display:block}
footer{margin-top:1.5em;color:#566270;font-size:.75em}
"""

SPARK_W, SPARK_H, _PAD = 320, 64, 4


def _spark(values: list[float], band: Optional[tuple] = None,
           baseline: Optional[float] = None,
           regression: bool = False) -> str:
    """Inline-SVG sparkline: value polyline over equal-spaced x, optional
    shaded gate band + baseline rule drawn on the same y scale."""
    if not values:
        return f'<svg width="{SPARK_W}" height="{SPARK_H}"></svg>'
    lo, hi = min(values), max(values)
    if band:
        lo, hi = min(lo, band[0]), max(hi, band[1])
    if baseline is not None:
        lo, hi = min(lo, baseline), max(hi, baseline)
    span = (hi - lo) or 1.0

    def y(v: float) -> float:
        return round(_PAD + (SPARK_H - 2 * _PAD) * (hi - v) / span, 1)

    def x(i: int) -> float:
        n = max(1, len(values) - 1)
        return round(_PAD + (SPARK_W - 2 * _PAD) * i / n, 1)

    parts = [f'<svg width="{SPARK_W}" height="{SPARK_H}" '
             f'viewBox="0 0 {SPARK_W} {SPARK_H}">']
    if band:
        top, bot = y(band[1]), y(band[0])
        parts.append(
            f'<rect class="band" x="{_PAD}" y="{top}" '
            f'width="{SPARK_W - 2 * _PAD}" height="{max(1.0, bot - top)}" '
            f'fill="#2f6e3a" fill-opacity="0.25"/>')
    if baseline is not None:
        yb = y(baseline)
        parts.append(
            f'<line class="baseline" x1="{_PAD}" y1="{yb}" '
            f'x2="{SPARK_W - _PAD}" y2="{yb}" stroke="#5fb86a" '
            f'stroke-dasharray="3,3" stroke-width="1"/>')
    pts = " ".join(f"{x(i)},{y(v)}" for i, v in enumerate(values))
    color = "#e05252" if regression else "#6aa7e8"
    parts.append(f'<polyline points="{pts}" fill="none" '
                 f'stroke="{color}" stroke-width="1.5"/>')
    cx, cy = x(len(values) - 1), y(values[-1])
    parts.append(f'<circle cx="{cx}" cy="{cy}" r="2.5" fill="{color}"/>')
    parts.append("</svg>")
    return "".join(parts)


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def history_series(rows: list[dict]) -> dict:
    """Group history rows into regress's series identity, append order
    preserved: ``{(metric, per_device_batch): [row, ...]}``."""
    out: dict = {}
    for r in rows:
        out.setdefault((r.get("metric"), r.get("per_device_batch")),
                       []).append(r)
    return out


def _history_panel(key: tuple, series: list[dict], window: int,
                   threshold: float) -> str:
    metric, pdb = key
    # The gate's own math, on this series alone: analyze_history keys off
    # the sub-list's last row, and every row here shares its series key.
    v = regress.analyze_history(series, window=window, threshold=threshold)
    status = v.get("status", "no_history")
    base = v.get("baseline_value")
    band = None
    if isinstance(base, (int, float)):
        band = (round(base * (1.0 - threshold), 4),
                round(base * (1.0 + threshold), 4))
    values = [float(r["value"]) for r in series]
    title = html.escape(str(metric))
    if pdb is not None:
        title += f" · b{pdb}"
    attrs = (f'data-metric="{html.escape(str(metric), quote=True)}" '
             f'data-status="{status}"')
    if pdb is not None:
        attrs += f' data-pdb="{pdb}"'
    if band:
        attrs += (f' data-baseline="{_fmt(base)}"'
                  f' data-band-lo="{_fmt(band[0])}"'
                  f' data-band-hi="{_fmt(band[1])}"')
    if status == "regression":
        verdict = ('<span class="flag">REGRESSION: '
                   + html.escape("; ".join(v.get("reasons", []))) + "</span>")
    elif status == "pass":
        verdict = '<span class="ok">pass</span>'
    else:
        verdict = f'<span class="noband">{status}</span>'
    unit = html.escape(str(series[-1].get("unit") or ""))
    return (
        f'<div class="panel {status}" {attrs}>'
        f"<h3>{title}</h3>"
        + _spark(values, band=band,
                 baseline=base if isinstance(base, (int, float)) else None,
                 regression=status == "regression")
        + f'<p class="num">latest {_fmt(values[-1])} {unit} · '
          f"median {_fmt(base)} · band {_fmt(band[0]) if band else '-'}"
          f"–{_fmt(band[1]) if band else '-'} · n={len(series)} · "
        + verdict + "</p></div>")


def _live_panels(live_rows: list[dict], window_s: Optional[float]) -> str:
    series = tsdb.query(live_rows, window=window_s)
    parts = []
    for name, pts in series.items():
        if not pts:
            continue
        values = [v for _, v in pts]
        span = pts[-1][0] - pts[0][0]
        parts.append(
            f'<div class="panel live" data-series="{name}">'
            f"<h3>{name}</h3>" + _spark(values)
            + f'<p class="num">latest {_fmt(values[-1])} · '
              f"{len(values)} samples over {span:.0f}s</p></div>")
    return "".join(parts)


def _incident_panels(incidents: list[dict]) -> str:
    """One panel per incident bundle (newest first): trigger, suspect
    rank, artifact inventory, and a file link to the bundle dir."""
    parts = []
    for m in reversed(incidents):
        iid = html.escape(str(m.get("id", "?")))
        trigger = html.escape(str(m.get("trigger", "?")))
        d = m.get("dir") or ""
        n_dumps = len(m.get("dumps") or [])
        n_caps = len(m.get("captures") or [])
        arts = ", ".join(html.escape(a) for a in (m.get("artifacts")
                                                  or [])[:6]) or "-"
        link = (f'<a href="file://{html.escape(os.path.abspath(d))}">'
                f"{iid}</a>" if d else iid)
        parts.append(
            f'<div class="panel incident" data-incident="{iid}" '
            f'data-trigger="{trigger}">'
            f"<h3>{link}</h3>"
            f'<p class="num">trigger {trigger} · suspect rank '
            f"{_fmt(m.get('suspect_rank'))} · {n_dumps} dump(s) · "
            f"{n_caps} capture(s)</p>"
            f'<p class="num">artifacts: {arts}</p></div>')
    return "".join(parts)


def render(history_rows: Optional[list] = None,
           live_rows: Optional[list] = None,
           window: int = 5, threshold: float = 0.10,
           live_window_s: Optional[float] = 600.0,
           refresh_s: Optional[int] = None,
           incidents: Optional[list] = None,
           title: str = "tpudist console") -> str:
    """The whole page as one string. ``refresh_s`` adds the meta-refresh
    used when served live; omit for static artifacts."""
    head = ['<!doctype html><html><head><meta charset="utf-8">',
            f"<title>{html.escape(title)}</title>"]
    if refresh_s:
        head.append(f'<meta http-equiv="refresh" content="{int(refresh_s)}">')
    head.append(f"<style>{_STYLE}</style></head><body>")
    head.append(f"<h1>{html.escape(title)}</h1>")
    body = []
    if live_rows:
        body.append('<h2>fleet (live tsdb window)</h2>'
                    '<div class="panels" id="live">')
        body.append(_live_panels(live_rows, live_window_s))
        body.append("</div>")
    if incidents:
        body.append('<h2>incidents (blackbox bundles)</h2>'
                    '<div class="panels" id="incidents">')
        body.append(_incident_panels(incidents))
        body.append("</div>")
    groups = history_series(history_rows or [])
    n_reg = 0
    if groups:
        body.append('<h2>bench history (trailing-median gate per series)'
                    '</h2><div class="panels" id="history">')
        for key in sorted(groups, key=lambda k: (str(k[0]), str(k[1]))):
            panel = _history_panel(key, groups[key], window, threshold)
            n_reg += 'data-status="regression"' in panel
            body.append(panel)
        body.append("</div>")
    elif not live_rows and not incidents:
        body.append("<p>no bench history and no live samples — nothing to "
                    "draw yet</p>")
    body.append(
        f'<footer id="summary" data-series="{len(groups)}" '
        f'data-regressions="{n_reg}">{len(groups)} series · '
        f"{n_reg} regression(s) · window={window} "
        f"threshold={threshold:g}</footer></body></html>")
    return "".join(head) + "".join(body)


def render_history_file(history: Optional[str] = None,
                        live_path: Optional[str] = None,
                        incidents_dir: Optional[str] = None, **kw) -> str:
    """Static render from files (the ``--dashboard`` artifact path).
    ``incidents_dir`` is a RUN DIR — its ``incidents/`` bundles (if any)
    render as the incidents panel."""
    rows = regress.load_history(history or regress.history_path())
    live = tsdb.load_rows(live_path) if live_path else None
    incidents = None
    if incidents_dir:
        from tpudist.blackbox import list_incidents
        incidents = list_incidents(incidents_dir)
    return render(history_rows=rows, live_rows=live, incidents=incidents,
                  **kw)


def write_static(out_path: str, history: Optional[str] = None,
                 live_path: Optional[str] = None, **kw) -> str:
    doc = render_history_file(history=history, live_path=live_path, **kw)
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(doc)
    return out_path


def live_renderer(ts_file: str, history: Optional[str] = None,
                  live_window_s: float = 600.0, refresh_s: int = 5,
                  incidents_dir: Optional[str] = None):
    """() -> HTML closure for ``MetricsServer(dashboard=...)``. File reads
    happen here, in the HTTP handler thread that called it — never on the
    supervision poll."""
    def _render() -> str:
        live = tsdb.load_rows(ts_file)
        rows = regress.load_history(history or regress.history_path())
        incidents = None
        if incidents_dir:
            from tpudist.blackbox import list_incidents
            incidents = list_incidents(incidents_dir)
        return render(history_rows=rows, live_rows=live,
                      live_window_s=live_window_s, refresh_s=refresh_s,
                      incidents=incidents)
    return _render


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="Render the bench-history trend dashboard to a static "
                    "HTML file")
    p.add_argument("--history", default=None,
                   help="bench_history.jsonl (env TPUDIST_BENCH_HISTORY)")
    p.add_argument("--tsdb", default=None,
                   help="optional fleet_ts.<n>.jsonl for a live-window "
                        "section")
    p.add_argument("--incidents", default=None, metavar="RUNDIR",
                   help="optional run dir whose incidents/ bundles render "
                        "as an incidents panel")
    p.add_argument("--out", required=True, help="output HTML path")
    a = p.parse_args(argv)
    path = write_static(a.out, history=a.history, live_path=a.tsdb,
                        incidents_dir=a.incidents)
    print(json.dumps({"dashboard": path,
                      "bytes": os.path.getsize(path)}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
