"""Post-compile introspection of a jitted step: where the FLOPs, bytes, and
HBM go *inside* the compiled program.

PR 2's telemetry can say a step took 300 ms; it cannot say whether that is
matmul FLOPs, an all-reduce that grew with the mesh, or an HBM spike from
XLA temp buffers. This module answers that from the three compiler surfaces
every ``lower().compile()`` executable already carries (no extra compile, no
runtime cost):

- ``cost_analysis()``  — program FLOPs / bytes-accessed / transcendentals
  (the same unwrap path ``tests/test_compiled_cost.py`` goldens);
- ``memory_analysis()`` — buffer-assignment breakdown: argument / output /
  temp (scratch) / generated-code bytes, minus donated aliases — the
  compiler-side HBM budget, attributing a spike to temps vs weights;
- the optimized HLO text — an **op census**: counts per op kind and a
  **collective census** (all-reduce / all-gather / reduce-scatter /
  all-to-all / collective-permute) with payload bytes per step, so comms
  growth is attributed, not just observed.

Everything is best-effort per section (a backend may expose any subset) and
returns plain JSON-serializable scalars, because the result is surfaced in
three places: the ``compile`` telemetry event (``event_fields``), the
``summarize`` report, and bench rows.
"""

from __future__ import annotations

import re
from typing import Callable, Optional

# dtype prefix → bytes/element for HLO shape strings like f32[64,128]{1,0}
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                   "collective-permute")

# `%name = <shapes> op-name(` — group 1: result shape(s) (possibly a tuple),
# group 2: the op kind. The shape class must admit TPU layout annotations —
# tiling `{1,0:T(8,128)}`, memory space `{1,0:S(1)}`, dynamic bounds
# `[<=8]` — or tiled instructions silently vanish from the census on the
# exact platform it targets. The op name is anchored as a LOWERCASE word
# after whitespace, which layout tokens (`T(`, `S(`) never satisfy.
# `-start` variants (async collectives) are folded into their base op;
# `-done` carries no payload and is skipped.
_HLO_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*([\w\[\](){}<=:,.\s/#*-]*?)\s+"
    r"([a-z][\w\-]*)\(")
# Dims admit bounded-dynamic sizes ("<=8" — counted at their upper bound).
_SHAPE = re.compile(r"([a-z]+\d*)\[([\d,<=]*)\]")


def shape_bytes(shape_str: str, largest_only: bool = False) -> int:
    """Bytes of the array shape(s) in an HLO result-type string (unknown
    dtypes count 0). Tuples SUM their elements by default (a variadic sync
    all-reduce's tuple is N real payloads); ``largest_only`` takes the
    single largest array instead — async ``-start`` ops return tuples that
    alias the INPUT next to the output (plus u32 context scalars), where
    summing would double-count the transfer."""
    sizes = []
    for dtype, dims in _SHAPE.findall(shape_str):
        unit = _DTYPE_BYTES.get(dtype)
        if unit is None:
            continue
        n = 1
        for d in dims.split(","):
            d = d.replace("<=", "")
            if d:
                n *= int(d)
        sizes.append(n * unit)
    if not sizes:
        return 0
    return max(sizes) if largest_only else sum(sizes)


# Per-line replica-group parses, for the link-traffic estimate: the literal
# form `replica_groups={{0,1,2,3},{4,5,6,7}}` (group size = first group's
# member count) and the iota form `replica_groups=[4,2]<=[8]` (4 groups of
# 2 — group size is the SECOND dimension).
_GROUPS_LITERAL = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_GROUPS_IOTA = re.compile(r"replica_groups=\[\d+,(\d+)\]")


def _group_size(line: str) -> Optional[int]:
    m = _GROUPS_LITERAL.search(line)
    if m:
        return m.group(1).count(",") + 1
    m = _GROUPS_IOTA.search(line)
    if m:
        return int(m.group(1))
    return None


def _link_bytes(base: str, payload: int, g: Optional[int]) -> int:
    """Estimated wire traffic of one collective from its census payload
    (= result bytes) and group size ``g``, using the standard ring costs:
    all-reduce moves 2(g−1)/g × its buffer, all-gather/all-to-all
    (g−1)/g × the gathered/exchanged buffer, reduce-scatter (g−1) × its
    (1/g-sized) result, a permute exactly its payload. The payload metric
    under-credits RS/AG decompositions (an all-reduce counts its full f32
    result once; the equivalent RS+AG pair counts ~1.25×n for the same
    wire work), so comms-shrinking rewrites are judged on THIS number —
    with no parseable group, the asymptotic factor stands in (documented
    estimate, not a measurement)."""
    if g is not None and g < 2:
        return 0
    if base == "all-reduce":
        return int(payload * (2 * (g - 1) / g if g else 2.0))
    if base == "reduce-scatter":
        return int(payload * (g - 1)) if g else payload
    if base in ("all-gather", "all-to-all"):
        return int(payload * ((g - 1) / g if g else 1.0))
    return payload                        # collective-permute


def hlo_op_census(hlo_text: str) -> dict:
    """Counts per op kind + collective payload bytes (+ estimated link
    traffic) from optimized HLO."""
    op_counts: dict[str, int] = {}
    collectives: dict[str, dict] = {}
    link_bytes: dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _HLO_INSTR.match(line)
        if not m:
            continue
        shapes, op = m.group(1), m.group(2)
        if op.endswith("-done"):
            continue                      # async pair: -start carried payload
        base = op[:-6] if op.endswith("-start") else op
        op_counts[base] = op_counts.get(base, 0) + 1
        if base in _COLLECTIVE_OPS:
            c = collectives.setdefault(base, {"count": 0, "bytes": 0})
            c["count"] += 1
            payload = shape_bytes(shapes,
                                  largest_only=op.endswith("-start"))
            c["bytes"] += payload
            link_bytes[base] = link_bytes.get(base, 0) + _link_bytes(
                base, payload, _group_size(line))
    return {"op_counts": op_counts, "collectives": collectives,
            "link_bytes": link_bytes}


# HLO op kind → coarse execution-unit category, for the summarize
# time-attribution table (VERDICT r5 weak #4: MFU 0.429 with nothing naming
# where the other 57% goes). Categories are chosen by which hardware
# resource the op *occupies*: MXU (systolic matmuls), VPU elementwise,
# reductions, pure data movement (layout/copy — zero arithmetic, pure
# HBM/VMEM traffic), collectives (ICI/DCN), and control/bookkeeping ops
# that cost nothing at runtime. Ops not listed fall into "other"
# (fusion wrappers excluded: their BODIES are censused line-by-line too,
# counting the wrapper would double-book every fused op).
_OP_CATEGORY = {}
for _op in ("dot", "convolution", "dot-general"):
    _OP_CATEGORY[_op] = "mxu"
for _op in ("add", "subtract", "multiply", "divide", "maximum", "minimum",
            "exponential", "log", "rsqrt", "sqrt", "power", "tanh",
            "logistic", "negate", "abs", "sign", "floor", "ceil",
            "round-nearest-afz", "compare", "select", "and", "or", "not",
            "xor", "clamp", "convert", "exponential-minus-one", "cosine",
            "sine", "is-finite", "remainder", "shift-left",
            "shift-right-logical", "shift-right-arithmetic", "atan2",
            "cbrt", "erf", "popcnt", "stochastic-convert"):
    _OP_CATEGORY[_op] = "vpu"
for _op in ("reduce", "reduce-window", "select-and-scatter", "sort",
            "reduce-precision"):
    _OP_CATEGORY[_op] = "reduce"
for _op in ("copy", "copy-start", "transpose", "reshape", "bitcast",
            "bitcast-convert", "broadcast", "slice", "dynamic-slice",
            "dynamic-update-slice", "concatenate", "pad", "gather",
            "scatter", "iota", "reverse"):
    _OP_CATEGORY[_op] = "copy"
for _op in _COLLECTIVE_OPS:
    _OP_CATEGORY[_op] = "collective"
for _op in ("parameter", "constant", "tuple", "get-tuple-element", "while",
            "conditional", "call", "after-all", "partition-id", "replica-id",
            "rng-bit-generator", "rng-get-and-update-state", "domain",
            "opt-barrier"):
    _OP_CATEGORY[_op] = "control"

OP_CATEGORIES = ("mxu", "vpu", "reduce", "copy", "collective", "control",
                 "other")


def op_category_counts(op_counts: dict) -> dict:
    """Roll the per-kind census up into execution-unit categories. Fusion
    wrappers are skipped (their bodies are already counted); custom-call is
    "other" (on TPU it is usually an opaque Mosaic/Pallas kernel)."""
    out = {c: 0 for c in OP_CATEGORIES}
    for op, n in op_counts.items():
        if op == "fusion":
            continue
        out[_OP_CATEGORY.get(op, "other")] += n
    return out


def memory_breakdown(compiled) -> dict:
    """``memory_analysis()``'s buffer-assignment numbers plus the one
    compiler-side HBM formula (args + outputs + temps + code − aliased) —
    the single definition of "compiled HBM" behind ``introspect`` (and
    thereby bench rows' ``hbm_compiled_gb`` and the compile event). Raises
    when the backend has no memory analysis; callers own the policy."""
    ma = compiled.memory_analysis()
    out = {"arg_bytes": int(ma.argument_size_in_bytes),
           "out_bytes": int(ma.output_size_in_bytes),
           "temp_bytes": int(ma.temp_size_in_bytes),
           "gen_code_bytes": int(ma.generated_code_size_in_bytes),
           "alias_bytes": int(ma.alias_size_in_bytes)}
    out["hbm_compiled_bytes"] = (out["arg_bytes"] + out["out_bytes"]
                                 + out["temp_bytes"] + out["gen_code_bytes"]
                                 - out["alias_bytes"])
    return out


def introspect(compiled, log: Optional[Callable[[str], None]] = None) -> dict:
    """Every number the three compiler surfaces give up, as flat scalars
    (plus the nested censuses). Missing surfaces simply leave their keys
    absent — callers treat the dict as sparse."""
    from tpudist.telemetry import cost_analysis_dict
    out: dict = {}

    def note(msg: str) -> None:
        if log is not None:
            try:
                log(msg)
            except Exception:
                pass

    try:
        cost = cost_analysis_dict(compiled)
        for key, name in (("flops", "flops"),
                          ("bytes accessed", "bytes_accessed"),
                          ("transcendentals", "transcendentals")):
            if cost.get(key):
                out[name] = float(cost[key])
        # Per-operand/output byte attribution when the backend provides it
        # (keys like "bytes accessed output" / "bytes accessed operand 0 {}").
        opd = {k: float(v) for k, v in cost.items()
               if k.startswith("bytes accessed ") and v}
        if opd:
            out["bytes_accessed_detail"] = opd
    except Exception as e:
        note(f"cost_analysis unavailable: {e!r}")

    try:
        out.update(memory_breakdown(compiled))
    except Exception as e:
        note(f"memory_analysis unavailable: {e!r}")

    try:
        census = hlo_op_census(compiled.as_text())
        out["op_counts"] = census["op_counts"]
        out["collectives"] = census["collectives"]
        out["collective_ops"] = sum(c["count"]
                                    for c in census["collectives"].values())
        out["collective_bytes_per_step"] = sum(
            c["bytes"] for c in census["collectives"].values())
        if census["collectives"]:
            out["collective_link_bytes"] = sum(
                census["link_bytes"].values())
    except Exception as e:
        note(f"HLO census unavailable: {e!r}")
    return out


# Flat numeric fields safe to ride on a telemetry ``compile`` event / bench
# row (the nested censuses stay out of the hot event stream; summarize
# re-derives what it needs from these).
EVENT_FIELDS = ("flops", "bytes_accessed", "transcendentals", "arg_bytes",
                "out_bytes", "temp_bytes", "gen_code_bytes", "alias_bytes",
                "hbm_compiled_bytes", "collective_ops",
                "collective_bytes_per_step", "collective_link_bytes") \
    + tuple(f"ops_{c}" for c in OP_CATEGORIES)


def event_fields(info: dict) -> dict:
    """The flat-scalar subset of ``introspect``'s result, for emitting on
    the ``compile`` telemetry event and stamping into bench rows."""
    out = {k: info[k] for k in EVENT_FIELDS
           if isinstance(info.get(k), (int, float))}
    # Op-category rollup as flat numeric fields: the compile event (and
    # bench rows) carry ops_mxu/ops_vpu/... so summarize can print the
    # time-attribution table without the full per-kind census.
    if info.get("op_counts"):
        for c, n in op_category_counts(info["op_counts"]).items():
            out[f"ops_{c}"] = n
    # Headline comms number: all-reduce count (the data-parallel gradient
    # sync — the op whose growth tracks mesh size).
    ar = (info.get("collectives") or {}).get("all-reduce")
    if ar:
        out["all_reduce_count"] = ar["count"]
        out["all_reduce_bytes"] = ar["bytes"]
    return out


def format_section(info: dict) -> list[str]:
    """Human lines for the summarize report (empty when nothing is known)."""
    L: list[str] = []
    if not info:
        return L
    gb = 2.0 ** 30
    if info.get("flops"):
        line = f"    flops/step {info['flops']:.3e}"
        if info.get("bytes_accessed"):
            line += (f", bytes accessed {info['bytes_accessed']:.3e} "
                     f"(arith intensity "
                     f"{info['flops'] / info['bytes_accessed']:.1f} "
                     f"flop/byte)")
        L.append(line)
    if info.get("hbm_compiled_bytes"):
        parts = [f"{name} {info[k] / gb:.3f}"
                 for name, k in (("args", "arg_bytes"), ("out", "out_bytes"),
                                 ("temps", "temp_bytes"),
                                 ("code", "gen_code_bytes"))
                 if info.get(k) is not None]
        alias = info.get("alias_bytes") or 0
        L.append(f"    HBM (compiler view) "
                 f"{info['hbm_compiled_bytes'] / gb:.3f} GB  "
                 f"[{', '.join(parts)}"
                 + (f", -aliased {alias / gb:.3f}" if alias else "") + "]")
    colls = info.get("collectives") or {}
    if colls:
        per = ", ".join(f"{op} x{c['count']} ({c['bytes'] / 2**20:.1f} MiB)"
                        for op, c in sorted(colls.items()))
        L.append(f"    collectives/step: {per}")
    elif info.get("collective_ops"):
        # Flat-field consumers (summarize reads the compile event, which
        # carries no per-op census beyond all-reduce): a reduce-scatter /
        # all-gather program must still show its comms total.
        L.append(f"    collectives/step: {info['collective_ops']:.0f} ops "
                 f"({(info.get('collective_bytes_per_step') or 0) / 2**20:.1f}"
                 f" MiB)")
    elif "collective_ops" in info or info.get("op_counts"):
        L.append("    collectives/step: none (single-device program)")
    return L
