"""Live observability plane (PR 3) — the layer that turns the PR-2 flight
recorder (``events.<rank>.jsonl`` + post-hoc ``python -m tpudist.summarize``)
into a control room you can watch while the job is alive:

- ``obs.server``   — opt-in (``--metrics-port``) zero-dependency HTTP endpoint
                     per rank serving Prometheus text format, fed from the
                     telemetry emit path (the hot loop gains no new clocks);
                     the launcher aggregates heartbeats + rank endpoints into
                     a fleet view with straggler gauges.
- ``obs.trace``    — merge every rank's event stream (plus the launcher's and
                     rotated segments) into one Chrome/Perfetto trace-event
                     JSON with per-rank tracks (``summarize --trace out.json``).
- ``obs.xla_introspect`` — post-compile cost/memory/collective introspection
                     of the jitted train step, surfaced in the ``compile``
                     telemetry event, in ``summarize``, and in bench rows.

Import-light by design (same contract as ``tpudist.telemetry``): no jax at
module import time, so the launcher and test helpers can use the server and
trace merger without touching an accelerator runtime.
"""
