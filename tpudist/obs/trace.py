"""Cross-rank Chrome/Perfetto trace export from a run dir's telemetry.

``python -m tpudist.summarize <rundir> --trace out.json`` (or
``export_trace_file``) merges every ``events.*.jsonl`` a run wrote — all
ranks, the launcher's stream, and size-rotated segments — into ONE
trace-event JSON that ``ui.perfetto.dev`` (or ``chrome://tracing``) loads
directly, making "which rank is slow and *when*" a single-file answer:

- one **process track per rank** (``pid`` = rank; the launcher is pid -1),
  with named threads for the step timeline, the phase breakdown, and the
  overhead timeline (compile / checkpoint / eval / epoch);
- **step spans** reconstructed from each step event's ``step_s`` (the event
  is stamped at the step's END), with the data→h2d→compute→drain phase
  spans laid out inside in their true execution order (boundaries within
  the step are reconstructed from the phase durations — the flight
  recorder stores durations, not per-phase wall stamps);
- **instant events** for faults, preemptions, rank exits, restarts, and
  straggler flags, so the failure chain lines up against the step timeline;
- **clock-skew alignment**: on a multi-host run each rank stamps events
  with its own host clock. Ranks rendezvous in ``jax.distributed``
  initialization immediately before their ``run_start`` emission, so the
  per-attempt ``run_start`` anchors are near-simultaneous in real time —
  each rank's timeline is shifted so its first-attempt anchor coincides
  with the fleet's earliest one (disable with ``align=False`` when clocks
  are known-good and genuine start offsets matter).

Everything here is pure functions of the event list (unit-testable against
synthetic timelines) and jax-free.
"""

from __future__ import annotations

import json
from typing import Optional

LAUNCHER_PID = -1

# Phase sub-spans inside one step, in execution order. data wait happens
# first (blocked on the loader), then host→device placement, then the device
# dispatch, then the (optional) metric drain; the unattributed remainder is
# host overhead ("other host" in summarize).
_STEP_PHASES = ("data_s", "h2d_s", "compute_s", "drain_s")
_PHASE_NAMES = {"data_s": "data wait", "h2d_s": "h2d",
                "compute_s": "compute", "drain_s": "drain"}

# Stable thread ids inside each rank's process track.
_TID_STEPS = 0
_TID_PHASES = 1
_TID_OVERHEAD = 2
_TID_MARKS = 3
_TID_NAMES = {_TID_STEPS: "steps", _TID_PHASES: "step phases",
              _TID_OVERHEAD: "compile/ckpt/eval", _TID_MARKS: "events"}


def _rank_of(ev: dict) -> int:
    """Track key: launcher-envelope events (rank -1) that are ABOUT a rank
    still land on the launcher's own track — the about-rank is kept in the
    event args instead, so the supervisor's view stays one timeline."""
    r = ev.get("rank", 0)
    return int(r) if isinstance(r, (int, float)) else LAUNCHER_PID


def clock_offsets(events: list[dict], align: bool = True) -> dict[int, float]:
    """Per-rank clock shift (seconds, SUBTRACTED from the rank's stamps).

    Anchors must come from the SAME attempt: ranks exit that attempt's
    distributed-init rendezvous together right before emitting run_start,
    so aligning its anchors cancels host clock skew — whereas anchoring one
    rank's attempt-0 against another's attempt-1 (rank 1 died before its
    first emit, or rotation dropped the segment) would translate a whole
    timeline by the crash-plus-restart gap. The earliest attempt with
    run_starts from >= 2 ranks is the anchor attempt; ranks without an
    anchor there (and the launcher) are left unshifted.
    """
    offsets: dict[int, float] = {}
    if not align:
        return offsets
    by_attempt: dict[int, dict[int, float]] = {}
    for ev in events:
        if ev.get("type") == "run_start":
            a = int(ev.get("attempt", 0))
            anchors = by_attempt.setdefault(a, {})
            r = _rank_of(ev)
            if r not in anchors or ev["t"] < anchors[r]:
                anchors[r] = ev["t"]
    anchors = next((by_attempt[a] for a in sorted(by_attempt)
                    if len(by_attempt[a]) >= 2), None)
    if anchors is None:
        return offsets
    t_ref = min(anchors.values())
    for r, t in anchors.items():
        if t != t_ref:
            offsets[r] = t - t_ref
    return offsets


def _span(pid, tid, name, t_start_us, dur_us, args=None, cat="tpudist"):
    ev = {"ph": "X", "pid": pid, "tid": tid, "name": name, "cat": cat,
          "ts": max(0.0, round(t_start_us, 3)),
          "dur": round(max(dur_us, 0.1), 3)}
    if args:
        ev["args"] = args
    return ev


def _instant(pid, tid, name, t_us, args=None, cat="tpudist"):
    ev = {"ph": "i", "s": "p", "pid": pid, "tid": tid, "name": name,
          "cat": cat, "ts": max(0.0, round(t_us, 3))}
    if args:
        ev["args"] = args
    return ev


def _num_args(ev: dict, skip=("t", "type", "rank", "attempt")) -> dict:
    return {k: v for k, v in ev.items()
            if k not in skip and isinstance(v, (int, float, str))}


def to_trace_events(events: list[dict], align: bool = True) -> list[dict]:
    """Pure transform: telemetry events → Chrome trace-event dicts.

    Timestamps are microseconds relative to the aligned fleet start (trace
    viewers dislike epoch-scale ``ts`` values); ``args.wall_t`` keeps the
    original epoch stamp for cross-referencing the jsonl.
    """
    offsets = clock_offsets(events, align=align)

    def t_of(ev: dict) -> float:
        return ev["t"] - offsets.get(_rank_of(ev), 0.0)

    if not events:
        return []
    # Spans are stamped at their END and extend BACKWARDS by their duration
    # (step_s / seconds); the trace origin must sit at the earliest span
    # START or the first step/compile would get a negative ts.
    t0 = min(t_of(e) - float(e.get("step_s") or e.get("seconds") or 0.0)
             for e in events)

    def us(ev: dict, back_s: float = 0.0) -> float:
        return (t_of(ev) - t0 - back_s) * 1e6

    out: list[dict] = []
    ranks = sorted({_rank_of(e) for e in events})
    for r in ranks:
        pname = "launcher" if r == LAUNCHER_PID else f"rank {r}"
        out.append({"ph": "M", "pid": r, "name": "process_name",
                    "args": {"name": pname}})
        out.append({"ph": "M", "pid": r, "name": "process_sort_index",
                    "args": {"sort_index": r}})
        for tid, tname in _TID_NAMES.items():
            out.append({"ph": "M", "pid": r, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
            out.append({"ph": "M", "pid": r, "tid": tid,
                        "name": "thread_sort_index",
                        "args": {"sort_index": tid}})

    for ev in events:
        r = _rank_of(ev)
        et = ev.get("type")
        args = _num_args(ev)
        args["wall_t"] = ev["t"]
        if et == "step":
            dur = ev["step_s"]
            start = us(ev, back_s=dur)
            out.append(_span(r, _TID_STEPS, f"step {ev.get('step', '?')}",
                             start, dur * 1e6, args))
            # Phase sub-spans in execution order; durations are what the
            # recorder has, so they tile from the step start and any
            # unattributed remainder (other-host) is the gap at the end.
            cursor = start
            for key in _STEP_PHASES:
                d = float(ev.get(key, 0.0) or 0.0)
                if d <= 0.0:
                    continue
                out.append(_span(r, _TID_PHASES, _PHASE_NAMES[key], cursor,
                                 d * 1e6, cat="tpudist.phase"))
                cursor += d * 1e6
        elif et in ("compile", "checkpoint_save", "checkpoint_restore",
                    "eval", "epoch"):
            dur = float(ev.get("seconds", 0.0) or 0.0)
            name = {"compile": f"compile:{ev.get('phase', '?')}",
                    "checkpoint_save": f"ckpt save:{ev.get('kind', '?')}",
                    "checkpoint_restore": "ckpt restore",
                    "eval": f"eval e{ev.get('epoch', '?')}",
                    "epoch": f"epoch {ev.get('epoch', '?')}"}[et]
            out.append(_span(r, _TID_OVERHEAD, name, us(ev, back_s=dur),
                             dur * 1e6, args))
        elif et in ("fault", "preempt", "straggler", "rank_exit", "restart",
                    "launcher_start", "run_start", "run_end", "program"):
            name = {"fault": f"fault:{ev.get('point', '?')}",
                    "preempt": f"preempt:{ev.get('signal', '?')}",
                    "straggler": f"straggler rank "
                                 f"{ev.get('straggler_rank', '?')}",
                    "rank_exit": f"rank {ev.get('exit_rank', '?')} exit "
                                 f"{ev.get('code', '?')}",
                    "restart": f"restart #{ev.get('attempt', '?')}",
                    "launcher_start": f"attempt {ev.get('attempt', '?')} "
                                      f"start",
                    "run_start": "run_start", "run_end": "run_end",
                    "program": "program compiled"}[et]
            out.append(_instant(r, _TID_MARKS, name, us(ev), args))
    return out


def export_trace(events: list[dict], align: bool = True) -> dict:
    """The full Chrome trace JSON object for a telemetry event list."""
    return {
        "traceEvents": to_trace_events(events, align=align),
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "tpudist.obs.trace",
            "clock_note": ("per-rank clocks aligned on run_start anchors"
                           if align else "raw host clocks"),
        },
    }


def export_trace_file(events: list[dict], path: str,
                      align: bool = True) -> Optional[dict]:
    obj = export_trace(events, align=align)
    with open(path, "w") as f:
        json.dump(obj, f)
    return obj
