"""Multi-process launcher (reference C18: ``torch.distributed.launch``,
``start.sh:3-4``).

On real TPU pods each HOST runs one process and the TPU runtime supplies the
topology, so no launcher is needed there (``jax.distributed.initialize()``
with no args). This launcher covers the other cases:

- simulating a multi-process (multi-host) run on one machine — N processes on
  the CPU backend with a local coordinator, the moral equivalent of
  ``python -m torch.distributed.launch --nproc_per_node=N`` on one box;
- launching with explicit coordinator/process ids on clusters without TPU
  metadata.

Usage::

    python -m tpudist.launch --nprocs 2 -- python -m tpudist --synthetic ...

Each child gets ``TPUDIST_COORDINATOR``, ``TPUDIST_NUM_PROCESSES``,
``TPUDIST_PROCESS_ID`` (read by ``dist.initialize_runtime``) and, for the
local-simulation case, a CPU device count per process. Rendezvous is the
jax.distributed coordinator (TCP) — the NCCL/TCPStore rendezvous of the
reference (``distributed.py:124``) with the coordinator service instead.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def find_free_port() -> int:
    # SO_REUSEADDR so the coordinator can bind even while the probe socket's
    # address lingers in TIME_WAIT. A concurrent process could still claim the
    # port between close and the coordinator's bind; rank 0 then fails to bind
    # and abort-on-peer-loss below tears the job down rather than hanging.
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _terminate_all(procs, grace: float = 10.0) -> None:
    """SIGTERM each child's process group, then SIGKILL stragglers after a
    grace period — a rank blocked in a collective (or its grandchildren)
    must not outlive the job."""
    for pr in procs:
        try:
            os.killpg(pr.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + grace
    for pr in procs:
        try:
            pr.wait(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(pr.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            pr.wait()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="tpudist multi-process launcher")
    p.add_argument("--nprocs", "-n", type=int, required=True,
                   help="number of processes to launch")
    p.add_argument("--coordinator", default=None,
                   help="host:port (default: 127.0.0.1:<free port>)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="CPU devices each process simulates (local runs)")
    p.add_argument("--platform", default="cpu",
                   help="JAX platform for children (cpu for simulation)")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic restarts: after abort-on-peer-loss tears a "
                        "failed job down, relaunch ALL ranks (fresh "
                        "coordinator) up to N times — with the trainer's "
                        "checkpoint-resume this continues from the last "
                        "completed epoch (torchrun --max-restarts analogue; "
                        "the reference's NCCL job just dies, SURVEY.md §5)")
    p.add_argument("--elastic", action="store_true",
                   help="gang reformation on rank loss: instead of a full "
                        "same-size restart, a reform-eligible rank exit "
                        "drains the survivors (SIGTERM -> emergency "
                        "checkpoint with the epoch's sample cursor -> exit "
                        "75) and relaunches the gang at the SURVIVING world "
                        "size, down to --min-ranks (tpudist/elastic/). "
                        "Reforms do not consume the --max-restarts budget "
                        "(they are bounded by the rank count). The command "
                        "should pass --resume auto --overwrite keep so the "
                        "reformed gang resumes the checkpoint; sets "
                        "TPUDIST_ELASTIC=1 so non-distributed CPU sims "
                        "shard data by the launcher-assigned identity")
    p.add_argument("--min-ranks", type=int, default=1, dest="min_ranks",
                   help="with --elastic: smallest world size worth training "
                        "at — losing more ranks than this falls back to the "
                        "same-size restart path (default 1)")
    p.add_argument("--drain-grace", type=float, default=30.0,
                   dest="drain_grace",
                   help="with --elastic: seconds survivors get to drain "
                        "(finish the in-flight step + write the emergency "
                        "checkpoint) after SIGTERM before SIGKILL; a "
                        "survivor blocked in a dead collective is killed at "
                        "the deadline and the reform resumes from the last "
                        "epoch checkpoint instead of the cursor")
    p.add_argument("--inject", default=os.environ.get("TPUDIST_INJECT", ""),
                   help="fault-injection spec propagated to every rank via "
                        "TPUDIST_INJECT (tpudist/faults.py), e.g. "
                        "'rank_exit@step=7@rank=1@attempt=0'; gates on "
                        "rank/attempt select which rank/launch-attempt "
                        "fires, so a restarted job can prove clean recovery")
    p.add_argument("--telemetry-dir", default="", dest="telemetry_dir",
                   help="run dir holding the ranks' telemetry (heartbeats/ + "
                        "events.*.jsonl, written when the trainer runs with "
                        "--telemetry). The launcher aggregates heartbeats "
                        "into straggler detection and appends its own "
                        "events.launcher.jsonl (rank exits with exit "
                        "classification, restarts, stragglers). Explicit "
                        "dir = eager (created immediately — combine with "
                        "--overwrite keep so a delete-mode rank 0 cannot "
                        "unlink the open event file). Default: when the "
                        "command passes --telemetry, its --outpath is used "
                        "LAZILY — the launcher waits for the ranks to set "
                        "the dir up, so --overwrite semantics are "
                        "untouched")
    p.add_argument("--metrics-port", type=int, default=-1,
                   dest="metrics_port",
                   help="serve the launcher's FLEET metrics view on this "
                        "port (0 = pick a free port): supervision counters "
                        "(attempt, restarts, rank exits by classification), "
                        "per-rank heartbeat gauges, straggler flags as "
                        "gauges, and headline samples aggregated from each "
                        "rank's own --metrics-port endpoint. Requires a "
                        "telemetry dir (--telemetry-dir, or a command that "
                        "passes --telemetry with --outpath). -1 = off")
    p.add_argument("--straggler-factor", type=float, default=4.0,
                   dest="straggler_factor",
                   help="flag a rank whose per-step host overhead (p50 over "
                        "a recent window, from its heartbeat) exceeds this "
                        "multiple of the other ranks' median; 0 disables. "
                        "Host overhead — not total step time — because "
                        "lockstep SPMD equalizes step time across ranks "
                        "(healthy ranks absorb a straggler inside the "
                        "collective wait)")
    p.add_argument("--evict-stragglers", type=int, default=0,
                   dest="evict_stragglers", metavar="N",
                   help="with --elastic: proactively DRAIN a rank the "
                        "straggler detector flags for N consecutive ~1s "
                        "supervision windows — SIGTERM its process group so "
                        "it takes the normal preemption path (finish the "
                        "in-flight step, emergency checkpoint with the "
                        "sample cursor, exit 75) and the gang reforms "
                        "without it. Counted separately from crash "
                        "restarts ('eviction' events + the fleet's "
                        "evictions_total counter); never evicts below "
                        "--min-ranks. 0 = off (flag-and-log only, the "
                        "pre-eviction behavior)")
    p.add_argument("--scale-up", default="", dest="scale_up",
                   metavar="W@S",
                   help="elastic SCALE-UP for collective-free replicas "
                        "(the tpudist.serve plane): after S seconds, spawn "
                        "additional ranks up to world W — e.g. '2@10' "
                        "grows a 1-replica serving fleet to 2 under load, "
                        "emitting a 'topology_change' (mesh_action "
                        "scale_up) so the fleet view follows. New ranks "
                        "get the next TPUDIST_PROCESS_ID and share the "
                        "command verbatim (point them at one "
                        "TPUDIST_COMPILE_CACHE so the newcomer serves "
                        "from the warm cache in seconds). Refused for "
                        "--distributed commands: a training gang's "
                        "collectives cannot admit members mid-flight")
    p.add_argument("--collective-deadline", type=float, default=0.0,
                   dest="collective_deadline", metavar="S",
                   help="dead-collective watchdog: when EVERY live rank's "
                        "heartbeat goes stale for more than S seconds (the "
                        "whole gang is wedged — a dead peer inside a "
                        "collective stalls everyone, and no rank exits on "
                        "its own), emit a loud 'collective_deadline' fault "
                        "event and drain the stalest (suspect) rank "
                        "(SIGTERM, SIGKILL after --drain-grace) so the "
                        "wedge converts to a reform/restart instead of a "
                        "hang. Size S above the longest legitimate "
                        "heartbeat gap (validation + checkpoint: "
                        "heartbeats only advance on TRAIN steps). 0 = off")
    p.add_argument("--incident-keep", type=int, default=4,
                   dest="incident_keep", metavar="K",
                   help="keep the newest K incident bundles under "
                        "<rundir>/incidents/ (the checkpoint keep-K "
                        "convention). The bundler arms itself only when a "
                        "rank runs with --blackbox (its blackbox/ dir "
                        "appears); runs without it are untouched")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to run (prefix with --)")
    args = p.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given (append: -- python -m tpudist ...)")
    if args.max_restarts < 0:
        p.error("--max-restarts must be >= 0 (there is no infinite mode: "
                "an unrecoverable fault would relaunch forever)")
    if args.elastic and not 1 <= args.min_ranks <= args.nprocs:
        p.error(f"--min-ranks must be in [1, --nprocs={args.nprocs}], "
                f"got {args.min_ranks}")
    if args.evict_stragglers < 0:
        p.error("--evict-stragglers must be >= 0")
    if args.evict_stragglers and not args.elastic:
        p.error("--evict-stragglers needs --elastic: draining a straggler "
                "only helps if the gang can reform without it")
    if args.evict_stragglers and args.straggler_factor <= 0:
        p.error("--evict-stragglers needs --straggler-factor > 0 (the "
                "eviction signal IS the straggler detector)")
    if args.incident_keep < 1:
        p.error("--incident-keep must be >= 1 (0 would delete every "
                "bundle the moment it lands)")
    args.scale_target, args.scale_after = 0, 0.0
    if args.scale_up:
        try:
            tgt, after = args.scale_up.split("@", 1)
            args.scale_target, args.scale_after = int(tgt), float(after)
        except ValueError:
            p.error(f"--scale-up must be 'WORLD@SECONDS' (e.g. '2@10'), "
                    f"got '{args.scale_up}'")
        if args.scale_target <= args.nprocs:
            p.error(f"--scale-up target {args.scale_target} must exceed "
                    f"--nprocs {args.nprocs}")
        if args.scale_after < 0:
            p.error("--scale-up delay must be >= 0 seconds")
        if "--distributed" in cmd:
            p.error("--scale-up is for collective-free replicas (serving): "
                    "a --distributed training gang's collectives cannot "
                    "admit members mid-flight — use --elastic reforms "
                    "instead")

    from tpudist.elastic.membership import (mesh_str, parse_mesh_args,
                                            plan_reform_topology,
                                            reform_world, rewrite_mesh_args)
    from tpudist.faults import classify_exit, parse_spec
    if args.inject:
        parse_spec(args.inject)        # fail fast on a typo'd spec
    telemetry = _launcher_telemetry(args, cmd)
    if args.evict_stragglers or args.collective_deadline > 0:
        # Both watchdogs read the RANKS' heartbeat files, which only exist
        # when the trainer command itself runs --telemetry — a launcher
        # stream alone (explicit --telemetry-dir) would leave them
        # silently inert, the no-op shape this repo's validation policy
        # forbids. (A mismatched --telemetry-dir vs the cmd's --outpath is
        # caught at runtime: the poll warns when heartbeats never appear.)
        if telemetry is None:
            p.error("--evict-stragglers/--collective-deadline read rank "
                    "heartbeats: pass --telemetry-dir, or run a command "
                    "with --telemetry and an --outpath")
        if "--telemetry" not in cmd:
            p.error("--evict-stragglers/--collective-deadline need rank "
                    "heartbeats, which only a command running with "
                    "--telemetry writes — add --telemetry to the trainer "
                    "command")
    fleet, fleet_server = _fleet_metrics(args, telemetry, parser=p)
    # Supervision counters: ``attempt`` numbers every supervise pass (it is
    # what TPUDIST_RESTART_COUNT / @attempt injection gates / heartbeat
    # attempt-gating see); restarts and reforms are counted SEPARATELY —
    # a reform shrinks the world instead of burning the restart budget
    # (it is bounded by the rank count, not --max-restarts).
    world = args.nprocs
    mesh_shape, mesh_axes = parse_mesh_args(cmd)
    attempt = restarts_used = reforms = 0
    exit_code = 0
    try:
        while True:
            exit_code, lost = _supervise_once(args, cmd, attempt, telemetry,
                                              fleet, world)
            if exit_code in (0, 130):      # success, or operator interrupt
                break
            new_world = reform_world(world, lost, exit_code,
                                     elastic=args.elastic,
                                     min_ranks=args.min_ranks)
            if new_world is not None:
                reforms += 1
                attempt += 1
                # Topology-aware reform (ISSUE 13): re-plan the mesh for
                # the surviving world — keep the model (tp) axis when the
                # survivors still divide it, else fold it into dp — and
                # relaunch with the rewritten --mesh-shape/--mesh-axes.
                new_shape, new_axes, action = plan_reform_topology(
                    mesh_shape, mesh_axes, new_world)
                mesh_note = ""
                if action == "fold":
                    cmd = rewrite_mesh_args(cmd, new_shape, new_axes)
                    mesh_note = (f"; mesh {mesh_str(mesh_shape, mesh_axes)}"
                                 f" -> {mesh_str(new_shape, new_axes)} "
                                 f"(model axis folded into data: world "
                                 f"{new_world} no longer divides tp)")
                elif mesh_shape and "model" in (mesh_axes or ()):
                    mesh_note = (f"; mesh {mesh_str(mesh_shape, mesh_axes)}"
                                 f" kept (world {new_world} still divides "
                                 f"tp)")
                print(f"[tpudist.launch] rank loss (exit {exit_code}: "
                      f"{classify_exit(exit_code)}; lost "
                      f"{sorted(lost)}) — REFORMING gang at world "
                      f"{new_world} (was {world}; reform {reforms}, restart "
                      f"budget untouched{mesh_note})",
                      file=sys.stderr, flush=True)
                if telemetry is not None:
                    telemetry.emit("topology_change", attempt=attempt,
                                   from_world=world, to_world=new_world,
                                   lost_ranks=",".join(
                                       str(r) for r in sorted(lost)),
                                   prev_exit=exit_code,
                                   from_mesh=mesh_str(mesh_shape, mesh_axes),
                                   to_mesh=mesh_str(new_shape, new_axes),
                                   mesh_action=action)
                world = new_world
                mesh_shape, mesh_axes = new_shape, new_axes
                continue
            if restarts_used < args.max_restarts:
                restarts_used += 1
                attempt += 1
                print(f"[tpudist.launch] job failed (exit {exit_code}: "
                      f"{classify_exit(exit_code)}) — "
                      f"restart {restarts_used}/{args.max_restarts}",
                      file=sys.stderr, flush=True)
                if telemetry is not None:
                    telemetry.emit("restart", attempt=attempt,
                                   prev_exit=exit_code)
                continue
            print(f"[tpudist.launch] job failed (exit {exit_code}: "
                  f"{classify_exit(exit_code)}) — restart budget "
                  f"exhausted", file=sys.stderr, flush=True)
            break
        if hasattr(telemetry, "flush"):
            telemetry.flush(force=True)  # job over: land any buffered events
    finally:
        if fleet_server is not None:
            fleet_server.close()
    return exit_code


def _fleet_metrics(args, telemetry, parser=None):
    """The launcher's live fleet view (``--metrics-port``): a FleetMetrics
    registry observing the launcher's own event stream + a zero-dependency
    HTTP server rendering its cached exposition. The registry refreshes from
    heartbeats/rank endpoints inside the existing ~1 s supervision poll —
    serving a scrape never touches the filesystem."""
    if getattr(args, "metrics_port", -1) < 0:
        return None, None
    if telemetry is None:
        msg = ("--metrics-port needs a telemetry dir: pass --telemetry-dir, "
               "or run a command with --telemetry and an --outpath")
        if parser is not None:
            parser.error(msg)
        raise SystemExit(msg)
    from tpudist.obs.server import FleetMetrics, MetricsServer
    fleet = FleetMetrics(telemetry.outpath, args.nprocs,
                         straggler_factor=args.straggler_factor)
    if hasattr(telemetry, "add_sink"):
        telemetry.add_sink(fleet.observe)
    else:
        telemetry.sink = fleet.observe     # _LazyLauncherTelemetry
    # attempt=0, not None: a relaunch into a still-warm --telemetry-dir
    # must not read the DEAD run's heartbeats with the attempt gate off
    # and publish its phantom straggler flags.
    fleet.refresh(attempt=0)
    # /dashboard: bench-history trend panels + the live tsdb window the
    # supervision poll records. File reads happen per HTTP GET in the
    # handler thread; latest_path resolves lazily so the page works even
    # before the first sample lands.
    from tpudist.obs import dashboard, tsdb
    rundir = telemetry.outpath

    def _render_dashboard() -> str:
        return dashboard.render_history_file(
            live_path=tsdb.latest_path(rundir), refresh_s=5,
            incidents_dir=rundir)

    server = MetricsServer(fleet, port=args.metrics_port,
                           dashboard=_render_dashboard).start()
    print(f"[tpudist.launch] fleet metrics on :{server.port} "
          f"(/metrics, /dashboard)", file=sys.stderr, flush=True)
    return fleet, server


def _maybe_bundler(args, telemetry, bundler):
    """Lazily create the incident bundler once a rank's ``blackbox/`` dir
    exists (i.e. the job opted into ``--blackbox``); until then a launch
    leaves no ``incidents/`` footprint. Idempotent — returns the existing
    bundler untouched."""
    if bundler is not None or telemetry is None:
        return bundler
    from tpudist.blackbox import IncidentBundler, blackbox_dir
    if not os.path.isdir(blackbox_dir(telemetry.outpath)):
        return None
    bundler = IncidentBundler(telemetry.outpath, telemetry=telemetry,
                              keep=getattr(args, "incident_keep", 4))
    # Observe the launcher's own stream for fleet-level triggers
    # (nonzero rank_exit, straggler, eviction, collective_deadline).
    # The lazy launcher telemetry has ONE .sink slot (the fleet view may
    # hold it) — chain rather than replace.
    if hasattr(telemetry, "add_sink"):
        telemetry.add_sink(bundler.observe)
    else:
        prev = getattr(telemetry, "sink", None)

        def _chained(ev, _prev=prev, _obs=bundler.observe):
            if _prev is not None:
                try:
                    _prev(ev)
                except Exception:
                    pass
            _obs(ev)

        telemetry.sink = _chained
    print(f"[tpudist.launch] incident bundler armed "
          f"(keep {bundler.keep}, {bundler.dir})",
          file=sys.stderr, flush=True)
    return bundler


class _LazyLauncherTelemetry:
    """Launcher event stream that defers touching the run dir until a rank
    has finished setting it up (its ``heartbeats/`` subdir exists).

    Creating the outpath eagerly would regress every non-telemetry launch:
    rank 0's ``output_process`` would find a directory that "already
    exists" (failing ``--overwrite prompt`` headlessly) or, under
    ``--overwrite delete``, unlink the launcher's open event file. Events
    emitted before the dir is ready are buffered (bounded) with their
    original timestamps and flushed on the first ready emit."""

    _MAX_BUFFER = 256

    def __init__(self, outpath: str):
        self.outpath = outpath
        self._tel = None
        self._buf: list[tuple[float, str, dict]] = []
        self.sink = None        # fleet-metrics observer (sees events live,
        #                         even while the file stream is still lazy)

    def flush(self, force: bool = False) -> bool:
        """Open the stream and drain the buffer if a rank has created the
        run dir by now; called opportunistically from the supervision loop
        (a clean run may otherwise never emit a second event to trigger
        the drain). ``force=True`` — used once at launcher exit — creates
        the dir itself: the ranks are dead, so there is no --overwrite
        race left, and a job that crash-looped before any rank could set
        the dir up (bad coordinator, init hang) must still leave its
        rank_exit/restart timeline on disk. Returns True once the stream
        is live."""
        from tpudist.telemetry import Telemetry, heartbeat_dir
        if self._tel is None:
            if not force and not os.path.isdir(heartbeat_dir(self.outpath)):
                return False
            self._tel = Telemetry(self.outpath, rank=-1, attempt=0,
                                  name="launcher", heartbeat=False)
            for t0, et, fl in self._buf:
                # "t" in fields overrides the envelope's emit-time stamp.
                self._tel.emit(et, t=t0, **fl)
            self._buf.clear()
        return True

    def emit(self, etype: str, **fields) -> None:
        if self.sink is not None:
            try:
                self.sink(dict(fields, t=time.time(), type=etype, rank=-1))
            except Exception:
                pass
        if not self.flush():
            if len(self._buf) < self._MAX_BUFFER:
                self._buf.append((time.time(), etype, fields))
            return
        self._tel.emit(etype, **fields)


def _launcher_telemetry(args, cmd):
    """The launcher's own event stream (``events.launcher.jsonl``) in the
    run's telemetry dir. An explicit ``--telemetry-dir`` enables it
    eagerly (the operator named the dir). Otherwise it auto-enables ONLY
    when the command itself opts into telemetry (``--telemetry`` present)
    and an ``--outpath`` is found — and lazily, so the launcher never
    creates the run dir out from under rank 0's --overwrite handling.
    None when neither applies: the launcher stays usable (and
    side-effect-free) for arbitrary commands."""
    if args.telemetry_dir:
        from tpudist.telemetry import Telemetry
        return Telemetry(args.telemetry_dir, rank=-1, attempt=0,
                         name="launcher", heartbeat=False)
    if "--telemetry" not in cmd:
        return None
    tdir = ""
    for i, tok in enumerate(cmd):
        if tok == "--outpath" and i + 1 < len(cmd):
            tdir = cmd[i + 1]
            break
        if tok.startswith("--outpath="):
            tdir = tok.split("=", 1)[1]
            break
    return _LazyLauncherTelemetry(tdir) if tdir else None


def _supervise_once(args, cmd, attempt: int, telemetry=None,
                    fleet=None, nprocs: int = None) -> tuple[int, set]:
    """One launch-and-supervise pass over ``nprocs`` ranks (the CURRENT
    world — smaller than ``args.nprocs`` after an elastic reform): start
    every rank, abort-on-peer-loss, return ``(exit_code, lost_ranks)``.
    ``lost_ranks`` holds the ranks whose own nonzero exits triggered/joined
    the failure (the membership the elastic reform subtracts); survivors
    the teardown SIGTERM'd — whether they drained to exit 75 or were
    SIGKILL'd while blocked in a collective — are NOT lost: they relaunch
    as members of the reformed gang. In the default (local) case each pass picks
    a FRESH coordinator port — the previous coordinator (rank 0's service)
    died with the failed job. An EXPLICIT --coordinator is reused verbatim:
    on a cluster the other hosts rendezvous at that fixed address, so
    rotating it here would strand them; the trade-off is that a lingering
    socket from the killed attempt can fail the retry's bind (which then
    counts against the restart budget)."""
    if nprocs is None:
        nprocs = args.nprocs
    coordinator = args.coordinator or f"127.0.0.1:{find_free_port()}"
    if args.coordinator and attempt:
        print(f"[tpudist.launch] reusing explicit coordinator "
              f"{args.coordinator} for restart {attempt}",
              file=sys.stderr, flush=True)
    procs: list[subprocess.Popen] = []

    # Children run in their own sessions (see Popen below), so a signal to the
    # launcher no longer reaches them implicitly — route SIGTERM/SIGINT
    # through the group-aware teardown instead of leaking orphaned ranks.
    # Once teardown has begun, further signals don't interrupt it (a second
    # KeyboardInterrupt raised inside the teardown handler would abandon the
    # SIGKILL-stragglers phase and leak ranks stuck in collectives) — but
    # they are RECORDED: an operator interrupt during a failed attempt's
    # teardown must stop the launcher, not let the retry loop relaunch the
    # job the operator just tried to kill.
    tearing_down = False
    interrupted = False

    def _on_signal(signum, frame):
        nonlocal interrupted
        if not tearing_down:
            raise KeyboardInterrupt
        interrupted = True

    prev_term = signal.signal(signal.SIGTERM, _on_signal)
    # SIGINT too: the default handler raises KeyboardInterrupt even DURING
    # teardown, which would abandon the SIGKILL-stragglers phase on a second
    # Ctrl-C; _on_signal swallows signals once tearing_down is set.
    prev_int = signal.signal(signal.SIGINT, _on_signal)
    exit_code = 0
    if telemetry is not None:
        from tpudist.elastic.membership import mesh_str, parse_mesh_args
        m_shape, m_axes = parse_mesh_args(cmd)
        telemetry.emit("launcher_start", attempt=attempt, nprocs=nprocs,
                       coordinator=coordinator,
                       mesh=mesh_str(m_shape, m_axes))
    rank_of: dict[int, int] = {}
    flagged: set[int] = set()
    lost: set[int] = set()
    # Proactive-eviction state (--evict-stragglers): consecutive flagged
    # windows per rank, and the ranks already being drained (so one
    # straggler is evicted once, not re-signalled every poll).
    streaks: dict[int, int] = {}
    evicting: set[int] = set()
    floor_warned: set[int] = set()
    # Dead-collective state (--collective-deadline): the suspect rank
    # SIGTERM'd when the whole gang's heartbeats went stale, with its
    # drain deadline for the SIGKILL escalation (a rank wedged inside a
    # collective usually cannot act on SIGTERM).
    suspect_pid = None                 # pid of the SIGTERM'd suspect
    suspect_kill_at = 0.0
    # Watchdogs armed but no heartbeat ever seen (e.g. --telemetry-dir
    # pointing somewhere the ranks don't write): warn loudly once instead
    # of staying silently inert.
    beatless_polls = 0
    # Fleet time-series recorder (obs.tsdb): one row per supervision poll,
    # built from the poll's OWN heartbeat read + the fleet view's in-memory
    # scrape samples — zero added filesystem reads. Created lazily (below)
    # once the run dir provably exists, for the same reason the launcher
    # telemetry stream is lazy: creating the dir here would break rank 0's
    # --overwrite handling.
    ts_recorder = None
    # Incident bundler (tpudist/blackbox.py): correlates rank blackbox
    # dumps + fleet-level triggers into incidents/<id>/. Created lazily
    # once a rank's blackbox/ dir exists — a launch without --blackbox
    # ranks stays byte-identical on disk. Its poll self-throttles the one
    # directory scan it adds (~every 2 s, off the heartbeat hot path).
    bundler = None
    beats_warned = False
    last_straggler_check = time.monotonic()
    world = nprocs
    t_pass0 = time.monotonic()
    try:
        for rank in range(nprocs):
            env = _rank_env(args, coordinator, rank, nprocs, attempt)
            # New session per child so teardown can signal whole process groups.
            procs.append(subprocess.Popen(cmd, env=env, start_new_session=True))
            rank_of[procs[-1].pid] = rank

        # Reference behavior: a dead rank hung NCCL forever (SURVEY.md §5
        # "failure detection: none"). Here: first failure tears down the job.
        while procs:
            for pr in list(procs):
                rc = pr.poll()
                if rc is None:
                    continue
                procs.remove(pr)
                if rc != 0 and telemetry is not None:
                    from tpudist.faults import classify_exit
                    telemetry.emit("rank_exit", attempt=attempt,
                                   exit_rank=rank_of.get(pr.pid, -1),
                                   code=rc,
                                   classification=classify_exit(rc))
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    lost.add(rank_of.get(pr.pid, -1))
                    tearing_down = True
                    survivors = procs
                    procs = []
                    # Abort-on-peer-loss. Under --elastic this teardown IS
                    # the drain: each survivor's preemption guard catches
                    # the SIGTERM, finishes the in-flight step, writes the
                    # emergency checkpoint (with the epoch's sample cursor),
                    # and exits 75 — so the grace window must cover a step
                    # plus a checkpoint write (--drain-grace), not just
                    # process teardown.
                    _terminate_all(survivors,
                                   grace=args.drain_grace if args.elastic
                                   else 10.0)
                    from tpudist.faults import (PREEMPTED_EXIT_CODE,
                                                classify_exit)
                    for sv in survivors:
                        src = sv.returncode
                        # Survivor exits are recorded ONLY under --elastic,
                        # where drain outcomes decide the reformed gang's
                        # membership; the non-elastic path keeps its
                        # one-rank_exit-per-failure event semantics (fault
                        # timelines and fleet exit counters are SLO inputs
                        # — the launcher's own teardown kills must not
                        # inflate them).
                        if args.elastic and src and telemetry is not None:
                            telemetry.emit("rank_exit", attempt=attempt,
                                           exit_rank=rank_of.get(sv.pid, -1),
                                           code=src,
                                           classification=classify_exit(src))
                        if src and src > 0 and src != PREEMPTED_EXIT_CODE:
                            # Crashed on its own during the drain (not our
                            # SIGTERM/SIGKILL, not a clean drain): this rank
                            # is lost too — the reform must subtract it.
                            lost.add(rank_of.get(sv.pid, -1))
                    break
            if procs and time.monotonic() - last_straggler_check >= 1.0:
                last_straggler_check = time.monotonic()
                if hasattr(telemetry, "flush"):
                    telemetry.flush()      # drain lazy buffer once dir exists
                world = _maybe_scale_up(args, telemetry, attempt, cmd,
                                        coordinator, procs, rank_of, world,
                                        t_pass0)
                # ONE heartbeat-dir read per poll, shared by the straggler
                # check, the eviction/deadline watchdogs, and the fleet
                # view (shared-FS listdir+parse per second is the
                # multi-host cost heartbeat throttling exists for — don't
                # pay it twice).
                beats = None
                if telemetry is not None and (args.straggler_factor > 0
                                              or fleet is not None
                                              or args.collective_deadline
                                              > 0):
                    from tpudist.telemetry import (heartbeat_dir,
                                                   read_heartbeats)
                    beats = read_heartbeats(
                        heartbeat_dir(telemetry.outpath))
                if (args.evict_stragglers or args.collective_deadline > 0) \
                        and not beats_warned:
                    if any(b.get("attempt") == attempt
                           for b in (beats or {}).values()):
                        beats_warned = True    # heartbeats flowing: satisfied
                    else:
                        beatless_polls += 1
                        if beatless_polls >= 60:
                            beats_warned = True
                            print(f"[tpudist.launch] WARNING: "
                                  f"--evict-stragglers/--collective-"
                                  f"deadline armed but no rank heartbeat "
                                  f"appeared in ~{beatless_polls}s — both "
                                  f"watchdogs are inert. Is the telemetry "
                                  f"dir ({telemetry.outpath}) the ranks' "
                                  f"--outpath?", file=sys.stderr,
                                  flush=True)
                live = _check_stragglers(args, telemetry, attempt, flagged,
                                         beats)
                _maybe_evict(args, telemetry, attempt, live, streaks,
                             evicting, floor_warned, procs, rank_of, world)
                suspect_pid, suspect_kill_at = _check_collective_deadline(
                    args, telemetry, attempt, beats, procs, rank_of,
                    suspect_pid, suspect_kill_at)
                if fleet is not None:
                    fleet.refresh(attempt=attempt, beats=beats)
                    if ts_recorder is None and telemetry is not None \
                            and (beats
                                 or getattr(telemetry, "_tel", True)
                                 is not None):
                        # Beats flowing (the ranks created the run dir) or
                        # the launcher stream is already live (explicit
                        # --telemetry-dir, or the lazy stream opened):
                        # safe to open our series file without racing
                        # rank 0's --overwrite handling.
                        from tpudist.obs.tsdb import FleetSeriesRecorder
                        ts_recorder = FleetSeriesRecorder(
                            telemetry.outpath, attempt=attempt)
                    if ts_recorder is not None:
                        ts_recorder.sample(fleet, beats)
                bundler = _maybe_bundler(args, telemetry, bundler)
                if bundler is not None:
                    bundler.poll()
            if procs:
                time.sleep(0.2)
    except KeyboardInterrupt:
        tearing_down = True
        _terminate_all(procs)
        exit_code = exit_code or 130
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
        if ts_recorder is not None:
            ts_recorder.sample(fleet, None)   # final counters row
            ts_recorder.close()
        # Final sweep: a dump written between the last poll and teardown
        # (the common case — the anomaly killed the job) must still
        # bundle. Also catches a blackbox/ dir that appeared too late for
        # the lazy in-loop creation.
        bundler = _maybe_bundler(args, telemetry, bundler)
        if bundler is not None:
            bundler.close()
    if interrupted:
        return 130, lost    # operator interrupt outranks the retry budget
    return exit_code, lost


def _rank_env(args, coordinator: str, rank: int, nprocs: int,
              attempt: int) -> dict:
    """One rank's child environment (rendezvous identity + platform
    hygiene) — shared by the initial spawn loop and the --scale-up path,
    so a scaled-in replica is configured exactly like a launched one."""
    env = dict(os.environ)
    env["TPUDIST_COORDINATOR"] = coordinator
    env["TPUDIST_NUM_PROCESSES"] = str(nprocs)
    env["TPUDIST_PROCESS_ID"] = str(rank)
    env["TPUDIST_RESTART_COUNT"] = str(attempt)
    if args.elastic:
        # Ranks (and their data plane) learn the CURRENT world from
        # the env even when jax.distributed is not initialized (the
        # CPU gang simulation) — see dist.data_rank_world.
        env["TPUDIST_ELASTIC"] = "1"
    if args.inject:
        env["TPUDIST_INJECT"] = args.inject
    if args.platform:
        env["JAX_PLATFORMS"] = args.platform
        if args.platform == "cpu":
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count="
                                f"{args.devices_per_proc}").strip()
            # Drop the sitecustomize dir that force-registers the
            # remote TPU-tunnel platform (it would override
            # JAX_PLATFORMS=cpu). Opt out: TPUDIST_KEEP_PYTHONPATH=1.
            if not env.get("TPUDIST_KEEP_PYTHONPATH"):
                env["PYTHONPATH"] = os.pathsep.join(
                    pth for pth in env.get("PYTHONPATH", "").split(os.pathsep)
                    if pth and ".axon_site" not in pth)
    return env


def _maybe_scale_up(args, telemetry, attempt: int, cmd, coordinator: str,
                    procs: list, rank_of: dict, world: int,
                    t_pass0: float) -> int:
    """Elastic scale-up (``--scale-up W@S``, the serving plane): once the
    delay has elapsed and every current rank is still alive, spawn the
    additional replicas and emit ``topology_change`` (mesh_action
    ``scale_up``) so the fleet view's world follows. Fires once per
    supervise pass (after it, ``world`` == the target). Returns the new
    world."""
    target = getattr(args, "scale_target", 0)
    if not target or world >= target \
            or time.monotonic() - t_pass0 < args.scale_after:
        return world
    print(f"[tpudist.launch] SCALE-UP: growing world {world} -> {target} "
          f"(+{args.scale_after:.0f}s reached; spawning rank(s) "
          f"{list(range(world, target))})", file=sys.stderr, flush=True)
    for rank in range(world, target):
        env = _rank_env(args, coordinator, rank, target, attempt)
        procs.append(subprocess.Popen(cmd, env=env, start_new_session=True))
        rank_of[procs[-1].pid] = rank
    if telemetry is not None:
        telemetry.emit("topology_change", attempt=attempt,
                       from_world=world, to_world=target,
                       mesh_action="scale_up")
    return target


def _check_stragglers(args, telemetry, attempt: int, flagged: set,
                      beats=None) -> list:
    """Aggregate the ranks' heartbeat files into straggler flags
    (``straggler`` events fire once per rank per attempt; the RETURNED
    list is every rank flagged THIS poll, which is what the eviction
    streak counter consumes). Heartbeats exist only when the trainer runs
    with --telemetry; absent files are simply an empty read. ``beats``
    lets the supervision poll share one heartbeat-dir read with the fleet
    view."""
    if telemetry is None or args.straggler_factor <= 0:
        return []
    from tpudist.telemetry import (find_stragglers, heartbeat_dir,
                                   read_heartbeats)
    if beats is None:
        beats = read_heartbeats(heartbeat_dir(telemetry.outpath))
    live = find_stragglers(beats, factor=args.straggler_factor,
                           attempt=attempt)
    for s in live:
        rank = s["straggler_rank"]
        if rank in flagged:
            continue
        flagged.add(rank)
        print(f"[tpudist.launch] straggler: rank {rank} per-step host "
              f"overhead p50 {s['host_p50_s'] * 1e3:.0f}ms vs fleet median "
              f"{s['median_others_s'] * 1e3:.0f}ms ({s['factor']:.1f}x, "
              f"attempt {attempt}) — investigate that host's input "
              f"pipeline/CPU before blaming the collective",
              file=sys.stderr, flush=True)
        telemetry.emit("straggler", attempt=attempt, straggler_rank=rank,
                       factor=s["factor"], host_p50_s=s["host_p50_s"],
                       median_others_s=s["median_others_s"])
    return live


def _maybe_evict(args, telemetry, attempt: int, live: list,
                 streaks: dict, evicting: set, floor_warned: set,
                 procs: list, rank_of: dict, nprocs: int) -> None:
    """Proactive straggler eviction (``--evict-stragglers N``): a rank the
    detector flags for N CONSECUTIVE supervision windows is drained —
    SIGTERM to its process group, so its preemption guard finishes the
    in-flight step, writes the emergency checkpoint (with the epoch's
    sample cursor), and exits 75, which the supervision loop then treats
    as the lost rank of an elastic reform. The persistent-straggler
    gauge grows teeth; a transient blip (streak broken by one healthy
    window) resets to zero."""
    if not args.evict_stragglers or telemetry is None:
        return
    cur = {s["straggler_rank"] for s in live}
    for rank in list(streaks):
        if rank not in cur:
            del streaks[rank]          # streak broken: transient, forgiven
    by_factor = {s["straggler_rank"]: s.get("factor") for s in live}
    for rank in sorted(cur):
        streaks[rank] = streaks.get(rank, 0) + 1
        if rank in evicting or streaks[rank] < args.evict_stragglers:
            continue
        if nprocs - len(evicting) - 1 < max(1, args.min_ranks):
            # Never evict below the --min-ranks floor: a slow gang beats
            # no gang. The rank keeps re-qualifying every N windows, so
            # warn ONCE per rank per attempt, not every requalification.
            if rank not in floor_warned:
                floor_warned.add(rank)
                print(f"[tpudist.launch] straggler rank {rank} qualifies "
                      f"for eviction but the survivors would drop below "
                      f"--min-ranks {args.min_ranks} — keeping it",
                      file=sys.stderr, flush=True)
            streaks[rank] = 0
            continue
        evicting.add(rank)
        print(f"[tpudist.launch] EVICTING straggler rank {rank} (flagged "
              f"{streaks[rank]} consecutive windows, "
              f"{by_factor.get(rank, 0):.1f}x the fleet median) — draining "
              f"it through SIGTERM -> emergency checkpoint -> reform",
              file=sys.stderr, flush=True)
        telemetry.emit("eviction", attempt=attempt, straggler_rank=rank,
                       windows=streaks[rank],
                       factor=float(by_factor.get(rank) or 0.0))
        for pr in procs:
            if rank_of.get(pr.pid) == rank:
                try:
                    os.killpg(pr.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass


def _check_collective_deadline(args, telemetry, attempt: int, beats,
                               procs: list, rank_of: dict,
                               suspect_pid, suspect_kill_at: float):
    """Dead-collective watchdog (``--collective-deadline S``): when EVERY
    live rank has a current-attempt heartbeat and every one of them is
    older than S seconds, the gang is wedged (one dead-ish peer stalls
    everyone inside a collective; nobody exits, so abort-on-peer-loss
    never triggers). Emit a loud ``collective_deadline`` event naming the
    stalest rank as the suspect, SIGTERM it, and SIGKILL it after
    --drain-grace if it cannot act on the signal (a rank blocked inside a
    collective usually cannot) — its exit then converts the hang into the
    normal drain -> reform/restart path. Fires once per attempt."""
    if args.collective_deadline <= 0 or telemetry is None or not procs:
        return suspect_pid, suspect_kill_at
    if suspect_pid is not None:
        # Escalation phase: the suspect got SIGTERM; if it is still alive
        # past the drain grace, SIGKILL its group.
        if time.monotonic() >= suspect_kill_at \
                and any(pr.pid == suspect_pid for pr in procs):
            try:
                os.killpg(suspect_pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        return suspect_pid, suspect_kill_at
    live_ranks = {rank_of.get(pr.pid, -1): pr for pr in procs}
    cur = {r: b for r, b in (beats or {}).items()
           if b.get("attempt") == attempt and r in live_ranks}
    if len(cur) < len(live_ranks):
        return suspect_pid, suspect_kill_at   # a rank has no beat yet
    now = time.time()
    ages = {r: now - float(b.get("updated_at", 0.0)) for r, b in cur.items()}
    if not ages or min(ages.values()) <= args.collective_deadline:
        return suspect_pid, suspect_kill_at
    suspect = max(ages, key=lambda r: ages[r])
    print(f"[tpudist.launch] COLLECTIVE DEADLINE: no rank has made step "
          f"progress for {min(ages.values()):.0f}s (deadline "
          f"{args.collective_deadline:.0f}s; stalest: rank {suspect} at "
          f"{ages[suspect]:.0f}s) — the gang looks wedged in a dead "
          f"collective; draining rank {suspect} so the job reforms "
          f"instead of hanging", file=sys.stderr, flush=True)
    telemetry.emit("collective_deadline", attempt=attempt,
                   suspect_rank=suspect,
                   max_age_s=round(ages[suspect], 3),
                   deadline_s=args.collective_deadline)
    pr = live_ranks[suspect]
    try:
        os.killpg(pr.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        pass
    grace = args.drain_grace if args.elastic else 10.0
    return pr.pid, time.monotonic() + grace


if __name__ == "__main__":
    sys.exit(main())
