"""Multi-process launcher (reference C18: ``torch.distributed.launch``,
``start.sh:3-4``).

On real TPU pods each HOST runs one process and the TPU runtime supplies the
topology, so no launcher is needed there (``jax.distributed.initialize()``
with no args). This launcher covers the other cases:

- simulating a multi-process (multi-host) run on one machine — N processes on
  the CPU backend with a local coordinator, the moral equivalent of
  ``python -m torch.distributed.launch --nproc_per_node=N`` on one box;
- launching with explicit coordinator/process ids on clusters without TPU
  metadata.

Usage::

    python -m tpudist.launch --nprocs 2 -- python -m tpudist --synthetic ...

Each child gets ``TPUDIST_COORDINATOR``, ``TPUDIST_NUM_PROCESSES``,
``TPUDIST_PROCESS_ID`` (read by ``dist.initialize_runtime``) and, for the
local-simulation case, a CPU device count per process. Rendezvous is the
jax.distributed coordinator (TCP) — the NCCL/TCPStore rendezvous of the
reference (``distributed.py:124``) with the coordinator service instead.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def find_free_port() -> int:
    # SO_REUSEADDR so the coordinator can bind even while the probe socket's
    # address lingers in TIME_WAIT. A concurrent process could still claim the
    # port between close and the coordinator's bind; rank 0 then fails to bind
    # and abort-on-peer-loss below tears the job down rather than hanging.
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _terminate_all(procs, grace: float = 10.0) -> None:
    """SIGTERM each child's process group, then SIGKILL stragglers after a
    grace period — a rank blocked in a collective (or its grandchildren)
    must not outlive the job."""
    for pr in procs:
        try:
            os.killpg(pr.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + grace
    for pr in procs:
        try:
            pr.wait(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(pr.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            pr.wait()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="tpudist multi-process launcher")
    p.add_argument("--nprocs", type=int, required=True,
                   help="number of processes to launch")
    p.add_argument("--coordinator", default=None,
                   help="host:port (default: 127.0.0.1:<free port>)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="CPU devices each process simulates (local runs)")
    p.add_argument("--platform", default="cpu",
                   help="JAX platform for children (cpu for simulation)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to run (prefix with --)")
    args = p.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given (append: -- python -m tpudist ...)")

    coordinator = args.coordinator or f"127.0.0.1:{find_free_port()}"
    procs: list[subprocess.Popen] = []

    # Children run in their own sessions (see Popen below), so a signal to the
    # launcher no longer reaches them implicitly — route SIGTERM/SIGINT
    # through the group-aware teardown instead of leaking orphaned ranks.
    # Once teardown has begun, further signals are ignored: a second
    # KeyboardInterrupt raised inside the teardown handler would abandon the
    # SIGKILL-stragglers phase and leak ranks stuck in collectives.
    tearing_down = False

    def _on_signal(signum, frame):
        if not tearing_down:
            raise KeyboardInterrupt

    prev_term = signal.signal(signal.SIGTERM, _on_signal)
    # SIGINT too: the default handler raises KeyboardInterrupt even DURING
    # teardown, which would abandon the SIGKILL-stragglers phase on a second
    # Ctrl-C; _on_signal swallows signals once tearing_down is set.
    prev_int = signal.signal(signal.SIGINT, _on_signal)
    exit_code = 0
    try:
        for rank in range(args.nprocs):
            env = dict(os.environ)
            env["TPUDIST_COORDINATOR"] = coordinator
            env["TPUDIST_NUM_PROCESSES"] = str(args.nprocs)
            env["TPUDIST_PROCESS_ID"] = str(rank)
            if args.platform:
                env["JAX_PLATFORMS"] = args.platform
                if args.platform == "cpu":
                    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                        f" --xla_force_host_platform_device_count="
                                        f"{args.devices_per_proc}").strip()
                    # Drop the sitecustomize dir that force-registers the
                    # remote TPU-tunnel platform (it would override
                    # JAX_PLATFORMS=cpu). Opt out: TPUDIST_KEEP_PYTHONPATH=1.
                    if not env.get("TPUDIST_KEEP_PYTHONPATH"):
                        env["PYTHONPATH"] = os.pathsep.join(
                            pth for pth in env.get("PYTHONPATH", "").split(os.pathsep)
                            if pth and ".axon_site" not in pth)
            # New session per child so teardown can signal whole process groups.
            procs.append(subprocess.Popen(cmd, env=env, start_new_session=True))

        # Reference behavior: a dead rank hung NCCL forever (SURVEY.md §5
        # "failure detection: none"). Here: first failure tears down the job.
        while procs:
            for pr in list(procs):
                rc = pr.poll()
                if rc is None:
                    continue
                procs.remove(pr)
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    tearing_down = True
                    _terminate_all(procs)     # abort-on-peer-loss
                    procs = []
                    break
            if procs:
                time.sleep(0.2)
    except KeyboardInterrupt:
        tearing_down = True
        _terminate_all(procs)
        exit_code = exit_code or 130
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
