"""ConvNeXt tiny/small/base/large in flax/NHWC (torchvision ``convnext.py``).

Zoo parity for the reference's by-name model build
(``/root/reference/distributed.py:131-137``; modern torchvision exposes the
ConvNeXt family). Structure: 4×4/s4 patchify stem + LayerNorm, four stages of
CNBlocks (7×7 depthwise → LN → 4× MLP with exact-erf GELU → layer-scale
γ·init 1e-6 → row-mode stochastic depth → residual) with LN+2×2/s2
downsamplers between stages, LN + Linear head. All weights trunc_normal
std 0.02, zero bias (torchvision's init loop).

TPU notes: torchvision permutes NCHW↔NHWC around every block's LN/MLP; here
the whole network is natively NHWC so those permutes vanish. The MLP Dense
pair is a pure MXU matmul at every spatial position, and LN/GELU/layer-scale
fuse into it under XLA. No BatchNorm anywhere — no ``batch_stats``
collection, and SyncBN flags are accepted-and-ignored like ViT's.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpudist.models.layers import stochastic_depth

_TRUNC02 = nn.initializers.truncated_normal(0.02)

# (c_in, c_out_after_downsample | None, num_blocks) per stage + sd prob —
# torchvision convnext_{tiny,small,base,large} block settings.
_VARIANTS: dict[str, Tuple[Sequence, float]] = {
    "convnext_tiny": (((96, 192, 3), (192, 384, 3), (384, 768, 9),
                       (768, None, 3)), 0.1),
    "convnext_small": (((96, 192, 3), (192, 384, 3), (384, 768, 27),
                        (768, None, 3)), 0.4),
    "convnext_base": (((128, 256, 3), (256, 512, 3), (512, 1024, 27),
                       (1024, None, 3)), 0.5),
    "convnext_large": (((192, 384, 3), (384, 768, 3), (768, 1536, 27),
                        (1536, None, 3)), 0.5),
}


class CNBlock(nn.Module):
    dim: int
    sd_prob: float = 0.0
    layer_scale: float = 1e-6
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        y = nn.Conv(self.dim, (7, 7), padding=[(3, 3), (3, 3)],
                    feature_group_count=self.dim, use_bias=True,
                    kernel_init=_TRUNC02, dtype=self.dtype, name="dwconv")(x)
        y = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm")(y)
        y = nn.Dense(4 * self.dim, kernel_init=_TRUNC02, dtype=self.dtype,
                     name="mlp_fc1")(y)
        y = nn.gelu(y, approximate=False)      # torch GELU is exact-erf
        y = nn.Dense(self.dim, kernel_init=_TRUNC02, dtype=self.dtype,
                     name="mlp_fc2")(y)
        gamma = self.param("layer_scale", nn.initializers.constant(
            self.layer_scale), (self.dim,))
        y = y * gamma.astype(y.dtype)
        rng = self.make_rng("dropout") if (train and self.sd_prob > 0.0) \
            else None
        return x + stochastic_depth(y, self.sd_prob, not train, rng)


class ConvNeXt(nn.Module):
    block_setting: Sequence            # ((c_in, c_out|None, n_blocks), ...)
    stochastic_depth_prob: float = 0.0
    num_classes: int = 1000
    dtype: Any = None
    # Accepted for zoo-uniform construction; ConvNeXt has no BatchNorm.
    sync_batchnorm: bool = False
    bn_axis_name: str = "data"

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = x.astype(self.dtype or x.dtype)
        c0 = self.block_setting[0][0]
        # Patchify stem: 4x4/s4 conv (bias=True) + LN — torchvision
        # Conv2dNormActivation(..., norm=LayerNorm2d, activation=None).
        x = nn.Conv(c0, (4, 4), strides=(4, 4), padding="VALID",
                    use_bias=True, kernel_init=_TRUNC02, dtype=self.dtype,
                    name="features_0_conv")(x)
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype,
                         name="features_0_norm")(x)
        # torchvision ramps sd over total_blocks - 1 (unlike EfficientNet).
        total = sum(n for *_, n in self.block_setting)
        block_id, feat = 0, 1
        for c_in, c_out, n in self.block_setting:
            for i in range(n):
                x = CNBlock(c_in,
                            sd_prob=self.stochastic_depth_prob * block_id
                            / max(total - 1.0, 1.0),
                            dtype=self.dtype,
                            name=f"features_{feat}_{i}")(x, train)
                block_id += 1
            feat += 1
            if c_out is not None:
                x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype,
                                 name=f"features_{feat}_norm")(x)
                x = nn.Conv(c_out, (2, 2), strides=(2, 2), padding="VALID",
                            use_bias=True, kernel_init=_TRUNC02,
                            dtype=self.dtype, name=f"features_{feat}_conv")(x)
                feat += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="classifier_0")(x)
        return nn.Dense(self.num_classes, kernel_init=_TRUNC02,
                        dtype=self.dtype, name="classifier_2")(x)


def _ctor(name: str):
    setting, sd = _VARIANTS[name]

    def build(num_classes: int = 1000, dtype: Any = None,
              sync_batchnorm: bool = False, bn_axis_name: str = "data",
              **kw) -> ConvNeXt:
        return ConvNeXt(block_setting=setting, stochastic_depth_prob=sd,
                        num_classes=num_classes, dtype=dtype,
                        sync_batchnorm=sync_batchnorm,
                        bn_axis_name=bn_axis_name)
    build.__name__ = name
    return build


convnext_tiny = _ctor("convnext_tiny")
convnext_small = _ctor("convnext_small")
convnext_base = _ctor("convnext_base")
convnext_large = _ctor("convnext_large")
